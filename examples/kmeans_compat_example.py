#!/usr/bin/env python
"""K-Means via the Spark-ML compat surface — the reference's PySpark twin
(examples/kmeans-pyspark/kmeans-pyspark.py:47-67): load libsvm data, fit
KMeans().setK(2).setSeed(1), transform, score the clustering with
ClusteringEvaluator (squared-euclidean silhouette), and print the cluster
centers.

Where the reference builds a SparkSession DataFrame from libsvm, the
compat surface takes a dict of numpy columns.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu K-Means compat example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "sample_kmeans_data.txt"))
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    args = p.parse_args()

    from oap_mllib_tpu.compat.spark import ClusteringEvaluator, KMeans
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_libsvm

    if args.device:
        set_config(device=args.device)
    if args.timing:
        set_config(timing=True)

    # spark.read.format("libsvm").load(path)
    _, x = read_libsvm(args.data)
    dataset = {"features": x}

    # KMeans().setK(2).setSeed(1); model = kmeans.fit(dataset)
    kmeans = KMeans().setK(args.k).setSeed(args.seed)
    model = kmeans.fit(dataset)

    # predictions = model.transform(dataset)
    predictions = model.transform(dataset)

    # evaluator = ClusteringEvaluator(); silhouette = evaluator.evaluate(...)
    evaluator = ClusteringEvaluator()
    silhouette = evaluator.evaluate(predictions)
    print("Silhouette with squared euclidean distance = " + str(silhouette))

    print("Cluster Centers: ")
    for center in model.clusterCenters():
        print(center)


if __name__ == "__main__":
    main()
