#!/usr/bin/env python
"""K-Means via the Spark-ML compat surface — the reference's PySpark twin
(examples/kmeans-pyspark/kmeans-pyspark.py:47-67): load libsvm data, fit
KMeans().setK(2).setSeed(1), transform, score the clustering with the
squared-euclidean silhouette (Spark's ClusteringEvaluator default), and
print the cluster centers.

Where the reference builds a SparkSession DataFrame from libsvm, the
compat surface takes a dict of numpy columns.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def silhouette_squared_euclidean(x: np.ndarray, labels: np.ndarray) -> float:
    """Mean silhouette with squared-euclidean distance (ClusteringEvaluator's
    default metric).  Per Spark's formulation the point-to-cluster distance
    is the MEAN squared distance to the cluster's points, computable from
    cluster means and second moments without pairwise distances."""
    uniq = np.unique(labels)
    if len(uniq) < 2:
        return float("nan")
    sq = np.einsum("ij,ij->i", x, x)
    means = np.stack([x[labels == c].mean(axis=0) for c in uniq])
    mean_sq = np.asarray([sq[labels == c].mean() for c in uniq])
    counts = np.asarray([(labels == c).sum() for c in uniq])
    # mean squared distance from point i to cluster c:
    #   E||p - x_i||^2 = E||p||^2 - 2 x_i . mean_c + ||x_i||^2
    d = mean_sq[None, :] - 2.0 * x @ means.T + sq[:, None]
    own = np.searchsorted(uniq, labels)
    n_own = counts[own]
    scores = np.zeros(len(x))
    valid = n_own > 1
    # a(i): exclude the point itself from its own cluster's mean distance
    a = d[np.arange(len(x)), own] * n_own / np.maximum(n_own - 1, 1)
    d_other = d.copy()
    d_other[np.arange(len(x)), own] = np.inf
    b = d_other.min(axis=1)
    scores[valid] = ((b - a) / np.maximum(a, b))[valid]
    return float(scores.mean())


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu K-Means compat example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "sample_kmeans_data.txt"))
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    args = p.parse_args()

    from oap_mllib_tpu.compat.spark import KMeans
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_libsvm

    if args.device:
        set_config(device=args.device)
    if args.timing:
        set_config(timing=True)

    # spark.read.format("libsvm").load(path)
    _, x = read_libsvm(args.data)
    dataset = {"features": x}

    # KMeans().setK(2).setSeed(1); model = kmeans.fit(dataset)
    kmeans = KMeans().setK(args.k).setSeed(args.seed)
    model = kmeans.fit(dataset)

    # predictions = model.transform(dataset)
    predictions = model.transform(dataset)

    # ClusteringEvaluator().evaluate(predictions)
    silhouette = silhouette_squared_euclidean(x, predictions["prediction"])
    print("Silhouette with squared euclidean distance = " + str(silhouette))

    print("Cluster Centers: ")
    for center in model.clusterCenters():
        print(center)


if __name__ == "__main__":
    main()
