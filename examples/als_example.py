#!/usr/bin/env python
"""ALS example — mirror of the reference's examples/als
(ALSExample.scala / als-pyspark.py): load user::item::rating data, fit
implicit-feedback ALS with the reference example's hyperparameters
(implicitPrefs=true, alpha=40, rank=10, maxIter=5 — reference
examples/als-pyspark/als-pyspark.py:52-54), print factors and training
RMSE."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu ALS example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "sample_als_ratings.txt"))
    p.add_argument("--rank", type=int, default=10)
    p.add_argument("--max-iter", type=int, default=5)
    p.add_argument("--reg", type=float, default=0.1)
    p.add_argument("--alpha", type=float, default=40.0)
    p.add_argument("--explicit", action="store_true",
                   help="explicit feedback (default implicit, like the reference example)")
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    p.add_argument("--als-kernel", default=None,
                   choices=["auto", "grouped", "coo"],
                   help="normal-equation layout (default auto: grouped "
                        "unless the degree distribution's padding blowup "
                        "trips the guard; fit summary records the choice)")
    args = p.parse_args()

    from oap_mllib_tpu import ALS
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_ratings

    if args.device:
        set_config(device=args.device)
    if args.als_kernel:
        set_config(als_kernel=args.als_kernel)
    if args.timing:
        import logging

        logging.basicConfig(level=logging.INFO)
        set_config(timing=True)

    users, items, ratings = read_ratings(args.data)
    print(f"Loaded {len(ratings)} ratings, {users.max()+1} users, {items.max()+1} items")

    model = ALS(
        rank=args.rank, max_iter=args.max_iter, reg_param=args.reg,
        alpha=args.alpha, implicit_prefs=not args.explicit,
    ).fit(users, items, ratings)

    print(f"Accelerated path: {model.summary['accelerated']}")
    print(f"User factors: {model.user_factors_.shape}, item factors: {model.item_factors_.shape}")
    pred = model.predict(users, items)
    if args.explicit:
        rmse = float(np.sqrt(np.mean((pred - ratings) ** 2)))
        print(f"Training RMSE: {rmse:.4f}")
    else:
        # implicit: report preference reconstruction (target is 1 for observed)
        rmse = float(np.sqrt(np.mean((pred - 1.0) ** 2)))
        print(f"Training preference RMSE (vs 1.0): {rmse:.4f}")
    recs = model.recommend_for_all_users(3)
    print("Top-3 recommendations for first 5 users:")
    for u in range(min(5, recs.shape[0])):
        print(f"  user {u}: items {recs[u].tolist()}")


if __name__ == "__main__":
    main()
