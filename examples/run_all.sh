#!/usr/bin/env bash
# Run all examples (the reference's examples/run-all-scala.sh /
# run-all-pyspark.sh analog). Any failure fails the script.
set -euo pipefail
cd "$(dirname "$0")"

for ex in kmeans_example.py pca_example.py als_example.py \
          kmeans_compat_example.py pca_compat_example.py als_compat_example.py \
          kmeans_pyspark_example.py pca_pyspark_example.py \
          als_pyspark_example.py; do
  echo "=== $ex ==="
  python "$ex" "$@"
  echo
done
echo "All examples completed."
