#!/usr/bin/env python
"""ALS via the Spark-ML compat surface — a line-for-line port of the
reference's PySpark example (examples/als-pyspark/als-pyspark.py:40-67):
parse user::item::rating lines, random 80/20 split, fit implicit ALS with
coldStartStrategy="drop" so the held-out RMSE never sees NaN, evaluate.

Where the reference builds a SparkSession DataFrame, the compat surface
takes a dict of numpy columns; everything from the ALS() builder call on
is the same API.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu ALS compat example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "sample_als_ratings.txt"))
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    args = p.parse_args()

    from oap_mllib_tpu.compat.spark import ALS, RegressionEvaluator
    from oap_mllib_tpu.config import set_config

    if args.device:
        set_config(device=args.device)
    if args.timing:
        set_config(timing=True)

    # lines.map(lambda row: row.value.split("::")) -> Row(userId, movieId, rating)
    parts = [ln.split("::") for ln in open(args.data) if ln.strip()]
    ratings = {
        "userId": np.asarray([int(r[0]) for r in parts], np.int64),
        "movieId": np.asarray([int(r[1]) for r in parts], np.int64),
        "rating": np.asarray([float(r[2]) for r in parts], np.float32),
    }

    # ratings.randomSplit([0.8, 0.2])
    rng = np.random.default_rng(args.seed)
    in_train = rng.random(len(ratings["rating"])) < 0.8
    training = {k: v[in_train] for k, v in ratings.items()}
    test = {k: v[~in_train] for k, v in ratings.items()}

    # reference hyperparameters (als-pyspark.py:52-54)
    als = (
        ALS()
        .setRank(10).setMaxIter(5).setRegParam(0.01)
        .setImplicitPrefs(True).setAlpha(40.0)
        .setUserCol("userId").setItemCol("movieId").setRatingCol("rating")
        .setColdStartStrategy("drop")
    )
    print(
        "\nALS training with implicitPrefs={}, rank={}, maxIter={}, "
        "regParam={}, alpha={}, seed={}\n".format(
            als.getImplicitPrefs(), als.getRank(), als.getMaxIter(),
            als.getRegParam(), als.getAlpha(), args.seed,
        )
    )
    model = als.fit(training)

    # RegressionEvaluator(metricName="rmse", labelCol="rating",
    # predictionCol="prediction") — reference als-pyspark.py:62; implicit
    # ALS predicts a preference/confidence score, so like the reference
    # example this is a smoke metric, not a ratings-scale fit
    predictions = model.transform(test)
    dropped = len(test["rating"]) - len(predictions["rating"])
    if dropped:
        print(f"coldStartStrategy=drop removed {dropped} cold test rows")
    evaluator = RegressionEvaluator(
        metricName="rmse", labelCol="rating", predictionCol="prediction"
    )
    rmse = evaluator.evaluate(predictions)
    print("Root-mean-square error = " + str(rmse))


if __name__ == "__main__":
    main()
