"""The reference's PySpark PCA example, verbatim-minus-import.

This is /root/reference/examples/pca-pyspark/pca-pyspark.py (itself
Apache-2.0 Spark sample code) with exactly ONE functional change: the
PCA import comes from ``oap_mllib_tpu.compat.pyspark`` instead of
``pyspark.ml.feature`` (Python has no classpath shadowing, so the import
line IS the drop-in point — see compat/pyspark.py module notes).
VectorAssembler stays a stock pyspark transformer, exactly as in the
reference, whose classpath shadowing also replaces only PCA.  Without a
pyspark installation this script reports the skip and exits 0 (so
examples/run_all.sh stays green in pyspark-less environments like this
image).  The same adapter flow runs against a mocked DataFrame in
tests/test_pyspark_compat.py.
"""

from __future__ import print_function

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

try:
    from pyspark.ml.feature import VectorAssembler
    from pyspark.sql import SparkSession
except ImportError:
    print("pyspark is not installed — skipping the drop-in PySpark example "
          "(the adapter's contract is covered by tests/test_pyspark_compat.py)")
    sys.exit(0)

# THE drop-in change: this line reads
#   from pyspark.ml.feature import PCA
# in the reference example (pca-pyspark.py:21)
from oap_mllib_tpu.compat.pyspark import PCA  # noqa: E402

if __name__ == "__main__":
    spark = SparkSession\
        .builder\
        .appName("PCAExample")\
        .getOrCreate()

    # positional args like the reference (pca-pyspark.py <csv> <K>);
    # run_all.sh's --device flags are for the non-pyspark examples and
    # fall through to the bundled default data here
    if len(sys.argv) == 3 and not sys.argv[1].startswith("--"):
        path, K = sys.argv[1], int(sys.argv[2])
    else:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pca_data.csv")
        K = 3

    input = spark.read.load(path, format="csv", inferSchema="true", header="false")

    assembler = VectorAssembler(
        inputCols=input.columns,
        outputCol="features")

    dataset = assembler.transform(input)
    dataset.show()

    pca = PCA(k=K, inputCol="features", outputCol="pcaFeatures")
    model = pca.fit(dataset)

    print("Principal Components: ", model.pc, sep='\n')
    print("Explained Variance: ", model.explainedVariance, sep='\n')

    spark.stop()
