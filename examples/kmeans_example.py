#!/usr/bin/env python
"""K-Means example — mirror of the reference's examples/kmeans
(KMeansExample.scala / kmeans-pyspark.py): load libsvm data, fit with the
accelerated estimator, print centers and cost.

Usage:
  python examples/kmeans_example.py [--data PATH] [--k 3] [--max-iter 20] \
      [--tol 1e-4] [--seed 0] [--init k-means||] [--timing]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu K-Means example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "sample_kmeans_data.txt"))
    p.add_argument("--k", type=int, default=3)
    p.add_argument("--max-iter", type=int, default=20)
    p.add_argument("--tol", type=float, default=1e-4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--init", default="k-means||", choices=["random", "k-means||"])
    p.add_argument("--device", default=None, help="tpu | cpu | auto")
    p.add_argument("--timing", action="store_true", help="per-phase wall times")
    args = p.parse_args()

    from oap_mllib_tpu import KMeans
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_libsvm

    if args.device:
        set_config(device=args.device)
    if args.timing:
        import logging

        logging.basicConfig(level=logging.INFO)
        set_config(timing=True)

    _, x = read_libsvm(args.data)
    print(f"Loaded {x.shape[0]} rows x {x.shape[1]} features from {args.data}")

    model = KMeans(
        k=args.k, max_iter=args.max_iter, tol=args.tol, seed=args.seed,
        init_mode=args.init,
    ).fit(x)

    s = model.summary
    print(f"Accelerated path: {s.accelerated}")
    print(f"Converged in {s.num_iter} iterations, total cost {s.training_cost:.6f}")
    print("Cluster centers:")
    for c in model.cluster_centers_:
        print("  [" + ", ".join(f"{v:.4f}" for v in c) + "]")
    pred = model.predict(x)
    print("Predictions:", pred.tolist())


if __name__ == "__main__":
    main()
