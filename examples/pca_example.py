#!/usr/bin/env python
"""PCA example — mirror of the reference's examples/pca
(PCAExample.scala / pca-pyspark.py): load dense CSV, fit, print principal
components and explained variance."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu PCA example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "pca_data.csv"))
    p.add_argument("--k", type=int, default=3)
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    p.add_argument("--model-parallel", type=int, default=None,
                   help="model-axis size: >1 shards the (d, d) Gram rows "
                        "over a (data, model) mesh (device count must be "
                        "divisible by it); an explicit 1 forces pure data "
                        "parallelism even if the env sets otherwise")
    args = p.parse_args()

    from oap_mllib_tpu import PCA
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_csv

    if args.device:
        set_config(device=args.device)
    if args.model_parallel is not None:
        set_config(model_parallel=args.model_parallel)
    if args.timing:
        import logging

        logging.basicConfig(level=logging.INFO)
        set_config(timing=True)

    x = read_csv(args.data)
    print(f"Loaded {x.shape[0]} rows x {x.shape[1]} features from {args.data}")

    model = PCA(k=args.k).fit(x)
    print(f"Accelerated path: {model.summary['accelerated']}")
    print("Principal components (columns):")
    for row in model.components_:
        print("  [" + ", ".join(f"{v: .4f}" for v in row) + "]")
    print("Explained variance ratios:",
          "[" + ", ".join(f"{v:.6f}" for v in model.explained_variance_) + "]")
    proj = model.transform(x[:3])
    print("First 3 projected rows:")
    for row in proj:
        print("  [" + ", ".join(f"{v: .4f}" for v in row) + "]")


if __name__ == "__main__":
    main()
