"""The reference's PySpark ALS example, verbatim-minus-import.

This is /root/reference/examples/als-pyspark/als-pyspark.py with exactly
ONE functional change: the estimator/evaluator imports come from
``oap_mllib_tpu.compat.pyspark`` instead of ``pyspark.ml.*`` (Python has
no classpath shadowing, so the import line IS the drop-in point — see
compat/pyspark.py module notes).  Everything else — the SparkSession,
the RDD parse of ``::``-separated ratings, the keyword-constructed ALS,
the transform + RegressionEvaluator flow — is the reference example's
own code and requires a pyspark installation; without one this script
reports the skip and exits 0 (so examples/run_all.sh stays green in
pyspark-less environments like this image).  The same adapter flow runs
against a mocked DataFrame in tests/test_pyspark_compat.py.
"""

from __future__ import print_function

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

try:
    from pyspark.sql import Row, SparkSession
except ImportError:
    print("pyspark is not installed — skipping the drop-in PySpark example "
          "(the adapter's contract is covered by tests/test_pyspark_compat.py)")
    sys.exit(0)

# THE drop-in change: these two lines read
#   from pyspark.ml.evaluation import RegressionEvaluator
#   from pyspark.ml.recommendation import ALS
# in the reference example (als-pyspark.py:27-28)
from oap_mllib_tpu.compat.pyspark import ALS, RegressionEvaluator  # noqa: E402

if __name__ == "__main__":
    spark = SparkSession.builder.appName("ALSExample").getOrCreate()

    path = (
        sys.argv[1]
        if len(sys.argv) == 2
        else os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "sample_als_ratings.txt")
    )

    lines = spark.read.text(path).rdd
    parts = lines.map(lambda row: row.value.split("::"))
    ratingsRDD = parts.map(lambda p: Row(userId=int(p[0]), movieId=int(p[1]),
                                         rating=float(p[2])))
    ratings = spark.createDataFrame(ratingsRDD)
    (training, test) = ratings.randomSplit([0.8, 0.2])

    # Build the recommendation model using ALS on the training data
    # Note we set cold start strategy to 'drop' to ensure we don't get
    # NaN evaluation metrics
    als = ALS(rank=10, maxIter=5, regParam=0.01, implicitPrefs=True, alpha=40.0,
              userCol="userId", itemCol="movieId", ratingCol="rating",
              coldStartStrategy="drop")
    print("\nALS training with implicitPrefs={}, rank={}, maxIter={}, "
          "regParam={}, alpha={}, seed={}\n".format(
              als.getImplicitPrefs(), als.getRank(), als.getMaxIter(),
              als.getRegParam(), als.getAlpha(), als.getSeed()))
    model = als.fit(training)

    # Evaluate the model by computing the RMSE on the test data
    predictions = model.transform(test)
    evaluator = RegressionEvaluator(metricName="rmse", labelCol="rating",
                                    predictionCol="prediction")
    rmse = evaluator.evaluate(predictions)
    print("Root-mean-square error = " + str(rmse))

    spark.stop()
