"""The reference's PySpark K-Means example, verbatim-minus-import.

This is /root/reference/examples/kmeans-pyspark/kmeans-pyspark.py (itself
Apache-2.0 Spark sample code) with exactly ONE functional change: the
estimator/evaluator imports come from ``oap_mllib_tpu.compat.pyspark``
instead of ``pyspark.ml.*`` (Python has no classpath shadowing, so the
import line IS the drop-in point — see compat/pyspark.py module notes).
Everything else — the SparkSession, the libsvm load, the builder-style
KMeans, the transform + ClusteringEvaluator flow — is the reference
example's own code and requires a pyspark installation; without one this
script reports the skip and exits 0 (so examples/run_all.sh stays green
in pyspark-less environments like this image).  The same adapter flow
runs against a mocked DataFrame in tests/test_pyspark_compat.py.
"""

from __future__ import print_function

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

try:
    from pyspark.sql import SparkSession
except ImportError:
    print("pyspark is not installed — skipping the drop-in PySpark example "
          "(the adapter's contract is covered by tests/test_pyspark_compat.py)")
    sys.exit(0)

# THE drop-in change: these two lines read
#   from pyspark.ml.clustering import KMeans
#   from pyspark.ml.evaluation import ClusteringEvaluator
# in the reference example (kmeans-pyspark.py:29-30)
from oap_mllib_tpu.compat.pyspark import ClusteringEvaluator, KMeans  # noqa: E402

if __name__ == "__main__":
    spark = SparkSession\
        .builder\
        .appName("KMeansExample")\
        .getOrCreate()

    # positional arg like the reference (kmeans-pyspark.py <libsvm path>);
    # run_all.sh's --device flags are for the non-pyspark examples and
    # fall through to the bundled default data here
    path = (
        sys.argv[1]
        if len(sys.argv) == 2 and not sys.argv[1].startswith("--")
        else os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "data", "sample_kmeans_data.txt")
    )

    # Loads data.
    dataset = spark.read.format("libsvm").load(path)

    # Trains a k-means model.
    kmeans = KMeans().setK(2).setSeed(1)
    model = kmeans.fit(dataset)

    # Make predictions
    predictions = model.transform(dataset)

    # Evaluate clustering by computing Silhouette score
    evaluator = ClusteringEvaluator()

    silhouette = evaluator.evaluate(predictions)
    print("Silhouette with squared euclidean distance = " + str(silhouette))

    # Shows the result.
    centers = model.clusterCenters()
    print("Cluster Centers: ")
    for center in centers:
        print(center)

    spark.stop()
