#!/usr/bin/env python
"""PCA via the Spark-ML compat surface — the reference's PySpark twin
(examples/pca-pyspark/pca-pyspark.py:30-46): read a headerless CSV,
assemble all columns into a features vector, fit PCA(k=K), print the
principal components and the explained-variance ratios.

Where the reference uses VectorAssembler on a SparkSession DataFrame,
the compat surface takes a dict of numpy columns.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    p = argparse.ArgumentParser(description="oap-mllib-tpu PCA compat example")
    p.add_argument("--data", default=os.path.join(HERE, "data", "pca_data.csv"))
    p.add_argument("--k", type=int, default=3)
    p.add_argument("--device", default=None)
    p.add_argument("--timing", action="store_true")
    args = p.parse_args()

    from oap_mllib_tpu.compat.spark import PCA
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.io import read_csv

    if args.device:
        set_config(device=args.device)
    if args.timing:
        set_config(timing=True)

    # spark.read.load(csv) + VectorAssembler(inputCols=..., outputCol="features")
    x = read_csv(args.data)
    dataset = {"features": x}
    print(f"dataset: {x.shape[0]} rows x {x.shape[1]} cols")

    # PCA(k=K, inputCol="features", outputCol="pcaFeatures")
    pca = PCA().setK(args.k).setInputCol("features").setOutputCol("pcaFeatures")
    model = pca.fit(dataset)

    print("Principal Components: ", model.pc, sep="\n")
    print("Explained Variance: ", model.explainedVariance, sep="\n")

    projected = model.transform(dataset)
    print("pcaFeatures (first 3 rows): ", projected["pcaFeatures"][:3], sep="\n")


if __name__ == "__main__":
    main()
