"""Pipeline / model-selection composability (Spark ml.Pipeline, ml.tuning).

The reference inherits Spark's composability for free (its shims slot
into `pyspark.ml.Pipeline` / `CrossValidator` because they shadow the
same classes).  This module provides the analog for this framework's
compat estimators: `Pipeline` chains any stages exposing the
fit/transform contract, and `CrossValidator` + `ParamGridBuilder` do
k-fold model selection driven by the compat evaluators — closing the
"no Pipeline/CrossValidator composability even in the dict world" gap
(round-3 review).

Works over BOTH data planes, because it only touches the stage
contract:
- dict "DataFrames" (`compat.spark` estimators) — k-fold row slicing is
  column slicing;
- real Spark DataFrames (`compat.pyspark` estimators): `Pipeline` /
  `PipelineModel` never look inside the data, and the tuners
  (`CrossValidator`, `TrainValidationSplit`) do the documented ONE
  collect themselves (the adapters' driver-collect scope) — split
  fit/evaluate runs on the collected dict plane (the pyspark adapters
  delegate dict inputs to their dict-plane base), and the winning
  params are refit on the ORIGINAL DataFrame so the returned
  ``bestModel`` transforms DataFrames.

Persistence: `Pipeline`/`PipelineModel`/`CrossValidatorModel`/
`TrainValidationSplitModel` all save/load (Spark's MLWritable surface,
which the reference inherits — e.g. IntelPCASuite.scala:90-104 tests
model read/write): a JSON manifest records each stage's class, and
fitted stages delegate to the stage model's own save/load (so e.g. a
loaded ALS stage keeps its coldStartStrategy and seen-id sets).

Param grids: Spark's `ParamGridBuilder.addGrid` takes `Param` objects
(`als.regParam`); these builders carry no Param descriptors, so
`addGrid` takes the SETTER NAME string instead ("regParam" →
`setRegParam(v)` on a copy of the estimator).  Same shape, one explicit
deviation, validated eagerly (an unknown name raises at addGrid, not
mid-CV).
"""

from __future__ import annotations

import copy
import importlib
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Persistence plumbing (Spark MLWritable analog): a JSON manifest per
# container records each stage's class; fitted stages delegate to the
# stage model's own save/load, unfitted estimators snapshot their param
# attributes (all simple scalars on the builder classes).
# ---------------------------------------------------------------------------


def _class_ref(obj) -> dict:
    return {"module": type(obj).__module__, "cls": type(obj).__qualname__}


def _resolve_class(ref: dict):
    module = ref["module"]
    # manifests name classes to import — constrain to this package so a
    # tampered manifest cannot import-and-instantiate arbitrary code
    if module != "oap_mllib_tpu" and not module.startswith("oap_mllib_tpu."):
        raise ValueError(f"refusing to load stage class from {module!r}")
    return getattr(importlib.import_module(module), ref["cls"])


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise TypeError(
        f"cannot persist non-scalar param value {v!r} ({type(v).__name__})"
    )


def _save_estimator(est, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    params = {k: _jsonable(v) for k, v in est.__dict__.items()}
    with open(os.path.join(path, "estimator.json"), "w") as f:
        json.dump({"ref": _class_ref(est), "params": params}, f)


def _load_estimator(path: str):
    with open(os.path.join(path, "estimator.json")) as f:
        blob = json.load(f)
    est = _resolve_class(blob["ref"])()
    est.__dict__.update(blob["params"])
    return est


def _save_stage(stage, path: str) -> None:
    """Estimators snapshot params; anything else must bring its own
    save (every model class in this package does)."""
    if hasattr(stage, "fit"):
        _save_estimator(stage, path)
    elif hasattr(stage, "save"):
        os.makedirs(path, exist_ok=True)
        stage.save(path)
    else:
        raise TypeError(
            f"stage {type(stage).__name__} has neither params to "
            "snapshot (fit) nor a save method"
        )


def _load_stage(ref: dict, path: str):
    if os.path.exists(os.path.join(path, "estimator.json")):
        return _load_estimator(path)
    cls = _resolve_class(ref)
    if not hasattr(cls, "load"):
        raise TypeError(f"stage class {cls.__name__} has no load method")
    return cls.load(path)


def _write_manifest(path: str, blob: dict) -> None:
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "pipeline_metadata.json"), "w") as f:
        json.dump(blob, f)


def _read_manifest(path: str, expect: str) -> dict:
    with open(os.path.join(path, "pipeline_metadata.json")) as f:
        blob = json.load(f)
    if blob.get("type") != expect:
        raise ValueError(
            f"not a {expect} directory: {path} (found {blob.get('type')!r})"
        )
    return blob


class Pipeline:
    """Chain of stages; each is fit on the running DataFrame and its
    transform feeds the next stage (ml.Pipeline semantics: estimators
    become models, transformers pass through)."""

    def __init__(self, *, stages: Optional[Sequence] = None):
        self._stages = list(stages or [])

    def setStages(self, stages):
        self._stages = list(stages)
        return self

    def getStages(self):
        return list(self._stages)

    def fit(self, dataset) -> "PipelineModel":
        fitted = []
        df = dataset
        # transform only feeds DOWNSTREAM fits: stages past the last
        # estimator never need the training frame scored (Spark's
        # indexOfLastEstimator rule — a trailing pre-fitted transformer
        # must not cost a full wasted pass over the training data)
        last_fit = max(
            (i for i, s in enumerate(self._stages) if hasattr(s, "fit")),
            default=-1,
        )
        for i, stage in enumerate(self._stages):
            if hasattr(stage, "fit"):
                model = stage.fit(df)
            elif hasattr(stage, "transform"):
                model = stage  # already a transformer
            else:
                raise TypeError(
                    f"pipeline stage {i} ({type(stage).__name__}) has "
                    "neither fit nor transform"
                )
            if i < last_fit:
                df = model.transform(df)
            fitted.append(model)
        return PipelineModel(fitted)

    def save(self, path: str) -> None:
        """Persist the (unfitted) stage list — param snapshots for
        estimators, model save for pre-fitted transformer stages."""
        stages = []
        for i, stage in enumerate(self._stages):
            d = f"stage_{i:02d}_{type(stage).__name__}"
            _save_stage(stage, os.path.join(path, d))
            stages.append({"dir": d, **_class_ref(stage)})
        _write_manifest(path, {"type": "Pipeline", "version": 1,
                               "stages": stages})

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        blob = _read_manifest(path, "Pipeline")
        return cls(stages=[
            _load_stage(s, os.path.join(path, s["dir"]))
            for s in blob["stages"]
        ])


class PipelineModel:
    def __init__(self, stages: List):
        self.stages = list(stages)

    def transform(self, dataset):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df

    def save(self, path: str) -> None:
        """Persist every fitted stage via its own save (column names,
        coldStartStrategy, seen-id sets all ride the stage models'
        metadata)."""
        stages = []
        for i, stage in enumerate(self.stages):
            d = f"stage_{i:02d}_{type(stage).__name__}"
            _save_stage(stage, os.path.join(path, d))
            stages.append({"dir": d, **_class_ref(stage)})
        _write_manifest(path, {"type": "PipelineModel", "version": 1,
                               "stages": stages})

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        blob = _read_manifest(path, "PipelineModel")
        return cls([
            _load_stage(s, os.path.join(path, s["dir"]))
            for s in blob["stages"]
        ])


class ParamGridBuilder:
    """Cartesian grid over setter-name -> values (see module notes for
    the string-name deviation from Spark's Param objects)."""

    def __init__(self):
        self._grid: Dict[str, list] = {}

    def addGrid(self, param: str, values) -> "ParamGridBuilder":
        if not str(param):
            raise ValueError("param name must be non-empty")
        self._grid[str(param)] = list(values)
        return self

    def baseOn(self, params: Dict[str, object]) -> "ParamGridBuilder":
        """Fixed params applied to every map (Spark's baseOn)."""
        for k, v in params.items():
            self._grid[str(k)] = [v]
        return self

    def build(self) -> List[Dict[str, object]]:
        maps = [{}]
        for name, values in self._grid.items():
            maps = [{**m, name: v} for m in maps for v in values]
        return maps


def _setter(est, name: str):
    setter = getattr(est, "set" + name[0].upper() + name[1:], None)
    if setter is None:
        raise ValueError(
            f"{type(est).__name__} has no setter for param {name!r}"
        )
    return setter


def _apply_params(estimator, param_map: Dict[str, object]):
    est = copy.deepcopy(estimator)
    for name, value in param_map.items():
        _setter(est, name)(value)
    return est


def _as_dict(dataset) -> dict:
    """Dict-plane copy of a DataFrame via the adapters' documented ONE
    collect (see compat/pyspark._collect_once: every column must come
    from the same materializing action).  Cell conversion (vectors,
    lists, scalars) is compat/pyspark._column_to_array — one set of
    duck-type rules for every ingestion path.  Dicts return
    unchanged."""
    if isinstance(dataset, dict):
        return dataset
    if not (hasattr(dataset, "collect") and hasattr(dataset, "columns")):
        raise TypeError(
            "dataset must be a dict DataFrame or a Spark DataFrame "
            f"(got {type(dataset).__name__})"
        )
    from oap_mllib_tpu.compat.pyspark import _column_to_array

    rows, cols = dataset.collect(), list(dataset.columns)
    return {
        c: _column_to_array([r[j] for r in rows])
        for j, c in enumerate(cols)
    }


def _tuner_prepare(estimator, evaluator, maps, dataset, kind: str):
    """Shared guard rails for both tuners: presence checks, the
    empty-grid error, EAGER setter validation (an unknown param must
    fail before any split is fit — and before the dataset is even
    collected), then the one-collect to the dict plane for Spark
    DataFrames.  Returns (param-map list, dict data for the split
    loop)."""
    if estimator is None or evaluator is None:
        raise ValueError("estimator and evaluator must be set")
    maps = [{}] if maps is None else list(maps)
    if not maps:
        # an EXPLICIT empty grid (e.g. addGrid with an empty values
        # list collapses the Cartesian product to zero maps) must not
        # silently become a defaults-only run
        raise ValueError(
            "estimatorParamMaps is empty — the param grid collapsed "
            "to zero maps (addGrid with an empty values list?)"
        )
    for m in maps:
        for name in m:
            _setter(estimator, name)
    import jax

    if jax.process_count() > 1:
        # splitting/refitting on collected copies would feed every rank
        # the FULL data as its "local shard" (world-duplicated rows);
        # tuning is a driver-side, single-process flow
        raise NotImplementedError(
            f"{kind} runs single-process; in a multi-process world run "
            "the tuner on one process (or fit the chosen params "
            "directly with the multi-host estimators)"
        )
    return maps, _as_dict(dataset)


def _select_and_refit(estimator, evaluator, maps, metrics, dataset,
                      label: str):
    """Shared selection tail: NaN guard (np.argmin/argmax return a
    NaN's index, so a single NaN split — e.g. coldStartStrategy="nan"
    leaking NaN predictions into RMSE — would silently win), argbest by
    the evaluator's direction, refit the winner on the full data.
    Returns (best_model, best_index)."""
    if any(np.isnan(a) for a in metrics):
        bad = [m for m, a in zip(maps, metrics) if np.isnan(a)]
        raise ValueError(
            f"{label} metric is NaN for param map(s) {bad} — with ALS "
            'use coldStartStrategy="drop" and ensure every split keeps '
            "evaluable rows"
        )
    larger = bool(evaluator.isLargerBetter())
    best = int(np.argmax(metrics) if larger else np.argmin(metrics))
    return _apply_params(estimator, maps[best]).fit(dataset), best


def _n_rows(df: dict) -> int:
    arrays = list(df.values())
    if not arrays:
        raise ValueError("empty DataFrame")
    n = len(np.asarray(arrays[0]))
    for a in arrays[1:]:
        if len(np.asarray(a)) != n:
            raise ValueError("ragged DataFrame columns")
    return n


def _take(df: dict, idx: np.ndarray) -> dict:
    return {k: np.asarray(v)[idx] for k, v in df.items()}


class CrossValidator:
    """k-fold model selection (ml.tuning.CrossValidator): for every
    param map, average the evaluator metric over numFolds held-out
    folds, pick the best by the evaluator's isLargerBetter, refit on
    the full data.  Dict-plane DataFrames only (see module notes)."""

    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds: int = 3, seed: int = 0):
        self._estimator = estimator
        self._maps = estimatorParamMaps
        self._evaluator = evaluator
        self._numFolds = numFolds
        self._seed = seed

    def setEstimator(self, v):          self._estimator = v; return self
    def setEstimatorParamMaps(self, v): self._maps = v; return self
    def setEvaluator(self, v):          self._evaluator = v; return self
    def setNumFolds(self, v):           self._numFolds = v; return self
    def setSeed(self, v):               self._seed = v; return self

    def getEstimator(self):          return self._estimator
    def getEstimatorParamMaps(self): return self._maps
    def getEvaluator(self):          return self._evaluator
    def getNumFolds(self):           return self._numFolds

    def fit(self, dataset) -> "CrossValidatorModel":
        if self._numFolds < 2:  # before _tuner_prepare's collect
            raise ValueError("numFolds must be >= 2")
        maps, data = _tuner_prepare(
            self._estimator, self._evaluator, self._maps, dataset,
            "CrossValidator",
        )
        n = _n_rows(data)
        if n < self._numFolds:
            raise ValueError(
                f"{n} rows cannot split into {self._numFolds} folds"
            )
        perm = np.random.default_rng(self._seed).permutation(n)
        folds = np.array_split(perm, self._numFolds)

        avg = []
        for m in maps:
            scores = []
            for f in range(self._numFolds):
                test_idx = folds[f]
                train_idx = np.concatenate(
                    [folds[g] for g in range(self._numFolds) if g != f]
                )
                est = _apply_params(self._estimator, m)
                model = est.fit(_take(data, train_idx))
                pred = model.transform(_take(data, test_idx))
                scores.append(float(self._evaluator.evaluate(pred)))
            avg.append(float(np.mean(scores)))

        # refit on the ORIGINAL dataset: a Spark-plane tuner must hand
        # back a bestModel that transforms DataFrames
        best_model, best = _select_and_refit(
            self._estimator, self._evaluator, maps, avg, dataset, "CV"
        )
        return CrossValidatorModel(best_model, avg, maps[best])


class CrossValidatorModel:
    def __init__(self, bestModel, avgMetrics: List[float],
                 bestParams: Dict[str, object]):
        self.bestModel = bestModel
        self.avgMetrics = list(avgMetrics)
        self.bestParams = dict(bestParams)

    def transform(self, dataset):
        return self.bestModel.transform(dataset)

    def save(self, path: str) -> None:
        _save_stage(self.bestModel, os.path.join(path, "bestModel"))
        _write_manifest(path, {
            "type": "CrossValidatorModel", "version": 1,
            "bestModel": {"dir": "bestModel", **_class_ref(self.bestModel)},
            "avgMetrics": [float(a) for a in self.avgMetrics],
            "bestParams": {k: _jsonable(v)
                           for k, v in self.bestParams.items()},
        })

    @classmethod
    def load(cls, path: str) -> "CrossValidatorModel":
        blob = _read_manifest(path, "CrossValidatorModel")
        best = _load_stage(blob["bestModel"],
                           os.path.join(path, blob["bestModel"]["dir"]))
        return cls(best, blob["avgMetrics"], blob["bestParams"])


class TrainValidationSplit:
    """Single-split model selection (ml.tuning.TrainValidationSplit):
    CrossValidator's cheaper sibling — one random train/validation
    split per param map instead of k folds.  Same dict-plane scope,
    setter-name grids, and NaN/empty-grid guard rails."""

    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, trainRatio: float = 0.75, seed: int = 0):
        self._estimator = estimator
        self._maps = estimatorParamMaps
        self._evaluator = evaluator
        self._trainRatio = trainRatio
        self._seed = seed

    def setEstimator(self, v):          self._estimator = v; return self
    def setEstimatorParamMaps(self, v): self._maps = v; return self
    def setEvaluator(self, v):          self._evaluator = v; return self
    def setTrainRatio(self, v):         self._trainRatio = v; return self
    def setSeed(self, v):               self._seed = v; return self

    def getEstimator(self):          return self._estimator
    def getEstimatorParamMaps(self): return self._maps
    def getEvaluator(self):          return self._evaluator
    def getTrainRatio(self):         return self._trainRatio

    def fit(self, dataset) -> "TrainValidationSplitModel":
        if not 0.0 < self._trainRatio < 1.0:  # before the collect
            raise ValueError("trainRatio must be in (0, 1)")
        maps, data = _tuner_prepare(
            self._estimator, self._evaluator, self._maps, dataset,
            "TrainValidationSplit",
        )
        n = _n_rows(data)
        n_train = int(n * self._trainRatio)
        if n_train < 1 or n_train >= n:
            raise ValueError(
                f"trainRatio={self._trainRatio} leaves an empty split "
                f"({n} rows)"
            )
        perm = np.random.default_rng(self._seed).permutation(n)
        train = _take(data, perm[:n_train])
        val = _take(data, perm[n_train:])

        metrics = []
        for m in maps:
            model = _apply_params(self._estimator, m).fit(train)
            metrics.append(
                float(self._evaluator.evaluate(model.transform(val)))
            )
        best_model, best = _select_and_refit(
            self._estimator, self._evaluator, maps, metrics, dataset,
            "validation",
        )
        return TrainValidationSplitModel(best_model, metrics, maps[best])


class TrainValidationSplitModel:
    def __init__(self, bestModel, validationMetrics: List[float],
                 bestParams: Dict[str, object]):
        self.bestModel = bestModel
        self.validationMetrics = list(validationMetrics)
        self.bestParams = dict(bestParams)

    def transform(self, dataset):
        return self.bestModel.transform(dataset)

    def save(self, path: str) -> None:
        _save_stage(self.bestModel, os.path.join(path, "bestModel"))
        _write_manifest(path, {
            "type": "TrainValidationSplitModel", "version": 1,
            "bestModel": {"dir": "bestModel", **_class_ref(self.bestModel)},
            "validationMetrics": [float(a) for a in self.validationMetrics],
            "bestParams": {k: _jsonable(v)
                           for k, v in self.bestParams.items()},
        })

    @classmethod
    def load(cls, path: str) -> "TrainValidationSplitModel":
        blob = _read_manifest(path, "TrainValidationSplitModel")
        best = _load_stage(blob["bestModel"],
                           os.path.join(path, blob["bestModel"]["dir"]))
        return cls(best, blob["validationMetrics"], blob["bestParams"])
