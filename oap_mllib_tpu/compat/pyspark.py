"""pyspark.ml-shaped estimators over REAL Spark DataFrames.

The reference's core promise is *user code unmodified*: its Scala shims
classpath-shadow ``org.apache.spark.ml`` so the stock PySpark examples
run verbatim (reference examples/als-pyspark/als-pyspark.py:52-54,
kmeans-pyspark.py, pca-pyspark.py).  Python has no classpath shadowing,
so the drop-in point is the import line — change only

    from pyspark.ml.recommendation import ALS
    from pyspark.ml.clustering import KMeans
    from pyspark.ml.feature import PCA
    from pyspark.ml.evaluation import RegressionEvaluator, ClusteringEvaluator

to

    from oap_mllib_tpu.compat.pyspark import (
        ALS, KMeans, PCA, RegressionEvaluator, ClusteringEvaluator)

and the rest of the example runs unchanged: keyword constructors,
builder setters, ``fit(dataframe)``, ``model.transform(dataframe)``
returning a DataFrame with the prediction column appended, evaluators
that consume that DataFrame.

Scope (documented, deliberate): in a single-process world the data
plane is DRIVER-COLLECT — the needed columns are collected to host
NumPy and the TPU framework takes over from there (mesh sharding
happens inside the estimators).  That matches this framework's design
point (the device mesh replaces the executor fleet; survey §2.5):
Spark is the front-end API, not the compute fabric.

In a MULTI-PROCESS world (``jax.process_count() > 1``) fits ingest
partition-wise instead (the executor-local conversion of the
reference, OneDAL.scala:92-166): each process materializes ONLY the
partitions assigned to it (``partition % world == rank``) via
``dataset.rdd.mapPartitionsWithIndex`` and feeds those rows as its
process-local shard of the multi-host fit — no process ever collects
the whole dataset (see ``_collect_local_partitions``).  Transform and
the evaluators remain driver-collect scoring paths.

Availability: importing this module does NOT require pyspark — every
DataFrame interaction goes through the duck-typed surface
(``df.select(...).collect()``, ``df.columns``, ``df.sparkSession
.createDataFrame(rows, schema)``), which is exactly what the contract
tests mock (tests/test_pyspark_compat.py).  With pyspark installed, a
real DataFrame satisfies the same surface; ``HAVE_PYSPARK`` reports
which world you are in (output vectors use pyspark.ml.linalg when
available, plain lists otherwise).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from oap_mllib_tpu.compat import spark as _compat

try:  # optional: only used to emit real ml.linalg vectors from transform
    from pyspark.ml.linalg import Vectors as _Vectors

    HAVE_PYSPARK = True
except ImportError:  # pragma: no cover - exercised when pyspark is absent
    _Vectors = None
    HAVE_PYSPARK = False


# ---------------------------------------------------------------------------
# DataFrame duck-typed surface
# ---------------------------------------------------------------------------


def _session_of(df):
    """The DataFrame's session (``sparkSession`` on 3.3+, ``sql_ctx
    .sparkSession`` on older lines)."""
    spark = getattr(df, "sparkSession", None)
    if spark is None:
        spark = df.sql_ctx.sparkSession
    return spark


def _collect_once(df):
    """ONE materializing action per adapter call: Spark does not
    guarantee identical row order across separate actions on an
    uncached DataFrame (randomSplit output recomputed after an executor
    loss, upstream shuffles/samples), so every extracted column AND the
    egress rows must come from the same collect() — zip-by-position
    across two actions would silently pair predictions with the wrong
    rows.  Returns (rows, column-name list)."""
    return df.collect(), list(df.columns)


def _collect_local_partitions(df, rank: Optional[int] = None,
                              world: Optional[int] = None):
    """Partition-wise ingestion for multi-process worlds: process r
    materializes ONLY partitions p with ``p % world == r`` (the
    reference's executor-local conversion, OneDAL.scala:92-166 — every
    executor converts its own partitions, never the dataset).  The
    kept rows become this process's LOCAL shard, which the estimators'
    multi-host fit contract already accepts (models treat array inputs
    as process-local when ``jax.process_count() > 1``).  Returns
    (rows, cols) like _collect_once."""
    import jax

    rank = jax.process_index() if rank is None else rank
    world = jax.process_count() if world is None else world
    rdd = getattr(df, "rdd", None)
    if rdd is None:
        raise TypeError(
            "multi-process ingestion needs dataset.rdd"
            ".mapPartitionsWithIndex (a Spark DataFrame or equivalent); "
            "a plain collect would hand every process the FULL dataset "
            "as its shard"
        )
    keep = rdd.mapPartitionsWithIndex(
        lambda pid, it, _r=rank, _w=world: it if pid % _w == _r else iter(())
    )
    rows = keep.collect()
    # a rank with zero partitions (fewer partitions than world, e.g.
    # coalesce(1)) must fail on EVERY rank together — a one-rank raise
    # would leave the others hanging in the fit's first collective
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(rows)], np.int64)
        )).reshape(-1)
        if (counts == 0).any():
            empty = [int(r) for r in np.nonzero(counts == 0)[0]]
            raise ValueError(
                f"process(es) {empty} received zero partitions "
                f"(world={world}); repartition the DataFrame to at "
                "least the process count"
            )
        # the pid % world routing assumes every process computed the SAME
        # partitioning of the same data; a nondeterministically
        # partitioned/ordered source (an uncached randomSplit recomputed
        # after an executor loss, an upstream sample) can silently drop
        # or duplicate rows globally.  One cheap extra action pins it:
        # the per-rank kept-row counts must sum to the DataFrame's count.
        count_fn = getattr(df, "count", None)
        total = int(counts.sum())
        expected = int(count_fn()) if count_fn is not None else total
        if total != expected:
            raise ValueError(
                f"partition-wise ingestion kept {total} rows across "
                f"{world} process(es) but df.count() is {expected} — the "
                "DataFrame's partitioning is not deterministic across "
                "processes (e.g. an uncached randomSplit/sample); "
                ".cache() or materialize it before the fit"
            )
    elif not rows:
        raise ValueError(
            f"process {rank} received zero partitions (world={world}); "
            "repartition the DataFrame to at least the process count"
        )
    return rows, list(df.columns)


def _ingest(df):
    """The fit-side ingestion dispatch: one driver collect in a
    single-process world, partition-wise local shards otherwise."""
    import jax

    if jax.process_count() > 1:
        return _collect_local_partitions(df)
    return _collect_once(df)


def _col_from(rows, cols, name: str, dtype=None) -> np.ndarray:
    j = cols.index(name)
    return np.asarray([r[j] for r in rows], dtype=dtype)


def _column_to_array(vals) -> np.ndarray:
    """One collected column's cells -> ndarray: vector cells (toArray
    duck-type) and list/tuple cells become (n, d) float64 matrices,
    scalars pass through.  THE converter for whole-frame ingestion
    (compat.pipeline._as_dict) — keep the duck-type rules here, next to
    _mat_from/_col_from, so the planes cannot drift."""
    if vals and hasattr(vals[0], "toArray"):
        return np.asarray(
            [np.asarray(v.toArray(), np.float64) for v in vals]
        )
    if vals and isinstance(vals[0], (list, tuple)):
        return np.asarray([np.asarray(v, np.float64) for v in vals])
    return np.asarray(vals)


def _mat_from(rows, cols, name: str) -> np.ndarray:
    """(n, d) float matrix from a vector column of materialized rows
    (pyspark.ml.linalg Vector — sparse or dense — via toArray();
    lists/arrays pass through)."""
    j = cols.index(name)
    return np.asarray(
        [
            np.asarray(
                r[j].toArray() if hasattr(r[j], "toArray") else r[j],
                np.float64,
            )
            for r in rows
        ]
    )


def _vectorize(mat: np.ndarray):
    """Rows of a matrix as output-column values: ml.linalg DenseVectors
    with pyspark installed, plain float lists otherwise."""
    if _Vectors is not None:
        return [_Vectors.dense([float(v) for v in row]) for row in mat]
    return [[float(v) for v in row] for row in mat]


def _out_pos(df, name: str) -> int:
    """Where the output column lands: pyspark.ml's transform is
    withColumn, which REPLACES a same-name column IN PLACE (appending
    blindly would produce duplicate names on a re-scored DataFrame,
    and moving the column to the end would break positional access and
    union-by-position vs real pyspark output).  New names append."""
    cols = list(df.columns)
    return cols.index(name) if name in cols else len(cols)


def _out_schema(df, name: str, kind: str):
    """Output schema = df.schema with one explicitly-typed column
    (kind: "int" | "double" | "vector") placed at _out_pos.  The
    explicit schema matters on real Spark: name-only inference raises
    on an EMPTY result (every row cold-dropped, an empty randomSplit
    slice) where pyspark.ml's own transform returns an empty typed
    DataFrame, and on all-null columns.  Mocks without .schema/pyspark
    fall back to the name list (inference never runs on them)."""
    j = _out_pos(df, name)

    def _drop_first(seq, match):
        # drop only the FIRST matching column: withColumn replaces one
        # slot in place, and a duplicate-name frame (Spark permits them
        # after joins) must keep row and schema lengths consistent —
        # dropping every match would shrink the schema below the rows
        out, dropped = [], False
        for f in seq:
            if not dropped and match(f):
                dropped = True
            else:
                out.append(f)
        return out

    base = getattr(df, "schema", None)
    if base is None or not HAVE_PYSPARK:
        cols = _drop_first(list(df.columns), lambda c: c == name)
        return cols[:j] + [name] + cols[j:]
    from pyspark.sql.types import (
        DoubleType,
        IntegerType,
        StructField,
        StructType,
    )

    if kind == "vector":
        from pyspark.ml.linalg import VectorUDT

        t = VectorUDT()
    elif kind == "int":
        t = IntegerType()
    else:
        t = DoubleType()
    fields = _drop_first(list(base.fields), lambda f: f.name == name)
    return StructType(fields[:j] + [StructField(name, t, True)] + fields[j:])


def _replace_cell(row, j: int, v):
    """Row tuple with the cell at ``j`` swapped for the output value —
    the withColumn in-place replace (see _out_pos)."""
    t = tuple(row)
    return t[:j] + (v,) + t[j + 1 :]


def _append_column(df, rows, name: str, values, kind: str) -> object:
    """New DataFrame = the ALREADY-MATERIALIZED rows + the output
    column (driver-side; the egress mirror of the driver-collect
    ingestion — same collect as the ingestion, see _collect_once).
    An existing same-name column is replaced in place (withColumn
    semantics, see _out_pos)."""
    schema = _out_schema(df, name, kind)
    if name in list(df.columns):
        j = _out_pos(df, name)
        data = [_replace_cell(r, j, v) for r, v in zip(rows, values)]
    else:
        data = [tuple(r) + (v,) for r, v in zip(rows, values)]
    return _session_of(df).createDataFrame(data, schema)


def _rebuild_rows(df, rows, keep_idx, name: str, values, kind: str) -> object:
    """Like _append_column but keeping only ``keep_idx`` of the
    materialized rows — the coldStartStrategy="drop" egress."""
    schema = _out_schema(df, name, kind)
    if name in list(df.columns):
        j = _out_pos(df, name)
        data = [
            _replace_cell(rows[int(i)], j, v)
            for i, v in zip(keep_idx, values)
        ]
    else:
        data = [
            tuple(rows[int(i)]) + (v,) for i, v in zip(keep_idx, values)
        ]
    return _session_of(df).createDataFrame(data, schema)


# ---------------------------------------------------------------------------
# K-Means
# ---------------------------------------------------------------------------


class KMeans(_compat.KMeans):
    """ml.clustering.KMeans over Spark DataFrames (keyword constructor +
    builder setters, Spark defaults)."""

    def __init__(self, *, featuresCol: str = "features",
                 predictionCol: str = "prediction", k: int = 2,
                 initMode: str = "k-means||", initSteps: int = 2,
                 tol: float = 1e-4, maxIter: int = 20,
                 seed: Optional[int] = None,
                 distanceMeasure: str = "euclidean",
                 weightCol: Optional[str] = None):
        super().__init__()
        self.setFeaturesCol(featuresCol).setPredictionCol(predictionCol)
        self.setK(k).setInitMode(initMode).setInitSteps(initSteps)
        self.setTol(tol).setMaxIter(maxIter)
        if seed is not None:  # unset flows to Config.seed (compat contract)
            self.setSeed(seed)
        self.setDistanceMeasure(distanceMeasure)
        if weightCol is not None:
            self.setWeightCol(weightCol)

    def fit(self, dataset):
        if isinstance(dataset, dict):
            # tuner split plane: compat.pipeline collects a Spark frame
            # once and fits the splits as dicts (dict-plane model out)
            return super().fit(dataset)
        want = [self._featuresCol] + (
            [self._weightCol] if self._weightCol is not None else []
        )
        rows, cols = _ingest(dataset.select(*want))
        data = {self._featuresCol: _mat_from(rows, cols, self._featuresCol)}
        if self._weightCol is not None:
            data[self._weightCol] = _col_from(
                rows, cols, self._weightCol, np.float64
            )
        inner = super().fit(data)
        return KMeansModel(inner)


class KMeansModel:
    def __init__(self, inner: _compat.KMeansModel):
        self._inner = inner

    def setFeaturesCol(self, v):
        self._inner.setFeaturesCol(v)
        return self

    def setPredictionCol(self, v):
        self._inner.setPredictionCol(v)
        return self

    def getFeaturesCol(self):    return self._inner.getFeaturesCol()
    def getPredictionCol(self):  return self._inner.getPredictionCol()

    def clusterCenters(self):
        return self._inner.clusterCenters()

    @property
    def summary(self):
        return self._inner.summary

    def predict(self, features):
        return self._inner.predict(
            features.toArray() if hasattr(features, "toArray") else features
        )

    def transform(self, dataset):
        if isinstance(dataset, dict):  # dict-plane passthrough (tuners)
            return self._inner.transform(dataset)
        rows, cols = _collect_once(dataset)
        if not rows:  # empty split: empty typed output, like pyspark.ml
            return _append_column(
                dataset, rows, self._inner._predictionCol, [], "int"
            )
        x = _mat_from(rows, cols, self._inner._featuresCol)
        out = self._inner.transform({self._inner._featuresCol: x})
        pred = [int(p) for p in out[self._inner._predictionCol]]
        return _append_column(
            dataset, rows, self._inner._predictionCol, pred, "int"
        )

    def save(self, path: str) -> None:
        self._inner.save(path)

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        return cls(_compat.KMeansModel.load(path))


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------


class PCA(_compat.PCA):
    """ml.feature.PCA over Spark DataFrames."""

    def __init__(self, *, k: Optional[int] = None,
                 inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None):
        super().__init__()
        if k is not None:
            self.setK(k)
        if inputCol is not None:
            self.setInputCol(inputCol)
        if outputCol is not None:
            self.setOutputCol(outputCol)

    def fit(self, dataset):
        if isinstance(dataset, dict):  # tuner split plane (see KMeans.fit)
            return super().fit(dataset)
        rows, cols = _ingest(dataset.select(self._inputCol))
        inner = super().fit(
            {self._inputCol: _mat_from(rows, cols, self._inputCol)}
        )
        return PCAModel(inner)


class PCAModel:
    def __init__(self, inner: _compat.PCAModel):
        self._inner = inner

    def setInputCol(self, v):
        self._inner.setInputCol(v)
        return self

    def setOutputCol(self, v):
        self._inner.setOutputCol(v)
        return self

    def getInputCol(self):   return self._inner.getInputCol()
    def getOutputCol(self):  return self._inner.getOutputCol()

    @property
    def pc(self) -> np.ndarray:
        return self._inner.pc

    @property
    def explainedVariance(self) -> np.ndarray:
        return self._inner.explainedVariance

    def transform(self, dataset):
        if isinstance(dataset, dict):  # dict-plane passthrough (tuners)
            return self._inner.transform(dataset)
        rows, cols = _collect_once(dataset)
        if not rows:  # empty split: empty typed output, like pyspark.ml
            return _append_column(
                dataset, rows, self._inner._outputCol, [], "vector"
            )
        x = _mat_from(rows, cols, self._inner._inputCol)
        out = self._inner.transform({self._inner._inputCol: x})
        return _append_column(
            dataset, rows, self._inner._outputCol,
            _vectorize(out[self._inner._outputCol]), "vector",
        )

    def save(self, path: str) -> None:
        self._inner.save(path)

    @classmethod
    def load(cls, path: str) -> "PCAModel":
        return cls(_compat.PCAModel.load(path))


# ---------------------------------------------------------------------------
# ALS
# ---------------------------------------------------------------------------


class ALS(_compat.ALS):
    """ml.recommendation.ALS over Spark DataFrames (full keyword
    constructor of the reference's example usage: als-pyspark.py:52-54)."""

    def __init__(self, *, rank: int = 10, maxIter: int = 10,
                 regParam: float = 0.1, numUserBlocks: Optional[int] = None,
                 numItemBlocks: Optional[int] = None,
                 implicitPrefs: bool = False, alpha: float = 1.0,
                 userCol: str = "user", itemCol: str = "item",
                 ratingCol: str = "rating", seed: Optional[int] = None,
                 nonnegative: bool = False,
                 checkpointInterval: int = 10,
                 coldStartStrategy: str = "nan",
                 predictionCol: str = "prediction"):
        super().__init__()
        self.setRank(rank).setMaxIter(maxIter).setRegParam(regParam)
        self.setImplicitPrefs(implicitPrefs).setAlpha(alpha)
        self.setUserCol(userCol).setItemCol(itemCol).setRatingCol(ratingCol)
        if seed is not None:  # unset flows to Config.seed (compat contract)
            self.setSeed(seed)
        self.setNonnegative(nonnegative)
        self.setCheckpointInterval(checkpointInterval)
        self.setColdStartStrategy(coldStartStrategy)
        self.setPredictionCol(predictionCol)
        if numUserBlocks is not None:
            self.setNumUserBlocks(numUserBlocks)
        if numItemBlocks is not None:
            self.setNumItemBlocks(numItemBlocks)

    def fit(self, dataset):
        if isinstance(dataset, dict):  # tuner split plane (see KMeans.fit)
            return super().fit(dataset)
        rows, cols = _ingest(
            dataset.select(self._userCol, self._itemCol, self._ratingCol)
        )
        inner = super().fit(
            {
                self._userCol: _col_from(rows, cols, self._userCol, np.int64),
                self._itemCol: _col_from(rows, cols, self._itemCol, np.int64),
                self._ratingCol: _col_from(
                    rows, cols, self._ratingCol, np.float32
                ),
            }
        )
        return ALSModel(inner)


class ALSModel:
    def __init__(self, inner: _compat.ALSModel):
        self._inner = inner

    def setUserCol(self, v):
        self._inner.setUserCol(v)
        return self

    def setItemCol(self, v):
        self._inner.setItemCol(v)
        return self

    def setPredictionCol(self, v):
        self._inner.setPredictionCol(v)
        return self

    def setColdStartStrategy(self, v):
        self._inner.setColdStartStrategy(v)
        return self

    def getUserCol(self):            return self._inner.getUserCol()
    def getItemCol(self):            return self._inner.getItemCol()
    def getPredictionCol(self):      return self._inner.getPredictionCol()
    def getColdStartStrategy(self):  return self._inner.getColdStartStrategy()

    @property
    def rank(self) -> int:
        return self._inner.rank

    @property
    def userFactors(self) -> np.ndarray:
        return self._inner.userFactors

    @property
    def itemFactors(self) -> np.ndarray:
        return self._inner.itemFactors

    def transform(self, dataset):
        """Prediction column for (user, item) rows; coldStartStrategy
        "nan"/"drop" rides the inner transform — a hidden row-index
        column reports which input rows survive "drop".  Dicts pass
        through to the dict-plane model (tuners, loaded containers)."""
        if isinstance(dataset, dict):
            return self._inner.transform(dataset)
        rows, cols = _collect_once(dataset)
        if not rows:  # empty split: empty typed output, like pyspark.ml
            return _append_column(
                dataset, rows, self._inner._predictionCol, [], "double"
            )
        u = _col_from(rows, cols, self._inner._userCol, np.int64)
        i = _col_from(rows, cols, self._inner._itemCol, np.int64)
        pairs = {
            self._inner._userCol: u,
            self._inner._itemCol: i,
            "__row_idx": np.arange(len(u)),
        }
        out = self._inner.transform(pairs)
        pred = [float(p) for p in out[self._inner._predictionCol]]
        idx = out["__row_idx"]
        if len(idx) == len(u) and np.array_equal(idx, np.arange(len(u))):
            return _append_column(
                dataset, rows, self._inner._predictionCol, pred, "double"
            )
        return _rebuild_rows(
            dataset, rows, idx, self._inner._predictionCol, pred, "double"
        )

    def recommendForAllUsers(self, numItems: int, withScores: bool = False):
        return self._inner.recommendForAllUsers(numItems,
                                                withScores=withScores)

    def recommendForAllItems(self, numUsers: int, withScores: bool = False):
        return self._inner.recommendForAllItems(numUsers,
                                                withScores=withScores)

    @staticmethod
    def _subset_col_dict(dataset, col: str):
        """DataFrame -> {col: int64 ids} for the subset recommenders,
        with a .distinct() pushdown when the frame supports it (Spark's
        own recommendForUserSubset distincts distributedly; collecting
        every raw row only for the dict plane to unique them away would
        bound driver IO by the ROW count instead of the distinct
        count).  Dicts pass through."""
        if isinstance(dataset, dict) or not hasattr(dataset, "select"):
            return dataset
        sel = dataset.select(col)
        distinct = getattr(sel, "distinct", None)
        if distinct is not None:
            sel = distinct()
        rows, cols = _collect_once(sel)
        return {col: _col_from(rows, cols, col, np.int64)}

    def recommendForUserSubset(self, dataset, numItems: int,
                               withScores: bool = False):
        """Subset recommendations from a DataFrame carrying the user id
        column (ml.recommendation.ALSModel.recommendForUserSubset);
        returns (user_ids, item_ids[, scores]) like the dict plane."""
        return self._inner.recommendForUserSubset(
            self._subset_col_dict(dataset, self._inner._userCol),
            numItems, withScores=withScores,
        )

    def recommendForItemSubset(self, dataset, numUsers: int,
                               withScores: bool = False):
        """Subset recommendations from a DataFrame carrying the item id
        column; shape contract as recommendForUserSubset."""
        return self._inner.recommendForItemSubset(
            self._subset_col_dict(dataset, self._inner._itemCol),
            numUsers, withScores=withScores,
        )

    def save(self, path: str) -> None:
        self._inner.save(path)

    @classmethod
    def load(cls, path: str) -> "ALSModel":
        return cls(_compat.ALSModel.load(path))


# ---------------------------------------------------------------------------
# Evaluators
# ---------------------------------------------------------------------------


class RegressionEvaluator(_compat.RegressionEvaluator):
    """ml.evaluation.RegressionEvaluator over Spark DataFrames (keyword
    constructor, als-pyspark.py:62 usage)."""

    def __init__(self, *, metricName: str = "rmse",
                 labelCol: str = "label", predictionCol: str = "prediction"):
        super().__init__(metricName=metricName, labelCol=labelCol,
                         predictionCol=predictionCol)

    def evaluate(self, dataset) -> float:
        if isinstance(dataset, dict):  # tuner split plane (see KMeans.fit)
            return super().evaluate(dataset)
        rows, cols = _collect_once(
            dataset.select(self._labelCol, self._predictionCol)
        )
        return super().evaluate(
            {
                self._labelCol: _col_from(rows, cols, self._labelCol,
                                          np.float64),
                self._predictionCol: _col_from(
                    rows, cols, self._predictionCol, np.float64
                ),
            }
        )


# Pipeline/tuning composability is data-plane agnostic — Pipeline only
# touches the stage fit/transform contract, and the tuners do their own
# one-collect on Spark frames — so the SAME classes serve real Spark
# DataFrames here (the pyspark.ml.Pipeline / ml.tuning import-line
# drop-in):
#   from oap_mllib_tpu.compat.pyspark import Pipeline, CrossValidator
from oap_mllib_tpu.compat.pipeline import (  # noqa: E402,F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    Pipeline,
    PipelineModel,
    TrainValidationSplit,
    TrainValidationSplitModel,
)


class ClusteringEvaluator(_compat.ClusteringEvaluator):
    """ml.evaluation.ClusteringEvaluator over Spark DataFrames
    (kmeans-pyspark.py:57 usage)."""

    def __init__(self, *, featuresCol: str = "features",
                 predictionCol: str = "prediction",
                 metricName: str = "silhouette",
                 distanceMeasure: str = "squaredEuclidean"):
        super().__init__()
        self.setFeaturesCol(featuresCol).setPredictionCol(predictionCol)
        self.setMetricName(metricName).setDistanceMeasure(distanceMeasure)

    def evaluate(self, dataset) -> float:
        if isinstance(dataset, dict):  # tuner split plane (see KMeans.fit)
            return super().evaluate(dataset)
        rows, cols = _collect_once(
            dataset.select(self._featuresCol, self._predictionCol)
        )
        return super().evaluate(
            {
                self._featuresCol: _mat_from(rows, cols, self._featuresCol),
                self._predictionCol: _col_from(
                    rows, cols, self._predictionCol, np.int64
                ),
            }
        )
