"""Spark-ML-style compatibility API.

The reference's public surface IS Spark ML: builder-style estimators
(``new KMeans().setK(2).setMaxIter(5).fit(df)``) over DataFrames with
named columns, shadowed by classpath substitution (survey §2.2).  This
package provides that calling convention for users migrating Spark ML /
PySpark code: the same param names in the same camelCase, column-oriented
input, ``transform`` that appends an output column.

A "DataFrame" here is a plain ``dict[str, np.ndarray]`` (column name ->
column values) — the dependency-free stand-in; ``fit`` also accepts a bare
ndarray for the features-only case.
"""

from oap_mllib_tpu.compat.pipeline import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    Pipeline,
    PipelineModel,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from oap_mllib_tpu.compat.spark import (
    ALS,
    ClusteringEvaluator,
    KMeans,
    PCA,
    RegressionEvaluator,
)

__all__ = [
    "KMeans", "PCA", "ALS", "ClusteringEvaluator", "RegressionEvaluator",
    "Pipeline", "PipelineModel", "ParamGridBuilder", "CrossValidator",
    "CrossValidatorModel", "TrainValidationSplit",
    "TrainValidationSplitModel",
]
