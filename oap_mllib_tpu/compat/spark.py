"""Builder-style estimators mirroring Spark ML's param surface.

Parity map (reference shims, survey §2.2):
- ``ml.clustering.KMeans``: setK/setMaxIter/setTol/setSeed/setInitMode/
  setInitSteps/setDistanceMeasure/setFeaturesCol/setPredictionCol/
  setWeightCol; model: clusterCenters(), predict(), summary.
- ``ml.feature.PCA``: setK/setInputCol/setOutputCol; model: pc,
  explainedVariance, transform.
- ``ml.recommendation.ALS``: setRank/setMaxIter/setRegParam/setAlpha/
  setImplicitPrefs/setSeed/setUserCol/setItemCol/setRatingCol/
  setPredictionCol/setNumUserBlocks/setNumItemBlocks/setNumBlocks/
  setColdStartStrategy/setCheckpointInterval (full param surface of
  reference spark-3.1.1/ml/recommendation/ALS.scala:241-245); model:
  userFactors, itemFactors, transform (appends the prediction column,
  honoring coldStartStrategy nan/drop),
  recommendForAllUsers/recommendForAllItems.

Input "DataFrames" are dicts of numpy columns; transform returns a new
dict with the output column appended (input never mutated).
"""

from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

from oap_mllib_tpu.models import als as _als
from oap_mllib_tpu.models import kmeans as _kmeans
from oap_mllib_tpu.models import pca as _pca

DataFrame = Dict[str, np.ndarray]


def _features_from(data: Union[np.ndarray, DataFrame], col: str) -> np.ndarray:
    if isinstance(data, dict):
        if col not in data:
            raise KeyError(f"column {col!r} not in data (has {list(data)})")
        return np.asarray(data[col])
    return np.asarray(data)


def _global_unique(ids: np.ndarray) -> np.ndarray:
    """Unique ids across ALL processes: the cold-start "seen in
    training" sets must be world-consistent when each rank only holds
    its shard (partition-wise ingestion, compat/pyspark module notes) —
    rank-local sets would make transform drop different rows on
    different ranks.  Fixed-shape allgather (lengths first, then padded
    ids) since every cross-process exchange here is fixed-shape."""
    import jax

    loc = np.unique(np.asarray(ids, np.int64))
    if jax.process_count() == 1:
        return loc
    from jax.experimental import multihost_utils

    n = int(np.max(multihost_utils.process_allgather(
        np.asarray([len(loc)], np.int64)
    )))
    pad = np.full((n,), -1, np.int64)
    pad[: len(loc)] = loc
    allv = np.asarray(multihost_utils.process_allgather(pad)).reshape(-1)
    return np.unique(allv[allv >= 0])


def _save_compat_meta(path: str, meta: dict) -> None:
    """Persist the compat surface alongside the core model artifacts —
    column names (and per-model extras) must survive save/load, like
    Spark's own model metadata (DefaultParamsWriter)."""
    import json as _json
    import os as _os

    with open(_os.path.join(path, "compat_metadata.json"), "w") as f:
        _json.dump(meta, f)


def _load_compat_meta(path: str) -> dict:
    """{} for pre-round-4 saves (callers fall back to defaults)."""
    import json as _json
    import os as _os

    p = _os.path.join(path, "compat_metadata.json")
    if not _os.path.exists(p):
        return {}
    with open(p) as f:
        return _json.load(f)


class KMeans:
    """Spark-ML-style K-Means builder (reference shim: ml.clustering.KMeans)."""

    def __init__(self):
        self._k = 2
        self._maxIter = 20
        self._tol = 1e-4
        self._seed = None  # unset -> Config.seed (OAP_MLLIB_TPU_SEED)
        self._initMode = "k-means||"
        self._initSteps = 2
        self._distanceMeasure = "euclidean"
        self._featuresCol = "features"
        self._predictionCol = "prediction"
        self._weightCol: Optional[str] = None

    # -- setters (each returns self, Spark-style) --
    def setK(self, v):                self._k = v; return self
    def setMaxIter(self, v):          self._maxIter = v; return self
    def setTol(self, v):              self._tol = v; return self
    def setSeed(self, v):             self._seed = v; return self
    def setInitMode(self, v):         self._initMode = v; return self
    def setInitSteps(self, v):        self._initSteps = v; return self
    def setDistanceMeasure(self, v):  self._distanceMeasure = v; return self
    def setFeaturesCol(self, v):      self._featuresCol = v; return self
    def setPredictionCol(self, v):    self._predictionCol = v; return self
    def setWeightCol(self, v):        self._weightCol = v; return self

    # -- getters --
    def getK(self):                return self._k
    def getMaxIter(self):          return self._maxIter
    def getTol(self):              return self._tol
    def getInitMode(self):         return self._initMode

    def getSeed(self):
        """The RESOLVED seed (Config.seed when unset) — the value the fit
        will actually use, mirroring how Spark's getSeed always returns a
        concrete value."""
        from oap_mllib_tpu.config import get_config

        return get_config().seed if self._seed is None else self._seed
    def getInitSteps(self):        return self._initSteps
    def getDistanceMeasure(self):  return self._distanceMeasure
    def getFeaturesCol(self):      return self._featuresCol
    def getPredictionCol(self):    return self._predictionCol

    def fit(self, data: Union[np.ndarray, DataFrame]) -> "KMeansModel":
        x = _features_from(data, self._featuresCol)
        w = None
        if self._weightCol is not None:
            if not isinstance(data, dict):
                raise ValueError(
                    f"weightCol={self._weightCol!r} is set but data has no "
                    "columns; pass a dict with the weight column"
                )
            w = np.asarray(data[self._weightCol])
        est = _kmeans.KMeans(
            k=self._k, max_iter=self._maxIter, tol=self._tol, seed=self._seed,
            init_mode=self._initMode, init_steps=self._initSteps,
            distance_measure=self._distanceMeasure,
        )
        return KMeansModel(est.fit(x, sample_weight=w), self._featuresCol,
                           self._predictionCol)


class KMeansModel:
    def __init__(self, inner: _kmeans.KMeansModel, features_col: str, prediction_col: str):
        self._inner = inner
        self._featuresCol = features_col
        self._predictionCol = prediction_col

    # Spark models re-expose their column params as setters (a fitted
    # ml.clustering.KMeansModel can be pointed at different columns)
    def setFeaturesCol(self, v):    self._featuresCol = v; return self
    def setPredictionCol(self, v):  self._predictionCol = v; return self

    def getFeaturesCol(self):    return self._featuresCol
    def getPredictionCol(self):  return self._predictionCol

    def clusterCenters(self) -> np.ndarray:
        return self._inner.cluster_centers_

    @property
    def summary(self):
        return self._inner.summary

    def predict(self, features: np.ndarray) -> int:
        """Single-vector predict (Spark's model.predict(Vector)).
        For batches, use ``transform`` — a 2-D input here is a misuse that
        would silently drop rows, so it raises."""
        features = np.asarray(features)
        if features.ndim != 1:
            raise TypeError(
                f"predict takes a single 1-D vector, got shape {features.shape}; "
                "use transform() for batches"
            )
        return int(self._inner.predict(features[None, :])[0])

    def transform(self, data: Union[np.ndarray, DataFrame]) -> DataFrame:
        x = _features_from(data, self._featuresCol)
        out = dict(data) if isinstance(data, dict) else {self._featuresCol: x}
        out[self._predictionCol] = self._inner.predict(x)
        return out

    def computeCost(self, data: Union[np.ndarray, DataFrame]) -> float:
        return self._inner.compute_cost(_features_from(data, self._featuresCol))

    def save(self, path: str) -> None:
        self._inner.save(path)
        _save_compat_meta(path, {
            "featuresCol": self._featuresCol,
            "predictionCol": self._predictionCol,
        })

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        meta = _load_compat_meta(path)
        return cls(
            _kmeans.KMeansModel.load(path),
            meta.get("featuresCol", "features"),
            meta.get("predictionCol", "prediction"),
        )


class PCA:
    """Spark-ML-style PCA builder (reference shim: ml.feature.PCA)."""

    def __init__(self):
        self._k = None
        self._inputCol = "features"
        self._outputCol = "pcaFeatures"

    def setK(self, v):          self._k = v; return self
    def setInputCol(self, v):   self._inputCol = v; return self
    def setOutputCol(self, v):  self._outputCol = v; return self

    def getK(self):         return self._k
    def getInputCol(self):  return self._inputCol
    def getOutputCol(self): return self._outputCol

    def fit(self, data: Union[np.ndarray, DataFrame]) -> "PCAModel":
        if self._k is None:
            raise ValueError("k is not set (call setK)")
        x = _features_from(data, self._inputCol)
        return PCAModel(_pca.PCA(k=self._k).fit(x), self._inputCol, self._outputCol)


class PCAModel:
    def __init__(self, inner: _pca.PCAModel, input_col: str, output_col: str):
        self._inner = inner
        self._inputCol = input_col
        self._outputCol = output_col

    # column setters on the fitted model (ml.feature.PCAModel surface)
    def setInputCol(self, v):   self._inputCol = v; return self
    def setOutputCol(self, v):  self._outputCol = v; return self

    def getInputCol(self):   return self._inputCol
    def getOutputCol(self):  return self._outputCol

    @property
    def pc(self) -> np.ndarray:
        """(d, k) principal components matrix (Spark's `pc`)."""
        return self._inner.components_

    @property
    def explainedVariance(self) -> np.ndarray:
        return self._inner.explained_variance_

    def transform(self, data: Union[np.ndarray, DataFrame]) -> DataFrame:
        x = _features_from(data, self._inputCol)
        out = dict(data) if isinstance(data, dict) else {self._inputCol: x}
        out[self._outputCol] = self._inner.transform(x)
        return out

    def save(self, path: str) -> None:
        self._inner.save(path)
        _save_compat_meta(path, {
            "inputCol": self._inputCol,
            "outputCol": self._outputCol,
        })

    @classmethod
    def load(cls, path: str) -> "PCAModel":
        meta = _load_compat_meta(path)
        return cls(
            _pca.PCAModel.load(path),
            meta.get("inputCol", "features"),
            meta.get("outputCol", "pcaFeatures"),
        )


class ALS:
    """Spark-ML-style ALS builder (reference shim: ml.recommendation.ALS)."""

    _supportedColdStartStrategies = ("nan", "drop")

    def __init__(self):
        self._rank = 10
        self._maxIter = 10
        self._regParam = 0.1
        self._alpha = 1.0
        self._implicitPrefs = False
        self._seed = None  # unset -> Config.seed (OAP_MLLIB_TPU_SEED)
        self._nonnegative = False
        self._userCol = "user"
        self._itemCol = "item"
        self._ratingCol = "rating"
        self._predictionCol = "prediction"
        # Spark defaults (reference ALS.scala:241-245): numUserBlocks=10,
        # numItemBlocks=10, checkpointInterval=10, coldStartStrategy="nan".
        # The block counts are only FORWARDED to the estimator when the
        # user sets them — Spark's 10 is a partitioning default, not a
        # device cap, and the mesh layout is the better default here.
        self._numUserBlocks = 10
        self._numItemBlocks = 10
        self._numBlocksSet = False
        self._checkpointInterval = 10
        self._coldStartStrategy = "nan"

    def setRank(self, v):           self._rank = v; return self
    def setMaxIter(self, v):        self._maxIter = v; return self
    def setRegParam(self, v):       self._regParam = v; return self
    def setAlpha(self, v):          self._alpha = v; return self
    def setImplicitPrefs(self, v):  self._implicitPrefs = v; return self
    def setSeed(self, v):           self._seed = v; return self
    def setNonnegative(self, v):    self._nonnegative = v; return self
    def setUserCol(self, v):        self._userCol = v; return self
    def setItemCol(self, v):        self._itemCol = v; return self
    def setRatingCol(self, v):      self._ratingCol = v; return self
    def setPredictionCol(self, v):  self._predictionCol = v; return self

    def setNumUserBlocks(self, v):
        if v < 1:
            raise ValueError("numUserBlocks must be >= 1")
        self._numUserBlocks = v
        self._numBlocksSet = True
        return self

    def setNumItemBlocks(self, v):
        if v < 1:
            raise ValueError("numItemBlocks must be >= 1")
        self._numItemBlocks = v
        self._numBlocksSet = True
        return self

    def setNumBlocks(self, v):
        """Set both numUserBlocks and numItemBlocks (ALS.scala:679-683)."""
        return self.setNumUserBlocks(v).setNumItemBlocks(v)

    @staticmethod
    def _validated_cold_start(v) -> str:
        """ONE case-insensitive validator + normalizer for estimator and
        model setters (Spark lowercases on read, ALS.scala:128 — storing
        normalized makes that a no-op here)."""
        s = str(v).lower()
        if s not in ALS._supportedColdStartStrategies:
            raise ValueError(
                f"coldStartStrategy must be one of "
                f"{ALS._supportedColdStartStrategies}, got {v!r}"
            )
        return s

    def setColdStartStrategy(self, v):
        """"nan" keeps NaN predictions for ids unseen in training; "drop"
        removes those rows from transform output (ALS.scala:119-128)."""
        self._coldStartStrategy = self._validated_cold_start(v)
        return self

    def setCheckpointInterval(self, v):
        """Accepted for API parity but a no-op, exactly like the reference:
        ALSDALImpl ignores checkpointInterval (survey §5 — the accelerated
        path has no intermediate RDD lineage to truncate; here the whole
        fit is one compiled program).  -1 disables, like Spark."""
        if v != -1 and v < 1:
            raise ValueError("checkpointInterval must be >= 1 or -1")
        self._checkpointInterval = v
        return self

    def getRank(self):          return self._rank
    def getMaxIter(self):       return self._maxIter
    def getRegParam(self):      return self._regParam
    def getAlpha(self):         return self._alpha
    def getImplicitPrefs(self): return self._implicitPrefs

    def getSeed(self):
        """The RESOLVED seed (Config.seed when unset) — see KMeans.getSeed."""
        from oap_mllib_tpu.config import get_config

        return get_config().seed if self._seed is None else self._seed
    def getNonnegative(self):   return self._nonnegative
    def getUserCol(self):       return self._userCol
    def getItemCol(self):       return self._itemCol
    def getRatingCol(self):     return self._ratingCol
    def getPredictionCol(self): return self._predictionCol
    def getNumUserBlocks(self): return self._numUserBlocks
    def getNumItemBlocks(self): return self._numItemBlocks
    def getCheckpointInterval(self): return self._checkpointInterval

    def getColdStartStrategy(self):
        return self._coldStartStrategy  # stored normalized

    def fit(self, data: DataFrame) -> "ALSModel":
        if not isinstance(data, dict):
            raise TypeError("ALS.fit expects a dict with user/item/rating columns")
        est = _als.ALS(
            rank=self._rank, max_iter=self._maxIter, reg_param=self._regParam,
            implicit_prefs=self._implicitPrefs, alpha=self._alpha, seed=self._seed,
            nonnegative=self._nonnegative,
            num_user_blocks=self._numUserBlocks if self._numBlocksSet else None,
            num_item_blocks=self._numItemBlocks if self._numBlocksSet else None,
        )
        users = np.asarray(data[self._userCol])
        items = np.asarray(data[self._itemCol])
        inner = est.fit(users, items, np.asarray(data[self._ratingCol]))
        return ALSModel(inner, self._userCol, self._itemCol,
                        prediction_col=self._predictionCol,
                        cold_start_strategy=self.getColdStartStrategy(),
                        seen_users=_global_unique(users),
                        seen_items=_global_unique(items))


class ALSModel:
    def __init__(self, inner: _als.ALSModel, user_col: str, item_col: str,
                 prediction_col: str = "prediction",
                 cold_start_strategy: str = "nan",
                 seen_users: Optional[np.ndarray] = None,
                 seen_items: Optional[np.ndarray] = None):
        self._inner = inner
        self._userCol = user_col
        self._itemCol = item_col
        self._predictionCol = prediction_col
        self._coldStartStrategy = cold_start_strategy
        # ids that actually appeared in training — Spark's cold-start set
        # is "unseen in training", which in a dense id space also covers
        # in-range ids whose every rating landed outside the training
        # split.  Persisted by save/load (like Spark's factor id lists);
        # None (a pre-round-4 save, or direct construction without the
        # sets) degrades to range checks.
        self._seenUsers = seen_users
        self._seenItems = seen_items

    # Spark's fitted ALSModel re-exposes these as model params
    # (ml.recommendation.ALSModel.setColdStartStrategy et al.) — a
    # loaded model can be re-pointed at different columns or switched
    # between nan/drop without refitting
    def setUserCol(self, v):        self._userCol = v; return self
    def setItemCol(self, v):        self._itemCol = v; return self
    def setPredictionCol(self, v):  self._predictionCol = v; return self

    def setColdStartStrategy(self, v):
        self._coldStartStrategy = ALS._validated_cold_start(v)
        return self

    def getUserCol(self):            return self._userCol
    def getItemCol(self):            return self._itemCol
    def getPredictionCol(self):      return self._predictionCol

    def getColdStartStrategy(self):
        # direct construction may carry a raw value; normalize on read
        return self._coldStartStrategy.lower()

    @property
    def rank(self) -> int:
        return self._inner.rank

    @property
    def userFactors(self) -> np.ndarray:
        return self._inner.user_factors_

    @property
    def itemFactors(self) -> np.ndarray:
        return self._inner.item_factors_

    def transform(self, data: DataFrame) -> DataFrame:
        """Append the prediction column for (user, item) pairs.

        Cold-start handling mirrors Spark (ALS.scala:119-128, ALSModel
        .transform): ids with no trained factor row get NaN predictions
        under "nan" (the default), or their rows removed from every column
        under "drop" — the mode cross-validation needs to avoid NaN
        metrics."""
        users = np.asarray(data[self._userCol])
        items = np.asarray(data[self._itemCol])
        n_u = self._inner.user_factors_.shape[0]
        n_i = self._inner.item_factors_.shape[0]
        seen = (users >= 0) & (users < n_u) & (items >= 0) & (items < n_i)
        if self._seenUsers is not None:
            seen &= np.isin(users, self._seenUsers)
        if self._seenItems is not None:
            seen &= np.isin(items, self._seenItems)
        # clip before the gather so device-side indexing never reads out of
        # range, then mask the cold rows
        pred = self._inner.predict(
            np.clip(users, 0, max(n_u - 1, 0)),
            np.clip(items, 0, max(n_i - 1, 0)),
        ).astype(np.float32)
        pred[~seen] = np.nan
        out = dict(data)
        if self._coldStartStrategy == "drop":
            out = {k: np.asarray(v)[seen] for k, v in out.items()}
            out[self._predictionCol] = pred[seen]
        else:
            out[self._predictionCol] = pred
        return out

    def recommendForAllUsers(self, numItems: int,
                             withScores: bool = False):
        """Top-N item ids per user; ``withScores=True`` also returns the
        predicted ratings (Spark's recommendForAllUsers returns
        (item, rating) structs)."""
        return self._inner.recommend_for_all_users(
            numItems, with_scores=withScores
        )

    def recommendForAllItems(self, numUsers: int,
                             withScores: bool = False):
        """Top-N user ids per item; ``withScores`` as above."""
        return self._inner.recommend_for_all_items(
            numUsers, with_scores=withScores
        )

    def _subset_ids(self, dataset, col, seen, n_rows: int) -> np.ndarray:
        """Spark's subset semantics (ALS.scala:379-429): take the id
        column, DISTINCT it, and keep only ids with a trained factor row
        (the join against the factor frame) — unseen ids silently drop,
        they do not error."""
        ids = np.asarray(
            dataset[col] if isinstance(dataset, dict) else dataset,
            np.int64,
        )
        ids = np.unique(ids)
        ids = ids[(ids >= 0) & (ids < n_rows)]
        if seen is not None:
            ids = ids[np.isin(ids, seen)]
        return ids

    def recommendForUserSubset(self, dataset, numItems: int,
                               withScores: bool = False):
        """Top-N items for the users in ``dataset`` (a dict with the
        userCol, or a bare id array) — ml.recommendation.ALSModel
        .recommendForUserSubset.  Returns (user_ids, item_ids[, scores]):
        row j of the matrices belongs to user_ids[j] (one row per
        distinct trained user, Spark's distinct-and-join semantics)."""
        ids = self._subset_ids(
            dataset, self._userCol, self._seenUsers,
            self._inner.user_factors_.shape[0],
        )
        out = self._inner.recommend_for_users(
            ids, numItems, with_scores=withScores
        )
        return (ids, *out) if withScores else (ids, out)

    def recommendForItemSubset(self, dataset, numUsers: int,
                               withScores: bool = False):
        """Top-N users for the items in ``dataset`` — ml.recommendation
        .ALSModel.recommendForItemSubset; shape contract as
        recommendForUserSubset."""
        ids = self._subset_ids(
            dataset, self._itemCol, self._seenItems,
            self._inner.item_factors_.shape[0],
        )
        out = self._inner.recommend_for_items(
            ids, numUsers, with_scores=withScores
        )
        return (ids, *out) if withScores else (ids, out)

    def save(self, path: str) -> None:
        """Persist factors AND the compat surface: column names,
        coldStartStrategy, and the seen-id sets — Spark's cold-start
        semantics ("unseen in training") must survive a save/load
        round-trip (its ALSModel persists the factor id lists,
        ALS.scala:119-128); without them a loaded model silently
        degrades to range checks."""
        import os as _os

        self._inner.save(path)
        if self._seenUsers is not None:
            np.save(_os.path.join(path, "seen_users.npy"), self._seenUsers)
        if self._seenItems is not None:
            np.save(_os.path.join(path, "seen_items.npy"), self._seenItems)
        _save_compat_meta(path, {
            "userCol": self._userCol,
            "itemCol": self._itemCol,
            "predictionCol": self._predictionCol,
            "coldStartStrategy": self._coldStartStrategy,
        })

    @classmethod
    def load(cls, path: str) -> "ALSModel":
        import os as _os

        meta = _load_compat_meta(path)

        def _opt(name):
            p = _os.path.join(path, name)
            return np.load(p) if _os.path.exists(p) else None

        return cls(
            _als.ALSModel.load(path),
            meta.get("userCol", "user"),
            meta.get("itemCol", "item"),
            prediction_col=meta.get("predictionCol", "prediction"),
            cold_start_strategy=meta.get("coldStartStrategy", "nan"),
            seen_users=_opt("seen_users.npy"),
            seen_items=_opt("seen_items.npy"),
        )


class ClusteringEvaluator:
    """Silhouette evaluator (Spark ml.evaluation.ClusteringEvaluator —
    used by the reference K-Means examples, examples/kmeans-pyspark/
    kmeans-pyspark.py:57).  Metrics: silhouette with squaredEuclidean
    (default) or cosine distance, computed via Spark's closed form —
    point-to-cluster distances from cluster aggregates, never an (n, n)
    pairwise matrix; rows stream in chunks so the live (chunk, k) block
    is bounded."""

    _CHUNK = 1 << 16

    def __init__(self):
        self._metricName = "silhouette"
        self._distanceMeasure = "squaredEuclidean"
        self._featuresCol = "features"
        self._predictionCol = "prediction"

    def setMetricName(self, v):       self._metricName = v; return self
    def setDistanceMeasure(self, v):  self._distanceMeasure = v; return self
    def setFeaturesCol(self, v):      self._featuresCol = v; return self
    def setPredictionCol(self, v):    self._predictionCol = v; return self

    def getMetricName(self):       return self._metricName
    def getDistanceMeasure(self):  return self._distanceMeasure
    def getFeaturesCol(self):      return self._featuresCol
    def getPredictionCol(self):    return self._predictionCol

    def isLargerBetter(self) -> bool:
        return True

    def evaluate(self, dataset: DataFrame) -> float:
        if self._metricName != "silhouette":
            raise ValueError(f"unknown metric {self._metricName!r}")
        if self._distanceMeasure not in ("squaredEuclidean", "cosine"):
            raise ValueError(
                f"distanceMeasure must be squaredEuclidean or cosine, "
                f"got {self._distanceMeasure!r}"
            )
        x = np.asarray(_features_from(dataset, self._featuresCol), np.float64)
        labels = np.asarray(dataset[self._predictionCol])
        uniq = np.unique(labels)
        if len(uniq) < 2:
            raise ValueError("silhouette needs at least 2 clusters")
        own = np.searchsorted(uniq, labels)
        counts = np.bincount(own, minlength=len(uniq)).astype(np.float64)
        if self._distanceMeasure == "cosine":
            # cosine distance = 1 - a^.b^; mean distance to a cluster is
            # 1 - a^ . mean(normalized members)  (Spark CosineSilhouette)
            x = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-300)
        sums = np.zeros((len(uniq), x.shape[1]))
        np.add.at(sums, own, x)
        means = sums / counts[:, None]
        if self._distanceMeasure == "squaredEuclidean":
            sq = np.einsum("ij,ij->i", x, x)
            mean_sq = np.zeros(len(uniq))
            np.add.at(mean_sq, own, sq)
            mean_sq /= counts
        total = 0.0
        n = len(x)
        for lo in range(0, n, self._CHUNK):
            xi = x[lo : lo + self._CHUNK]
            oi = own[lo : lo + self._CHUNK]
            if self._distanceMeasure == "squaredEuclidean":
                # E||p - x||^2 = E||p||^2 - 2 x.mean_c + ||x||^2
                d = (
                    mean_sq[None, :]
                    - 2.0 * xi @ means.T
                    + sq[lo : lo + self._CHUNK, None]
                )
            else:
                d = 1.0 - xi @ means.T
            rows = np.arange(len(xi))
            n_own = counts[oi]
            # a(i): exclude the point itself (distance 0) from its own
            # cluster's mean
            a = d[rows, oi] * n_own / np.maximum(n_own - 1, 1)
            d[rows, oi] = np.inf
            b = d.min(axis=1)
            # s(i) = 0 when max(a, b) == 0 (coincident duplicate points):
            # Spark/sklearn define the 0/0 case as 0, and without the guard
            # the NaN would propagate into the mean.
            denom = np.maximum(a, b)
            s = np.where(
                (n_own > 1) & (denom > 0),
                (b - a) / np.where(denom > 0, denom, 1.0),
                0.0,
            )
            total += float(s.sum())
        return total / n


class RegressionEvaluator:
    """Regression metrics (Spark ml.evaluation.RegressionEvaluator —
    used by the reference ALS examples, examples/als-pyspark/
    als-pyspark.py:62).  Metrics: rmse (default), mse, mae, r2, var.
    NaN predictions (coldStartStrategy="nan") must be dropped by the
    caller or via coldStartStrategy="drop", as in Spark."""

    def __init__(self, metricName: str = "rmse", labelCol: str = "label",
                 predictionCol: str = "prediction"):
        self._metricName = metricName
        self._labelCol = labelCol
        self._predictionCol = predictionCol

    def setMetricName(self, v):     self._metricName = v; return self
    def setLabelCol(self, v):       self._labelCol = v; return self
    def setPredictionCol(self, v):  self._predictionCol = v; return self

    def getMetricName(self):     return self._metricName
    def getLabelCol(self):       return self._labelCol
    def getPredictionCol(self):  return self._predictionCol

    def isLargerBetter(self) -> bool:
        return self._metricName in ("r2", "var")

    def evaluate(self, dataset: DataFrame) -> float:
        label = np.asarray(dataset[self._labelCol], np.float64)
        pred = np.asarray(dataset[self._predictionCol], np.float64)
        if len(label) == 0:
            return float("nan")
        err = pred - label
        if self._metricName == "rmse":
            return float(np.sqrt(np.mean(err ** 2)))
        if self._metricName == "mse":
            return float(np.mean(err ** 2))
        if self._metricName == "mae":
            return float(np.mean(np.abs(err)))
        if self._metricName == "r2":
            ss_res = float(np.sum(err ** 2))
            ss_tot = float(np.sum((label - label.mean()) ** 2))
            return 1.0 - ss_res / ss_tot if ss_tot > 0 else float("nan")
        if self._metricName == "var":
            return float(np.var(pred))
        raise ValueError(f"unknown metric {self._metricName!r}")
