"""Multi-host bootstrap: the KVS-rendezvous analog.

The reference forms its collective world with a oneCCL TCP KVS: the driver
discovers the first executor's IP (Utils.scala:60-74), probes a free port on
it starting at 3000 (Utils.scala:76-96, native OneCCL.cpp:207-247), passes
``ip_port`` to every rank, and each rank calls
``ccl::create_communicator(size, rank, kvs)`` which blocks until the world
is complete (OneCCL.cpp:47-86).  Config keys
``spark.oap.mllib.oneccl.kvs.ip/.port`` override discovery.

TPU-native equivalent: ``jax.distributed.initialize(coordinator_address,
num_processes, process_id)`` — process 0 hosts the coordination service
(the KVS analog), everyone else TCP-connects, and the global device mesh
then spans all hosts.  Discovery reuses the same pattern: first host's
non-loopback IP + free-port scan (native/net_probe.cpp), overridable via
``OAP_MLLIB_TPU_COORDINATOR_ADDRESS`` / ``_PORT`` (the spark conf analog).

Single-process runs (the `local[*]` analog) skip initialization entirely —
same behavior as the reference's 1-rank world (Utils.scala:119-121).
"""

from __future__ import annotations

import logging
import os
import socket
import time
from typing import Optional

from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")

_initialized = False


def local_ip() -> str:
    """First non-loopback IPv4 of this host (native probe, Python fallback)."""
    from oap_mllib_tpu import native

    ip = native.local_ip()
    if ip:
        return ip
    # Python fallback: kernel-chosen source IP for an outbound route
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))  # no packets sent (UDP, no data)
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def free_port(ip: str = "", start: int = 3000) -> int:
    """First bindable TCP port >= start (reference scans from 3000)."""
    from oap_mllib_tpu import native

    port = native.free_port(ip, start)
    if port:
        return port
    for p in range(start, 65536):
        s = socket.socket()
        try:
            # SO_REUSEADDR: without it a just-closed coordinator port
            # lingers in TIME_WAIT and the probe skips a port the real
            # bind (which sets the option) could take — tests restarting
            # worlds back-to-back then drift to ever-higher ports
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((ip or "", p))
            return p
        except OSError:
            continue
        finally:
            s.close()
    raise RuntimeError("no free port found")


def default_coordinator(start_port: int = 3000) -> str:
    """ip:port string for process 0 to host coordination on."""
    ip = local_ip()
    return f"{ip}:{free_port(ip, start_port)}"


def world_layout() -> dict:
    """The live world's shape, as recorded in checkpoint manifests
    (utils/checkpoint.py): process count/rank plus the global device
    count.  A restore compares this against the manifest's copy to
    decide between bit-identical continuation (same world) and the
    collective resharding pass (world changed) — the elastic-worlds
    analog of the reference re-creating its communicator per job
    (OneCCL.cpp:60-99) with the world size Spark handed it."""
    import jax

    return {
        "processes": int(jax.process_count()),
        "rank": int(jax.process_index()),
        "devices": int(len(jax.devices())),
    }


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host world; returns True if distributed init ran.

    No-op (returns False) for single-process configs.  Idempotent — the
    reference creates/destroys a communicator per training job
    (OneCCL.cpp:60-99), but JAX's runtime is process-wide, so one init
    serves all subsequent fits.
    """
    global _initialized
    cfg = get_config()
    num_processes = num_processes if num_processes is not None else cfg.num_processes
    process_id = process_id if process_id is not None else cfg.process_id
    if num_processes <= 1:
        return False
    if _initialized:
        return True

    if coordinator_address is None:
        if cfg.coordinator_address:
            port = cfg.coordinator_port or 3000
            coordinator_address = f"{cfg.coordinator_address}:{port}"
        elif process_id == 0:
            coordinator_address = default_coordinator()
        else:
            # name the env values actually seen: a misconfigured world
            # (typo'd var, value exported on the wrong host) fails with
            # the evidence instead of a generic instruction
            raise ValueError(
                "non-zero process_id requires a coordinator address "
                "(set OAP_MLLIB_TPU_COORDINATOR_ADDRESS / _PORT); saw "
                "OAP_MLLIB_TPU_COORDINATOR_ADDRESS="
                f"{os.environ.get('OAP_MLLIB_TPU_COORDINATOR_ADDRESS')!r}, "
                "OAP_MLLIB_TPU_COORDINATOR_PORT="
                f"{os.environ.get('OAP_MLLIB_TPU_COORDINATOR_PORT')!r}, "
                f"config.coordinator_address={cfg.coordinator_address!r}, "
                f"process_id={process_id}, num_processes={num_processes}"
            )

    import jax

    from oap_mllib_tpu.utils import faults, resilience

    log.info(
        "joining world: coordinator=%s size=%d rank=%d",
        coordinator_address, num_processes, process_id,
    )
    # Coordinator connection retries with backoff under Config
    # .bootstrap_timeout: ranks routinely come up before the coordinator
    # (process 0 may still be importing jax), and the reference's KVS
    # connect blocks/retries the same way (OneCCL.cpp:47-86).  Only
    # TRANSIENT faults (connection refused / Unavailable / injected
    # "bootstrap.connect" faults) retry; anything else propagates.
    from oap_mllib_tpu.telemetry import metrics as _tm

    timeout_s = max(float(cfg.bootstrap_timeout), 0.0)
    policy = resilience.RetryPolicy.from_config()
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            _tm.counter(
                "oap_bootstrap_connect_attempts_total",
                help="Coordinator connection attempts",
            ).inc()
            faults.maybe_fault("bootstrap.connect")
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _tm.counter(
                "oap_bootstrap_connect_seconds_total",
                help="Wall from first attempt to a joined world",
            ).inc(time.monotonic() - t0)
            break
        except Exception as e:
            elapsed = time.monotonic() - t0
            kind = resilience.classify_fault(e)
            delay = policy.delay_s(attempt, "bootstrap.connect")
            if kind != resilience.TRANSIENT or elapsed + delay > timeout_s:
                raise RuntimeError(
                    f"failed to join world: coordinator="
                    f"{coordinator_address} rank={process_id}/"
                    f"{num_processes} after {elapsed:.1f}s "
                    f"({attempt} connection retries, bootstrap_timeout="
                    f"{timeout_s:g}s): {e}"
                ) from e
            attempt += 1
            _tm.counter(
                "oap_bootstrap_connect_retries_total",
                help="Coordinator connection retries",
            ).inc()
            log.warning(
                "bootstrap connect to %s failed (%s); retry %d in %.2fs "
                "(%.1fs of %gs budget elapsed)",
                coordinator_address, e, attempt, delay, elapsed, timeout_s,
            )
            time.sleep(delay)
    _initialized = True
    return True
