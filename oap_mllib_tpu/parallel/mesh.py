"""Device mesh construction and sharding helpers.

The reference forms its collective world as (world size = Spark executor
count, rank = partition index) with partition->executor pinning via a custom
coalescer (reference OneCCL.scala:42, ExecutorInProcessCoalescePartitioner
.scala:28-57).  The TPU-native equivalent is a named `jax.sharding.Mesh`:

- ``data`` axis — row sharding across devices (the executor-count analog);
- ``model`` axis — optional feature/factor sharding for tables whose second
  dimension outgrows one chip's HBM (the survey §5 "mesh-sharded linalg"
  scope; the reference has no equivalent because oneDAL kernels are
  single-node-memory bound).

A mesh is cheap to build; estimators call :func:`get_mesh` per fit, mirroring
the reference's per-training-job communicator lifecycle (OneCCL.cpp:60-99).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import get_config


def get_mesh(
    n_devices: Optional[int] = None,
    model_parallel: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over available devices.

    ``model_parallel`` splits the device pool into a second axis used to
    shard feature/factor dimensions; defaults to ``Config.model_parallel``
    (1 = pure data parallel, the reference's only mode — survey §2.5).
    """
    cfg = get_config()
    if model_parallel is None:
        model_parallel = cfg.model_parallel
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(
            f"device count {n} not divisible by model_parallel={model_parallel}"
        )
    dev_array = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(dev_array, (cfg.data_axis, cfg.model_axis))


def data_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Rows sharded over the data axis, remaining dims replicated."""
    cfg = get_config()
    spec = P(cfg.data_axis, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_rows(x: np.ndarray, multiple: int, fill: float = 0.0):
    """Pad the leading dim of ``x`` up to a multiple; returns (padded, n_valid).

    XLA requires static shapes, so row counts that don't divide the data-axis
    size are padded and masked, replacing the reference's variable-length
    per-rank tables (OneDAL.scala:92-166; survey §2.6 "fixed-shape padded
    tensor exchange" design note).
    """
    n = x.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return x, n
    pad_width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=fill), n


def shard_rows(x: np.ndarray, mesh: Mesh) -> jax.Array:
    """Pad ``x`` to the data-axis size and place it row-sharded on the mesh.

    This is the data plane: the analog of the reference's
    ``vectorsToMergedNumericTables`` RDD->native-table conversion
    (OneDAL.scala:92-166), except the result is a single logically-global
    jax.Array whose shards live one-per-device.
    """
    cfg = get_config()
    n_data = mesh.shape[cfg.data_axis]
    padded, _ = pad_rows(np.asarray(x), n_data)
    return jax.device_put(padded, data_sharding(mesh, padded.ndim))


def row_mask(n_valid: int, n_padded: int, dtype=None) -> np.ndarray:
    """Validity mask for padded rows (True for real rows)."""
    mask = np.zeros((n_padded,), dtype=bool)
    mask[:n_valid] = True
    return mask
