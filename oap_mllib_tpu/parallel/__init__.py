"""Parallelism layer: device mesh, shardings, collectives, multi-host bootstrap.

TPU-native replacement for the reference's L1 communication layer
(mllib-dal/src/main/scala/org/apache/spark/ml/util/OneCCL.scala + native
OneCCL.cpp): instead of a oneCCL rank/world communicator carrying serialized
byte blobs over libfabric TCP, this layer builds a `jax.sharding.Mesh` over
(hosts x chips), annotates tensors with `NamedSharding`, and lets XLA compile
psum/all_gather/all_to_all collectives onto ICI/DCN.
"""

from oap_mllib_tpu.parallel.mesh import (
    get_mesh,
    data_sharding,
    replicated_sharding,
    shard_rows,
    pad_rows,
)
from oap_mllib_tpu.parallel.collective import (
    broadcast,
    allgather_rows,
    allreduce_sum,
    alltoall_rows,
)

__all__ = [
    "get_mesh",
    "data_sharding",
    "replicated_sharding",
    "shard_rows",
    "pad_rows",
    "broadcast",
    "allgather_rows",
    "allreduce_sum",
    "alltoall_rows",
]
