"""Capability-weighted shard planning + live straggler rebalancing
(ISSUE 15 — ROADMAP item 5, "one world across unequal ranks").

Every distributed pass in this stack used to assign EQUAL row/block
shards, so a mixed or degraded world (TPU+CPU ranks, a relaunched rank
with cold caches, a throttled host) finishes each pass at the slowest
rank's pace — the unified-heterogeneous-cluster gap SparkCL names
(PAPERS.md, arXiv:1505.01120).  PR 11 built the measurement layer (the
``oap_fleet_*`` rollups: per-rank pass walls, skew ratio, imbalance
trend); this module closes the loop, in the stack's own map-reduce
idiom (DrJAX, arXiv:2403.07128): only the per-rank map EXTENT changes —
the per-pass reductions keep their fixed shapes, so bucketed programs
and the collective schedule are untouched.

Three layers:

- **Capability** — each rank's relative throughput, measured once per
  process by a tiny deterministic-seeded microbench
  (``utils/dispatch.throughput_probe``) or pinned via
  ``Config.rank_capability``; allgathered ONCE per world size over the
  sanctioned host-collective seam (``ops/stream_ops.capability_sync``
  — so the gather inherits the deadline watchdog and the collective
  sanitizer's fingerprinting) together with each rank's memory budgets
  (``utils/membudget``), and cached.

- **Planner** — :func:`plan_extents` converts capability weights into
  uneven per-rank row ranges, QUANTIZED TO WHOLE CHUNKS so every rank
  keeps launching the same bucketed per-chunk program (a rank's share
  changes its chunk COUNT, never the chunk shape); per-rank host-budget
  caps bound a fast-but-small rank's share (the membudget pricing — a
  fast rank with little RAM must not be handed rows it cannot stage).
  :func:`plan_block_offsets` is the block-ALS analog: uneven user-block
  boundaries under a deadband (near-equal capabilities keep the exact
  uniform layout, so homogeneous worlds are bit-identical to the
  pre-balance code).

- **Controller** — :func:`observe_pass` rides the fleet rollups
  (ops/stream_ops._fleet_pass hands it the same gathered frames every
  rank already holds, so every rank computes the IDENTICAL decision —
  the rank-uniform-collective contract by construction): when the skew
  ratio exceeds ``Config.rebalance_threshold`` for
  ``rebalance_patience`` consecutive passes and the imbalance trend is
  not falling (a cold-cache relaunch warming up heals itself), extents
  re-plan at the next pass boundary from the measured per-rank
  throughput (rows assigned / pass wall, EMA-blended).  A rank that
  stays slowest through ``2 x patience`` over-threshold passes AFTER a
  re-plan already tried is a persistent offender: rank 0 writes a
  machine-readable hint (``balance.hint.json`` in ``Config.crash_dir``)
  the supervisor (utils/supervisor.py) counts toward its shrink/evict
  decision.

Every decision lands in ``summary.balance``, a ``balance`` child span,
and ``oap_balance_*`` metrics.  This module issues NO collectives
itself — the gather seam lives in ops/stream_ops.py (the fleet.py
precedent); everything here is pure planning + fold.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

log = logging.getLogger("oap_mllib_tpu")

ORIGIN_PROBE = "probe"
ORIGIN_PINNED = "pinned"
ORIGIN_EQUAL = "equal"
ORIGIN_MIXED = "mixed"

# near-equal capabilities keep the EXACT equal layout: probe noise on a
# homogeneous world must not churn extents (or un-pin the block-ALS
# uniform offsets the 2-D identity mapping depends on)
DEADBAND = 0.05

# fraction of a rank's host budget the planner lets a memory-backed
# shard occupy (the rest covers staging buffers, the interpreter, and
# the pre-existing resident table on single-host layouts)
_HOST_FRACTION = 0.5

# weight floor, relative to the mean: the planner never starves a rank
# to zero on its own — eviction is the supervisor's decision, and a
# zero-extent rank could never measure its way back in
_WEIGHT_FLOOR = 0.05

# EMA blend of current plan weights vs measured throughput on a re-plan
# (damps oscillation between two layouts when the measurement is noisy)
_EMA = 0.5

_MAX_REPLANS = 8

# phases at which a re-plan may take effect: between full-table passes
# whose math is a pure function of the iterate (Lloyd passes, the PCA
# moment passes).  The k-means|| init keeps per-chunk host state across
# rounds (stream_ops dmin cache), so extents are frozen through init —
# those passes never reach observe_pass anyway (no _fleet_pass seam).
_REPLAN_PHASES = ("lloyd_loop", "covariance_streamed")

HINT_FILENAME = "balance.hint.json"


class BalanceError(RuntimeError):
    """Invalid balance configuration or an unplannable layout."""


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def capability_sharding_cfg(cfg=None) -> str:
    """Validated ``Config.capability_sharding`` — a typo must raise, not
    silently disarm (the kmeans_kernel/fault_spec contract)."""
    cfg = cfg or get_config()
    mode = cfg.capability_sharding
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"capability_sharding must be auto|on|off, got {mode!r}"
        )
    return mode


def rebalance_threshold_cfg(cfg=None) -> float:
    cfg = cfg or get_config()
    thr = float(cfg.rebalance_threshold)
    if thr <= 1.0:
        raise ValueError(
            f"rebalance_threshold must be > 1.0 (a skew ratio), got {thr}"
        )
    return thr


def rebalance_patience_cfg(cfg=None) -> int:
    cfg = cfg or get_config()
    pat = int(cfg.rebalance_patience)
    if pat < 1:
        raise ValueError(
            f"rebalance_patience must be >= 1, got {pat}"
        )
    return pat


def armed(world: int, cfg=None) -> bool:
    """Should capability weighting apply?  A pure function of
    (config, world size) so every rank decides identically."""
    mode = capability_sharding_cfg(cfg)
    if mode == "off":
        return False
    if mode == "on":
        return True
    return world > 1


def _rank() -> int:
    import jax

    try:
        return int(jax.process_index())
    except RuntimeError:
        return int(get_config().process_id)


def _world() -> int:
    import jax

    try:
        return int(jax.process_count())
    except RuntimeError:
        return max(1, int(get_config().num_processes))


# ---------------------------------------------------------------------------
# capability gathering (the collective seam lives in ops/stream_ops.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CapabilityWorld:
    """One world's gathered capability frame: normalized weights (mean
    1.0), per-rank origins, and per-rank memory budgets (bytes; 0 =
    unbounded)."""

    world: int
    weights: np.ndarray  # (world,) f64, mean 1.0
    raw: np.ndarray  # (world,) the un-normalized capabilities
    origins: Tuple[str, ...]
    hbm: np.ndarray  # (world,) bytes
    host: np.ndarray  # (world,) bytes

    @property
    def origin(self) -> str:
        kinds = set(self.origins)
        if kinds == {ORIGIN_PINNED}:
            return ORIGIN_PINNED
        if kinds == {ORIGIN_PROBE}:
            return ORIGIN_PROBE
        return ORIGIN_MIXED


def local_capability_frame() -> np.ndarray:
    """This rank's fixed-shape capability frame for the fit-start
    allgather: ``[capability, origin_code, hbm_budget, host_budget]``
    float64 — origin_code 1.0 = pinned, 0.0 = probed.  Budgets come from
    the membudget resolution so the planner can cap a fast-but-small
    rank (0 = unbounded)."""
    from oap_mllib_tpu.utils import membudget
    from oap_mllib_tpu.utils.dispatch import rank_capability

    cap, origin = rank_capability()
    budgets = membudget.Budgets.resolve()
    return np.asarray(
        [cap, 1.0 if origin == ORIGIN_PINNED else 0.0,
         float(budgets.hbm), float(budgets.host)],
        np.float64,
    )


def fold_world(gathered) -> CapabilityWorld:
    """Fold the gathered ``(world, 4)`` capability frames — identical on
    every rank — into a :class:`CapabilityWorld` (pure; tests feed
    synthetic frames)."""
    frames = np.asarray(gathered, np.float64)
    if frames.ndim != 2 or frames.shape[1] != 4:
        raise ValueError(
            f"capability frame shape {frames.shape} != (world, 4)"
        )
    raw = np.maximum(frames[:, 0], 1e-9)
    weights = raw / raw.mean()
    origins = tuple(
        ORIGIN_PINNED if c > 0.5 else ORIGIN_PROBE for c in frames[:, 1]
    )
    return CapabilityWorld(
        world=frames.shape[0], weights=weights, raw=raw, origins=origins,
        hbm=frames[:, 2].copy(), host=frames[:, 3].copy(),
    )


_sync_lock = locktrace.TrackedLock("balance.sync", threading.Lock())
_sync_cache: Dict[tuple, CapabilityWorld] = {}


def world_capabilities(world: Optional[int] = None) -> CapabilityWorld:
    """The gathered capability world, allgathered once per (world size,
    ``Config.probe_epoch``) and cached (the "once at fit start"
    contract: the first armed plan of a process pays one probe + one
    tiny fixed-shape allgather; every later plan reads the cache, and a
    supervisor-bumped epoch invalidates it so relaunched ranks
    re-measure).  Fits are serialized per process, so the gather itself
    runs outside the cache lock (no collective under a lock — the R21
    contract) without risking a divergent double gather."""
    world = _world() if world is None else int(world)
    key = (world, int(get_config().probe_epoch))
    with _sync_lock:
        cached = _sync_cache.get(key)
    if cached is not None:
        return cached
    frame = local_capability_frame()
    if world == 1:
        gathered = frame[None]
    else:
        from oap_mllib_tpu.ops.stream_ops import capability_sync

        gathered = capability_sync(frame)
    cw = fold_world(gathered)
    with _sync_lock:
        _sync_cache[key] = cw
    if _rank() == 0:
        for r in range(cw.world):
            _tm.gauge(
                "oap_balance_capability", {"rank": str(r)},
                help="Per-rank capability weight (normalized, mean 1.0)",
            ).set(float(cw.weights[r]))
    log.info(
        "balance: world capabilities (%s) = %s",
        cw.origin, [round(float(w), 3) for w in cw.weights],
    )
    return cw


# ---------------------------------------------------------------------------
# planners (pure)
# ---------------------------------------------------------------------------


def _apportion(total: int, weights: np.ndarray,
               caps: Optional[np.ndarray]) -> Tuple[np.ndarray, bool]:
    """Integer apportionment of ``total`` units proportional to
    ``weights``, each rank bounded by ``caps`` (None / <= 0 entries =
    uncapped).  Waterfill + largest-remainder: capped ranks saturate and
    their excess redistributes among the uncapped; deterministic ties
    (lower rank first).  Returns ``(units (world,), over_cap)`` —
    ``over_cap`` means the caps were infeasible (sum(caps) < total) and
    the planner overflowed them proportionally rather than drop data
    (budgets steer, they never reject — the membudget auto contract)."""
    world = len(weights)
    w = np.maximum(np.asarray(weights, np.float64), 1e-12)
    cap_arr = np.full((world,), np.inf)
    if caps is not None:
        c = np.asarray(caps, np.float64)
        cap_arr = np.where(c > 0, c, np.inf)
    if np.isfinite(cap_arr).all() and cap_arr.sum() < total:
        # infeasible caps: overflow proportionally to weight (loudly)
        cap_arr = np.full((world,), np.inf)
        over = True
    else:
        over = False
    shares = np.zeros((world,), np.float64)
    remaining = float(total)
    free = np.ones((world,), bool)
    while remaining > 1e-9 and free.any():
        # spread what's left over the unsaturated ranks by weight; any
        # rank this pushes past its cap saturates there and the loop
        # redistributes its excess (terminates: each round saturates at
        # least one rank or distributes everything)
        add = remaining * (w * free) / float((w * free).sum())
        trial = shares + np.where(free, add, 0.0)
        hit = free & (trial >= cap_arr)
        if not hit.any():
            shares = trial
            break
        shares[hit] = cap_arr[hit]
        free &= ~hit
        remaining = max(0.0, float(total - shares.sum()))
    units = np.floor(shares).astype(np.int64)
    # largest remainder, bounded by caps, ties to the lower rank
    frac = shares - units
    order = np.argsort(-frac, kind="stable")
    leftover = int(total - units.sum())
    for r in order:
        if leftover <= 0:
            break
        if units[r] + 1 <= cap_arr[r]:
            units[r] += 1
            leftover -= 1
    i = 0
    while leftover > 0 and i < world:  # caps all saturated: spill in order
        units[order[i % world]] += 1
        leftover -= 1
        i += 1
    return units, over


def plan_extents(
    n_rows: int, chunk_rows: int, weights: Sequence[float],
    caps_rows: Optional[Sequence[int]] = None,
) -> Tuple[List[Tuple[int, int]], bool]:
    """Weight-proportional per-rank row ranges, quantized to whole
    chunks: rank r gets rows ``[start, start + rows)`` where every
    boundary except the global tail is a ``chunk_rows`` multiple — so
    each rank's pass is the same bucketed per-chunk program, just a
    different chunk COUNT.  Returns ``(extents, over_cap)``; extents
    always cover exactly ``[0, n_rows)`` (sum of rows == n_rows).
    World size 1 degenerates to the identity extent."""
    n = int(n_rows)
    if n < 1:
        raise ValueError(f"n_rows must be >= 1, got {n}")
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    world = len(weights)
    if world == 1:
        # nowhere else to put rows: the identity extent, loudly over-cap
        # when the one rank's budget cannot hold them (advisory)
        over1 = bool(
            caps_rows is not None and len(caps_rows) == 1
            and caps_rows[0] and 0 < caps_rows[0] < n
        )
        return [(0, n)], over1
    n_chunks = -(-n // chunk_rows)
    caps_c = None
    if caps_rows is not None:
        # a participating rank stages at least ONE chunk (a sub-chunk
        # budget floors there rather than silently uncapping)
        caps_c = np.asarray(
            [max(1, int(c) // chunk_rows) if c and c > 0 else 0
             for c in caps_rows],
            np.float64,
        )
    w = np.maximum(
        np.asarray(weights, np.float64), _WEIGHT_FLOOR
        * max(float(np.mean(weights)), 1e-12),
    )
    chunks, over = _apportion(n_chunks, w, caps_c)
    extents: List[Tuple[int, int]] = []
    start = 0
    for r in range(world):
        rows = min(int(chunks[r]) * chunk_rows, n - start)
        rows = max(rows, 0)
        extents.append((start, rows))
        start += rows
    # rounding can leave a sub-chunk tail uncovered when a capped rank
    # absorbed the last whole chunk: hand the tail to the last rank
    # with any rows (the global tail is the one sub-chunk boundary)
    if start < n:
        for r in range(world - 1, -1, -1):
            s, rows = extents[r]
            if rows > 0 or r == 0:
                extents[r] = (s, rows + (n - start))
                break
    if sum(rows for _, rows in extents) != n:
        raise BalanceError(
            f"planner bug: extents {extents} do not cover {n} rows"
        )
    return extents, over


def host_caps_rows(capworld: CapabilityWorld, row_bytes: int,
                   backing: str) -> Optional[List[int]]:
    """Per-rank row caps from the gathered host budgets: a
    memory-backed shard must fit ``_HOST_FRACTION`` of its rank's host
    budget (disk/spill-backed sources stream O(chunk) host and are
    uncapped).  0 entries = uncapped."""
    if backing in ("disk", "spill") or row_bytes <= 0:
        return None
    caps = []
    for b in capworld.host:
        caps.append(
            int(b * _HOST_FRACTION / row_bytes) if b > 0 else 0
        )
    if all(c == 0 for c in caps):
        return None
    return caps


def plan_block_offsets(
    n_keys: int, weights: Sequence[float],
    caps_keys: Optional[Sequence[int]] = None,
    deadband: float = DEADBAND,
) -> Optional[np.ndarray]:
    """Capability-weighted block boundaries for the block-ALS user axis:
    ``(world + 1,)`` key offsets proportional to weight, each block
    non-empty when ``n_keys >= world``.  Returns None when the weights
    sit within ``deadband`` of equal — the caller keeps the exact
    uniform ``ceil(n/world)`` layout, so homogeneous worlds (and the 2-D
    sharded-item layout, whose identity mapping REQUIRES uniform blocks
    — see ops/als_block.prepare_block_inputs) are untouched."""
    world = len(weights)
    if world <= 1:
        return None
    w = np.asarray(weights, np.float64)
    w = w / max(float(w.mean()), 1e-12)
    if float(np.max(np.abs(w - 1.0))) <= deadband:
        return None
    n = int(n_keys)
    caps = None
    if caps_keys is not None:
        caps = np.asarray(
            [int(c) if c and c > 0 else 0 for c in caps_keys], np.float64
        )
    keys, _ = _apportion(n, w, caps)
    if n >= world:
        # every block must own at least one key (block runners assume a
        # non-degenerate local row range); steal from the largest
        for r in range(world):
            while keys[r] < 1:
                donor = int(np.argmax(keys))
                if keys[donor] <= 1:
                    break
                keys[donor] -= 1
                keys[r] += 1
    offsets = np.zeros((world + 1,), np.int64)
    offsets[1:] = np.cumsum(keys)
    offsets[-1] = n
    return offsets


# fraction of a rank's HBM budget its block-ALS key share may imply in
# resident factor/moment state (the rest is the edge tables, the
# replicated other side, and XLA temporaries)
_HBM_BLOCK_FRACTION = 0.25


def block_offsets(
    n_keys: int, mesh_world: int, bytes_per_key: int = 0,
    capworld: Optional[CapabilityWorld] = None,
) -> Optional[np.ndarray]:
    """Capability-weighted user-block offsets for the REPLICATED-item
    block-ALS layout, or None to keep the uniform split (disarmed,
    deadband, or an irregular mesh/process ratio).  Each process's
    capability weight spreads over its mesh slots (blocks are per
    device, capabilities per host); ``bytes_per_key`` prices a block's
    resident factor+moment state against the rank's HBM budget so a
    fast-but-small-HBM rank is not handed more keys than it can hold
    (the membudget pricing).  The 2-D sharded-item layout must NOT use
    this — its identity mapping requires uniform blocks
    (ops/als_block.prepare_block_inputs); the models/als dispatch only
    consults it on the replicated layout."""
    cfg = get_config()
    nproc = _world()
    if capworld is None:
        if not armed(nproc, cfg):
            return None
        capworld = world_capabilities(nproc)
    slots = max(1, int(mesh_world) // capworld.world)
    if capworld.world * slots != int(mesh_world):
        return None  # irregular slot layout: keep the uniform split
    w = np.repeat(capworld.weights, slots)
    caps = None
    if bytes_per_key > 0:
        caps = []
        for b in capworld.hbm:
            per_slot = (
                int(b * _HBM_BLOCK_FRACTION / (slots * bytes_per_key))
                if b > 0 else 0
            )
            caps.extend([per_slot] * slots)
    offsets = plan_block_offsets(n_keys, w, caps_keys=caps)
    if offsets is not None:
        log.info(
            "balance: capability-weighted block offsets (%s): %s",
            capworld.origin, [int(o) for o in offsets],
        )
        if _rank() == 0:
            _tm.counter(
                "oap_balance_block_plans_total",
                help="Capability-weighted block-ALS layouts planned",
            ).inc()
    return offsets


# ---------------------------------------------------------------------------
# the shard plan + balanced source views
# ---------------------------------------------------------------------------


class ShardPlan:
    """One world's live extent assignment.  Extents are read at each
    pass's iteration start and may be re-planned by the controller
    BETWEEN passes (the consumer thread owns both sides: streamed
    passes fully close their prefetcher before the reduction that
    precedes :func:`observe_pass`, so no producer thread is alive
    during a swap)."""

    def __init__(self, n_rows: int, chunk_rows: int,
                 capworld: CapabilityWorld, origin: str,
                 extents: List[Tuple[int, int]], over_cap: bool,
                 caps_rows: Optional[List[int]] = None):
        self.n_rows = int(n_rows)
        self.chunk_rows = int(chunk_rows)
        self.world = capworld.world
        self.origin = origin
        self.over_cap = bool(over_cap)
        self.caps_rows = caps_rows
        self._capworld = capworld
        self._lock = threading.Lock()
        self._extents = list(extents)
        self._weights = np.array(capworld.weights, np.float64)

    def extents(self) -> List[Tuple[int, int]]:
        with self._lock:
            return list(self._extents)

    def local_extent(self, rank: int) -> Tuple[int, int]:
        with self._lock:
            return self._extents[rank]

    def weights(self) -> np.ndarray:
        with self._lock:
            return np.array(self._weights)

    def set_extents(self, extents: List[Tuple[int, int]],
                    weights: np.ndarray) -> None:
        with self._lock:
            self._extents = list(extents)
            self._weights = np.array(weights, np.float64)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            extents = list(self._extents)
            weights = [round(float(w), 4) for w in self._weights]
        out: Dict[str, Any] = {
            "world": self.world,
            "origin": self.origin,
            "chunk_rows": self.chunk_rows,
            "n_rows": self.n_rows,
            "weights": weights,
            "extents": [[int(s), int(r)] for s, r in extents],
        }
        if self.over_cap:
            out["over_cap"] = True
        if self.caps_rows is not None:
            out["caps_rows"] = [int(c) for c in self.caps_rows]
        return out


def make_plan(
    n_rows: int, chunk_rows: int, *, row_bytes: int = 0,
    backing: str = "memory", world: Optional[int] = None,
    capworld: Optional[CapabilityWorld] = None,
) -> ShardPlan:
    """Build (and activate) the shard plan for one global table: armed,
    weights come from the gathered capability world (probe/pinned) and
    host-budget caps price the extents; disarmed, the plan is the equal
    layout (origin ``"equal"``) — same machinery, so equal-vs-weighted
    comparisons run the identical code path."""
    cfg = get_config()
    world = _world() if world is None else int(world)
    caps_rows = None
    if armed(world, cfg):
        if capworld is None and world != _world():
            raise BalanceError(
                f"cannot plan a {world}-rank world from a "
                f"{_world()}-process one without an explicit capworld "
                "(the capability gather only covers live ranks)"
            )
        cw = capworld or world_capabilities(world)
        origin = cw.origin
        caps_rows = host_caps_rows(cw, row_bytes, backing)
    else:
        cw = CapabilityWorld(
            world=world, weights=np.ones((world,)),
            raw=np.ones((world,)),
            origins=tuple([ORIGIN_EQUAL] * world),
            hbm=np.zeros((world,)), host=np.zeros((world,)),
        )
        origin = ORIGIN_EQUAL
    extents, over = plan_extents(
        n_rows, chunk_rows, cw.weights, caps_rows=caps_rows
    )
    plan = ShardPlan(
        n_rows, chunk_rows, cw, origin, extents, over, caps_rows
    )
    if over:
        log.warning(
            "balance: per-rank host caps infeasible for %d rows — "
            "extents overflow the budget proportionally (advisory)",
            n_rows,
        )
    if _rank() == 0:
        _tm.counter(
            "oap_balance_plans_total",
            help="Shard plans built by the balance planner",
        ).inc()
        for r, (_, rows) in enumerate(extents):
            _tm.gauge(
                "oap_balance_extent_rows", {"rank": str(r)},
                help="Rows assigned to each rank by the current plan",
            ).set(float(rows))
    activate(plan)
    return plan


class BalancedView(ChunkSource):
    """A rank's live view of one globally-shared table: a real
    :class:`~oap_mllib_tpu.data.stream.ChunkSource` (so the models'
    streamed routing, weight lockstep validation, and the resilience
    ladder treat it like any other source) whose row range is the
    plan's CURRENT extent, read at each pass's iteration start — a
    re-plan between passes moves rows across ranks with no source
    rebuild.  ``data`` is anything row-sliceable with ``.shape``
    (ndarray, memmap, an ``np.load(mmap_mode="r")`` array)."""

    def __init__(self, data, plan: ShardPlan, chunk_rows: int,
                 rank: Optional[int] = None):
        if getattr(data, "ndim", len(getattr(data, "shape", ()))) != 2:
            raise ValueError("BalancedView needs 2-D row-sliceable data")
        self._data = data
        self._plan = plan
        self._rank = _rank() if rank is None else int(rank)
        if not (0 <= self._rank < plan.world):
            raise ValueError(
                f"rank {self._rank} outside plan world {plan.world}"
            )
        super().__init__(
            self._pieces, int(data.shape[1]), chunk_rows,
            n_rows=plan.local_extent(self._rank)[1],
            dtype=np.dtype(getattr(data, "dtype", np.float32)),
            backing="memory",
        )
        if plan.chunk_rows % self.chunk_rows and \
                self.chunk_rows % plan.chunk_rows:
            raise ValueError(
                f"view chunk_rows {self.chunk_rows} must divide (or be a "
                f"multiple of) the plan's {plan.chunk_rows} — extents are "
                "quantized to the plan's chunk width"
            )

    @property
    def plan(self) -> ShardPlan:
        return self._plan

    def _pieces(self):
        start, rows = self._plan.local_extent(self._rank)
        cr = self.chunk_rows
        for lo in range(0, rows, cr):
            take = min(cr, rows - lo)
            yield np.asarray(
                self._data[start + lo: start + lo + take], self.dtype
            )

    def with_chunk_rows(self, chunk_rows: int) -> "BalancedView":
        """Resilience-ladder re-chunk (geometric OOM rung): same plan,
        same extent, narrower chunks — extents stay aligned because the
        halved width divides the plan's quantum."""
        return BalancedView(
            self._data, self._plan, chunk_rows, rank=self._rank
        )

    def __iter__(self):
        # refresh the expected row count from the LIVE extent before
        # delegating to the base walk (its cross-pass determinism check
        # would otherwise reject the first pass after a re-plan)
        self._n_rows = self._plan.local_extent(self._rank)[1]
        return super().__iter__()


def local_sources(
    x, sample_weight=None, chunk_rows: Optional[int] = None,
    plan: Optional[ShardPlan] = None, rank: Optional[int] = None,
):
    """Build this rank's balanced source view(s) over one GLOBAL table
    (the capability-weighted replacement for hand-slicing equal shards):
    every rank calls this with the SAME ``x`` (and optional per-row
    ``sample_weight`` vector) and receives a ChunkSource-compatible view
    of its planned extent; the weight view shares the data view's plan,
    so the two stay in lockstep across re-plans.  Returns ``source`` or
    ``(source, weight_source)``."""
    from oap_mllib_tpu.data.stream import DEFAULT_CHUNK_ROWS

    if getattr(x, "ndim", 0) != 2:
        x = np.asarray(x)
        if x.ndim != 2:
            raise ValueError(f"expected 2-D data, got shape {x.shape}")
    chunk_rows = DEFAULT_CHUNK_ROWS if chunk_rows is None else int(
        chunk_rows)
    from oap_mllib_tpu.data.bucketing import bucket_rows

    cr = bucket_rows(chunk_rows)
    if plan is None:
        plan = make_plan(
            int(x.shape[0]), cr,
            row_bytes=int(x.shape[1]) * np.dtype(
                getattr(x, "dtype", np.float32)).itemsize,
            backing="memory",
        )
    src = BalancedView(x, plan, cr, rank=rank)
    if sample_weight is None:
        return src
    w = np.asarray(sample_weight, np.float64).reshape(-1, 1)
    if w.shape[0] != x.shape[0]:
        raise ValueError(
            f"sample_weight rows {w.shape[0]} != data rows {x.shape[0]}"
        )
    return src, BalancedView(w, plan, cr, rank=rank)


# ---------------------------------------------------------------------------
# the live straggler controller (module state, reset per fit)
# ---------------------------------------------------------------------------

# tracked (utils/locktrace.py): the /healthz handler thread reads the
# active plan + decisions while fit passes write them
_state_lock = locktrace.TrackedLock("balance.state", threading.Lock())
_active: Optional[ShardPlan] = None
_skews: List[float] = []
_over_count = 0
_streak_rank: Optional[int] = None
_streak = 0
_decisions: List[Dict[str, Any]] = []
_hint: Optional[Dict[str, Any]] = None


def activate(plan: ShardPlan) -> None:
    """Register ``plan`` as the fit's live plan (the controller's
    re-plan target and the summary's decision trail)."""
    global _active
    with _state_lock:
        _active = plan


def active_plan() -> Optional[ShardPlan]:
    with _state_lock:
        return _active


def deactivate() -> None:
    global _active
    with _state_lock:
        _active = None


def observe_pass(phase: str, frames) -> Optional[Dict[str, Any]]:
    """Controller seam, called from ops/stream_ops._fleet_pass with the
    SAME gathered per-rank frames every rank holds (identical data →
    identical decision → rank-uniform extents, no extra collective).
    Returns the decision record when a re-plan fired (tests/gate)."""
    frames = np.asarray(frames, np.float64)
    if frames.ndim != 2 or frames.shape[0] < 1:
        return None
    world = frames.shape[0]
    cfg = get_config()
    if not armed(world, cfg):
        return None
    with _state_lock:
        plan = _active
    if plan is None or plan.world != world:
        return None
    thr = rebalance_threshold_cfg(cfg)
    pat = rebalance_patience_cfg(cfg)
    walls = frames[:, 0]
    mean = float(walls.mean())
    skew = float(walls.max() / mean) if mean > 0 else 1.0
    slowest = int(np.argmax(walls))
    global _over_count, _streak_rank, _streak
    with _state_lock:
        _skews.append(skew)
        over = skew > thr
        _over_count = _over_count + 1 if over else 0
        if over and slowest == _streak_rank:
            _streak += 1
        elif over:
            _streak_rank, _streak = slowest, 1
        else:
            _streak_rank, _streak = None, 0
        over_count = _over_count
        streak = _streak
        skews = list(_skews)
        n_replans = len(_decisions)
    if not over or over_count < pat:
        return None
    from oap_mllib_tpu.telemetry.fleet import _trend

    trend = _trend(skews[-max(2 * pat, 4):])
    if trend == "falling":
        return None  # a warming-up relaunch is healing itself
    if phase not in _REPLAN_PHASES:
        return None
    if n_replans >= _MAX_REPLANS or streak >= 2 * pat and n_replans > 0:
        _maybe_hint(plan, slowest, skew, streak, cfg)
        if n_replans >= _MAX_REPLANS:
            return None
    return _replan(plan, frames, skew, slowest, trend)


def _replan(plan: ShardPlan, frames: np.ndarray, skew: float,
            slowest: int, trend: str) -> Optional[Dict[str, Any]]:
    walls = frames[:, 0]
    old_extents = plan.extents()
    rows = np.asarray([r for _, r in old_extents], np.float64)
    # measured effective throughput = rows this rank processed / its
    # wall; a zero-extent rank measures nothing and keeps its weight
    with np.errstate(divide="ignore", invalid="ignore"):
        meas = np.where(
            (walls > 0) & (rows > 0),
            rows / np.maximum(walls, 1e-9), 0.0,
        )
    cur = plan.weights()
    active_sel = meas > 0
    if not active_sel.any():
        return None
    meas_n = np.array(cur)
    meas_norm = meas[active_sel] / meas[active_sel].mean()
    meas_n[active_sel] = meas_norm
    new_w = _EMA * cur + (1.0 - _EMA) * meas_n
    new_w = np.maximum(new_w / new_w.mean(), _WEIGHT_FLOOR)
    new_extents, _ = plan_extents(
        plan.n_rows, plan.chunk_rows, new_w, caps_rows=plan.caps_rows
    )
    decision = {
        "pass": len(_skews),
        "skew_ratio": round(skew, 4),
        "slowest_rank": slowest,
        "trend": trend,
        "weights": [round(float(w), 4) for w in new_w],
        "old_extents": [[int(s), int(r)] for s, r in old_extents],
        "new_extents": [[int(s), int(r)] for s, r in new_extents],
    }
    global _over_count
    if new_extents == old_extents:
        decision["noop"] = True
        with _state_lock:
            _over_count = 0  # nothing to move; stop re-deciding each pass
            _decisions.append(decision)
        return decision
    plan.set_extents(new_extents, new_w)
    with _state_lock:
        _over_count = 0
        _decisions.append(decision)
    if _rank() == 0:
        _tm.counter(
            "oap_balance_replans_total",
            help="Live extent re-plans by the straggler controller",
        ).inc()
        for r, (_, rws) in enumerate(new_extents):
            _tm.gauge(
                "oap_balance_extent_rows", {"rank": str(r)},
                help="Rows assigned to each rank by the current plan",
            ).set(float(rws))
    from oap_mllib_tpu.telemetry import flightrec

    if flightrec.enabled():
        flightrec.record(
            "balance", "replan",
            f"skew={skew:.2f} slowest=r{slowest}",
        )
    log.warning(
        "balance: re-planned extents (skew %.2f, slowest rank %d, "
        "trend %s): %s -> %s", skew, slowest, trend,
        [r for _, r in old_extents], [r for _, r in new_extents],
    )
    return decision


def _maybe_hint(plan: ShardPlan, rank: int, skew: float, streak: int,
                cfg) -> None:
    """Persistent-offender escalation: record (and, with the recovery
    sideband armed, write) a supervisor hint naming the rank that stayed
    slowest through the controller's attempts — the shrink/evict path is
    the supervisor's, not ours (utils/supervisor.py reads the hint)."""
    global _hint
    with _state_lock:
        if _hint is not None:
            return
        _hint = {
            "schema": 1,
            "rank": int(rank),
            "skew_ratio": round(float(skew), 4),
            "streak_passes": int(streak),
            "replans": len(_decisions),
            "reason": "persistent straggler despite re-planning",
        }
        hint = dict(_hint)
    _tm.counter(
        "oap_balance_supervisor_hints_total",
        help="Persistent-straggler hints handed to the supervisor",
    ).inc()
    log.warning(
        "balance: rank %d is a persistent straggler (skew %.2f for %d "
        "passes despite re-planning) — handing to the supervisor's "
        "shrink/evict path", rank, skew, streak,
    )
    if cfg.crash_dir and _rank() == 0:
        import os

        from oap_mllib_tpu.data import io as _io

        try:
            os.makedirs(cfg.crash_dir, exist_ok=True)
            _io.atomic_write_json(
                os.path.join(cfg.crash_dir, HINT_FILENAME), hint
            )
        except OSError as e:  # noqa: PERF203 — hint is advisory
            log.warning("balance: hint write failed: %s", e)


def decisions() -> List[Dict[str, Any]]:
    with _state_lock:
        return list(_decisions)


def summary_block(world: int) -> Optional[Dict[str, Any]]:
    """The per-fit ``balance`` block, or None when no plan is active."""
    with _state_lock:
        plan = _active
        dec = list(_decisions)
        hint = dict(_hint) if _hint is not None else None
        passes = len(_skews)
    if plan is None:
        return None
    block = dict(plan.as_dict())
    block["enabled"] = armed(world)
    block["passes_observed"] = passes
    block["replans"] = dec
    if hint is not None:
        block["supervisor_hint"] = hint
    return block


def finalize_fit(summary, root) -> None:
    """Fit-boundary hook (telemetry/export.finalize_fit): land the
    ``balance`` block + a ``balance`` child span, then reset the per-fit
    controller state.  The plan itself stays active (its adapted extents
    warm-start the next fit over the same sources); one config-read +
    None-check when the plane never planned anything."""
    with _state_lock:
        plan = _active
    if plan is None:
        return
    try:
        world = _world()
    except Exception:  # noqa: BLE001 — exposition must not kill a fit
        world = plan.world
    block = summary_block(world)
    _reset_fit_state()
    if summary is None or block is None:
        return
    if isinstance(summary, dict):
        summary["balance"] = block
    else:
        summary.balance = block
    if root is not None:
        root.node("balance").attrs.update({
            "origin": block["origin"],
            "world": block["world"],
            "replans": len(block["replans"]),
            "weights": block["weights"],
        })


def _reset_fit_state() -> None:
    global _over_count, _streak_rank, _streak, _hint
    with _state_lock:
        _skews.clear()
        _decisions.clear()
        _over_count = 0
        _streak_rank, _streak = None, 0
        _hint = None


def cached_capability() -> float:
    """This rank's capability as already gathered/pinned, or 0.0 when
    nothing has been probed yet (the fleet frame's 'unknown' marker —
    reading it must never trigger a probe or a collective)."""
    with _sync_lock:
        for cw in _sync_cache.values():
            r = _rank()
            if r < cw.world:
                return float(cw.weights[r])
    return 0.0


def _reset_for_tests() -> None:
    global _active
    with _sync_lock:
        _sync_cache.clear()
    with _state_lock:
        _active = None
    _reset_fit_state()
