"""Collective facade: named-axis collectives over the mesh.

The reference funnels every cross-rank exchange through four oneCCL
primitives carrying serialized oneDAL archives: ``broadcast`` (2-phase,
length then payload — KMeansDALImpl.cpp:49-59), ``allgatherv``
(KMeansDALImpl.cpp:97-99, PCADALImpl.cpp:111-113), and
``alltoall``/``alltoallv`` (ALSShuffle.cpp:92-109).  Because XLA programs
have static shapes, the TPU-native facade exchanges fixed-shape tensors
(padded where sizes differ per rank) and compiles to ICI/DCN collectives.

These wrappers are `shard_map`-based so they can be called eagerly on
sharded arrays (useful in drivers and tests); inside jitted estimator
kernels the same collectives are emitted implicitly by XLA from sharding
annotations, or explicitly via `lax.psum` etc. under `shard_map`.

Every facade dispatch is instrumented (ISSUE 4): per-op invocation
counts, payload bytes, and dispatch wall go to the process metrics
registry (``oap_collective_*``, telemetry/metrics.py) and onto the
thread's active span (telemetry/spans.current_span) — DrJAX and the
array-redistribution work (PAPERS.md) both name collectives as the
dominant, hardest-to-see cost at scale, and scattered wall prints can't
see them at all.  The wall is dispatch time (trace + compile on the
first shape, async dispatch after), not on-wire DMA — the profiler
trace layer owns that.
"""

from __future__ import annotations

import time

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.telemetry.spans import current_span
from oap_mllib_tpu.utils import faults, recovery, sanitizers
from oap_mllib_tpu.utils.jax_compat import shard_map


def _shard_map(f, mesh, in_specs, out_specs):
    # check_vma=False: outputs of all_gather/psum ARE replicated over the
    # data axis, but the static replication checker can't always prove it
    # for P(None, ...) out_specs on a multi-axis mesh.
    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )


def _payload_bytes(x) -> int:
    """Per-PROCESS payload bytes of one facade operand: the fraction of
    the global array whose shards live on this process's devices — the
    bytes this rank actually contributes to the wire.  Booking the full
    unsharded ``nbytes`` (the pre-ISSUE-7 behavior) over-counted
    shard_map-inner traffic world-fold: every rank claimed the whole
    array, so a 2-process world's byte counters summed to 2x the global
    payload.  Host arrays (no sharding) and single-process worlds book
    the full size, unchanged."""
    nbytes = int(getattr(x, "nbytes", 0) or 0)
    sharding = getattr(x, "sharding", None)
    if sharding is None or nbytes == 0:
        return nbytes
    try:
        devs = sharding.device_set
        total = len(devs)
        pidx = jax.process_index()
        local = sum(1 for d in devs if d.process_index == pidx)
        if total:
            return (nbytes * local) // total
    except Exception:
        pass  # exotic shardings fall back to the global size
    return nbytes


def _instrumented(op: str, x: jax.Array, dispatch):
    """Run one facade dispatch with telemetry: invocation count, payload
    bytes (this process's shard share — see :func:`_payload_bytes`),
    and dispatch wall, booked to the registry and the active span; with
    the ``collective`` sanitizer armed, the dispatch signature is also
    fingerprinted and cross-checked across ranks first
    (utils/sanitizers.note_collective).  The dispatch itself is a fault
    site (``collective.dispatch`` — where a dead peer surfaces) and runs
    under the recovery plane's deadline watchdog when
    ``Config.collective_timeout`` is armed (utils/recovery
    .guarded_dispatch; disarmed = one config check)."""
    faults.maybe_fault("collective.dispatch")
    nbytes = _payload_bytes(x)
    axis = get_config().data_axis
    if flightrec.enabled():
        # the dispatch fingerprint lands in the event ring BEFORE the
        # cross-check/dispatch, so a divergence diagnosis or a timeout
        # post-mortem can point at this exact event's seq
        flightrec.record(
            "collective", op,
            f"{axis}|{tuple(getattr(x, 'shape', ()))}"
            f"|{getattr(x, 'dtype', '')}",
        )
    sanitizers.note_collective(
        op, axis, getattr(x, "shape", ()), getattr(x, "dtype", ""),
    )
    t0 = time.perf_counter()
    out = recovery.guarded_dispatch(op, axis, dispatch)
    dt = time.perf_counter() - t0
    lab = {"op": op}
    _tm.counter("oap_collective_ops_total", lab,
                help="Collective facade dispatches by op").inc()
    _tm.counter("oap_collective_bytes_total", lab,
                help="Operand bytes through the collective facade"
                ).inc(nbytes)
    _tm.histogram("oap_collective_dispatch_seconds", lab,
                  help="Per-dispatch wall (compile included on first shape)"
                  ).observe(dt)
    sp = current_span()
    if sp is not None:
        sp.note_collective(op, nbytes, dt)
    return out


# -- in-jit collective seam --------------------------------------------------
# Estimator kernels running under shard_map cannot call the eager facade
# below (it would nest shard_map), so they route their named-axis
# collectives through these thin wrappers instead — every collective in
# the package is then emitted at one seam (oaplint rule R3,
# raw-collective; the DrJAX argument that the map-reduce primitives are
# THE explicit composition point, PAPERS.md arXiv:2403.07128).  The
# counter increments at TRACE time — once per compiled program, not per
# dispatch — so ``oap_collective_emitted_total`` is a census of
# collectives emitted into programs, complementing the facade's
# per-dispatch ``oap_collective_ops_total``.


def _note_emitted(op: str) -> None:
    _tm.counter(
        "oap_collective_emitted_total", {"op": op},
        help="Collective ops emitted into compiled programs "
             "(trace-time census, not a dispatch count)",
    ).inc()


def psum(x, axis_name):
    """``lax.psum`` at the collective seam (shard_map/jit bodies)."""
    _note_emitted("psum")
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    """``lax.pmean`` at the collective seam."""
    _note_emitted("pmean")
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, **kwargs):
    """``lax.all_gather`` at the collective seam (axis/tiled kwargs
    pass through unchanged)."""
    _note_emitted("all_gather")
    return lax.all_gather(x, axis_name, **kwargs)


def ppermute(x, axis_name, perm):
    """``lax.ppermute`` at the collective seam."""
    _note_emitted("ppermute")
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, **kwargs):
    """``lax.all_to_all`` at the collective seam (split/concat axis
    kwargs pass through unchanged)."""
    _note_emitted("all_to_all")
    return lax.all_to_all(x, axis_name, **kwargs)


def broadcast(x: jax.Array, mesh: Mesh, root: int = 0) -> jax.Array:
    """Replicate the root shard of a row-sharded array to all devices.

    Analog of the reference's serialized-centroid broadcast
    (KMeansDALImpl.cpp:49-59); here it is one compiled collective, no
    length pre-exchange needed.
    """
    cfg = get_config()
    axis = cfg.data_axis

    def _bcast(shard):
        full = lax.all_gather(shard, axis, tiled=True)
        size = shard.shape[0]
        return lax.dynamic_slice_in_dim(full, root * size, size, axis=0)

    spec = P(axis, *([None] * (x.ndim - 1)))
    return _instrumented(
        "broadcast", x,
        lambda: _shard_map(_bcast, mesh, (spec,), spec)(x),
    )


def allgather_rows(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Gather row shards onto every device (replicated result).

    Analog of allgatherv of serialized partials (PCADALImpl.cpp:111-113),
    with fixed-shape shards instead of variable-length archives.
    """
    cfg = get_config()
    axis = cfg.data_axis

    def _ag(shard):
        return lax.all_gather(shard, axis, tiled=True)

    in_spec = P(axis, *([None] * (x.ndim - 1)))
    return _instrumented(
        "allgather_rows", x,
        lambda: _shard_map(_ag, mesh, (in_spec,), P(*([None] * x.ndim)))(x),
    )


def allreduce_sum(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Sum identically-shaped per-device values; replicated result.

    The reference has no direct allreduce — it emulates one with
    allgatherv + a root-side master step (KMeansDALImpl.cpp:97-131); on
    TPU a psum rides ICI directly.
    """
    cfg = get_config()
    axis = cfg.data_axis

    def _ar(shard):
        return lax.psum(shard, axis)

    in_spec = P(axis, *([None] * (x.ndim - 1)))
    out_spec = P(*([None] * x.ndim))
    return _instrumented(
        "allreduce_sum", x,
        lambda: _shard_map(_ar, mesh, (in_spec,), out_spec)(x),
    )


def alltoall_rows(x: jax.Array, mesh: Mesh) -> jax.Array:
    """All-to-all exchange of equal row blocks.

    Each device's shard is viewed as ``world_size`` equal sub-blocks along
    rows; sub-block j goes to device j.  Analog of the reference's rating
    shuffle ``alltoallv`` (ALSShuffle.cpp:92-109) after padding each bucket
    to the max bucket size (survey §7.3 variable-length-exchange note).
    """
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]

    def _a2a(shard):
        blocks = shard.reshape((world, shard.shape[0] // world) + shard.shape[1:])
        out = lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0)
        return out.reshape(shard.shape)

    spec = P(axis, *([None] * (x.ndim - 1)))
    return _instrumented(
        "alltoall_rows", x,
        lambda: _shard_map(_a2a, mesh, (spec,), spec)(x),
    )
