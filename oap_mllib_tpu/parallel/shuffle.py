"""Distributed ratings shuffle: the ALS 2-D block data plane.

Reference flow (survey §3.3): each rank packs its ratings into a byte
buffer (ALSDALImpl.scala:172-182), calls native cShuffleData which buckets
records by user block and exchanges them via oneCCL alltoall (lengths) +
alltoallv (payload), then sorts and builds a one-based CSR block
(ALSShuffle.cpp:62-127, OneDAL.cpp:109-145).

TPU-native redesign:
- Host prep per rank (bucket, sort, count) is the C++ layer
  (native/shuffle.cpp) — same role as the reference's host-side bucketing.
- The exchange is ONE compiled XLA ``all_to_all`` of a fixed-shape padded
  tensor (survey §2.6: variable-length alltoallv becomes max-bucket-padded
  static shapes; the size pre-exchange disappears because shapes are
  static).
- The received block becomes a zero-based local CSR (data/table.CSRTable)
  with user ids rebased by the block offset — the userOffset bookkeeping
  the reference threads through ALSResult (ALSDALImpl.cpp:529-575).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data.table import CSRTable


@dataclasses.dataclass
class ShuffledBlocks:
    """Per-rank user-block shards after the exchange (host-side view)."""

    blocks: List[CSRTable]  # one per rank; local (rebased) user rows
    block_offsets: np.ndarray  # (world + 1,) global user-id boundaries
    n_items: int


def _pad_bucket(arr: np.ndarray, size: int, fill) -> np.ndarray:
    pad = size - arr.shape[0]
    if pad <= 0:
        return arr[:size]
    return np.concatenate([arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)])


def exchange_ratings(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, np.ndarray]:
    """Run the block shuffle through a compiled all_to_all on the mesh.

    The input is split evenly across ranks in arrival order (the arbitrary
    Spark partitioning analog); the output is (users, items, ratings,
    valid) sharded so rank b holds exactly user-block b, padded to the
    global max bucket size.  Returns device arrays + block offsets.
    """
    from oap_mllib_tpu import native

    if n_users >= 2**31 or (len(items) and int(np.max(items)) >= 2**31):
        raise ValueError(
            "ids must fit int32 (the on-device CSR index dtype); "
            f"got n_users={n_users}, max item={int(np.max(items))}"
        )
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    kpb = max(1, math.ceil(n_users / world))
    offsets = np.minimum(np.arange(world + 1) * kpb, n_users)

    n = len(users)
    per_src = math.ceil(n / world)

    # host prep per source rank: bucket + sort + count (native C++)
    src_buckets = []  # [src][dst] -> (u, i, r) arrays
    max_bucket = 1
    for s in range(world):
        lo, hi = s * per_src, min((s + 1) * per_src, n)
        us, it, rs, counts, _ = native.shuffle_prep(
            users[lo:hi], items[lo:hi], ratings[lo:hi], kpb, world
        )
        row = []
        pos = 0
        for b in range(world):
            c = int(counts[b])
            row.append((us[pos:pos + c], it[pos:pos + c], rs[pos:pos + c]))
            max_bucket = max(max_bucket, c)
            pos += c
        src_buckets.append(row)

    # pack into (world_src * world_dst * max_bucket, 4) padded records
    rec = np.zeros((world, world, max_bucket, 4), dtype=np.float64)
    for s in range(world):
        for b in range(world):
            u, i, r = src_buckets[s][b]
            c = len(u)
            rec[s, b, :c, 0] = u
            rec[s, b, :c, 1] = i
            rec[s, b, :c, 2] = r
            rec[s, b, :c, 3] = 1.0  # valid flag
    flat = rec.reshape(world * world * max_bucket, 4)

    # ONE compiled all_to_all: rank s's bucket b -> rank b
    from oap_mllib_tpu.parallel.collective import alltoall_rows

    sharded = jax.device_put(
        jnp.asarray(flat), NamedSharding(mesh, P(axis, None))
    )
    exchanged = alltoall_rows(sharded, mesh)  # rank b now holds all s's bucket b

    out_u = exchanged[:, 0].astype(jnp.int32)
    out_i = exchanged[:, 1].astype(jnp.int32)
    out_r = exchanged[:, 2].astype(jnp.float32)
    out_valid = exchanged[:, 3].astype(jnp.float32)
    return out_u, out_i, out_r, out_valid, offsets


def shuffle_to_blocks(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
    n_items: int,
) -> ShuffledBlocks:
    """Full host-visible pipeline: exchange + per-rank local CSR build
    (~ cShuffleData + bufferToCSRNumericTable, ALSDALImpl.scala:107-109)."""
    from oap_mllib_tpu import native

    cfg = get_config()
    world = mesh.shape[cfg.data_axis]
    u, i, r, valid, offsets = exchange_ratings(users, items, ratings, mesh, n_users)

    # pull per-rank shards back to host for CSR construction
    per_rank = u.shape[0] // world
    uh = np.asarray(u).reshape(world, per_rank)
    ih = np.asarray(i).reshape(world, per_rank)
    rh = np.asarray(r).reshape(world, per_rank)
    vh = np.asarray(valid).reshape(world, per_rank)

    blocks = []
    for b in range(world):
        sel = vh[b] > 0
        lo, hi = int(offsets[b]), int(offsets[b + 1])
        local_rows = hi - lo
        blocks.append(
            CSRTable.from_coo(
                uh[b][sel] - lo,  # rebase to local row ids
                ih[b][sel],
                rh[b][sel],
                n_rows=max(local_rows, 1),
                n_cols=n_items,
            )
        )
    return ShuffledBlocks(blocks=blocks, block_offsets=offsets, n_items=n_items)
