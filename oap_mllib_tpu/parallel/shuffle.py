"""Distributed ratings shuffle: the ALS 2-D block data plane.

Reference flow (survey §3.3): each rank packs its ratings into a byte
buffer (ALSDALImpl.scala:172-182), calls native cShuffleData which buckets
records by user block and exchanges them via oneCCL alltoall (lengths) +
alltoallv (payload), then sorts and builds a one-based CSR block
(ALSShuffle.cpp:62-127, OneDAL.cpp:109-145).

TPU-native redesign:
- Host prep per rank (bucket, sort, count) is the C++ layer
  (native/shuffle.cpp) — same role as the reference's host-side bucketing.
- The exchange is ONE compiled XLA ``all_to_all`` of a fixed-shape padded
  tensor (survey §2.6: variable-length alltoallv becomes max-bucket-padded
  static shapes; the size pre-exchange disappears because shapes are
  static).
- The received block becomes a zero-based local CSR (data/table.CSRTable)
  with user ids rebased by the block offset — the userOffset bookkeeping
  the reference threads through ALSResult (ALSDALImpl.cpp:529-575).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data.table import CSRTable
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.jax_compat import shard_map


@dataclasses.dataclass
class ShuffledBlocks:
    """Per-rank user-block shards after the exchange (host-side view)."""

    blocks: List[CSRTable]  # one per rank; local (rebased) user rows
    block_offsets: np.ndarray  # (world + 1,) global user-id boundaries
    n_items: int


def _pad_bucket(arr: np.ndarray, size: int, fill) -> np.ndarray:
    pad = size - arr.shape[0]
    if pad <= 0:
        return arr[:size]
    return np.concatenate([arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)])


_EDGE_MULTIPLE = 2048  # compacted per-rank edges pad to this (chunk factors)


def _local_bucket_build(users, items, ratings, kpb, world, local_sources,
                        offsets=None):
    """Bucket this process's edges by destination block and balance each
    bucket round-robin across the process's local source shards.

    Balancing bounds the padded exchange: the per-(src, dst) bucket max —
    which sets the all_to_all pad size — becomes ~avg over local sources
    instead of whatever the arrival-order split produced.

    ``offsets``: explicit (uneven) block boundaries — the capability-
    weighted layout (parallel/balance.py) buckets by searchsorted
    instead of the uniform kpb division.

    Returns (buckets[s][b] -> (u, i, r), counts (local_sources, world)).
    """
    from oap_mllib_tpu import native

    if offsets is not None:
        us, it, rs, counts, _ = native.shuffle_prep_offsets(
            users, items, ratings, offsets
        )
    else:
        us, it, rs, counts, _ = native.shuffle_prep(
            users, items, ratings, kpb, world
        )
    buckets = [[None] * world for _ in range(local_sources)]
    out_counts = np.zeros((local_sources, world), np.int64)
    pos = 0
    for b in range(world):
        c = int(counts[b])
        ub, ib, rb = us[pos:pos + c], it[pos:pos + c], rs[pos:pos + c]
        pos += c
        for s in range(local_sources):
            sel = slice(s, None, local_sources)  # round-robin split
            buckets[s][b] = (ub[sel], ib[sel], rb[sel])
            out_counts[s, b] = len(ub[sel])
    return buckets, out_counts


def _pack_records(u, i, r, cap):
    """(cap, 4) int32 records: user, item, rating bits, valid flag."""
    rec = np.zeros((cap, 4), np.int32)
    c = len(u)
    rec[:c, 0] = u
    rec[:c, 1] = i
    rec[:c, 2] = r.astype(np.float32).view(np.int32)
    rec[:c, 3] = 1
    return rec


def exchange_ratings(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
    offsets: Optional[np.ndarray] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, np.ndarray]:
    """Run the block shuffle through a compiled all_to_all on the mesh.

    Multi-host contract (the reference's per-rank shuffle,
    ALSDALImpl.scala:95-109): each process passes only its LOCAL ratings;
    bucket prep runs per process, bucket counts are allgathered (the
    reference's alltoall(lengths) analog), and one compiled all_to_all
    moves the padded int32 records.  Memory per process is
    O(local_nnz + local_sources * world * max_bucket) with buckets
    balanced round-robin across local source shards — never the round-1
    O(world^2 * max_bucket) single-host tensor.  After the exchange each
    rank compacts its block valid-first to O(block nnz) rows (the skew
    bound: a hot user block costs its own size, not world * max_bucket).

    Returns (users, items, ratings, valid) block-sharded device arrays +
    block offsets.  Ratings travel as exact f32 bit patterns (int32
    bitcast), ids as int32 — nothing is rounded through a float payload.

    ``offsets``: explicit ``(world + 1,)`` block boundaries — the
    capability-weighted uneven layout (parallel/balance
    .plan_block_offsets); every rank must pass the SAME offsets (they
    are a pure function of the gathered capability world).  None keeps
    the uniform ``ceil(n_users / world)`` split.
    """
    if n_users >= 2**31 or (len(items) and int(np.max(items)) >= 2**31):
        raise ValueError(
            "ids must fit int32 (the on-device CSR index dtype); "
            f"got n_users={n_users}, max item={int(np.max(items))}"
        )
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    nproc = jax.process_count()
    local_sources = max(1, world // nproc)
    kpb = max(1, math.ceil(n_users / world))
    if offsets is None:
        offsets = np.minimum(np.arange(world + 1) * kpb, n_users)
        bucket_offsets = None
    else:
        offsets = np.asarray(offsets, np.int64)
        if len(offsets) != world + 1 or int(offsets[-1]) != n_users:
            raise ValueError(
                f"offsets must be (world+1,)={world + 1} entries ending "
                f"at n_users={n_users}, got {offsets!r}"
            )
        bucket_offsets = offsets

    buckets, counts_local = _local_bucket_build(
        users, items, ratings, kpb, world, local_sources,
        offsets=bucket_offsets,
    )

    # exchange bucket sizes (host metadata, ~ the reference's
    # alltoall(lens) pre-exchange, ALSShuffle.cpp:92-99)
    if nproc > 1:
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(counts_local)
        ).reshape(world, world)
    else:
        counts = counts_local
    max_bucket = max(1, int(counts.max()))

    # pack this process's buckets: (local_sources * world * max_bucket, 4)
    local_rec = np.concatenate(
        [
            _pack_records(*buckets[s][b], max_bucket)
            for s in range(local_sources)
            for b in range(world)
        ],
        axis=0,
    )

    sharding = NamedSharding(mesh, P(axis, None))
    if nproc > 1:
        sharded = jax.make_array_from_process_local_data(sharding, local_rec)
    else:
        sharded = jax.device_put(jnp.asarray(local_rec), sharding)

    # ONE compiled all_to_all: rank s's bucket b -> rank b
    from oap_mllib_tpu.parallel.collective import alltoall_rows

    exchanged = alltoall_rows(sharded, mesh)  # rank b holds all s's bucket b

    # device-side compaction: rank b's true edge count is sum_s counts[s,b];
    # keep valid-first rows so padded memory is O(max block nnz)
    per_block = counts.sum(axis=0)
    per_block_max = int(np.max(per_block))
    cap = max(_EDGE_MULTIPLE, -(-per_block_max // _EDGE_MULTIPLE) * _EDGE_MULTIPLE)
    total = world * max_bucket
    if total < cap:
        # can't take more rows than physically exist; keep the
        # _EDGE_MULTIPLE alignment (power-of-two chunk factors for the
        # normal-equation scan) by rounding the physical size down to the
        # multiple — unless that would drop valid edges, in which case
        # alignment yields to correctness
        aligned_total = (total // _EDGE_MULTIPLE) * _EDGE_MULTIPLE
        cap = aligned_total if aligned_total >= per_block_max else total

    def compact(rows):  # (world * max_bucket, 4) per rank
        order = jnp.argsort(1 - rows[:, 3], stable=True)
        return rows[order[:cap]]

    # one compiled program per (mesh, axis, cap): the old per-call
    # jit(shard_map) closure rebuilt (and re-traced) on every exchange
    compacted = progcache.get_or_build(
        "shuffle.compact",
        (progcache.mesh_fingerprint(mesh), axis, cap),
        lambda: jax.jit(
            shard_map(
                compact, mesh=mesh,
                in_specs=P(axis, None), out_specs=P(axis, None),
                check_vma=False,
            )
        ),
    )(exchanged)

    out_u = compacted[:, 0]
    out_i = compacted[:, 1]
    out_r = jax.lax.bitcast_convert_type(compacted[:, 2], jnp.float32)
    out_valid = compacted[:, 3].astype(jnp.float32)
    return out_u, out_i, out_r, out_valid, offsets


def reshard_factor_rows(
    ids: np.ndarray,
    vals: np.ndarray,
    mesh: Mesh,
    offsets: np.ndarray,
    per: int,
) -> jax.Array:
    """Collective redistribution of factor-table rows onto the mesh's
    block layout — the elastic-worlds restore path (utils/checkpoint.py).

    Each process contributes the host rows it read from an arbitrary
    subset of checkpoint shards (``ids`` (m,) global row ids, ``vals``
    (m, r) float32), every global row appearing on exactly one process.
    Rows are bucketed by destination block under the NEW ``offsets``,
    exchanged through ONE compiled ``all_to_all`` of max-bucket-padded
    int32 records (the exchange_ratings machinery with factor payloads
    instead of rating triples — the portable-collective redistribution
    of arXiv:2112.01075), and scattered into a ``(world * per, r)``
    block-sharded array by a registry-cached jit(shard_map) program.  No
    host ever materializes the full table; factor values travel as exact
    f32 bit patterns (int32 bitcast).  Rows absent from every process's
    input land as zeros (a block's padding rows beyond its boundary).
    """
    ids = np.asarray(ids, np.int64)
    vals = np.asarray(vals, np.float32)
    if ids.size and int(ids.max()) >= 2**31:
        raise ValueError(
            f"factor row ids must fit int32; got max id {int(ids.max())}"
        )
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    nproc = jax.process_count()
    if world % nproc:
        # the bucket round-robin and the (world, world) counts reshape
        # below assume every process addresses world // nproc mesh slots
        # (the exchange_ratings machinery's contract); an uneven split
        # would silently misassign rows, so refuse it loudly
        raise ValueError(
            f"reshard_factor_rows requires the {axis!r} axis size "
            f"({world}) to be a multiple of process_count ({nproc})"
        )
    local_sources = world // nproc
    r = vals.shape[1]

    dst = np.clip(
        np.searchsorted(np.asarray(offsets), ids, side="right") - 1,
        0, world - 1,
    )
    buckets = [[None] * world for _ in range(local_sources)]
    counts_local = np.zeros((local_sources, world), np.int64)
    for b in range(world):
        sel = np.nonzero(dst == b)[0]
        for s in range(local_sources):
            part = sel[s::local_sources]  # round-robin balance (as above)
            buckets[s][b] = part
            counts_local[s, b] = len(part)

    if nproc > 1:
        from jax.experimental import multihost_utils

        # r rides the counts allgather: every process derives the padded
        # record width (r + 2) from ITS vals, and a rank-divergent width
        # would crash or hang the all_to_all with mismatched shapes —
        # diagnose it here instead (shard-less restore ranks get their r
        # from the checkpoint manifest, utils/checkpoint._load)
        payload = np.concatenate(
            [np.asarray([r], np.int64), counts_local.reshape(-1)]
        )
        gathered = np.asarray(multihost_utils.process_allgather(payload))
        peer_r = gathered[:, 0]
        if not (peer_r == r).all():
            raise ValueError(
                "reshard_factor_rows: factor width r diverges across "
                f"ranks: {sorted(set(int(x) for x in peer_r))}"
            )
        counts = gathered[:, 1:].reshape(world, world)
    else:
        counts = counts_local
    max_bucket = max(1, int(counts.max()))

    def _pack(part: np.ndarray) -> np.ndarray:
        rec = np.zeros((max_bucket, r + 2), np.int32)
        c = len(part)
        rec[:c, 0] = ids[part].astype(np.int32)
        rec[:c, 1 : r + 1] = vals[part].view(np.int32)
        rec[:c, r + 1] = 1
        return rec

    local_rec = np.concatenate(
        [_pack(buckets[s][b]) for s in range(local_sources) for b in range(world)],
        axis=0,
    )
    sharding = NamedSharding(mesh, P(axis, None))
    if nproc > 1:
        sharded = jax.make_array_from_process_local_data(sharding, local_rec)
    else:
        sharded = jax.device_put(jnp.asarray(local_rec), sharding)

    from oap_mllib_tpu.parallel.collective import alltoall_rows

    exchanged = alltoall_rows(sharded, mesh)  # rank b holds its block's rows

    def scatter(rows, offs):  # per-rank (world * max_bucket, r + 2) int32
        b = jax.lax.axis_index(axis)
        lo = offs[b]
        valid = rows[:, r + 1] > 0
        # invalid/foreign rows index past the block -> mode="drop"
        idx = jnp.where(valid, rows[:, 0] - lo, per)
        v = jax.lax.bitcast_convert_type(rows[:, 1 : r + 1], jnp.float32)
        return jnp.zeros((per, r), jnp.float32).at[idx].set(v, mode="drop")

    scatter_fn = progcache.get_or_build(
        "shuffle.reshard_scatter",
        (progcache.mesh_fingerprint(mesh), axis, world * max_bucket, per, r),
        lambda: jax.jit(
            shard_map(
                scatter, mesh=mesh,
                in_specs=(P(axis, None), P()), out_specs=P(axis, None),
                check_vma=False,
            )
        ),
    )
    return scatter_fn(exchanged, jnp.asarray(np.asarray(offsets), jnp.int32))


def shuffle_to_blocks(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
    n_items: int,
) -> ShuffledBlocks:
    """Full host-visible pipeline: exchange + per-rank local CSR build
    (~ cShuffleData + bufferToCSRNumericTable, ALSDALImpl.scala:107-109)."""
    from oap_mllib_tpu import native

    cfg = get_config()
    world = mesh.shape[cfg.data_axis]
    u, i, r, valid, offsets = exchange_ratings(users, items, ratings, mesh, n_users)

    # pull per-rank shards back to host for CSR construction
    per_rank = u.shape[0] // world
    uh = np.asarray(u).reshape(world, per_rank)
    ih = np.asarray(i).reshape(world, per_rank)
    rh = np.asarray(r).reshape(world, per_rank)
    vh = np.asarray(valid).reshape(world, per_rank)

    blocks = []
    for b in range(world):
        sel = vh[b] > 0
        lo, hi = int(offsets[b]), int(offsets[b + 1])
        local_rows = hi - lo
        blocks.append(
            CSRTable.from_coo(
                uh[b][sel] - lo,  # rebase to local row ids
                ih[b][sel],
                rh[b][sel],
                n_rows=max(local_rows, 1),
                n_cols=n_items,
            )
        )
    return ShuffledBlocks(blocks=blocks, block_offsets=offsets, n_items=n_items)
