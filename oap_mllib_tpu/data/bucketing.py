"""Shape bucketing: round padded row counts up to geometric buckets.

XLA specializes every program on its input shapes, so a service fitting
many differently-sized datasets recompiles the entire Lloyd / covariance
/ ALS program per distinct row count — seconds of XLA latency per
request shape (the DrJAX observation, PAPERS.md: MapReduce-style JAX
programs amortize precisely when traced shapes are stable).  Bucketing
collapses the shape space: padded row counts round up to a geometric
series (default x2 steps anchored at the shard multiple), so every fit
whose rows land in one bucket reuses one compiled program.  Padding
rows carry mask/weight 0 — the same contract the kernels already rely
on for shard padding — so results match the unbucketed path.

Cost model (docs/user-guide.md "Compile amortization"): a x2 bucket
wastes at most half its rows as masked padding, which costs memory and
per-pass FLOPs proportionally; the win is that the 2nd-through-Nth fit
of ANY size in the bucket pays zero XLA compiles.  ``Config
.shape_bucketing`` tunes the trade: ``"off"`` restores exact padding,
a numeric value sets a gentler growth factor (e.g. ``"1.25"``).
"""

from __future__ import annotations

from typing import Optional

from oap_mllib_tpu.config import get_config


def bucket_factor(value: Optional[str] = None) -> Optional[float]:
    """Resolve ``Config.shape_bucketing`` to a growth factor.

    ``"on"``/``"x2"`` = 2.0 (the default geometric step), ``"off"`` =
    None (exact padding, today's behavior), a numeric string = custom
    factor (must be > 1).  Unknown values raise — a typo must not
    silently disable amortization (the kmeans_kernel/als_kernel
    contract)."""
    raw = get_config().shape_bucketing if value is None else value
    s = str(raw).strip().lower()
    if s == "off":
        return None
    if s in ("on", "x2"):
        return 2.0
    try:
        factor = float(s.lstrip("x"))
    except ValueError:
        raise ValueError(
            "shape_bucketing must be 'on', 'off', 'x2', or a numeric "
            f"growth factor > 1, got {raw!r}"
        ) from None
    if factor <= 1.0:
        raise ValueError(
            f"shape_bucketing factor must be > 1, got {factor}"
        )
    return factor


def bucket_rows(n: int, multiple: int = 1,
                factor: Optional[float] = None) -> int:
    """Smallest bucket >= ``n`` from the geometric series anchored at
    ``multiple`` (each bucket is ceil(prev * factor) rounded up to the
    multiple, so bucketed counts stay shard-divisible).  ``factor``
    None reads the config; bucketing off returns ``n`` rounded up to
    the multiple (exact padding)."""
    if n < 0:
        raise ValueError(f"row count must be >= 0, got {n}")
    multiple = max(1, int(multiple))
    if factor is None:
        factor = bucket_factor()
    exact = -(-max(n, 1) // multiple) * multiple
    if factor is None:
        return exact
    bucket = multiple
    while bucket < n:
        bucket = max(
            bucket + multiple, -(-int(bucket * factor) // multiple) * multiple
        )
    return bucket
