"""Double-buffered async chunk prefetch: overlap host staging with device
compute on every streamed path.

Every streamed route (K-Means/PCA passes in ops/stream_ops.py, ALS edge
uploads in ops/als_stream.py and ops/als_block_stream.py) used to be
strictly serial per chunk: pull from the source, pad/convert on host,
``device_put``, dispatch the step, repeat.  The device sat idle through
each chunk's staging and the host sat idle through each chunk's compute —
BASELINE.md attributes the streamed numbers largely to exactly that
host->device tunnel time.  This module is the shared communication-hiding
stage (cf. arxiv 2112.01075's transfer/compute overlap): a bounded
background thread runs the host half of the pipeline up to
``Config.prefetch_depth`` chunks ahead of the consumer, so chunk N+1's
staging and transfer issue while chunk N's step is still executing.

Contracts:

- **Order and math are untouched.**  Chunks reach the consumer in source
  order whatever the depth; depth only moves WHEN staging happens, so
  results are bit-identical across depths (and depth=1 runs the exact
  pre-pipeline serial loop, no thread at all).
- **Bounded memory.**  The producer owns a semaphore slot per staged
  chunk, acquired BEFORE pulling from the source and released when the
  consumer retires the chunk — the pipeline never holds more than
  ``depth`` staged chunks (queued + consumer-held) nor runs the source
  more than ``depth`` pulls ahead.
- **Fail-fast multi-process semantics.**  A staging failure (source
  error, conversion error, device_put OOM) is captured in the producer
  and re-raised from the consumer's next ``__next__`` — which sits inside
  the caller's ``_PassGuard`` block, so the error rides the next
  collective reduction and every rank fails together instead of peers
  hanging in process_allgather (ops/stream_ops._PassGuard).
- **Buffer retirement.**  With ``retire=True`` the jax arrays of the
  previously consumed chunk are ``delete()``d when the consumer advances
  (the runtime frees them once in-flight steps finish) — the streamed
  paths' donation analog: the consumed chunk's HBM returns to the pool
  immediately instead of at garbage collection, keeping peak device
  memory at O(depth x chunk) even under allocator pressure.
- **Clean shutdown.**  ``close()`` (or the context-manager exit) cancels
  the producer and drains it; abandoning the iterator mid-pass (an early
  break, an exception in the consumer) cannot leave a thread blocked on
  the queue.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import sanitizers
from oap_mllib_tpu.utils.faults import maybe_fault
from oap_mllib_tpu.utils.timing import tick

log = logging.getLogger("oap_mllib_tpu")


def resolve_depth(depth: Optional[int] = None) -> int:
    """The effective prefetch depth: the argument if given, else
    ``Config.prefetch_depth`` (env ``OAP_MLLIB_TPU_PREFETCH_DEPTH``)."""
    d = get_config().prefetch_depth if depth is None else depth
    d = int(d)
    if d < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {d}")
    return d


class PrefetchStats:
    """Per-pipeline accounting for the stage/transfer/compute split.

    - ``stage_s``: host time inside the stage callable (pad/convert +
      transfer dispatch).
    - ``transfer_s``: the portion of ``stage_s`` spent issuing device
      transfers (stage callables wrap their ``device_put`` in
      :meth:`transfer`); dispatch time, not DMA completion — the async
      runtime overlaps the DMA itself.
    - ``wait_s``: time the CONSUMER spent blocked waiting for a staged
      chunk.  Serial (depth=1) this equals ``stage_s``; with overlap it
      shrinks toward zero — the visible win.
    - ``chunks``: chunks that reached the consumer.
    - ``bytes_staged`` / ``rows``: payload staged through the pipeline —
      total array bytes of every staged item and the (padded) row count
      of its leading 2-D array — the per-pass throughput denominators
      the telemetry registry exports.
    - ``leaked_threads``: producer threads that failed to join within
      the shutdown timeout (daemon threads, so the process still exits,
      but a nonzero count means a stage callable is wedged — logged
      with the pending site and asserted zero in tests).

    :meth:`finalize` writes the split into a ``Timings`` registry as
    ``<prefix>/stage`` (host-only), ``<prefix>/transfer``,
    ``<prefix>/compute`` (= pass wall - wait) and ``<prefix>/stream_wall``
    so ``Timings.overlap_efficiency`` / bench.py can report how much
    staging was hidden behind compute — and mirrors the whole split into
    the process metrics registry (telemetry/metrics.py,
    ``oap_prefetch_*`` / ``oap_stream_*`` labelled by phase).
    """

    __slots__ = ("stage_s", "transfer_s", "wait_s", "chunks",
                 "bytes_staged", "rows", "leaked_threads")

    def __init__(self) -> None:
        self.stage_s = 0.0
        self.transfer_s = 0.0
        self.wait_s = 0.0
        self.chunks = 0
        self.bytes_staged = 0
        self.rows = 0
        self.leaked_threads = 0

    @contextlib.contextmanager
    def transfer(self):
        elapsed = tick()
        try:
            yield
        finally:
            self.transfer_s += elapsed()

    def note_staged(self, item: Any) -> None:
        """Account one staged item's payload (producer side): sum the
        array bytes it carries and the row count of its leading 2-D
        array (padded rows — what the device actually processes)."""
        b, r = _payload_size(item)
        self.bytes_staged += b
        self.rows += r

    def finalize(self, timings, prefix: str, wall: float) -> None:
        """Record this pipeline's split under ``prefix`` (accumulates
        across passes — Timings.as_dict sums duplicate phases) and
        mirror it into the process metrics registry."""
        lab = {"phase": prefix}
        _tm.counter("oap_prefetch_stage_seconds_total", lab,
                    help="Host staging wall (pad/convert, transfer excluded)"
                    ).inc(max(self.stage_s - self.transfer_s, 0.0))
        _tm.counter("oap_prefetch_transfer_seconds_total", lab,
                    help="Device-transfer dispatch wall inside staging"
                    ).inc(self.transfer_s)
        _tm.counter("oap_prefetch_wait_seconds_total", lab,
                    help="Consumer wall blocked waiting for a staged chunk"
                    ).inc(self.wait_s)
        _tm.counter("oap_prefetch_compute_seconds_total", lab,
                    help="Pass wall not spent waiting on staging"
                    ).inc(max(wall - self.wait_s, 0.0))
        _tm.counter("oap_prefetch_chunks_total", lab,
                    help="Chunks that reached the consumer").inc(self.chunks)
        _tm.counter("oap_stream_bytes_staged_total", lab,
                    help="Array bytes staged through the pipeline"
                    ).inc(self.bytes_staged)
        _tm.counter("oap_stream_rows_total", lab,
                    help="Padded rows staged through the pipeline"
                    ).inc(self.rows)
        if timings is None:
            return
        timings.add(prefix + "/stage", max(self.stage_s - self.transfer_s, 0.0))
        timings.add(prefix + "/transfer", self.transfer_s)
        timings.add(prefix + "/compute", max(wall - self.wait_s, 0.0))
        timings.add(prefix + "/stream_wall", wall)


def _payload_size(item: Any) -> tuple:
    """(total array bytes, leading-2-D-array rows) of a staged item —
    tuples/lists walked recursively, scalars ignored.  Rows count the
    FIRST matrix found (the data chunk; masks/weights ride along but do
    not double-count rows)."""
    nbytes = 0
    rows = 0
    stack = [item]
    while stack:
        v = stack.pop()
        if isinstance(v, (tuple, list)):
            stack.extend(reversed(v))
            continue
        b = getattr(v, "nbytes", None)
        shape = getattr(v, "shape", None)
        if b is None or shape is None:
            continue
        nbytes += int(b)
        if rows == 0 and len(shape) >= 2:
            rows = int(shape[0])
    return nbytes, rows


def _delete_jax_arrays(item: Any) -> None:
    """Best-effort ``delete()`` of every jax array inside a staged item
    (tuples/lists walked recursively; host np arrays untouched).  The
    runtime defers the actual free until in-flight steps consuming the
    buffer complete, so retiring immediately after the consumer advances
    is safe."""
    if isinstance(item, (tuple, list)):
        for v in item:
            _delete_jax_arrays(v)
        return
    delete = getattr(item, "delete", None)
    if delete is not None and hasattr(item, "is_deleted"):
        try:
            if not item.is_deleted():
                delete()
        except Exception:
            pass  # freeing is an optimization; never fail a pass over it


class _Serial:
    """depth=1: the exact pre-pipeline loop — stage inline on demand, no
    thread.  Kept as its own tiny class so the serial path shares zero
    concurrency machinery (the bit-identical baseline the parity tests
    pin)."""

    def __init__(self, items: Iterator, stage, stats: PrefetchStats, retire):
        self._items = items
        self._stage = stage
        self._stats = stats
        self._retire = retire
        self._prev = None

    def __iter__(self):
        return self

    def __next__(self):
        if self._retire and self._prev is not None:
            _delete_jax_arrays(self._prev)
            self._prev = None
        elapsed = tick()
        item = next(self._items)  # StopIteration propagates
        out = item if self._stage is None else self._stage(item)
        dt = elapsed()
        # serial staging blocks the consumer: it is both stage and wait
        self._stats.stage_s += dt
        self._stats.wait_s += dt
        self._stats.chunks += 1
        if self._retire:
            self._prev = out
        return out

    def close(self):
        if self._retire and self._prev is not None:
            _delete_jax_arrays(self._prev)
            self._prev = None


class _Sentinel:
    __slots__ = ("err",)

    def __init__(self, err: Optional[BaseException]):
        self.err = err


# producer join budget at shutdown, seconds (module-level so tests can
# shrink it when deliberately wedging a stage callable)
JOIN_TIMEOUT_S = 5.0


class _ClosableSource:
    """Iterator wrapper the consumer can exhaust remotely: after
    :meth:`close` the next pull raises StopIteration, so a wedged
    producer that eventually wakes cannot keep reading a retired
    source (ISSUE 14 satellite — the close() contract)."""

    __slots__ = ("_it", "_closed")

    def __init__(self, it: Iterator):
        self._it = it
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        return next(self._it)


class _PoisonQueue:
    """The retired pipeline's queue stand-in: anything a late (wedged,
    now woken) producer stages after close() is retired on the spot and
    counted — it can never reach a consumer or pin device memory."""

    __slots__ = ("_retire",)

    def __init__(self, retire: bool):
        self._retire = retire

    def put(self, item) -> None:
        if self._retire and not isinstance(item, _Sentinel):
            _delete_jax_arrays(item)
        _tm.counter(
            "oap_prefetch_poisoned_puts_total",
            help="Staged items discarded because the pipeline was "
                 "already retired when the producer woke",
        ).inc()

    def get(self, *a, **kw):  # pragma: no cover - consumers are gone
        raise queue.Empty

    def get_nowait(self):
        raise queue.Empty


class _Threaded:
    """depth>=2: bounded background staging (module docstring)."""

    def __init__(self, items: Iterator, stage, depth: int,
                 stats: PrefetchStats, retire):
        self._items = _ClosableSource(items)
        self._stage = stage
        self._stats = stats
        self._retire = retire
        self._depth = depth
        self._slots = threading.Semaphore(depth)
        self._q: queue.Queue = queue.Queue()
        self._cancel = threading.Event()
        self._prev = None
        self._done = False
        self._thread = threading.Thread(
            target=self._produce, name="oap-mllib-tpu-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer (background thread) ---------------------------------------

    def _acquire_slot(self) -> bool:
        while not self._slots.acquire(timeout=0.05):
            if self._cancel.is_set():
                return False
        if self._cancel.is_set():
            return False
        return True

    def _produce(self) -> None:
        try:
            while True:
                # slot BEFORE the source pull: bounds how far the source
                # itself runs ahead, not just the staged queue
                if not self._acquire_slot():
                    return
                try:
                    item = next(self._items)
                except StopIteration:
                    self._q.put(_Sentinel(None))
                    return
                elapsed = tick()
                out = item if self._stage is None else self._stage(item)
                self._stats.stage_s += elapsed()
                self._q.put(out)
        except BaseException as e:  # noqa: BLE001 — must cross the thread
            self._q.put(_Sentinel(e))

    # -- consumer ------------------------------------------------------------

    def _join_producer(self, where: str) -> None:
        """Join the producer; a thread still alive past the timeout is a
        wedged stage callable (hung device_put / IO).  It used to be
        ignored silently — now it is counted (``PrefetchStats
        .leaked_threads``, asserted zero in tests), logged with the
        pending site, AND quarantined: the source is marked exhausted
        and the staging queue is swapped for a poison queue, so if the
        wedged thread ever wakes it cannot stage into a retired
        pipeline — its output is retired on arrival and its next source
        pull ends it (the ISSUE 14 wedged-producer contract)."""
        self._thread.join(timeout=JOIN_TIMEOUT_S)
        if self._thread.is_alive():
            self._stats.leaked_threads += 1
            _tm.counter(
                "oap_prefetch_leaked_threads_total",
                help="Producer threads that failed to join at shutdown",
            ).inc()
            log.warning(
                "prefetch producer thread failed to join within %.1fs at "
                "%s; leaking daemon thread %r (source poisoned: a late "
                "wake cannot write into the retired pipeline)",
                JOIN_TIMEOUT_S, where, self._thread.name,
            )
            self._items.close()  # next pull raises StopIteration
            self._q = _PoisonQueue(self._retire)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self._prev is not None:
            if self._retire:
                _delete_jax_arrays(self._prev)
            self._prev = None
            self._slots.release()
        elapsed = tick()
        out = self._q.get()
        self._stats.wait_s += elapsed()
        if isinstance(out, _Sentinel):
            self._done = True
            self._join_producer("__next__ (end-of-stream drain)")
            if out.err is not None:
                raise out.err
            raise StopIteration
        self._stats.chunks += 1
        self._prev = out
        return out

    def close(self):
        self._cancel.set()
        self._items.close()  # a producer mid-pull ends at the source too
        # drain so a producer blocked on put/semaphore wakes and exits
        try:
            while True:
                item = self._q.get_nowait()
                if self._retire and not isinstance(item, _Sentinel):
                    _delete_jax_arrays(item)
                self._slots.release()
        except queue.Empty:
            pass
        if self._prev is not None:
            if self._retire:
                _delete_jax_arrays(self._prev)
            self._prev = None
        self._join_producer("close() (cancel drain)")
        self._done = True


class Prefetcher:
    """Iterate ``stage(item)`` over ``items`` with up to ``depth`` chunks
    staged ahead by a background thread (depth=1: inline serial loop).

    Use as a context manager — exit closes the pipeline so an early break
    or consumer exception never strands the producer::

        with Prefetcher(chunks, stage, stats=stats, retire=True) as pf:
            for staged in pf:
                ...dispatch the step...
    """

    def __init__(
        self,
        items: Iterable,
        stage: Optional[Callable[[Any], Any]] = None,
        depth: Optional[int] = None,
        stats: Optional[PrefetchStats] = None,
        retire: bool = False,
    ):
        self.stats = PrefetchStats() if stats is None else stats
        self.depth = resolve_depth(depth)
        it = iter(items)
        # every stage call is a fault-injection site ("prefetch.stage",
        # utils/faults.py) — stageless pipelines included, so staging
        # faults are drillable on identity passes like reservoir
        # sampling; unarmed, maybe_fault is a dict miss
        inner = stage
        stats_ref = self.stats

        def staged(item):
            maybe_fault("prefetch.stage")
            out = item if inner is None else inner(item)
            stats_ref.note_staged(out)
            return out

        if self.depth == 1:
            self._impl = _Serial(it, staged, self.stats, retire)
        else:
            self._impl = _Threaded(it, staged, self.depth, self.stats, retire)

    def __iter__(self):
        it = iter(self._impl)
        # sanitizer plane (utils/sanitizers.py, Config.sanitizers):
        # "transfer" runs each CONSUMER body under a disallow transfer
        # guard; "retrace" asserts zero new XLA compiles after the
        # first chunk.  Off (the default) returns the raw iterator —
        # two cached string checks per pass, nothing per chunk.
        guard = sanitizers.enabled("transfer")
        watch = (
            sanitizers.RetraceWatch("prefetch")
            if sanitizers.enabled("retrace") else None
        )
        # flight recorder (telemetry/flightrec.py): one "chunk" event per
        # consumed chunk when armed, so a post-mortem tail shows how far
        # into a pass each rank got.  Off = one config check per pass.
        if flightrec.enabled():
            it = self._recorded(it)
        if not guard and watch is None:
            return it
        return self._sanitized(it, guard, watch)

    @staticmethod
    def _recorded(it):
        for i, item in enumerate(it):
            flightrec.record("chunk", "prefetch", f"#{i}")
            yield item

    @staticmethod
    def _sanitized(it, guard: bool, watch):
        """Yield chunks with the armed sanitizers active in the consumer
        body: the transfer guard covers exactly the code between yields
        (the per-chunk step dispatch), and the retrace watch checks the
        XLA compile count at every chunk boundary past the first."""
        index = 0
        for item in it:
            if guard:
                with sanitizers.transfer_scope():
                    yield item
            else:
                yield item
            if watch is not None:
                watch.chunk_done(index)
            index += 1

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self._impl.close()

    def close(self) -> None:
        self._impl.close()
