"""Device-resident table abstractions.

Replaces the reference's native table layer:

- ``DenseTable`` ~ oneDAL ``HomogenNumericTable`` / ``RowMergedNumericTable``
  (built in OneDAL.scala:92-166 via per-partition memcpy + executor-local
  merge).  Here: one padded, row-sharded `jax.Array` plus valid-row count; a
  per-row validity mask replaces variable per-rank row counts.
- ``CSRTable`` ~ the one-based CSR table the reference builds for ALS
  (ALSDALImpl.scala:184-230, OneDAL.cpp:109-145).  Here: zero-based COO/CSR
  segment arrays padded to static shapes, the XLA-friendly sparse layout
  (gather/segment_sum instead of sparse BLAS).

Memory lifetime is JAX's (GC'd device buffers) — no explicit
``releaseNumericTables`` registry needed (reference OneDAL.scala:81-90);
``delete()`` is provided for eager HBM release on large tables.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu.data.bucketing import bucket_rows
from oap_mllib_tpu.parallel.mesh import data_sharding, pad_rows

# rows are padded per shard to this multiple (cheap: padding is masked)
_ROW_MULTIPLE = 256


def _padded_row_target(n: int, multiple: int) -> int:
    """Padded row count for an n-row table: the shape-bucketed target
    (geometric x2 buckets anchored at the shard multiple, so one
    compiled program serves every size in a bucket — data/bucketing.py)
    or the exact multiple when bucketing is off.  Bucketed counts are
    multiple * 2^j, i.e. highly divisible — which is exactly what
    auto_row_chunks / _accumulate_chunked want to see."""
    return bucket_rows(n, multiple)


@dataclasses.dataclass
class DenseTable:
    """A row-sharded dense matrix with padded rows.

    ``data`` is (n_padded, d) sharded P(data, None) over the mesh;
    ``mask`` is (n_padded,) float (1.0 valid / 0.0 pad), sharded the same
    way so masked reductions stay local + psum.
    """

    data: jax.Array
    mask: jax.Array
    n_rows: int  # valid rows
    # multi-host bookkeeping (None for single-process tables): this
    # process's valid-row count, and every process's valid-row counts —
    # recorded so per-row vectors (sample weights) can be aligned to the
    # per-process padding layout and valid-row indices mapped into it
    local_valid: Optional[int] = None
    per_process_valid: Optional[np.ndarray] = None

    @property
    def n_padded(self) -> int:
        return self.data.shape[0]

    @property
    def n_features(self) -> int:
        return self.data.shape[1]

    @classmethod
    def from_numpy(cls, x: np.ndarray, mesh, dtype=None) -> "DenseTable":
        from oap_mllib_tpu.data import sparse as _sparse

        n_data = mesh.shape[mesh.axis_names[0]]
        if _sparse.is_sparse(x):
            # SciPy input: densify per row block straight into the
            # padded table (data/sparse.densify_into) — peak host extra
            # is the padded table + one block, never CSR + a second
            # full dense copy
            if x.ndim != 2:
                raise ValueError(f"expected 2-D data, got shape {x.shape}")
            n_valid = int(x.shape[0])
            target = _padded_row_target(n_valid, n_data * _ROW_MULTIPLE)
            out_dtype = np.dtype(
                dtype if dtype is not None
                else (x.dtype if x.dtype.kind == "f" else np.float64)
            )
            padded = np.zeros((target, int(x.shape[1])), out_dtype)
            _sparse.densify_into(padded, x, n_valid)
        else:
            x = np.asarray(x)
            if x.ndim != 2:
                raise ValueError(f"expected 2-D data, got shape {x.shape}")
            if dtype is not None:
                x = x.astype(dtype)
            # pad so every data-axis shard has equal rows AND, with
            # bucketing on (the default), so the padded count lands on a
            # geometric bucket — every fit whose rows share a bucket
            # reuses one compiled program, and the bucketed count's
            # power-of-two chunk factors feed the chunked Lloyd cleanly
            padded, n_valid = pad_rows(
                x, _padded_row_target(x.shape[0], n_data * _ROW_MULTIPLE)
            )
        mask = np.zeros((padded.shape[0],), dtype=padded.dtype)
        mask[:n_valid] = 1.0
        sharding2 = data_sharding(mesh, 2)
        sharding1 = data_sharding(mesh, 1)
        return cls(
            data=jax.device_put(padded, sharding2),
            mask=jax.device_put(mask, sharding1),
            n_rows=n_valid,
        )

    @classmethod
    def from_process_local(cls, x_local: np.ndarray, mesh, dtype=None) -> "DenseTable":
        """Multi-host ingestion: each process contributes its LOCAL row shard
        and the result is one global row-sharded table spanning all hosts.

        This is the multi-host analog of the reference's per-executor table
        build (OneDAL.scala:92-166, where each executor converts only its
        partitions) — here `jax.make_array_from_process_local_data` stitches
        the per-host shards into a global array without any host ever
        holding the full table.  Every process must call this collectively
        with equally-shaped shards (pad the last host's shard with zero-
        weight rows).  In a single-process world it's identical to
        ``from_numpy``.
        """
        import jax

        x_local = np.asarray(x_local)
        if dtype is not None:
            x_local = x_local.astype(dtype)
        n_proc = getattr(jax, "process_count", lambda: 1)()
        if n_proc == 1:
            return cls.from_numpy(x_local, mesh, dtype)
        n_data = mesh.shape[mesh.axis_names[0]]
        from oap_mllib_tpu.parallel.mesh import data_sharding

        local_devices = max(1, n_data // n_proc)
        # bucket per-process shards too: the allgathered max below then
        # lands on a bucket, so multi-host tables amortize exactly like
        # single-host ones (every process re-pads to the common max)
        padded, n_valid_local = pad_rows(
            x_local,
            _padded_row_target(
                x_local.shape[0], local_devices * _ROW_MULTIPLE
            ),
        )
        # Per-process shards pad independently, so valid-row counts landing
        # in different padding buckets (e.g. 100 vs 1100 rows) would yield
        # UNEQUAL local shapes — breaking both the global-shape inference of
        # make_array_from_process_local_data and the n_padded // nproc
        # layout math in valid_to_padded/align_weights.  Allgather the
        # actual padded sizes (alongside the exact valid counts — summing
        # the f32 mask on device loses integers past 2^24) and re-pad every
        # shard to the common max.
        from jax.experimental import multihost_utils

        gathered = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([n_valid_local, padded.shape[0]], np.int64)
            )
        ).reshape(-1, 2)
        counts = gathered[:, 0]
        target = int(gathered[:, 1].max())
        if padded.shape[0] < target:
            padded = np.concatenate(
                [padded,
                 np.zeros((target - padded.shape[0], padded.shape[1]),
                          padded.dtype)]
            )
        mask_local = np.zeros((padded.shape[0],), dtype=padded.dtype)
        mask_local[:n_valid_local] = 1.0
        data = jax.make_array_from_process_local_data(
            data_sharding(mesh, 2), padded
        )
        mask = jax.make_array_from_process_local_data(
            data_sharding(mesh, 1), mask_local
        )
        return cls(
            data=data,
            mask=mask,
            n_rows=int(counts.sum()),
            local_valid=n_valid_local,
            per_process_valid=counts,
        )

    def valid_to_padded(self, idx):
        """Map valid-row indices [0, n_rows) to padded-layout row indices.

        Single-process tables store valid rows contiguously (identity).
        Multi-host tables pad per process, so zero rows sit mid-array —
        sampling initial centers by global valid index must skip them
        (otherwise an all-zero padding row can become a centroid).
        """
        idx = np.asarray(idx)
        if self.per_process_valid is None:
            return idx
        local_padded = self.n_padded // len(self.per_process_valid)
        prefix = np.concatenate([[0], np.cumsum(self.per_process_valid)])
        proc = np.searchsorted(prefix, idx, side="right") - 1
        return proc * local_padded + (idx - prefix[proc])

    def align_weights(self, w: np.ndarray, mesh) -> jax.Array:
        """Per-row weights aligned to this table's padding layout.

        Single-process tables: ``w`` covers all ``n_rows`` valid rows and is
        padded with zeros to ``n_padded``.  Multi-host tables (built by
        ``from_process_local``): ``w`` is this process's LOCAL weights — the
        per-process zero padding sits in the middle of the global array, so
        weights must be stitched collectively with the same layout as the
        mask (they cannot be placed from a global vector).
        """
        w = np.asarray(w, dtype=np.dtype(self.mask.dtype))
        if self.local_valid is None:
            if w.shape[0] != self.n_rows:
                raise ValueError(
                    f"sample_weight has {w.shape[0]} rows, data has {self.n_rows}"
                )
            padded = np.zeros((self.n_padded,), dtype=w.dtype)
            padded[: self.n_rows] = w
            return jax.device_put(padded, data_sharding(mesh, 1))
        if w.shape[0] != self.local_valid:
            raise ValueError(
                f"sample_weight has {w.shape[0]} rows, this process's local "
                f"shard has {self.local_valid}"
            )
        local_padded = self.n_padded // jax.process_count()
        padded = np.zeros((local_padded,), dtype=w.dtype)
        padded[: self.local_valid] = w
        return jax.make_array_from_process_local_data(
            data_sharding(mesh, 1), padded
        )

    def to_numpy(self) -> np.ndarray:
        """Gather valid rows back to host (reverse data plane,
        ~ numericTableToVectors, OneDAL.scala:37-52)."""
        return np.asarray(self.data)[: self.n_rows]

    def delete(self) -> None:
        """Eagerly drop device buffers (~ cFreeDataMemory, OneDAL.cpp:83-89)."""
        self.data.delete()
        self.mask.delete()


@dataclasses.dataclass
class CSRTable:
    """A sparse ratings block in padded COO form with CSR row offsets.

    Arrays are host-or-device; all zero-based (the reference's one-based CSR
    is a oneDAL requirement, OneDAL.cpp:123-126 — not carried over).

    - ``rows``/``cols``: (nnz_padded,) int32 indices; padding entries point
      at row ``n_rows`` (one past the end) so segment ops drop them.
    - ``values``: (nnz_padded,) float32.
    - ``row_offsets``: (n_rows + 1,) int32 CSR offsets over the *valid* nnz.
    - ``nnz``: valid entry count.
    """

    rows: jax.Array
    cols: jax.Array
    values: jax.Array
    row_offsets: jax.Array
    n_rows: int
    n_cols: int
    nnz: int

    @classmethod
    def from_coo(
        cls,
        rows: np.ndarray,
        cols: np.ndarray,
        values: np.ndarray,
        n_rows: int,
        n_cols: int,
        nnz_padded: Optional[int] = None,
    ) -> "CSRTable":
        """Build from COO triples; sorts by (row, col) like the reference's
        post-shuffle sort (ALSShuffle.cpp:111)."""
        rows = np.asarray(rows, dtype=np.int32)
        cols = np.asarray(cols, dtype=np.int32)
        values = np.asarray(values, dtype=np.float32)
        if len(rows) and (rows.max() >= n_rows or rows.min() < 0):
            raise ValueError(f"row index out of range [0, {n_rows})")
        if len(cols) and (cols.max() >= n_cols or cols.min() < 0):
            raise ValueError(f"col index out of range [0, {n_cols})")
        order = np.lexsort((cols, rows))
        rows, cols, values = rows[order], cols[order], values[order]
        nnz = len(values)
        counts = np.bincount(rows, minlength=n_rows)
        row_offsets = np.zeros((n_rows + 1,), dtype=np.int32)
        np.cumsum(counts, out=row_offsets[1:])
        if nnz_padded is not None and nnz_padded > nnz:
            pad = nnz_padded - nnz
            rows = np.concatenate([rows, np.full((pad,), n_rows, np.int32)])
            cols = np.concatenate([cols, np.zeros((pad,), np.int32)])
            values = np.concatenate([values, np.zeros((pad,), np.float32)])
        return cls(
            rows=jnp.asarray(rows),
            cols=jnp.asarray(cols),
            values=jnp.asarray(values),
            row_offsets=jnp.asarray(row_offsets),
            n_rows=n_rows,
            n_cols=n_cols,
            nnz=nnz,
        )

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.n_rows, self.n_cols), dtype=np.float32)
        r = np.asarray(self.rows)[: self.nnz]
        c = np.asarray(self.cols)[: self.nnz]
        v = np.asarray(self.values)[: self.nnz]
        out[r, c] = v
        return out
