"""File readers for the example/data formats the reference consumes.

Formats (survey §2.8):
- libsvm — ``examples/data/sample_kmeans_data.txt`` (label idx:val ...);
  1-based feature indices, as Spark's libsvm loader expects.
- dense CSV — ``examples/data/pca_data.csv``.
- ratings — ``onedal_als_csr_ratings.txt``: ``user::item::rating`` lines
  (MovieLens style, parsed in examples/als/.../ALSExample.scala).

A fast C++ parser backs these when the native library is built
(oap_mllib_tpu/native); these NumPy versions are the always-available
fallback and the correctness oracle.

This module also owns the low-level durable-write/read primitives of the
checkpoint subsystem (utils/checkpoint.py) and the hardened model
persistence (models/*.save): atomic JSON manifests and npz shard files
written tmp+``os.replace`` so a reader NEVER observes a torn file — a
kill mid-write leaves either the old generation or a stray ``*.tmp``
that validation ignores.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np


# -- atomic manifest/shard primitives (checkpoint + model persistence) --------


def atomic_write_json(path: str, payload: dict) -> int:
    """Durably write ``payload`` as JSON via tmp+``os.replace`` (atomic on
    POSIX within one filesystem).  Returns bytes written."""
    data = json.dumps(payload, sort_keys=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(data)


def read_json(path: str) -> dict:
    """Read a JSON file written by :func:`atomic_write_json`."""
    with open(path) as f:
        return json.load(f)


def atomic_save_npz(path: str, arrays: Dict[str, np.ndarray]) -> int:
    """Durably write an uncompressed ``.npz`` of ``arrays`` via
    tmp+``os.replace``.  Returns bytes written."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes


def load_npz(path: str) -> Dict[str, np.ndarray]:
    """Load every array of an ``.npz`` shard into host memory (the file
    handle must not outlive the call — checkpoint GC unlinks old
    generations while restored state is still in use)."""
    with np.load(path) as z:
        return {k: np.asarray(z[k]) for k in z.files}


def atomic_save_npy(path: str, array: np.ndarray) -> int:
    """Durably write one ``.npy`` array via tmp+``os.replace`` (the
    hardened ``models/*.save`` write primitive)."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.save(f, array)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes

def _force_py() -> bool:
    """Env kill-switch for the native host layer: forces the pure-Python
    parsers AND the native ALS host prep (ops/als_ops grouped-edge build)
    back to NumPy (tests, debugging).  ``OAP_MLLIB_TPU_PURE_PYTHON`` is
    the canonical name; ``..._IO`` is kept for back-compat.  Read per
    call so it works even when set after import."""
    # oaplint: disable=config-field-contract -- deliberate non-Config env kill-switch
    for var in ("OAP_MLLIB_TPU_PURE_PYTHON", "OAP_MLLIB_TPU_PURE_PYTHON_IO"):
        if os.environ.get(var, "").strip().lower() in ("1", "true", "yes", "on"):
            return True
    return False


def _native():
    if _force_py():
        return None
    from oap_mllib_tpu import native

    return native if native.available() else None


def read_libsvm(path: str, n_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Read a libsvm file into dense (labels, X). 1-based indices."""
    nat = _native()
    if nat is not None:
        return nat.parse_libsvm(path, n_features or 0)
    labels = []
    rows = []
    max_idx = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                idx, val = tok.split(":")
                idx = int(idx)
                feats[idx] = float(val)
                max_idx = max(max_idx, idx)
            rows.append(feats)
    d = n_features if n_features is not None else max_idx
    if n_features is not None and max_idx > n_features:
        raise ValueError(
            f"libsvm feature index {max_idx} exceeds n_features={n_features}"
        )
    X = np.zeros((len(rows), d), dtype=np.float64)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            X[i, idx - 1] = val
    return np.asarray(labels), X


def read_csv(path: str, delimiter: str = ",") -> np.ndarray:
    """Read a dense numeric CSV (no header) into an (n, d) array."""
    nat = _native()
    if nat is not None:
        return nat.parse_csv(path, delimiter)
    return np.loadtxt(path, delimiter=delimiter, dtype=np.float64, ndmin=2)


def read_ratings(path: str, sep: str = "::") -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Read ``user<sep>item<sep>rating`` lines into (users, items, ratings)."""
    nat = _native()
    if nat is not None:
        return nat.parse_ratings(path, sep)
    users, items, ratings = [], [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            u, i, r = line.split(sep)[:3]
            users.append(int(u))
            items.append(int(i))
            ratings.append(float(r))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(ratings, dtype=np.float32),
    )
