"""File readers for the example/data formats the reference consumes.

Formats (survey §2.8):
- libsvm — ``examples/data/sample_kmeans_data.txt`` (label idx:val ...);
  1-based feature indices, as Spark's libsvm loader expects.
- dense CSV — ``examples/data/pca_data.csv``.
- ratings — ``onedal_als_csr_ratings.txt``: ``user::item::rating`` lines
  (MovieLens style, parsed in examples/als/.../ALSExample.scala).

A fast C++ parser backs these when the native library is built
(oap_mllib_tpu/native); these NumPy versions are the always-available
fallback and the correctness oracle.

This module also owns the low-level durable-write/read primitives of the
checkpoint subsystem (utils/checkpoint.py) and the hardened model
persistence (models/*.save): atomic JSON manifests and npz shard files
written tmp+``os.replace`` so a reader NEVER observes a torn file — a
kill mid-write leaves either the old generation or a stray ``*.tmp``
that validation ignores.

The out-of-core read plane (ISSUE 12) lives here too: mmap'd ``.npy``
row readers and parquet piece readers back the disk-backed
``ChunkSource`` constructors (data/stream.py), and :class:`SpillWriter`
is the resilience ladder's spill primitive — a host-OOM'd fit stages its
table to disk chunk-by-chunk (same tmp+``os.replace`` protocol, so a
kill mid-spill leaves no torn spill) and re-enters the streamed route
reading it back.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("oap_mllib_tpu")


# -- atomic manifest/shard primitives (checkpoint + model persistence) --------


def atomic_write_json(path: str, payload: dict) -> int:
    """Durably write ``payload`` as JSON via tmp+``os.replace`` (atomic on
    POSIX within one filesystem).  Returns bytes written."""
    data = json.dumps(payload, sort_keys=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(data)


def read_json(path: str) -> dict:
    """Read a JSON file written by :func:`atomic_write_json`."""
    with open(path) as f:
        return json.load(f)


def atomic_save_npz(path: str, arrays: Dict[str, np.ndarray]) -> int:
    """Durably write an uncompressed ``.npz`` of ``arrays`` via
    tmp+``os.replace``.  Returns bytes written."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes


def load_npz(path: str) -> Dict[str, np.ndarray]:
    """Load every array of an ``.npz`` shard into host memory (the file
    handle must not outlive the call — checkpoint GC unlinks old
    generations while restored state is still in use)."""
    with np.load(path) as z:
        return {k: np.asarray(z[k]) for k in z.files}


def atomic_save_npy(path: str, array: np.ndarray) -> int:
    """Durably write one ``.npy`` array via tmp+``os.replace`` (the
    hardened ``models/*.save`` write primitive)."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".", suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.save(f, array)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes

# -- out-of-core read plane: mmap'd .npy + parquet piece readers --------------


def open_npy_mmap(path: str) -> np.ndarray:
    """Open a 2-D ``.npy`` file as a read-only memory map: row slices
    read from disk on demand, so a beyond-host-RAM table costs O(slice)
    resident memory, not O(file)."""
    arr = np.load(path, mmap_mode="r")
    if arr.ndim != 2:
        raise ValueError(
            f"{path}: expected a 2-D array, got shape {arr.shape}"
        )
    return arr


def iter_npy_rows(path: str, chunk_rows: int,
                  fault_site: str = "disk.read") -> Iterator[np.ndarray]:
    """Yield row slices of an mmap'd ``.npy`` file, ``chunk_rows`` at a
    time.  Each slice read is a registered fault site (``disk.read``, or
    ``spill.read`` for spill-backed sources) so the chaos/fault plane
    covers the media path.  The mmap handle lives only for the walk —
    re-iteration reopens it, so a concurrently replaced spill generation
    is picked up cleanly."""
    from oap_mllib_tpu.utils.faults import maybe_fault

    arr = open_npy_mmap(path)
    for lo in range(0, arr.shape[0], chunk_rows):
        maybe_fault(fault_site)
        # np.asarray forces the disk read here (inside the fault site's
        # accounting) and detaches the yielded piece from the mmap
        yield np.asarray(arr[lo: lo + chunk_rows])


def iter_parquet_rows(
    path: str, chunk_rows: int,
    columns: Optional[Sequence[str]] = None,
) -> Iterator[np.ndarray]:
    """Yield dense row blocks of a parquet file, ``chunk_rows`` per
    batch, reading piece by piece (pyarrow ``iter_batches`` — row groups
    never materialize whole).  Requires pyarrow; raises a clear error
    when the optional dep is absent instead of an opaque ImportError
    deep in a pass."""
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - dep present in CI
        raise RuntimeError(
            "parquet sources require pyarrow (pip install pyarrow); "
            "use ChunkSource.from_npy / from_csv for stdlib-only reads"
        ) from e
    from oap_mllib_tpu.utils.faults import maybe_fault

    pf = pq.ParquetFile(path)
    cols = list(columns) if columns is not None else None
    for batch in pf.iter_batches(batch_size=chunk_rows, columns=cols):
        maybe_fault("disk.read")
        arrays = [
            np.asarray(batch.column(i), dtype=np.float64)
            for i in range(batch.num_columns)
        ]
        yield np.stack(arrays, axis=1)


def parquet_schema(path: str) -> Tuple[int, int]:
    """(n_rows, n_columns) of a parquet file from its footer metadata —
    the planner prices disk sources without touching row data."""
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - dep present in CI
        raise RuntimeError(
            "parquet sources require pyarrow (pip install pyarrow)"
        ) from e
    meta = pq.ParquetFile(path).metadata
    return int(meta.num_rows), int(meta.num_columns)


class SpillWriter:
    """Chunk-at-a-time writer of one 2-D ``.npy`` spill file.

    The resilience ladder's host-OOM rung walks a source once, feeding
    each piece to :meth:`write`, then :meth:`commit` atomically replaces
    ``path`` (tmp+``os.replace``, the checkpoint protocol) — a reader
    can never observe a torn spill, and a kill mid-spill leaves only a
    stray ``*.tmp`` the next attempt overwrites.  Every chunk write is
    the ``spill.write`` fault site, so a failed/killed spill is
    drillable in CI (dev/oom_gate.py).

    Rows may be unknown upfront (file sources discover their length on
    the first pass): data lands in a raw tmp stream and the ``.npy``
    header is written at commit, when the true shape is known.
    """

    def __init__(self, path: str, n_features: int, dtype=np.float32):
        self.path = path
        self.n_features = int(n_features)
        self.dtype = np.dtype(dtype)
        self.rows = 0
        self.bytes_written = 0
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        fd, self._tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
        )
        self._f = os.fdopen(fd, "wb")
        self._committed = False

    def write(self, piece: np.ndarray) -> None:
        """Append one row block (C-order raw bytes at the spill dtype)."""
        from oap_mllib_tpu.utils.faults import maybe_fault

        maybe_fault("spill.write")
        piece = np.ascontiguousarray(piece, dtype=self.dtype)
        if piece.ndim != 2 or piece.shape[1] != self.n_features:
            raise ValueError(
                f"spill piece shape {piece.shape} does not match "
                f"n_features={self.n_features}"
            )
        self._f.write(piece.tobytes())
        self.rows += int(piece.shape[0])
        self.bytes_written += piece.nbytes

    def commit(self) -> str:
        """Finalize: prepend the ``.npy`` header for the discovered
        shape, fsync, and atomically replace ``path``.  Returns the
        committed path."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        final_tmp = self._tmp + ".hdr"
        try:
            with open(final_tmp, "wb") as out:
                np.lib.format.write_array_header_2_0(
                    out,
                    {"descr": np.lib.format.dtype_to_descr(self.dtype),
                     "fortran_order": False,
                     "shape": (self.rows, self.n_features)},
                )
                with open(self._tmp, "rb") as raw:
                    shutil.copyfileobj(raw, out, 1 << 22)
                out.flush()
                os.fsync(out.fileno())
            os.replace(final_tmp, self.path)
        except BaseException:
            for p in (final_tmp,):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            raise
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass
        self._committed = True
        return self.path

    def abort(self) -> None:
        """Drop the tmp stream (failed spill): ``path`` is untouched."""
        try:
            self._f.close()
        except Exception:
            pass
        try:
            os.unlink(self._tmp)
        except OSError:
            pass

    def __enter__(self) -> "SpillWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.abort()
        elif not self._committed:
            self.commit()


def _force_py() -> bool:
    """Env kill-switch for the native host layer: forces the pure-Python
    parsers AND the native ALS host prep (ops/als_ops grouped-edge build)
    back to NumPy (tests, debugging).  ``OAP_MLLIB_TPU_PURE_PYTHON`` is
    the canonical name; ``..._IO`` is kept for back-compat.  Read per
    call so it works even when set after import."""
    # oaplint: disable=config-field-contract -- deliberate non-Config env kill-switch
    for var in ("OAP_MLLIB_TPU_PURE_PYTHON", "OAP_MLLIB_TPU_PURE_PYTHON_IO"):
        if os.environ.get(var, "").strip().lower() in ("1", "true", "yes", "on"):
            return True
    return False


def _native():
    if _force_py():
        return None
    from oap_mllib_tpu import native

    return native if native.available() else None


def read_libsvm(path: str, n_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Read a libsvm file into dense (labels, X). 1-based indices."""
    nat = _native()
    if nat is not None:
        return nat.parse_libsvm(path, n_features or 0)
    labels = []
    rows = []
    max_idx = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                idx, val = tok.split(":")
                idx = int(idx)
                feats[idx] = float(val)
                max_idx = max(max_idx, idx)
            rows.append(feats)
    d = n_features if n_features is not None else max_idx
    if n_features is not None and max_idx > n_features:
        raise ValueError(
            f"libsvm feature index {max_idx} exceeds n_features={n_features}"
        )
    X = np.zeros((len(rows), d), dtype=np.float64)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            X[i, idx - 1] = val
    return np.asarray(labels), X


def read_csv(path: str, delimiter: str = ",") -> np.ndarray:
    """Read a dense numeric CSV (no header) into an (n, d) array."""
    nat = _native()
    if nat is not None:
        return nat.parse_csv(path, delimiter)
    return np.loadtxt(path, delimiter=delimiter, dtype=np.float64, ndmin=2)


def read_ratings(path: str, sep: str = "::") -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Read ``user<sep>item<sep>rating`` lines into (users, items, ratings)."""
    nat = _native()
    if nat is not None:
        return nat.parse_ratings(path, sep)
    users, items, ratings = [], [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            u, i, r = line.split(sep)[:3]
            users.append(int(u))
            items.append(int(i))
            ratings.append(float(r))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(ratings, dtype=np.float32),
    )
