"""Out-of-core row streaming: fit on tables larger than one chip's HBM.

The reference never holds the full dataset in one process — Spark executors
each hold a partition as a native table (OneDAL.scala:92-166) and total
cluster RAM bounds the problem.  The mesh-sharded path here is the direct
analog (HBM summed over chips).  This module adds the axis the reference
does NOT have: a single host streaming a table through ONE chip's HBM in
fixed-size row chunks, bounding device memory by O(chunk) while K-Means /
PCA make full passes per iteration.  The chunk shape is static, so every
pass reuses one compiled program (XLA static-shape contract, survey §2.6).

``ChunkSource`` is a re-iterable sequence of equal-width row chunks.  The
final partial chunk is padded with zero rows and reported via the per-chunk
valid count — padded rows carry weight 0 through every kernel, the same
masking contract as ``DenseTable``.

Consumers do not iterate a source directly: every streamed pass pulls
through the prefetch pipeline (``data/prefetch.py``), which stages and
device_puts chunk N+1 on a bounded background thread while chunk N's step
executes (``Config.prefetch_depth``; depth=1 = the serial loop).  Sources
therefore must tolerate being advanced from a non-main thread — plain
generators and file reads do; a source wrapping thread-affine state must
confine it to the iterator itself.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

import numpy as np

# default rows per chunk: 64k rows x 256 features x f32 = 64 MB host
# buffer — big enough to keep the MXU busy, far under any HBM budget
DEFAULT_CHUNK_ROWS = 1 << 16


class ChunkSource:
    """Re-iterable source of ``(chunk, n_valid)`` row blocks.

    Every chunk has exactly ``(chunk_rows, n_features)`` shape at
    ``dtype``; the last one is zero-padded and its ``n_valid <
    chunk_rows`` says how many rows are real.  Sources must be
    deterministic across passes (K-Means streaming re-walks the data every
    Lloyd iteration; k-means|| relies on stable chunk order for its
    distance state).
    """

    def __init__(
        self,
        make_iter: Callable[[], Iterator[np.ndarray]],
        n_features: int,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
        n_rows: Optional[int] = None,
        dtype=np.float32,
    ):
        if chunk_rows < 1:
            raise ValueError("chunk_rows must be >= 1")
        if n_features < 1:
            raise ValueError("n_features must be >= 1")
        self._make_iter = make_iter
        self.n_features = int(n_features)
        # shape-bucket the chunk width (data/bucketing.py): every
        # compiled per-chunk program is keyed on (chunk_rows, d), so
        # rounding requested widths up to geometric buckets lets sources
        # with nearby chunk sizes share one program instead of each
        # compiling its own.  The padding contract is unchanged — a
        # wider buffer just means the tail chunk reports a smaller
        # n_valid; results are identical.  Power-of-two requests (the
        # 1 << 16 default included) land on themselves.
        from oap_mllib_tpu.data.bucketing import bucket_rows

        self.chunk_rows = bucket_rows(int(chunk_rows))
        self._n_rows = None if n_rows is None else int(n_rows)
        # buffer at the source's own precision: re-buffering f32 data at
        # f64 would triple host memory traffic on exactly the pass-heavy
        # workloads this module exists for
        self.dtype = np.dtype(dtype)

    @property
    def n_rows(self) -> Optional[int]:
        """Total valid rows — known upfront for array sources, discovered
        after the first full pass for file sources."""
        return self._n_rows

    def to_array(self) -> np.ndarray:
        """Materialize the source to one host array (fallback paths; the
        CPU reference semantics assume host-RAM-resident data)."""
        return np.concatenate([c[:v] for c, v in self], axis=0)

    def with_chunk_rows(self, chunk_rows: int) -> "ChunkSource":
        """The same source re-chunked at a different width — the halved
        -chunk rung of the resilience ladder rebuilds a fit's sources at
        ``chunk_rows // 2`` after a device OOM (utils/resilience.py).
        Row content and order are identical; only the block shape (and
        therefore per-step device memory) changes."""
        return ChunkSource(
            self._make_iter, self.n_features, chunk_rows,
            n_rows=self._n_rows, dtype=self.dtype,
        )

    def __iter__(self) -> Iterator[Tuple[np.ndarray, int]]:
        """Yield (chunk (chunk_rows, d), n_valid) blocks; re-iterable."""
        from oap_mllib_tpu.utils.faults import maybe_fault

        buf = np.zeros((self.chunk_rows, self.n_features), self.dtype)
        fill = 0
        total = 0
        for piece in self._make_iter():
            # the host-I/O fault-injection site: one call per piece the
            # underlying reader yields (utils/faults.py "stream.read")
            maybe_fault("stream.read")
            piece = np.atleast_2d(np.asarray(piece, self.dtype))
            if piece.shape[1] != self.n_features:
                raise ValueError(
                    f"chunk width {piece.shape[1]} != n_features {self.n_features}"
                )
            off = 0
            while off < piece.shape[0]:
                take = min(self.chunk_rows - fill, piece.shape[0] - off)
                buf[fill : fill + take] = piece[off : off + take]
                fill += take
                off += take
                if fill == self.chunk_rows:
                    total += fill
                    yield buf, fill
                    buf = np.zeros_like(buf)
                    fill = 0
        if fill:
            total += fill
            yield buf, fill
        if self._n_rows is None:
            self._n_rows = total
        elif self._n_rows != total:
            # In a multi-host streamed fit only the observing process sees
            # this; stream_ops._PassGuard carries it to the next collective
            # reduction so ALL ranks fail together instead of the peers
            # hanging in process_allgather until the distributed timeout.
            raise ValueError(
                f"source yielded {total} rows this pass but {self._n_rows} "
                "before — streamed fits require a deterministic source"
            )

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_array(cls, x, chunk_rows: int = DEFAULT_CHUNK_ROWS) -> "ChunkSource":
        """Wrap an in-memory array or np.memmap (zero-copy row slices)."""
        x = np.asarray(x) if not isinstance(x, np.memmap) else x
        if x.ndim != 2:
            raise ValueError(f"expected 2-D data, got shape {x.shape}")

        def gen():
            for start in range(0, x.shape[0], chunk_rows):
                yield x[start : start + chunk_rows]

        return cls(gen, x.shape[1], chunk_rows, n_rows=x.shape[0], dtype=x.dtype)

    @classmethod
    def from_csv(
        cls, path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS,
        delimiter: str = ",", n_features: Optional[int] = None,
        dtype=np.float64,
    ) -> "ChunkSource":
        """Stream a headerless numeric CSV without loading it whole.
        ``dtype`` defaults to f64 to match the eager read_csv reader."""
        if n_features is None:
            with open(path) as f:
                first = f.readline()
            n_features = len(first.strip().split(delimiter))

        def gen():
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rows.append([float(v) for v in line.split(delimiter)])
                    if len(rows) == chunk_rows:
                        yield np.asarray(rows)
                        rows = []
            if rows:
                yield np.asarray(rows)

        return cls(gen, n_features, chunk_rows, dtype=dtype)

    @classmethod
    def from_libsvm(
        cls, path: str, n_features: int, chunk_rows: int = DEFAULT_CHUNK_ROWS,
        dtype=np.float64,
    ) -> "ChunkSource":
        """Stream a libsvm file (1-based indices); labels are dropped, as in
        the K-Means examples.  ``n_features`` must be given — a streaming
        reader cannot discover the max index without a full pass.
        ``dtype`` defaults to f64 to match the eager read_libsvm reader."""

        def gen():
            rows = np.zeros((chunk_rows, n_features), dtype)
            fill = 0
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    for tok in line.split()[1:]:
                        idx, val = tok.split(":")
                        i = int(idx)
                        if i > n_features:
                            raise ValueError(
                                f"libsvm index {i} exceeds n_features={n_features}"
                            )
                        rows[fill, i - 1] = float(val)
                    fill += 1
                    if fill == chunk_rows:
                        yield rows
                        rows = np.zeros_like(rows)
                        fill = 0
            if fill:
                yield rows[:fill]

        return cls(gen, n_features, chunk_rows, dtype=dtype)
