"""Out-of-core row streaming: fit on tables larger than one chip's HBM.

The reference never holds the full dataset in one process — Spark executors
each hold a partition as a native table (OneDAL.scala:92-166) and total
cluster RAM bounds the problem.  The mesh-sharded path here is the direct
analog (HBM summed over chips).  This module adds the axis the reference
does NOT have: a single host streaming a table through ONE chip's HBM in
fixed-size row chunks, bounding device memory by O(chunk) while K-Means /
PCA make full passes per iteration.  The chunk shape is static, so every
pass reuses one compiled program (XLA static-shape contract, survey §2.6).

``ChunkSource`` is a re-iterable sequence of equal-width row chunks.  The
final partial chunk is padded with zero rows and reported via the per-chunk
valid count — padded rows carry weight 0 through every kernel, the same
masking contract as ``DenseTable``.

Consumers do not iterate a source directly: every streamed pass pulls
through the prefetch pipeline (``data/prefetch.py``), which stages and
device_puts chunk N+1 on a bounded background thread while chunk N's step
executes (``Config.prefetch_depth``; depth=1 = the serial loop).  Sources
therefore must tolerate being advanced from a non-main thread — plain
generators and file reads do; a source wrapping thread-affine state must
confine it to the iterator itself.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

import numpy as np

# default rows per chunk: 64k rows x 256 features x f32 = 64 MB host
# buffer — big enough to keep the MXU busy, far under any HBM budget
DEFAULT_CHUNK_ROWS = 1 << 16


class ChunkSource:
    """Re-iterable source of ``(chunk, n_valid)`` row blocks.

    Every chunk has exactly ``(chunk_rows, n_features)`` shape at
    ``dtype``; the last one is zero-padded and its ``n_valid <
    chunk_rows`` says how many rows are real.  Sources must be
    deterministic across passes (K-Means streaming re-walks the data every
    Lloyd iteration; k-means|| relies on stable chunk order for its
    distance state).
    """

    def __init__(
        self,
        make_iter: Callable[[], Iterator[np.ndarray]],
        n_features: int,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
        n_rows: Optional[int] = None,
        dtype=np.float32,
        backing: str = "stream",
    ):
        if chunk_rows < 1:
            raise ValueError("chunk_rows must be >= 1")
        if n_features < 1:
            raise ValueError("n_features must be >= 1")
        self._make_iter = make_iter
        self.n_features = int(n_features)
        # what holds this source's rows between passes — the memory-
        # budget planner (utils/membudget.py) prices host residency off
        # it: "memory" (an in-RAM array/memmap'd-hot buffer), "disk"
        # (file-backed: .npy/parquet/csv/libsvm readers — O(chunk) host),
        # "spill" (a disk spill the resilience ladder staged), "stream"
        # (an opaque generator: host cost unknown, assumed O(chunk))
        self.backing = backing
        # shape-bucket the chunk width (data/bucketing.py): every
        # compiled per-chunk program is keyed on (chunk_rows, d), so
        # rounding requested widths up to geometric buckets lets sources
        # with nearby chunk sizes share one program instead of each
        # compiling its own.  The padding contract is unchanged — a
        # wider buffer just means the tail chunk reports a smaller
        # n_valid; results are identical.  Power-of-two requests (the
        # 1 << 16 default included) land on themselves.
        from oap_mllib_tpu.data.bucketing import bucket_rows

        self.chunk_rows = bucket_rows(int(chunk_rows))
        self._n_rows = None if n_rows is None else int(n_rows)
        # buffer at the source's own precision: re-buffering f32 data at
        # f64 would triple host memory traffic on exactly the pass-heavy
        # workloads this module exists for
        self.dtype = np.dtype(dtype)

    @property
    def n_rows(self) -> Optional[int]:
        """Total valid rows — known upfront for array sources, discovered
        after the first full pass for file sources."""
        return self._n_rows

    def to_array(self) -> np.ndarray:
        """Materialize the source to one host array (fallback paths; the
        CPU reference semantics assume host-RAM-resident data)."""
        return np.concatenate([c[:v] for c, v in self], axis=0)

    def with_chunk_rows(self, chunk_rows: int) -> "ChunkSource":
        """The same source re-chunked at a different width — the halved
        -chunk rung of the resilience ladder rebuilds a fit's sources at
        ``chunk_rows // 2`` after a device OOM (utils/resilience.py).
        Row content and order are identical; only the block shape (and
        therefore per-step device memory) changes."""
        return ChunkSource(
            self._make_iter, self.n_features, chunk_rows,
            n_rows=self._n_rows, dtype=self.dtype, backing=self.backing,
        )

    def __iter__(self) -> Iterator[Tuple[np.ndarray, int]]:
        """Yield (chunk (chunk_rows, d), n_valid) blocks; re-iterable."""
        from oap_mllib_tpu.utils.faults import maybe_fault

        buf = np.zeros((self.chunk_rows, self.n_features), self.dtype)
        fill = 0
        total = 0
        for piece in self._make_iter():
            # the host-I/O fault-injection site: one call per piece the
            # underlying reader yields (utils/faults.py "stream.read")
            maybe_fault("stream.read")
            piece = np.atleast_2d(np.asarray(piece, self.dtype))
            if piece.shape[1] != self.n_features:
                raise ValueError(
                    f"chunk width {piece.shape[1]} != n_features {self.n_features}"
                )
            off = 0
            while off < piece.shape[0]:
                take = min(self.chunk_rows - fill, piece.shape[0] - off)
                buf[fill : fill + take] = piece[off : off + take]
                fill += take
                off += take
                if fill == self.chunk_rows:
                    total += fill
                    yield buf, fill
                    buf = np.zeros_like(buf)
                    fill = 0
        if fill:
            total += fill
            yield buf, fill
        if self._n_rows is None:
            self._n_rows = total
        elif self._n_rows != total:
            # In a multi-host streamed fit only the observing process sees
            # this; stream_ops._PassGuard carries it to the next collective
            # reduction so ALL ranks fail together instead of the peers
            # hanging in process_allgather until the distributed timeout.
            raise ValueError(
                f"source yielded {total} rows this pass but {self._n_rows} "
                "before — streamed fits require a deterministic source"
            )

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_array(cls, x, chunk_rows: int = DEFAULT_CHUNK_ROWS) -> "ChunkSource":
        """Wrap an in-memory array, np.memmap (zero-copy row slices), or
        SciPy sparse matrix.  Sparse inputs densify PER CHUNK at staging
        time — peak host memory is O(chunk) dense + the CSR itself, not
        the full dense table (the Spark sparse-vector ingestion analog,
        without the up-front densify)."""
        from oap_mllib_tpu.data.sparse import is_sparse

        if is_sparse(x):
            csr = x.tocsr()
            if csr.ndim != 2:
                raise ValueError(f"expected 2-D data, got shape {csr.shape}")
            dtype = csr.dtype if csr.dtype.kind == "f" else np.float64

            def sgen():
                for start in range(0, csr.shape[0], chunk_rows):
                    # the per-chunk densify: only this row slice is ever
                    # dense on the host at once
                    yield csr[start : start + chunk_rows].toarray()

            return cls(sgen, csr.shape[1], chunk_rows, n_rows=csr.shape[0],
                       dtype=dtype, backing="memory")
        x = np.asarray(x) if not isinstance(x, np.memmap) else x
        if x.ndim != 2:
            raise ValueError(f"expected 2-D data, got shape {x.shape}")

        def gen():
            for start in range(0, x.shape[0], chunk_rows):
                yield x[start : start + chunk_rows]

        return cls(gen, x.shape[1], chunk_rows, n_rows=x.shape[0],
                   dtype=x.dtype, backing="memory")

    @classmethod
    def from_npy(cls, path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS,
                 fault_site: str = "disk.read") -> "ChunkSource":
        """Stream a 2-D ``.npy`` file via a read-only memory map: host
        memory stays O(chunk) however large the file — the beyond-host-
        RAM ingestion path (data/io.iter_npy_rows; each slice read is
        the ``disk.read`` fault site).  ``fault_site="spill.read"`` is
        how spill-backed sources tag their reads."""
        from oap_mllib_tpu.data import io as _io

        arr = _io.open_npy_mmap(path)  # validates 2-D, reads shape only
        n, d = arr.shape
        dtype = arr.dtype
        del arr

        def gen():
            yield from _io.iter_npy_rows(path, chunk_rows, fault_site)

        backing = "spill" if fault_site == "spill.read" else "disk"
        return cls(gen, d, chunk_rows, n_rows=n, dtype=dtype,
                   backing=backing)

    @classmethod
    def from_parquet(
        cls, path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS,
        columns=None, dtype=np.float64,
    ) -> "ChunkSource":
        """Stream a parquet file piece by piece (pyarrow ``iter_batches``
        — no row group materializes whole; data/io.iter_parquet_rows).
        ``columns`` optionally selects/orders numeric columns; row and
        column counts come from the footer, so the planner prices the
        source without touching data."""
        from oap_mllib_tpu.data import io as _io

        n, d_all = _io.parquet_schema(path)
        d = len(columns) if columns is not None else d_all

        def gen():
            yield from _io.iter_parquet_rows(path, chunk_rows, columns)

        return cls(gen, d, chunk_rows, n_rows=n, dtype=dtype,
                   backing="disk")

    def spill_to_disk(self, path: Optional[str] = None) -> "ChunkSource":
        """Stage this source's rows to one atomic ``.npy`` spill file and
        return a disk-backed source over it (same chunk_rows / dtype /
        row order — the streamed pass structure, and therefore the math,
        is unchanged).  The resilience ladder's host-OOM rung calls this
        (utils/membudget.spill_source); a kill mid-spill leaves only a
        ``*.tmp`` the relaunched attempt overwrites (data/io.SpillWriter
        protocol, drilled by dev/oom_gate.py)."""
        import tempfile

        from oap_mllib_tpu.config import get_config
        from oap_mllib_tpu.data import io as _io

        if path is None:
            import os

            d = get_config().spill_dir or tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            fd, path = tempfile.mkstemp(
                dir=d, prefix="oap-spill.", suffix=".npy"
            )
            os.close(fd)
        with _io.SpillWriter(path, self.n_features, self.dtype) as w:
            for chunk, n_valid in self:
                w.write(chunk[:n_valid])
        return ChunkSource.from_npy(
            path, self.chunk_rows, fault_site="spill.read"
        )

    @classmethod
    def from_csv(
        cls, path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS,
        delimiter: str = ",", n_features: Optional[int] = None,
        dtype=np.float64,
    ) -> "ChunkSource":
        """Stream a headerless numeric CSV without loading it whole.
        ``dtype`` defaults to f64 to match the eager read_csv reader."""
        if n_features is None:
            with open(path) as f:
                first = f.readline()
            n_features = len(first.strip().split(delimiter))

        def gen():
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rows.append([float(v) for v in line.split(delimiter)])
                    if len(rows) == chunk_rows:
                        yield np.asarray(rows)
                        rows = []
            if rows:
                yield np.asarray(rows)

        return cls(gen, n_features, chunk_rows, dtype=dtype,
                   backing="disk")

    @classmethod
    def from_libsvm(
        cls, path: str, n_features: int, chunk_rows: int = DEFAULT_CHUNK_ROWS,
        dtype=np.float64,
    ) -> "ChunkSource":
        """Stream a libsvm file (1-based indices); labels are dropped, as in
        the K-Means examples.  ``n_features`` must be given — a streaming
        reader cannot discover the max index without a full pass.
        ``dtype`` defaults to f64 to match the eager read_libsvm reader."""

        def gen():
            rows = np.zeros((chunk_rows, n_features), dtype)
            fill = 0
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    for tok in line.split()[1:]:
                        idx, val = tok.split(":")
                        i = int(idx)
                        if i > n_features:
                            raise ValueError(
                                f"libsvm index {i} exceeds n_features={n_features}"
                            )
                        rows[fill, i - 1] = float(val)
                    fill += 1
                    if fill == chunk_rows:
                        yield rows
                        rows = np.zeros_like(rows)
                        fill = 0
            if fill:
                yield rows[:fill]

        return cls(gen, n_features, chunk_rows, dtype=dtype,
                   backing="disk")
