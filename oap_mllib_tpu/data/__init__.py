"""Data plane: device-resident tables and file readers.

TPU-native replacement for the reference's L3 data-plane conversion
(mllib-dal OneDAL.scala: RDD[Vector] -> per-partition HomogenNumericTable ->
executor-local RowMergedNumericTable) and its Java/C++ table layer
(OneDAL.cpp).  Here a "table" is a logically-global `jax.Array` row-sharded
over the mesh, with explicit valid-row accounting because XLA shapes are
static and rows are padded.
"""

from oap_mllib_tpu.data.table import DenseTable, CSRTable
from oap_mllib_tpu.data.io import (
    read_libsvm,
    read_csv,
    read_ratings,
)
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats

__all__ = [
    "DenseTable",
    "CSRTable",
    "ChunkSource",
    "Prefetcher",
    "PrefetchStats",
    "read_libsvm",
    "read_csv",
    "read_ratings",
]
