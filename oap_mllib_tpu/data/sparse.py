"""SciPy-sparse ingestion helpers: densify per chunk, never whole.

Spark's estimators accept sparse vectors natively; this stack's device
tables are dense (the XLA static-shape contract), so sparse input must
densify SOMEWHERE.  Before ISSUE 12 the somewhere was the caller — a
full ``.toarray()`` whose peak host footprint is the entire dense table
on top of the CSR.  These helpers densify one row block at a time at
staging time instead: ``ChunkSource.from_array`` yields per-chunk dense
slices, and :func:`densify_into` fills a preallocated padded table block
by block for ``DenseTable.from_numpy`` — peak host extra is O(block),
regression-tested in tests/test_sparse_ingest.py.

SciPy stays an OPTIONAL dependency: detection duck-types on the module
name, so this package never imports scipy unless the caller already
passed a scipy object in.
"""

from __future__ import annotations

import numpy as np

# rows densified per block when filling a dense table from CSR — 8k rows
# of f32 at d=256 is ~8 MB, far under any host budget while keeping the
# per-block overhead negligible
DENSIFY_BLOCK_ROWS = 8192


def is_sparse(x) -> bool:
    """True for scipy.sparse matrices/arrays (any format), without
    importing scipy: anything the caller passes is already imported."""
    mod = type(x).__module__ or ""
    return mod.startswith("scipy.sparse") and hasattr(x, "tocsr")


def densify_into(out: np.ndarray, x, n_rows: int,
                 block_rows: int = DENSIFY_BLOCK_ROWS) -> None:
    """Fill ``out[:n_rows]`` with the dense rows of sparse ``x``, one
    ``block_rows`` slice at a time (CSR row slicing is O(slice nnz)).
    ``out`` is the caller's preallocated (padded) table — no full dense
    intermediate ever exists."""
    csr = x.tocsr()
    for lo in range(0, n_rows, block_rows):
        hi = min(lo + block_rows, n_rows)
        out[lo:hi] = csr[lo:hi].toarray()


def nbytes(x) -> int:
    """Host bytes a sparse matrix actually occupies (data + indices +
    indptr) — what the planner prices for sparse inputs instead of the
    dense n*d footprint."""
    csr = x.tocsr()
    return int(csr.data.nbytes + csr.indices.nbytes + csr.indptr.nbytes)
