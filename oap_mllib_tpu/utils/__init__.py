"""Utilities: capability dispatch, per-phase timing, logging."""

from oap_mllib_tpu.utils.dispatch import (
    accelerator_available,
    platform_compatible,
    should_accelerate,
)
from oap_mllib_tpu.utils.timing import phase_timer, Timings

__all__ = [
    "accelerator_available",
    "platform_compatible",
    "should_accelerate",
    "phase_timer",
    "Timings",
]
