"""Capability check + transparent dispatch.

Replaces the reference's L5 runtime dispatch: the cluster-wide platform
compatibility gate (``Utils.checkClusterPlatformCompatibility`` running
``daal_check_is_intel_cpu()`` on driver + every executor, reference
Utils.scala:98-115 / OneDAL.cpp:96-102) and the per-algorithm guards in the
Spark shims (e.g. euclidean-only + no-weight for K-Means,
spark-3.1.1/ml/clustering/KMeans.scala:349-351; d<65535 for PCA,
PCA.scala:103; implicitPrefs for ALS, ALS.scala:925).

Semantics preserved: when the predicate fails and ``config.fallback`` is
True, the estimator silently runs the CPU/NumPy reference path — user code
unchanged.  When fallback is disabled, failing the predicate raises.
"""

from __future__ import annotations

import logging

import jax

from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")

# PCA feature-count guard, mirroring the reference's numFeatures < 65535
# (spark-3.1.1/ml/feature/PCA.scala:103) — there it is a oneDAL table limit,
# here it bounds the replicated d x d Gram matrix (65534^2 f64 ~ 34 GB is
# far past one chip's HBM; realistic ceiling enforced at estimator level).
MAX_PCA_FEATURES = 65535


def accelerator_available() -> bool:
    """True if a non-CPU XLA backend is present (~ daal_check_is_intel_cpu)."""
    try:
        return jax.devices()[0].platform != "cpu"
    except RuntimeError:
        return False


def platform_compatible() -> bool:
    """Cluster-wide compatibility: can we run compiled sharded programs?

    Single-process: any JAX backend works (CPU included — the CPU backend is
    this framework's 1-rank pseudo-cluster, like the reference's local[*]
    1-rank CCL world, Utils.scala:119-121).  The ``device`` config forces the
    decision either way.
    """
    cfg = get_config()
    if cfg.device == "cpu":
        return False
    if cfg.device == "tpu":
        return accelerator_available()
    # auto: accelerated path whenever JAX initializes at all
    try:
        jax.devices()
        return True
    except RuntimeError:
        return False


def should_accelerate(algo: str, guard_ok: bool, reason: str = "") -> bool:
    """Decide accelerated vs. fallback path; raise if fallback disabled.

    Every estimator fit funnels through here, so this is also where the
    persistent XLA compilation cache is wired (Config
    .compilation_cache_dir -> jax compilation_cache_dir, idempotent) —
    before the first program of the fit traces."""
    cfg = get_config()
    if cfg.compilation_cache_dir:
        from oap_mllib_tpu.utils.progcache import ensure_persistent_cache

        ensure_persistent_cache(cfg.compilation_cache_dir)
    ok = platform_compatible() and guard_ok
    if ok:
        return True
    if not guard_ok:
        why = reason or "guard failed"
    else:
        why = "platform incompatible"
    if not cfg.fallback:
        raise RuntimeError(
            f"{algo}: accelerated path unavailable ({why}) and fallback disabled"
        )
    log.info("%s: falling back to CPU reference path (%s)", algo, why)
    return False


def allow_fallback(algo: str, why: str) -> bool:
    """The DYNAMIC half of the fallback contract: may a fit that already
    passed :func:`should_accelerate` but then faulted at runtime degrade
    to the CPU reference path?

    ``should_accelerate`` is the static gate (decided once, up front);
    this is its runtime twin, consulted by the resilience ladder
    (utils/resilience.resilient_fit) as its final rung after transient
    retries and the halved-chunk OOM rung are exhausted.  Same knob
    (``Config.fallback``), same logging shape — so the escalation is
    visible in logs exactly like a static fallback, just with the fault
    that caused it."""
    cfg = get_config()
    if not cfg.fallback:
        return False
    log.warning("%s: degrading to CPU reference path (%s)", algo, why)
    return True
