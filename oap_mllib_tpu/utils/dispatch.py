"""Capability check + transparent dispatch.

Replaces the reference's L5 runtime dispatch: the cluster-wide platform
compatibility gate (``Utils.checkClusterPlatformCompatibility`` running
``daal_check_is_intel_cpu()`` on driver + every executor, reference
Utils.scala:98-115 / OneDAL.cpp:96-102) and the per-algorithm guards in the
Spark shims (e.g. euclidean-only + no-weight for K-Means,
spark-3.1.1/ml/clustering/KMeans.scala:349-351; d<65535 for PCA,
PCA.scala:103; implicitPrefs for ALS, ALS.scala:925).

Semantics preserved: when the predicate fails and ``config.fallback`` is
True, the estimator silently runs the CPU/NumPy reference path — user code
unchanged.  When fallback is disabled, failing the predicate raises.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")

# PCA feature-count guard, mirroring the reference's numFeatures < 65535
# (spark-3.1.1/ml/feature/PCA.scala:103) — there it is a oneDAL table limit,
# here it bounds the replicated d x d Gram matrix (65534^2 f64 ~ 34 GB is
# far past one chip's HBM; realistic ceiling enforced at estimator level).
MAX_PCA_FEATURES = 65535


def accelerator_available() -> bool:
    """True if a non-CPU XLA backend is present (~ daal_check_is_intel_cpu)."""
    try:
        return jax.devices()[0].platform != "cpu"
    except RuntimeError:
        return False


def platform_compatible() -> bool:
    """Cluster-wide compatibility: can we run compiled sharded programs?

    Single-process: any JAX backend works (CPU included — the CPU backend is
    this framework's 1-rank pseudo-cluster, like the reference's local[*]
    1-rank CCL world, Utils.scala:119-121).  The ``device`` config forces the
    decision either way.
    """
    cfg = get_config()
    if cfg.device == "cpu":
        return False
    if cfg.device == "tpu":
        return accelerator_available()
    # auto: accelerated path whenever JAX initializes at all
    try:
        jax.devices()
        return True
    except RuntimeError:
        return False


def should_accelerate(algo: str, guard_ok: bool, reason: str = "") -> bool:
    """Decide accelerated vs. fallback path; raise if fallback disabled.

    Every estimator fit funnels through here, so this is also where the
    persistent XLA compilation cache is wired (Config
    .compilation_cache_dir -> jax compilation_cache_dir, idempotent) —
    before the first program of the fit traces — and where the kernel
    autotuner's mode string is validated (ops/pallas/autotune.parse_mode:
    a Config.tuning typo raises HERE, at fit entry, not deep inside a
    kernel launch)."""
    cfg = get_config()
    if cfg.compilation_cache_dir:
        from oap_mllib_tpu.utils.progcache import ensure_persistent_cache

        ensure_persistent_cache(cfg.compilation_cache_dir)
    from oap_mllib_tpu.ops.pallas.autotune import parse_mode

    parse_mode(cfg.tuning)
    ok = platform_compatible() and guard_ok
    if ok:
        return True
    if not guard_ok:
        why = reason or "guard failed"
    else:
        why = "platform incompatible"
    if not cfg.fallback:
        raise RuntimeError(
            f"{algo}: accelerated path unavailable ({why}) and fallback disabled"
        )
    log.info("%s: falling back to CPU reference path (%s)", algo, why)
    return False


# ---------------------------------------------------------------------------
# Per-rank throughput probe (ISSUE 15: capability-weighted sharding)
# ---------------------------------------------------------------------------

# probe geometry: small enough to cost tens of milliseconds anywhere,
# big enough that the matmul leg exercises the MXU/BLAS path and the
# stream leg a real host->device transfer (1 MB)
_PROBE_DIM = 256
_PROBE_STREAM_ROWS = 1024
_PROBE_CHAIN = 8  # chained matmuls per timed launch (amortizes dispatch)
_PROBE_REPS = 3
# reference walls a "typical" host lands near, so capability ~= 1.0 on
# ordinary hardware and the weights read as relative speeds.  Absolute
# calibration does not matter — the planner normalizes to mean 1 — but
# a stable scale keeps logs and pinned-vs-probed values comparable.
_PROBE_REF_COMPUTE_S = 2e-3
_PROBE_REF_STREAM_S = 1e-3

_probe_cache: dict = {}


def throughput_probe(seed: int = 0) -> float:
    """This rank's measured throughput capability (relative scalar, > 0).

    A tiny calibrated microbench: ``_PROBE_CHAIN`` chained
    (256, 256) matmuls through one registry-cached compiled program
    (the compute leg) plus a 1 MB host->device stage (the stream leg),
    best-of-``_PROBE_REPS`` each, combined harmonically — a rank slow at
    EITHER leg is a slow rank (streamed passes pay both).  The input is
    deterministic-seeded so every rank times the same program on the
    same bits; the result is cached per process per
    ``Config.probe_epoch`` (the once-per-fit-start allgather in
    ops/stream_ops.capability_sync reads the cache).  The supervisor
    bumps the epoch on every relaunch attempt, so a relaunched rank
    re-measures its CURRENT capability instead of trusting its
    pre-preemption value.  ``Config.rank_capability`` pins the value
    instead (tests, known deployments) — see :func:`pinned_capability`.
    """
    key = (int(seed), int(get_config().probe_epoch))
    if key in _probe_cache:
        return _probe_cache[key]
    import numpy as np

    from oap_mllib_tpu.utils.progcache import get_or_build

    rng = np.random.default_rng(seed)
    a = np.asarray(rng.normal(size=(_PROBE_DIM, _PROBE_DIM)), np.float32)
    stream_buf = np.asarray(
        rng.normal(size=(_PROBE_STREAM_ROWS, _PROBE_DIM)), np.float32
    )

    def _build():
        import jax
        import jax.numpy as jnp

        def chain(x):
            y = x
            for _ in range(_PROBE_CHAIN):
                y = jnp.dot(y, x, precision=jax.lax.Precision.HIGHEST)
                # renormalize so the chain cannot overflow whatever the
                # seed drew; one cheap VPU op per matmul
                y = y * (1.0 / jnp.maximum(jnp.max(jnp.abs(y)), 1.0))
            return y

        return jax.jit(chain)

    import jax

    fn = get_or_build(
        "dispatch.probe",
        (jax.default_backend(), _PROBE_DIM, _PROBE_CHAIN),
        _build,
    )
    aj = jax.device_put(a)
    np.asarray(fn(aj))  # warm: compile + first dispatch
    compute_s = min(
        _timed(lambda: np.asarray(fn(aj))) for _ in range(_PROBE_REPS)
    )
    np.asarray(jax.device_put(stream_buf))[0, 0]  # warm the transfer path
    stream_s = min(
        _timed(lambda: np.asarray(jax.device_put(stream_buf))[0, 0])
        for _ in range(_PROBE_REPS)
    )
    c = _PROBE_REF_COMPUTE_S / max(compute_s, 1e-9)
    s = _PROBE_REF_STREAM_S / max(stream_s, 1e-9)
    cap = 2.0 / (1.0 / max(c, 1e-9) + 1.0 / max(s, 1e-9))  # harmonic mean
    cap = max(float(cap), 1e-6)
    _probe_cache[key] = cap
    log.info(
        "throughput probe: compute %.3f ms, stream %.3f ms -> "
        "capability %.3f", compute_s * 1e3, stream_s * 1e3, cap,
    )
    return cap


def _timed(fn) -> float:
    from oap_mllib_tpu.utils.timing import tick

    elapsed = tick()
    fn()
    return elapsed()


def pinned_capability(cfg=None) -> Optional[float]:
    """The pinned capability for THIS rank from ``Config.rank_capability``,
    or None when the probe should run.  Grammar: ``""`` = probe; a bare
    float (``"0.25"``) pins this rank; a comma map keyed by rank
    (``"0:1.0,1:0.25"``) pins per rank — ranks absent from the map fall
    back to the probe.  Values must be > 0; a typo raises (the
    kmeans_kernel/fault_spec contract: a capability that silently parses
    to nothing defeats the planner)."""
    from oap_mllib_tpu.config import get_config as _gc

    cfg = cfg or _gc()
    spec = cfg.rank_capability.strip()
    if not spec:
        return None

    def _value(tok: str) -> float:
        try:
            v = float(tok)
        except ValueError:
            raise ValueError(
                "rank_capability must be empty (probe), a float, or a "
                f"comma map 'rank:value,...'; got {cfg.rank_capability!r}"
            ) from None
        if v <= 0:
            raise ValueError(
                f"rank_capability values must be > 0, got {tok!r}"
            )
        return v

    if ":" not in spec:
        return _value(spec)
    rank = _probe_rank()
    found = None
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(
                "rank_capability map entries must be 'rank:value', got "
                f"{entry!r}"
            )
        r_s, v_s = entry.split(":", 1)
        try:
            r = int(r_s)
        except ValueError:
            raise ValueError(
                f"rank_capability map rank must be an int, got {r_s!r}"
            ) from None
        v = _value(v_s)
        if r == rank:
            found = v
    return found


def _probe_rank() -> int:
    try:
        return int(jax.process_index())
    except RuntimeError:
        from oap_mllib_tpu.config import get_config as _gc

        return int(_gc().process_id)


def rank_capability(seed: int = 0) -> "tuple[float, str]":
    """This rank's capability weight and its origin: ``("pinned", v)``
    from ``Config.rank_capability`` when it covers this rank, else the
    cached :func:`throughput_probe` measurement."""
    pinned = pinned_capability()
    if pinned is not None:
        return pinned, "pinned"
    return throughput_probe(seed), "probe"


def _reset_probe_for_tests() -> None:
    _probe_cache.clear()


def allow_fallback(algo: str, why: str) -> bool:
    """The DYNAMIC half of the fallback contract: may a fit that already
    passed :func:`should_accelerate` but then faulted at runtime degrade
    to the CPU reference path?

    ``should_accelerate`` is the static gate (decided once, up front);
    this is its runtime twin, consulted by the resilience ladder
    (utils/resilience.resilient_fit) as its final rung after transient
    retries and the halved-chunk OOM rung are exhausted.  Same knob
    (``Config.fallback``), same logging shape — so the escalation is
    visible in logs exactly like a static fallback, just with the fault
    that caused it."""
    cfg = get_config()
    if not cfg.fallback:
        return False
    log.warning("%s: degrading to CPU reference path (%s)", algo, why)
    return True
