"""Version tolerance for the jax APIs the framework leans on.

The sharded programs target the stable ``jax.shard_map`` entry point
(newer jax lines); older installed lines only ship
``jax.experimental.shard_map.shard_map`` and spell the replication
checker ``check_rep`` instead of ``check_vma``.  One shim keeps every
call site on the new spelling so the package runs on both without a
version pin (the container's jax is whatever the image baked in).
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where available, else the experimental entry
    with ``check_vma`` mapped onto its ``check_rep`` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def set_cpu_device_count(n: int) -> None:
    """Ask for ``n`` virtual CPU devices: the config option on jax lines
    that have it, the XLA_FLAGS env (must be set before the backend
    initializes) otherwise.  Callers set the env var themselves before
    importing jax; this only applies the config-option half."""
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", n)
