"""Runtime sanitizer plane: witness the SPMD invariants the static pass
proves (dev/oaplint/dataflow.py), at the moment they would otherwise
become a hang.

The multi-rank failure mode this framework must never ship is the silent
one: a collective issued under rank-divergent control flow does not
error — every rank blocks inside a different (or missing) collective
until the distributed timeout kills the world, with no diagnostic naming
the op that diverged.  The static analyzer catches the *reachable*
divergences (oaplint R16-R18); this module catches the rest at runtime,
opt-in via ``Config.sanitizers`` (comma-set, default off — the
sanitizers-off path is one cached string check per seam):

- ``collective`` — every host-level collective dispatch (the eager
  facade in parallel/collective.py and the host-mediated
  ``process_allgather`` reductions in ops/stream_ops.py) records an
  (op, axis, shape, dtype) fingerprint AND cross-checks it against every
  other rank *before* dispatching.  A rank-divergent collective then
  raises :class:`CollectiveDivergenceError` on every rank, naming this
  rank's op and the first differing rank's op — instead of hanging.  The
  per-fit fingerprint sequence is digested into the fit summary at
  finalization (telemetry/export.finalize_fit) and cross-checked once
  more there, so a tail divergence (one rank issuing extra ops after the
  last common collective) is caught at the fit boundary.
- ``transfer`` — streamed per-chunk consumer loop bodies run under
  ``jax.transfer_guard("disallow")`` (data/prefetch.Prefetcher), so an
  *implicit* device<->host transfer in the hot loop fails loudly — the
  runtime ground truth behind oaplint R4 (stream-host-sync).  The two
  audited host-accumulation sites in ops/stream_ops.py (which carry
  reasoned lint suppressions) run under :func:`allow_transfers`, the
  runtime analog of the suppression.  Backend caveat: the CPU backend's
  device buffers alias host memory, so device->host reads never
  trigger the guard there — CPU legs witness implicit host->device
  transfers only; TPU witnesses both directions.
- ``retrace`` — steady-state loops must compile nothing after warmup:
  the prefetch pipeline asserts zero new XLA backend compiles
  (utils/progcache.xla_compile_count — the same ground truth the
  compile gate uses) from the second consumed chunk on, and
  :func:`steady_state` offers the same assertion as a scope for
  fit/score loops (dev/sanitizer_gate.py drives it).
- ``locks`` — the host thread plane's analog of ``collective``: the
  registered :class:`~oap_mllib_tpu.utils.locktrace.TrackedLock` seams
  (serving registry, fleet state/server, telemetry sink, this module's
  sequence lock) record per-thread acquisition stacks and fold a
  process-wide acquisition-order graph; a live lock-order inversion
  raises :class:`LockOrderError` naming BOTH witness stacks before it
  can deadlock, every release feeds the ``oap_lock_hold_seconds``
  factor-4 histogram, and a hold exceeding the collective deadline is
  flagged (never killed).  The runtime half of the static concurrency
  pass (dev/oaplint/concurrency.py R19-R22), exactly as this module is
  the runtime half of R16-R18.

The cross-check protocol piggybacks on ``process_allgather`` with a
FIXED-shape signature frame, so the check itself can never diverge in
shape: ranks exchange their padded signature bytes, every rank compares
the full set, and all ranks raise together on mismatch.  The portable
-collective redistribution work and DrJAX's MapReduce primitives
(PAPERS.md arXiv:2112.01075, arXiv:2403.07128) both assume exactly the
invariant being witnessed here — every rank executes the same collective
sequence over well-formed axes.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
from typing import Dict, FrozenSet, List, Optional, Tuple

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

VALID = ("collective", "transfer", "retrace", "locks")

# fixed signature frame for the cross-check gather: every rank always
# contributes exactly this many bytes, whatever its op — the check
# itself is shape-uniform by construction
_SIG_BYTES = 192


class SanitizerError(RuntimeError):
    """Base class for sanitizer-witnessed invariant violations."""


class CollectiveDivergenceError(SanitizerError):
    """Ranks disagreed on the next collective (op, axis, shape, dtype)."""


class RetraceError(SanitizerError):
    """A steady-state loop compiled a new XLA program after warmup."""


class LockOrderError(SanitizerError):
    """Two threads acquired the same two tracked locks in opposite
    orders — a deadlock caught at acquisition time, naming both witness
    stacks (raised by utils/locktrace before blocking)."""


# -- Config.sanitizers parsing ------------------------------------------------

_parse_cache: Dict[str, FrozenSet[str]] = {}
# guards _parse_cache mutation: enabled_set is reachable from prefetch
# producer threads (tracked-lock seams book metrics there), and a bare
# dict write from two threads is exactly what oaplint R20 flags.  A
# dedicated plain lock — NOT the tracked sequence lock — because the
# locks sanitizer's own arming check routes through here (recursion).
_parse_lock = threading.Lock()


def enabled_set(cfg=None) -> FrozenSet[str]:
    """The validated sanitizer set from ``Config.sanitizers`` (env
    ``OAP_MLLIB_TPU_SANITIZERS``).  A typo'd name raises naming the
    valid set — the kmeans_kernel/fault_spec contract: a sanitizer
    config that silently arms nothing defeats the point."""
    raw = (cfg or get_config()).sanitizers
    hit = _parse_cache.get(raw)
    if hit is not None:
        return hit
    names = frozenset(n.strip() for n in raw.split(",") if n.strip())
    unknown = sorted(names - set(VALID))
    if unknown:
        raise ValueError(
            f"Config.sanitizers names unknown sanitizer(s) {unknown}; "
            f"valid names: {VALID} (comma-separated)"
        )
    with _parse_lock:
        _parse_cache[raw] = names
    return names


def enabled(name: str) -> bool:
    """Is one sanitizer armed?  The off path is one config-string read
    plus a dict hit — cheap enough for per-dispatch seams."""
    raw = get_config().sanitizers
    if not raw:
        return False
    return name in enabled_set()


# -- collective fingerprinting + cross-check ----------------------------------

# the sequence lock rides the locks sanitizer's own seam (a tracked
# lock is a plain lock + one cached config check while disarmed)
_lock = locktrace.TrackedLock("sanitizers.seq", threading.Lock())
_SEQ: List[str] = []  # host-level dispatch signatures, process-lifetime
_finalized_idx = 0  # start of the current fit's window into _SEQ


def _world() -> int:
    import jax

    return jax.process_count()


def _signature(op: str, axis: str, shape, dtype) -> str:
    return f"{op}|{axis}|{tuple(shape)}|{dtype}"


def _reduced_tag(dtype) -> str:
    # tag reduced-precision payloads so a policy divergence (one rank
    # staging bf16, another f32) shows up in the fingerprint too
    from oap_mllib_tpu.utils import precision as psn

    return "reduced" if psn.is_reduced_dtype(dtype) else "full"


def _gather_frames(frame: bytes) -> List[bytes]:
    """Exchange one fixed-size signature frame per rank; returns the
    rank-ordered frames.  The payload shape is identical on every rank
    whatever its op, so this gather pairs even when the ops diverge —
    that pairing is what converts the hang into a diagnostic."""
    import numpy as np
    from jax.experimental import multihost_utils

    from oap_mllib_tpu.utils import recovery

    buf = np.zeros((_SIG_BYTES,), np.uint8)
    raw = frame[:_SIG_BYTES]
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    # the cross-check itself is a host collective: a peer that died
    # before ITS check must not wedge the checker — the deadline
    # watchdog applies here like at every other dispatch seam
    gathered = np.asarray(recovery.guarded_dispatch(
        "sanitizer.crosscheck", "host",
        lambda: multihost_utils.process_allgather(buf),
    ))
    return [bytes(gathered[r]).rstrip(b"\x00") for r in range(gathered.shape[0])]


def _raise_divergence(kind: str, mine: str, frames: List[bytes]) -> None:
    import jax

    from oap_mllib_tpu.telemetry import flightrec

    me = jax.process_index()
    peers = []
    first_bad = None
    for r, f in enumerate(frames):
        sig = f.decode("utf-8", "replace")
        peers.append(f"  rank {r}: {sig}")
        if first_bad is None and sig != mine:
            first_bad = (r, sig)
    _tm.counter(
        "oap_sanitizer_violations_total", {"sanitizer": "collective"},
        help="Sanitizer-witnessed invariant violations",
    ).inc()
    assert first_bad is not None
    # with the flight recorder armed, the dispatch being cross-checked is
    # the newest collective event in THIS rank's ring — the per-op check
    # fires on the first disagreement, so that seq IS the first
    # diverging event; the peers' rings ride their crash records
    recorder_note = ""
    if flightrec.enabled():
        recorder_note = (
            f"  Flight recorder: first diverging event on this rank is "
            f"seq {flightrec.last_seq()} (peer tails ride crash records "
            "— docs/observability.md#flight-recorder).\n"
        )
    raise CollectiveDivergenceError(
        f"collective sanitizer: rank-divergent {kind} — rank {me} is "
        f"dispatching [{mine}] but rank {first_bad[0]} is dispatching "
        f"[{first_bad[1]}]; every rank must issue the same collective "
        "sequence (static-world contract, docs/distributed.md).\n"
        + recorder_note
        + "Full world view:\n" + "\n".join(peers)
    )


def note_collective(op: str, axis: str, shape, dtype,
                    crosscheck: bool = True) -> None:
    """Record one host-level collective dispatch signature and — in a
    multi-process world — cross-check it against every rank BEFORE the
    dispatch.  Called from the eager facade (parallel/collective.py) and
    the host-mediated reductions (ops/stream_ops.py); no-op unless the
    ``collective`` sanitizer is armed."""
    if not enabled("collective"):
        return
    sig = _signature(op, axis, shape, f"{dtype}:{_reduced_tag(dtype)}")
    with _lock:
        _SEQ.append(sig)
    _tm.counter(
        "oap_sanitizer_collective_ops_total",
        help="Host-level collective dispatches fingerprinted by the "
             "collective sanitizer",
    ).inc()
    if crosscheck and _world() > 1:
        frames = _gather_frames(b"op:" + sig.encode())
        mine = "op:" + sig
        if any(f.decode("utf-8", "replace") != mine for f in frames):
            _raise_divergence("collective", mine, frames)


def fingerprint(since: Optional[int] = None) -> Tuple[int, str]:
    """(op count, hex digest) of the recorded dispatch sequence from
    ``since`` (default: the current fit window) to now."""
    with _lock:
        start = _finalized_idx if since is None else since
        window = _SEQ[start:]
    h = hashlib.sha256()
    for sig in window:
        h.update(sig.encode())
        h.update(b"\x00")
    return len(window), h.hexdigest()[:16]


def finalize_fit_sanitizers(summary) -> None:
    """Fit-boundary hook (telemetry/export.finalize_fit): attach the
    armed sanitizer set and the fit's collective fingerprint to the
    summary, and cross-check the fingerprint across ranks — the backstop
    that catches a tail divergence (extra ops after the last common
    collective, which no per-op check could pair).  Advances the fit
    window so the next fit fingerprints only its own ops."""
    global _finalized_idx
    cfg = get_config()
    if not cfg.sanitizers:
        return
    armed = enabled_set(cfg)
    payload: Dict[str, object] = {"enabled": sorted(armed)}
    if "collective" in armed:
        count, digest = fingerprint()
        with _lock:
            _finalized_idx = len(_SEQ)
        checked = False
        if _world() > 1:
            frame = f"fit:{count}:{digest}".encode()
            frames = _gather_frames(frame)
            mine = frame.decode()
            if any(f.decode("utf-8", "replace") != mine for f in frames):
                _raise_divergence(
                    "fit collective fingerprint (op count:digest)",
                    mine, frames,
                )
            checked = True
        payload["collective"] = {
            "ops": count, "fingerprint": digest, "world_checked": checked,
        }
    if "locks" in armed:
        payload["locks"] = locktrace.summary_block()
    if summary is not None:
        if isinstance(summary, dict):
            summary["sanitizers"] = payload
        else:
            summary.sanitizers = payload


# -- transfer sanitizer --------------------------------------------------------


@contextlib.contextmanager
def transfer_scope():
    """``jax.transfer_guard("disallow")`` for a streamed consumer loop
    body — an implicit device<->host transfer inside raises.  Caller
    guards on :func:`enabled`; this scope always applies."""
    import jax

    _tm.counter(
        "oap_sanitizer_transfer_scopes_total",
        help="Per-chunk consumer bodies guarded by the transfer sanitizer",
    ).inc()
    with jax.transfer_guard("disallow"):
        yield


@contextlib.contextmanager
def allow_transfers():
    """Audited opt-out inside a guarded loop — the runtime analog of a
    reasoned ``stream-host-sync`` lint suppression: the two
    host-accumulation sites in ops/stream_ops.py are *designed* host
    syncs, so the transfer sanitizer must not convert the audit into a
    false positive.  No-op when the sanitizer is off."""
    if not enabled("transfer"):
        yield
        return
    import jax

    with jax.transfer_guard("allow"):
        yield


# -- retrace sanitizer ---------------------------------------------------------


def _compile_count() -> int:
    from oap_mllib_tpu.utils import progcache

    return progcache.xla_compile_count()


class RetraceWatch:
    """Zero-compiles-after-warmup assertion for a chunk loop: arm after
    the first consumed chunk (its step legitimately pays trace + XLA
    compile for the pass's program), then every later chunk boundary
    must see the same XLA backend-compile count."""

    __slots__ = ("label", "_base")

    def __init__(self, label: str):
        self.label = label
        self._base: Optional[int] = None

    def chunk_done(self, index: int) -> None:
        """Called after the consumer finished chunk ``index`` (0-based)."""
        if index == 0:
            self._base = _compile_count()
            return
        if self._base is None:
            return
        now = _compile_count()
        if now > self._base:
            _tm.counter(
                "oap_sanitizer_violations_total", {"sanitizer": "retrace"},
                help="Sanitizer-witnessed invariant violations",
            ).inc()
            raise RetraceError(
                f"retrace sanitizer: {now - self._base} new XLA backend "
                f"compile(s) after warmup in steady-state loop "
                f"'{self.label}' (chunk {index}); steady-state chunks must "
                "reuse the pass's compiled program (utils/progcache; "
                "compare dev/compile_gate.py's bucketing contract)"
            )


@contextlib.contextmanager
def steady_state(label: str):
    """Assert a scope compiles NOTHING — the serving/refit contract
    after warmup (progcache + shape bucketing guarantee steady-state
    fits compile zero XLA programs).  No-op unless the ``retrace``
    sanitizer is armed; callers run their warmup fits outside the
    scope."""
    if not enabled("retrace"):
        yield
        return
    base = _compile_count()
    yield
    delta = _compile_count() - base
    if delta > 0:
        _tm.counter(
            "oap_sanitizer_violations_total", {"sanitizer": "retrace"},
            help="Sanitizer-witnessed invariant violations",
        ).inc()
        raise RetraceError(
            f"retrace sanitizer: {delta} new XLA backend compile(s) "
            f"inside steady-state scope '{label}'; warm up the exact "
            "shapes first, or widen shape bucketing "
            "(Config.shape_bucketing)"
        )


def _reset_for_tests() -> None:
    """Drop the recorded sequence + fit window (test isolation only)."""
    global _finalized_idx
    # the INNER lock, deliberately: reset must work under any config,
    # including a typo'd sanitizer set whose validation would raise at
    # the tracked seam (the raise belongs to real seams, not teardown)
    with _lock._inner:
        _SEQ.clear()
        _finalized_idx = 0
    with _parse_lock:
        _parse_cache.clear()
    locktrace._reset_for_tests()
