"""Runtime lock-order & hold-time sanitizer: the ``locks`` member of
the sanitizer plane (``Config.sanitizers``).

The static concurrency pass (dev/oaplint/concurrency.py, R19-R22)
proves the *reachable* lock-order inversions and blocking-under-lock
shapes away at build time — but its call resolution is by name and
callables passed as values are opaque, so dynamic interleavings
(callbacks, trampolines, locks taken data-dependently) escape it.  This
module witnesses the same invariants live, the exact pairing PR 7 built
for collectives (analyzer proves what is provable, sanitizer catches
the rest at the moment it would otherwise become a hang):

- :class:`TrackedLock` wraps a ``threading.Lock``/``RLock`` behind the
  registered seams (the serving registry lock, the fleet state/server
  locks, the telemetry sink lock, the sanitizer sequence lock).
  Disarmed (the default), every operation is the inner lock plus ONE
  cached config-string check — the ~0% seam dev/concurrency_gate.py
  bounds on the 20-fit microbench.
- Armed (``locks`` in ``Config.sanitizers``), each acquisition records
  the per-thread held stack and folds (held -> acquiring) edges into a
  process-wide acquisition-order graph.  Acquiring B while holding A
  when some thread previously acquired A while holding B raises
  :class:`~oap_mllib_tpu.utils.sanitizers.LockOrderError` **before**
  blocking on the inner lock — the deadlock becomes a diagnostic naming
  BOTH witness stacks (the recorded first-ordering stack and the live
  inverted one).
- Every release observes the hold time into the factor-4 log-bucket
  ``oap_lock_hold_seconds`` histogram (labelled by lock name), and a
  hold exceeding the collective deadline (``Config.collective_timeout``
  when armed) FLAGS — ``oap_lock_hold_flags_total`` + a warning naming
  the lock — but never kills: a long hold is a diagnosis, not a fault
  (the deadline watchdog owns killing, and only for collectives).

The analyzer models :class:`TrackedLock` construction exactly like a
raw ``threading.Lock`` (``_LOCK_TAILS`` in the concurrency pass), so
wrapping a lock never removes it from the static model.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from oap_mllib_tpu import config as _config_mod
from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")

# frames of the live stack kept per witness (innermost last, tracer
# frames trimmed) — enough to name the call path without dumping pages
_WITNESS_FRAMES = 8

# plain (untracked) lock guarding the order graph; never visible to the
# tracer itself, so it cannot participate in the orders it records
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Dict[str, object]] = {}
_registry: Dict[str, "TrackedLock"] = {}
_tls = threading.local()


def _armed() -> bool:
    """One cached config-string check on the off path; the full
    validated-set parse (typo raises) only once sanitizers are set at
    all.  The live Config object is read WITHOUT the config lock —
    ``set_config`` mutates it in place and a reset leaves ``None``
    (routed to the locking initializer), so the lock-free read is
    always either current or deferred — this seam runs on every
    tracked acquisition and must cost one attribute read when off."""
    cfg = _config_mod._config
    if cfg is None:
        cfg = get_config()
    raw = cfg.sanitizers
    if not raw:
        return False
    from oap_mllib_tpu.utils import sanitizers

    return sanitizers.enabled("locks")


def _held() -> List[List[object]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> List[str]:
    frames = traceback.extract_stack()
    trimmed = [f for f in frames if "locktrace.py" not in f.filename]
    return [
        f"{f.filename}:{f.lineno} in {f.name}"
        for f in trimmed[-_WITNESS_FRAMES:]
    ]


def _order_error(name: str, against: str, witness: Dict[str, object]):
    from oap_mllib_tpu.telemetry import metrics as _tm
    from oap_mllib_tpu.utils.sanitizers import LockOrderError

    _tm.counter(
        "oap_sanitizer_violations_total", {"sanitizer": "locks"},
        help="Sanitizer-witnessed invariant violations",
    ).inc()
    here = "\n    ".join(_stack())
    there = "\n    ".join(witness.get("stack", ()))  # type: ignore[arg-type]
    return LockOrderError(
        f"locks sanitizer: lock-order inversion — thread "
        f"{threading.current_thread().name!r} is acquiring "
        f"{name!r} while holding {against!r}, but thread "
        f"{witness.get('thread')!r} previously acquired {against!r} "
        f"while holding {name!r}.  Two threads interleaving these "
        "orders deadlock; pick one global order (the static analyzer's "
        "R19 finds the reachable cases — dev/oaplint).\n"
        f"  This acquisition:\n    {here}\n"
        f"  Recorded witness ({against!r} after {name!r}):\n    {there}"
    )


def _before_acquire(name: str) -> None:
    """Order check + edge recording, BEFORE blocking on the inner lock
    (so an inversion raises instead of deadlocking)."""
    held = _held()
    if any(h[0] == name for h in held):
        return  # reentrant RLock acquisition: no new edge, no new clock
    held_names = [h[0] for h in held]
    if not held_names:
        return
    with _graph_lock:
        for h in held_names:
            witness = _edges.get((name, h))
            if witness is not None:
                raise _order_error(name, h, witness)
        for h in held_names:
            if (h, name) not in _edges:
                _edges[(h, name)] = {
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                }


def _after_acquire(name: str) -> None:
    held = _held()
    for h in held:
        if h[0] == name:
            h[2] += 1  # type: ignore[operator]
            return
    held.append([name, time.perf_counter(), 1])


def _after_release(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] != name:
            continue
        held[i][2] -= 1  # type: ignore[operator]
        if held[i][2]:
            return
        t0 = held[i][1]
        del held[i]
        _observe_hold(name, time.perf_counter() - float(t0))  # type: ignore[arg-type]
        return


def _observe_hold(name: str, hold_s: float) -> None:
    from oap_mllib_tpu.telemetry import metrics as _tm

    _tm.histogram(
        "oap_lock_hold_seconds", {"lock": name},
        help="Tracked-lock hold times under the locks sanitizer",
    ).observe(hold_s)
    try:
        deadline = float(get_config().collective_timeout)
    except (TypeError, ValueError):
        deadline = 0.0
    if deadline > 0 and hold_s > deadline:
        _tm.counter(
            "oap_lock_hold_flags_total", {"lock": name},
            help="Tracked-lock holds that exceeded the collective "
                 "deadline (flagged, never killed)",
        ).inc()
        log.warning(
            "locks sanitizer: lock %r held %.3fs — longer than "
            "collective_timeout=%.3fs; any collective waiting on work "
            "behind this lock would have expired its deadline "
            "(flagging only, nothing is killed)",
            name, hold_s, deadline,
        )


class TrackedLock:
    """A named lock behind the ``locks`` sanitizer seam.

    Drop-in for the ``threading.Lock``/``RLock`` it wraps (``with``,
    ``acquire``/``release``, ``locked``).  Pass an ``RLock`` as
    ``inner`` for reentrant semantics — reentrant acquisitions are
    recognized per thread and neither re-edge the order graph nor
    restart the hold clock."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner=None):
        self.name = name
        self._inner = threading.Lock() if inner is None else inner
        _registry[name] = self

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        armed = _armed()
        if armed:
            _before_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got and armed:
            _after_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        if _armed():
            _after_release(self.name)

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"TrackedLock({self.name!r})"


def tracked_lock(name: str, inner=None) -> TrackedLock:
    """The functional spelling of :class:`TrackedLock` (same registry)."""
    return TrackedLock(name, inner)


def order_edges() -> Dict[Tuple[str, str], Dict[str, object]]:
    """A copy of the recorded acquisition-order graph (tests/gate)."""
    with _graph_lock:
        return {k: dict(v) for k, v in _edges.items()}


def tracked_names() -> List[str]:
    return sorted(_registry)


def hold_quantile(q: float) -> float:
    """The ``q``-quantile of tracked-lock hold times, merged across
    every lock's ``oap_lock_hold_seconds`` series (0.0 when nothing was
    observed) — the bench's ``lock_hold_p99`` source."""
    from oap_mllib_tpu.telemetry import metrics as _tm

    reg = _tm.registry()
    with _tm._LOCK:
        series = [
            m for (name, _), m in reg._metrics.items()
            if name == "oap_lock_hold_seconds"
        ]
    merged: Optional[_tm.Histogram] = None
    for h in series:
        if merged is None:
            merged = _tm.Histogram(h.bounds)
        for i, c in enumerate(h.counts):
            merged.counts[i] += c
        merged.sum += h.sum
        merged.count += h.count
    if merged is None or merged.count == 0:
        return 0.0
    return _tm.histogram_quantile(merged, q)


def summary_block() -> Dict[str, object]:
    """The ``locks`` entry of ``summary.sanitizers`` when armed."""
    with _graph_lock:
        n_edges = len(_edges)
    return {
        "tracked": len(_registry),
        "order_edges": n_edges,
        "hold_p99_s": hold_quantile(0.99),
    }


def _reset_for_tests() -> None:
    """Drop the order graph and this thread's held stack (test
    isolation; other threads' stacks die with their threads)."""
    with _graph_lock:
        _edges.clear()
    _tls.held = []
