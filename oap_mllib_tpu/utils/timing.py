"""Per-phase wall-time instrumentation.

The reference hand-rolls std::chrono timers around every expensive phase and
prints to stdout (KMeansDALImpl.cpp:202-222, PCADALImpl.cpp:61-120,
ALSDALImpl.cpp:337-437, OneCCL.cpp:53-72; survey §5).  Here the same
observability is one structured registry: ``phase_timer`` context managers
record named durations into a ``Timings`` object attached to each fitted
model's training summary, and optionally log when ``config.timing`` is set.

For deep profiles, wrap a fit in ``jax.profiler.trace`` — the XLA/ICI-level
analog the reference has no equivalent of.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")


class Timings:
    """Ordered registry of (phase -> seconds) measurements."""

    def __init__(self) -> None:
        self._records: List[tuple] = []

    def add(self, phase: str, seconds: float) -> None:
        self._records.append((phase, seconds))
        if get_config().timing:
            log.info("phase %-28s %8.3f s", phase, seconds)

    def as_dict(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for phase, sec in self._records:
            out[phase] = out.get(phase, 0.0) + sec
        return out

    def total(self) -> float:
        return sum(sec for _, sec in self._records)

    def subphases(self, prefix: str) -> Dict[str, float]:
        """The ``<prefix>/<sub>`` records as ``{sub: seconds}`` — the
        streamed pipeline's stage/transfer/compute split lives under the
        owning phase name (``lloyd_loop/stage`` etc.,
        data/prefetch.PrefetchStats.finalize)."""
        out: Dict[str, float] = {}
        pre = prefix + "/"
        for phase, sec in self.as_dict().items():
            if phase.startswith(pre):
                out[phase[len(pre):]] = sec
        return out

    def overlap_efficiency(self, prefix: str) -> Optional[float]:
        """Fraction of a streamed phase's staging (stage + transfer) that
        was hidden behind device compute, in [0, 1]: 0 = fully serial
        (the consumer waited out every stage), 1 = fully hidden.  None
        when the phase recorded no streamed split (not a streamed fit, or
        staging was too fast to measure)."""
        sub = self.subphases(prefix)
        staging = sub.get("stage", 0.0) + sub.get("transfer", 0.0)
        if "stream_wall" not in sub or staging <= 0.0:
            return None
        wait = max(sub["stream_wall"] - sub.get("compute", 0.0), 0.0)
        return max(0.0, min(1.0, 1.0 - wait / staging))

    def compile_split(self, prefix: str) -> Optional[Dict[str, float]]:
        """The ``{compile, execute}`` wall split the program-cache launch
        wrappers record under a phase (utils/progcache.launch): compile =
        first-seen-program launches (trace + XLA compile + first
        dispatch), execute = cache-hit launches (dispatch wall for async
        programs; streamed per-chunk hits are excluded by design — their
        device time is the prefetch ``compute`` split).  None when the
        phase recorded no launches through the registry (e.g. a fallback
        fit)."""
        sub = self.subphases(prefix)
        if "compile" not in sub and "execute" not in sub:
            return None
        return {
            "compile": sub.get("compile", 0.0),
            "execute": sub.get("execute", 0.0),
        }

    def __repr__(self) -> str:
        parts = ", ".join(f"{p}={s:.3f}s" for p, s in self._records)
        return f"Timings({parts})"


@contextlib.contextmanager
def phase_timer(timings: Timings, phase: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        timings.add(phase, time.perf_counter() - t0)


@contextlib.contextmanager
def x64_scope(enable: bool):
    """Temporarily enable jax x64 for one fit; restores the prior value so
    one f64 fit doesn't permanently flip the whole process (the flag is
    process-global)."""
    if not enable:
        yield
        return
    import jax

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)
