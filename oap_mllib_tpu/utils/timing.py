"""Per-phase wall-time instrumentation, stored as a span tree.

The reference hand-rolls std::chrono timers around every expensive phase
and prints to stdout (KMeansDALImpl.cpp:202-222, PCADALImpl.cpp:61-120,
ALSDALImpl.cpp:337-437, OneCCL.cpp:53-72; survey §5).  Here the same
observability is structured: ``phase_timer`` context managers record
named durations into a :class:`Timings` attached to each fitted model's
training summary, and optionally log when ``config.timing`` is set.

Storage moved in ISSUE 4 from a flat record list to a **span tree**
(telemetry/spans.py): ``Timings`` owns a root span named after the fit
(``kmeans.fit`` etc.), ``add``/``phase_timer`` record ``a/b``-style
phase paths as nested spans, and the flat accessors (``as_dict``,
``subphases``, ``overlap_efficiency``, ``compile_split``) are VIEWS over
the tree that return exactly what the record list returned — existing
callers and tests are untouched, while the exporters
(oap_mllib_tpu.telemetry) get real structure to serialize.  Phases
entered via :meth:`Timings.span` also become the thread's *active span*
so deeper layers (the collective facade) can attach measurements, and
emit a ``jax.profiler.TraceAnnotation`` when a profiler trace is live.

For deep profiles, wrap a fit in ``jax.profiler.trace`` — the XLA/ICI-
level analog the reference has no equivalent of (utils/profiling.py).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry.spans import Span, enter

log = logging.getLogger("oap_mllib_tpu")


class Timings:
    """Per-fit phase registry: a named span tree with flat views.

    ``root`` names the owning fit (``kmeans.fit``; bare ``Timings()``
    keeps the anonymous ``"fit"`` root for ad-hoc use).  Phase names may
    be ``a/b`` paths — each segment is a tree level."""

    def __init__(self, root: str = "fit") -> None:
        self.root = Span(root)

    def _owner(self) -> str:
        """The log-line owner tag: the fit root, rank-qualified in
        multi-process worlds so concurrent ranks' interleaved phase
        lines stay attributable (two fits in one log used to be
        indistinguishable — the ISSUE 4 satellite)."""
        cfg = get_config()
        if cfg.num_processes > 1:
            return f"{self.root.name}[r{cfg.process_id}]"
        return self.root.name

    def add(self, phase: str, seconds: float) -> None:
        self.root.node(phase).record(seconds)
        if get_config().timing:
            log.info(
                "%s phase %-28s %8.3f s", self._owner(), phase, seconds
            )

    @contextlib.contextmanager
    def span(self, phase: str):
        """Time one entry of ``phase`` as the thread's active span
        (telemetry/spans.enter: TraceAnnotation when a profiler trace is
        live, collective attribution target otherwise)."""
        node = self.root.node(phase)
        t0 = time.perf_counter()
        try:
            with enter(node):
                yield node
        finally:
            if get_config().timing:
                log.info(
                    "%s phase %-28s %8.3f s",
                    self._owner(), phase, time.perf_counter() - t0,
                )

    # -- flat views (the pre-span-tree surface, value-identical) -------------

    def as_dict(self) -> Dict[str, float]:
        return self.root.flat()

    def total(self) -> float:
        return sum(self.as_dict().values())

    def subphases(self, prefix: str) -> Dict[str, float]:
        """The ``<prefix>/<sub>`` records as ``{sub: seconds}`` — the
        streamed pipeline's stage/transfer/compute split lives under the
        owning phase name (``lloyd_loop/stage`` etc.,
        data/prefetch.PrefetchStats.finalize)."""
        out: Dict[str, float] = {}
        pre = prefix + "/"
        for phase, sec in self.as_dict().items():
            if phase.startswith(pre):
                out[phase[len(pre):]] = sec
        return out

    def overlap_efficiency(self, prefix: str) -> Optional[float]:
        """Fraction of a streamed phase's staging (stage + transfer) that
        was hidden behind device compute, in [0, 1]: 0 = fully serial
        (the consumer waited out every stage), 1 = fully hidden.  None
        when the phase recorded no streamed split (not a streamed fit, or
        staging was too fast to measure)."""
        sub = self.subphases(prefix)
        staging = sub.get("stage", 0.0) + sub.get("transfer", 0.0)
        if "stream_wall" not in sub or staging <= 0.0:
            return None
        wait = max(sub["stream_wall"] - sub.get("compute", 0.0), 0.0)
        return max(0.0, min(1.0, 1.0 - wait / staging))

    def compile_split(self, prefix: str) -> Optional[Dict[str, float]]:
        """The ``{compile, execute}`` wall split the program-cache launch
        wrappers record under a phase (utils/progcache.launch): compile =
        first-seen-program launches (trace + XLA compile + first
        dispatch), execute = cache-hit launches (dispatch wall for async
        programs; streamed per-chunk hits are excluded by design — their
        device time is the prefetch ``compute`` split).  None when the
        phase recorded no launches through the registry (e.g. a fallback
        fit)."""
        sub = self.subphases(prefix)
        if "compile" not in sub and "execute" not in sub:
            return None
        return {
            "compile": sub.get("compile", 0.0),
            "execute": sub.get("execute", 0.0),
        }

    def __repr__(self) -> str:
        parts = ", ".join(
            f"{p}={s:.3f}s" for p, s in self.as_dict().items()
        )
        return f"Timings({parts})"


@contextlib.contextmanager
def phase_timer(timings: Timings, phase: str):
    with timings.span(phase):
        yield


def tick():
    """Duration clock: returns a zero-arg callable yielding the seconds
    elapsed since the ``tick()`` call.  The compute plane (ops/, models/,
    data/) books stage/transfer/wait/compute walls through this instead
    of reading ``time.*`` directly, so clock access stays confined to
    this module and telemetry/ — the oaplint ``nondeterminism`` rule
    (R8) enforces the confinement statically."""
    t0 = time.perf_counter()
    return lambda: time.perf_counter() - t0


@contextlib.contextmanager
def x64_scope(enable: bool):
    """Temporarily enable jax x64 for one fit; restores the prior value so
    one f64 fit doesn't permanently flip the whole process (the flag is
    process-global)."""
    if not enable:
        yield
        return
    import jax

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)
