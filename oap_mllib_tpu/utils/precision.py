"""Process-wide mixed-precision compute policy: bf16/TF32 matmul paths
with f32 accumulation.

The matmul-dominated hot paths (the K-Means Lloyd cross-distances, the
PCA Gram/colsum, the ALS normal-equation moments) all ran at full
f32/``matmul_precision`` while the TPU's native bf16 MXU throughput
(~2x FLOPs, half the HBM bytes per operand) sat idle — BENCH_r05 pins
the Pallas K-Means kernel at MFU 0.333 with ``precision: "high"``.  The
linear-algebraic formulation of these kernels (cf. arXiv:2601.17136's
communication-avoiding kernel K-Means) is exactly the shape where
reduced-precision INPUTS with f32 ACCUMULATION is a bounded-error win,
so this module makes the trade a first-class, per-fit policy:

======  ====================================================================
tier    meaning
======  ====================================================================
f32     today's behavior, bit-compatible: operands stay f32 and every dot
        runs at the configured ``matmul_precision`` tier (the default)
tf32    f32 operands, dots at ``lax.Precision.HIGH`` (bf16_3x — the TPU
        analog of NVIDIA's TF32: reduced-precision multiplies, f32
        accumulation, ~1e-5 of full f32)
bf16    operands cast to bfloat16 — at STAGING time on the streamed paths,
        so host->device transfer bytes halve too — with every dot
        accumulating in f32 (``preferred_element_type``); solves, norms,
        centroid/Gram/moment accumulators and convergence state stay f32
auto    bf16 where a parity bound is registered for the algorithm AND the
        backend has fast bf16 MXUs (mirroring the ``pallas_preferred``
        auto-rule's measured-shapes contract), f32 otherwise
======  ====================================================================

Resolution (:func:`resolve`) honors per-algorithm overrides
(``Config.kmeans_precision`` / ``pca_precision`` / ``als_precision``;
empty inherits ``Config.compute_precision``), pins f32 under
``enable_x64`` (f64 has no bf16 fast path to buy anything with), and
respects the resilience ladder's f32-degradation scope
(:func:`force_f32`): a non-finite iterate under a reduced-precision
policy steps the ladder's ``precision`` rung — the fit retries at f32
instead of failing (utils/resilience.resilient_fit).

The chosen policy is recorded in every accelerated fit summary
(``precision``), on the fit's span-tree root (``attrs["precision"]``,
exported through the telemetry JSONL sink), and in bench JSON.
``dev/precision_gate.py`` asserts the registered parity bounds and that
the f32 policy reproduces pre-policy numerics bit-for-bit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

import numpy as np

from oap_mllib_tpu.config import get_config

TIERS = ("f32", "tf32", "bf16")
CHOICES = TIERS + ("auto",)
ALGOS = ("kmeans", "pca", "als")

# Registered bf16-vs-f32 parity bounds per algorithm, on the fixed-seed
# gate datasets (dev/precision_gate.py asserts them; tests/test_precision
# .py pins them on smaller shapes).  `auto` resolves to bf16 ONLY for
# algorithms registered here — an algorithm without a measured bound must
# not be silently downgraded (the pallas_preferred contract: auto picks
# the fast path only where it was measured safe).  Bounds reflect bf16's
# ~8-bit mantissa (~4e-3 relative per rounding) amplified by the
# conditioning of each estimator's reduction:
PARITY_BOUNDS = {
    # converged centroids (relative to the data scale) and relative cost
    # — cost is the tight bound: bf16 rounding can tie-break boundary
    # points differently and settle a NEARBY local optimum of the same
    # quality, so the centroid bound absorbs benign assignment flips
    "kmeans": {"centroid_rel": 5e-2, "cost_rel": 1e-2},
    # top-k principal-subspace angle (radians) + explained-variance-ratio
    "pca": {"subspace_rad": 5e-2, "ratio_abs": 1e-2},
    # factor RMSE relative to the factor scale + prediction RMSE delta
    "als": {"factor_rel": 5e-2, "rmse_rel": 2e-2},
}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One fit's resolved compute-precision policy.

    ``name`` is the resolved tier (never ``auto``); ``requested`` is what
    config asked for (``auto`` preserved, for summaries/debugging).
    ``input_dtype``/``accum_dtype`` are numpy dtype NAMES (hashable, so a
    policy can ride static jit args); ``dot_tier`` is the legacy
    ``matmul_precision`` tier the f32 dots run at.
    """

    name: str
    requested: str
    input_dtype: str
    accum_dtype: str
    dot_tier: str


def check_tier(name: str) -> str:
    """Validate a resolved tier name (ops-level entry guard): a typo'd
    policy string must raise, never silently run f32 (the
    kmeans_kernel/als_kernel config contract)."""
    if name not in TIERS:
        raise ValueError(
            f"compute_precision tier must be one of {TIERS}, got {name!r}"
        )
    return name


def _check_choice(field: str, value: str) -> str:
    if value not in CHOICES:
        raise ValueError(
            f"{field} must be one of {CHOICES} (empty inherits "
            f"compute_precision for the per-algorithm overrides), got "
            f"{value!r}"
        )
    return value


def legacy_precision(tier: str):
    """Map a ``matmul_precision`` tier to a ``lax.Precision`` (the same
    table as kmeans_ops._prec / pca_ops._cov_prec; duplicated here so
    the policy layer has no import cycle with the ops it serves).
    Unknown values raise — a typo must not silently degrade to bf16."""
    from jax import lax

    try:
        return {
            "highest": lax.Precision.HIGHEST,
            "high": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[tier]
    except KeyError:
        raise ValueError(
            "matmul_precision must be 'highest', 'high', or 'default', "
            f"got {tier!r}"
        ) from None


def _fast_bf16_backend() -> bool:
    """Does the backend have native bf16 matmul units?  TPUs do (the MXU
    is bf16-first); CPU gets no throughput from bf16 casts (jax emulates
    them), so ``auto`` stays f32 there — explicit ``bf16`` still works
    everywhere (parity tests run it on CPU)."""
    import jax

    return jax.default_backend() == "tpu"


# -- thread-local attempt tracking (the resilience ladder's view) ------------

_tls = threading.local()


def begin_attempt() -> None:
    """Reset the resolved-policy record for one fit attempt
    (utils/resilience.resilient_fit calls this before each attempt so
    :func:`reduced_active` reflects only the attempt that faulted)."""
    _tls.resolved = []


def reduced_active() -> bool:
    """Did the current attempt resolve any reduced-precision policy?
    The resilience ladder steps its ``precision`` rung (retry at f32)
    only when this is true — a fit already at f32 must keep the exact
    pre-policy fault semantics."""
    return any(p != "f32" for p in getattr(_tls, "resolved", []))


def forcing_f32() -> bool:
    return bool(getattr(_tls, "force_f32", False))


@contextlib.contextmanager
def force_f32():
    """Scope in which :func:`resolve` pins every policy to f32 — the
    resilience ladder's ``precision`` degradation rung."""
    prev = getattr(_tls, "force_f32", False)
    _tls.force_f32 = True
    try:
        yield
    finally:
        _tls.force_f32 = prev


# -- resolution ---------------------------------------------------------------


def resolve(algo: str, cfg=None) -> PrecisionPolicy:
    """The per-fit policy for ``algo`` ("kmeans" | "pca" | "als").

    Order: per-algorithm override (``<algo>_precision``, empty inherits)
    -> ``compute_precision`` -> ``auto`` resolution (bf16 iff a parity
    bound is registered AND the backend has fast bf16) -> pins: x64
    fits stay f32 (no bf16 fast path for f64), and an active
    :func:`force_f32` scope (the resilience ladder's precision rung)
    overrides everything.  Validates ``matmul_precision`` too, so a
    typo'd tier raises at fit entry on every policy — not only when the
    f32 dots would have read it."""
    if algo not in ALGOS:
        raise ValueError(f"unknown algorithm {algo!r}; expected one of {ALGOS}")
    cfg = cfg or get_config()
    legacy_precision(cfg.matmul_precision)  # typo'd tier fails fast
    requested = _check_choice(
        "compute_precision", cfg.compute_precision
    )
    override = {
        "kmeans": cfg.kmeans_precision,
        "pca": cfg.pca_precision,
        "als": cfg.als_precision,
    }[algo]
    if override:
        requested = _check_choice(f"{algo}_precision", override)
    if forcing_f32():
        name = "f32"
    elif requested == "auto":
        name = (
            "bf16"
            if algo in PARITY_BOUNDS
            and not cfg.enable_x64
            and _fast_bf16_backend()
            else "f32"
        )
    elif cfg.enable_x64:
        # the x64 parity lane always wins: reduced precision under f64
        # would silently break the bit-level reference contract
        name = "f32"
    else:
        name = requested
    if cfg.enable_x64:
        in_dt = acc_dt = "float64"
    elif name == "bf16":
        in_dt, acc_dt = "bfloat16", "float32"
    else:
        in_dt = acc_dt = "float32"
    dot_tier = {
        "f32": cfg.matmul_precision, "tf32": "high", "bf16": "default"
    }[name]
    policy = PrecisionPolicy(
        name=name, requested=requested, input_dtype=in_dt,
        accum_dtype=acc_dt, dot_tier=dot_tier,
    )
    resolved = getattr(_tls, "resolved", None)
    if resolved is None:
        resolved = _tls.resolved = []
    resolved.append(name)
    return policy


def kernel_tier(name: str, matmul_tier: str) -> str:
    """The legacy K-Means/PCA kernel-tier string a policy maps onto
    (the Pallas mode and the XLA Lloyd/Gram ``precision`` argument):
    f32 keeps the configured ``matmul_precision``, tf32 is the bf16_3x
    "high" tier, bf16 the single-pass "default" tier.  One mapping so
    the kernel-dispatch rules (``pallas_preferred``) price a policy
    exactly like the tier it runs at."""
    check_tier(name)
    return {"f32": matmul_tier, "tf32": "high", "bf16": "default"}[name]


def is_reduced_dtype(dtype) -> bool:
    """Is ``dtype`` a reduced-precision tier under the policy (bf16/f16)?
    Shared vocabulary for the collective sanitizer's payload fingerprints
    (utils/sanitizers.py tags reduced payloads so a cross-rank POLICY
    divergence — one rank staging bf16 while another stages f32 — shows
    up in the fingerprint) and for oaplint R18's runtime counterpart."""
    try:
        name = str(np.dtype(dtype))
    except TypeError:
        name = str(dtype)
    return name in ("bfloat16", "float16")


# -- staging-time casts -------------------------------------------------------


def staging_dtype(name: str, base_dtype) -> np.dtype:
    """The numpy dtype streamed chunks are STAGED at under a policy: bf16
    halves the host pad/convert output and the host->device transfer
    bytes (the prefetch pipeline stages chunks in this dtype, so the
    reduction applies before the wire, not after).  f32/tf32 (and any
    f64 lane) keep the accumulation dtype — bit-compatible staging."""
    check_tier(name)
    if name == "bf16" and np.dtype(base_dtype) == np.float32:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(base_dtype)


# -- policy-aware dots --------------------------------------------------------


def upcast(x):
    """bf16 -> f32 view for VPU reductions (squared norms, centering):
    the values already carry bf16 rounding, but the REDUCTION must
    accumulate in f32 — summing squares in bf16 loses whole rows at
    realistic d.  No-op (bit-compatible) for f32/f64 inputs."""
    import jax.numpy as jnp

    return x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x


def _is_f64(*ops) -> bool:
    return any(np.dtype(o.dtype) == np.float64 for o in ops)


def pdot(a, b, policy: str = "f32", tier: str = "highest"):
    """``a @ b`` under a policy, always accumulating in f32 (f64 on the
    x64 lane):

    - ``bf16``: both operands cast to bfloat16 (no-op when staging
      already delivered bf16) with ``preferred_element_type=f32`` — the
      MXU's native mode, half the operand HBM bytes;
    - ``tf32``: ``lax.Precision.HIGH`` (bf16_3x) on f32 operands;
    - ``f32``: the legacy ``tier`` — bit-compatible with the
      pre-policy call sites.

    f64 operands always run full precision (policy resolution pins x64
    fits to f32, so this is a defensive invariant, not a path)."""
    import jax.numpy as jnp
    from jax import lax

    check_tier(policy)
    if policy == "bf16" and not _is_f64(a, b):
        return jnp.matmul(
            a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    prec = (
        lax.Precision.HIGH if policy == "tf32" and not _is_f64(a, b)
        else legacy_precision(tier)
    )
    return jnp.matmul(upcast(a), upcast(b), precision=prec)


def peinsum(subscripts: str, a, b, policy: str = "f32"):
    """Two-operand einsum under a policy — the ALS normal-equation
    moment kernels' entry (they ran HIGHEST unconditionally before the
    policy existed, so the f32 policy keeps HIGHEST: bit-compatible).
    bf16 casts both operands and accumulates f32; tf32 runs bf16_3x."""
    import jax.numpy as jnp
    from jax import lax

    check_tier(policy)
    if policy == "bf16" and not _is_f64(a, b):
        return jnp.einsum(
            subscripts, a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    prec = (
        lax.Precision.HIGH if policy == "tf32" and not _is_f64(a, b)
        else lax.Precision.HIGHEST
    )
    return jnp.einsum(subscripts, upcast(a), upcast(b), precision=prec)


# -- summary/telemetry plumbing ----------------------------------------------


def record(summary, timings, policy: PrecisionPolicy) -> None:
    """Stamp the chosen policy on a fit: dict summaries (PCA/ALS) get a
    ``"precision"`` key, object summaries (KMeansSummary) a
    ``.precision`` attribute, and the span-tree root an
    ``attrs["precision"]`` entry so the policy rides the telemetry
    exporters (JSONL sink, ``telemetry.report``) next to the phase
    walls."""
    if summary is not None:
        if isinstance(summary, dict):
            summary["precision"] = policy.name
        else:
            summary.precision = policy.name
    if timings is not None:
        timings.root.attrs["precision"] = policy.name
