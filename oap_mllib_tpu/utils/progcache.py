"""Process-wide compiled-program registry: the compile-amortization core.

The reference pays a JNI + oneDAL kernel dispatch per phase; this port
pays XLA *compiles* instead — seconds of latency the first time any
program shape is seen.  Three things keep that cost amortized across the
many differently-sized fits of a long-lived service (the ROADMAP north
star), and this module is their shared registry:

1. **Program cache** — generalizes the ad-hoc ``functools.lru_cache``
   pattern that grew around the shard_map closures
   (``kmeans_ops._lloyd_model_sharded_fn``, ``pca_ops
   ._model_sharded_cov_fn``; the block-ALS runners rebuilt theirs every
   call): :func:`get_or_build` caches built callables process-wide,
   keyed by (algo, statics, mesh fingerprint), with LRU eviction and
   hit/miss/evict counters.
2. **Launch accounting** — the jitted entry points :func:`note` every
   launch under the same key space, so a fit summary can report how many
   programs it compiled vs reused, and :func:`launch` attributes the
   wall of first-seen launches to ``<phase>/compile`` and cache-hit
   launches to ``<phase>/execute`` in a :class:`~oap_mllib_tpu.utils
   .timing.Timings` (first-call wall = trace + XLA compile + first
   dispatch; hit wall = dispatch only for async launches).
3. **XLA ground truth** — :func:`xla_compile_count` counts actual
   backend compiles via jax.monitoring's
   ``/jax/core/compile/backend_compile_duration`` event, so benches and
   CI gates assert on what XLA really did, not what the registry thinks.

The persistent half lives in :func:`ensure_persistent_cache`: wiring
``Config.compilation_cache_dir`` through ``jax_compilation_cache_dir``
so a warm *process* skips XLA compilation entirely (DrJAX's
amortization argument, PAPERS.md, applied across process lifetimes).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from oap_mllib_tpu.telemetry import metrics as _tm

# -- registry ---------------------------------------------------------------


def _count(what: str, algo: str) -> None:
    """Mirror one registry increment into the process metrics registry
    (telemetry/metrics.py) — the summaries keep reading ``stats()``,
    exporters read ``oap_progcache_*_total{algo=...}``."""
    _tm.counter(
        f"oap_progcache_{what}_total", {"algo": algo},
        help=f"Program-cache {what} by algo key",
    ).inc()


class ProgramCache:
    """Keyed registry of built programs + launch counters.

    Two kinds of entries share one key space ``(algo, key)``:

    - *built* entries hold a value (a compiled/jit-wrapped callable) and
      are LRU-evicted past ``maxsize``;
    - *noted* entries hold no value — they only record that a jitted
      entry point has launched this program shape before (jit owns the
      executable; the registry owns the accounting).
    """

    def __init__(self, maxsize: int = 128, note_maxsize: int = 4096):
        self.maxsize = maxsize
        self.note_maxsize = note_maxsize
        self._lock = threading.RLock()
        self._built: "OrderedDict[tuple, Any]" = OrderedDict()
        self._noted: "OrderedDict[tuple, int]" = OrderedDict()
        self._counts: Dict[str, Dict[str, int]] = {}

    def _algo(self, algo: str) -> Dict[str, int]:
        return self._counts.setdefault(
            algo, {"hits": 0, "misses": 0, "evictions": 0}
        )

    def get_or_build(self, algo: str, key: tuple, build: Callable[[], Any]):
        """Return the cached value for ``(algo, key)``, building (and
        counting a miss) on first use.  The build runs outside the lock —
        building traces/compiles and must not serialize unrelated
        lookups; a racing duplicate build is benign (last one wins)."""
        full = (algo, key)
        with self._lock:
            if full in self._built:
                self._built.move_to_end(full)
                self._algo(algo)["hits"] += 1
                _count("hits", algo)
                return self._built[full]
            self._algo(algo)["misses"] += 1
            _count("misses", algo)
        value = build()
        with self._lock:
            self._built[full] = value
            self._built.move_to_end(full)
            while len(self._built) > self.maxsize:
                (ev_algo, _), _ = self._built.popitem(last=False)
                self._algo(ev_algo)["evictions"] += 1
                _count("evictions", ev_algo)
        return value

    def note(self, algo: str, key: tuple) -> bool:
        """Record one launch of a jit-managed program; True = first seen
        (the launch that pays trace + XLA compile)."""
        full = (algo, key)
        with self._lock:
            if full in self._noted:
                self._noted.move_to_end(full)
                self._noted[full] += 1
                self._algo(algo)["hits"] += 1
                _count("hits", algo)
                return False
            self._noted[full] = 1
            self._algo(algo)["misses"] += 1
            _count("misses", algo)
            while len(self._noted) > self.note_maxsize:
                (ev_algo, _), _ = self._noted.popitem(last=False)
                self._algo(ev_algo)["evictions"] += 1
                _count("evictions", ev_algo)
            return True

    def stats(self) -> Dict[str, Any]:
        """Aggregate + per-algo counters.  ``hit_rate`` is per-launch:
        of everything that went through the registry, the fraction that
        reused an existing program."""
        with self._lock:
            by_algo = {a: dict(c) for a, c in self._counts.items()}
        hits = sum(c["hits"] for c in by_algo.values())
        misses = sum(c["misses"] for c in by_algo.values())
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "evictions": sum(c["evictions"] for c in by_algo.values()),
            "entries": len(self._built) + len(self._noted),
            "hit_rate": (hits / total) if total else None,
            "by_algo": by_algo,
        }

    def clear(self) -> None:
        """Drop every entry AND counter (tests; a cleared registry makes
        the next launch of everything a miss, but jit's own executable
        cache is untouched — only the accounting resets)."""
        with self._lock:
            self._built.clear()
            self._noted.clear()
            self._counts.clear()


_CACHE = ProgramCache()

# the registry is reachable from the serving dispatcher thread as well
# as fit flows; entry mutation is guarded inside ProgramCache._lock,
# and the module-level clear (a cross-thread registry reset) takes this
# tracked seam so the "locks" sanitizer can witness it
from oap_mllib_tpu.utils import locktrace as _locktrace  # noqa: E402

_CLEAR_LOCK = _locktrace.TrackedLock("progcache.clear")


def get_or_build(algo: str, key: tuple, build: Callable[[], Any]):
    return _CACHE.get_or_build(algo, key, build)


def note(algo: str, key: tuple) -> bool:
    return _CACHE.note(algo, key)


def stats() -> Dict[str, Any]:
    return _CACHE.stats()


def clear() -> None:
    with _CLEAR_LOCK:
        _CACHE.clear()


def delta(before: Dict[str, Any]) -> Dict[str, Any]:
    """Per-fit registry activity: ``stats() - before`` for the scalar
    counters (models snapshot ``stats()`` at fit entry and attach the
    delta to the training summary)."""
    now = stats()
    out = {
        k: now[k] - before.get(k, 0) for k in ("hits", "misses", "evictions")
    }
    total = out["hits"] + out["misses"]
    out["hit_rate"] = (out["hits"] / total) if total else None
    return out


@contextlib.contextmanager
def launch(algo: str, key: tuple, timings=None, phase: Optional[str] = None,
           record_execute: bool = True):
    """Count one program launch and attribute its wall time.

    A first-seen key books the wall under ``<phase>/compile`` (for a jit
    entry the first call is where trace + XLA compile happen,
    synchronously, before the async dispatch); a hit books under
    ``<phase>/execute``.  ``record_execute=False`` is the per-chunk
    streamed-loop mode: misses still book compile, but the thousands of
    async per-chunk dispatch walls would be noise (the real device time
    is already recorded as the prefetch pipeline's ``compute`` split),
    so hits only count."""
    # the jitted-fit launch chokepoint doubles as the ``fit.execute``
    # fault-injection site (utils/faults.py): an armed device-OOM fault
    # raises here, BEFORE the launch is noted, exactly where a real XLA
    # RESOURCE_EXHAUSTED would surface — so the resilience ladder's
    # halved-chunk rung is testable without real hardware pressure
    from oap_mllib_tpu.utils.faults import maybe_fault

    maybe_fault("fit.execute")
    miss = _CACHE.note(algo, key)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if timings is not None and phase is not None:
            if miss:
                timings.add(phase + "/compile", time.perf_counter() - t0)
            elif record_execute:
                timings.add(phase + "/execute", time.perf_counter() - t0)


# -- key helpers ------------------------------------------------------------


def array_key(*arrays) -> tuple:
    """Hashable signature of array arguments: (shape, dtype, sharding).

    Sharding rides along because jit specializes on it — the same shapes
    on a different mesh layout are a different executable."""
    out = []
    for a in arrays:
        try:  # tracers (an entry called inside an outer jit) may not
            shard = str(getattr(a, "sharding", ""))  # carry a sharding
        except Exception:
            shard = ""
        out.append((
            tuple(getattr(a, "shape", ())),
            str(getattr(a, "dtype", type(a).__name__)),
            shard,
        ))
    return tuple(out)


def mesh_fingerprint(mesh) -> tuple:
    """Stable hashable identity of a mesh: axis layout + device ids +
    platform.  Two fits on meshes with this fingerprint can share one
    compiled shard_map program."""
    devs = [d for d in mesh.devices.flat]
    return (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(d.id for d in devs),
        devs[0].platform if devs else "none",
    )


def backend_fingerprint() -> tuple:
    """Identity of the default-device world, for single-program entry
    points that jit without an explicit mesh (GSPMD decides placement
    from the argument shardings, which array_key captures)."""
    import jax

    return (jax.default_backend(), len(jax.devices()), jax.process_count())


def key_digest(key) -> str:
    """Short stable hex digest of a hashable-repr key tuple, for layers
    that file registry-style keys on DISK (the tuning cache names its
    JSON entries with this; a raw repr would produce filesystem-hostile
    names).  repr-based, so only use with keys built from primitives —
    exactly what the registry key conventions already require."""
    import hashlib

    return hashlib.sha1(repr(key).encode()).hexdigest()[:16]


# -- XLA compile ground truth ----------------------------------------------

_XLA_EVENTS = {"count": 0, "secs": 0.0}
# the compile-event listener fires on whatever thread XLA compiles on,
# and the counters are read from fit flows AND the serving dispatcher
# thread (the request tracer's compile attribution) — same witnessed
# seam as _CLEAR_LOCK
_XLA_EVENTS_LOCK = _locktrace.TrackedLock("progcache.xla_events")
_xla_listener_installed = False
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install_xla_listener() -> None:
    global _xla_listener_installed
    if _xla_listener_installed:
        return
    try:
        from jax import monitoring

        def _on_event(event, duration_secs, **kwargs):
            if event == _BACKEND_COMPILE_EVENT:
                with _XLA_EVENTS_LOCK:
                    _XLA_EVENTS["count"] += 1
                    _XLA_EVENTS["secs"] += float(duration_secs)
                _tm.counter(
                    "oap_xla_compiles_total",
                    help="Real XLA backend compiles (jax monitoring event)",
                ).inc()
                _tm.counter(
                    "oap_xla_compile_seconds_total",
                    help="Wall spent in XLA backend compilation",
                ).inc(float(duration_secs))
                _tm.histogram(
                    "oap_xla_compile_seconds",
                    help="Per-program XLA backend compile wall",
                ).observe(float(duration_secs))

        monitoring.register_event_duration_secs_listener(_on_event)
        _xla_listener_installed = True
    except Exception:  # monitoring API absent on this jax: counter stays 0
        pass


def xla_compile_count() -> int:
    """Monotone count of real XLA backend compiles in this process (the
    ``/jax/core/compile/backend_compile_duration`` event).  Snapshot
    before/after a region and subtract — that difference is the ground
    truth the compile-sweep bench and the CI gate assert on (the
    registry's miss count is what *we* think; this is what XLA did)."""
    _install_xla_listener()
    with _XLA_EVENTS_LOCK:
        return _XLA_EVENTS["count"]


def xla_compile_secs() -> float:
    """Cumulative seconds spent in XLA backend compilation (same event
    stream as :func:`xla_compile_count`)."""
    _install_xla_listener()
    with _XLA_EVENTS_LOCK:
        return _XLA_EVENTS["secs"]


# install at import so compiles that happen before the first explicit
# snapshot (e.g. a warm-up fit) are still counted into the baseline
_install_xla_listener()


# -- persistent (cross-process) compilation cache ---------------------------

_persist_applied: Optional[str] = None


def ensure_persistent_cache(cache_dir: str) -> None:
    """Wire ``Config.compilation_cache_dir`` into jax's persistent
    compilation cache (idempotent; re-applies only when the dir
    changes).  With a dir set, XLA executables serialize to disk keyed
    by (HLO, compile options, backend version) — a warm process skips
    backend compilation entirely, which is the cross-process half of
    compile amortization (shape bucketing is the within-process half).

    The min-size/min-time thresholds are zeroed so the small per-chunk
    streamed programs persist too — jax's defaults only persist
    programs that took >1s to compile, which would exclude most of this
    framework's kernels on a warm CPU tier."""
    global _persist_applied
    if not cache_dir or _persist_applied == cache_dir:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:  # older jax lines lack the knob; dir alone works
            pass
    # jax pins its cache object to the first dir it initialized with;
    # drop it so the (possibly changed) dir takes effect — it re-creates
    # lazily on the next compile
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
    _persist_applied = cache_dir
