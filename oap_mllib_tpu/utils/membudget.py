"""Memory-budget-governed route planning: every accelerated fit picks
its scale route (in-memory / chunked / streamed / streamed-block) as an
EXPLICIT, auditable, budget-driven decision.

Before ISSUE 12 the route was an accident of input type and scattered
heuristics: an ndarray always ran the fully-resident in-memory path
(however large), a ChunkSource always streamed (however small), and the
ALS streamed entry silently MATERIALIZED its source back to in-memory
layouts on exactly the long-tail degree distributions most likely to
need streaming — the standing round-5 VERDICT criticism.  The map-reduce
primitive decomposition (DrJAX, arXiv:2403.07128) and the simplified-
MapReduce K-Means architecture (arXiv:1610.05601) both argue the
streamed pass is a first-class representation, not a fallback: route
selection should be planned against an explicit memory budget, degrade
gracefully and LOUDLY, and never silently.

This module is that planner:

- **Budgets** (``Config.memory_budget_hbm`` / ``memory_budget_host``,
  default auto-detected; ``utils/membudget.parse_budget`` grammar) bound
  the per-device accelerator working set and the staged host footprint.
- **Estimates**: per candidate route, the planner prices the table /
  factor / accumulator / prefetch-buffer footprints from the fit's
  shapes (calibrated by the bytes-staged accounting telemetry already
  collects — see :func:`record_plan`), and records EVERY candidate's
  estimate and rejection reason, not just the winner.
- **Policy** (``Config.scale_policy``): ``auto`` picks the fastest
  feasible route and degrades loudly when the budget forces a slower
  one; ``strict`` raises :class:`BudgetError` instead of deviating from
  the fit's natural route; ``pin:<route>`` forces a route outright.
- **Exposure**: the decision, candidates, budgets, and (on streamed
  routes) the estimate-vs-actual staged-bytes cross-check land in
  ``summary.route``, a ``route`` span node, and ``oap_route_*`` metrics.

The SPILL primitive lives here too: :func:`spill_source` /
:func:`spill_array` are the resilience ladder's host-OOM rung — stage
the fit's source to an atomic disk spill (data/io.SpillWriter) and swap
the attempt onto the disk-backed streamed route.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Dict, List, Optional, Tuple

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm

log = logging.getLogger("oap_mllib_tpu")

ROUTE_IN_MEMORY = "in-memory"
ROUTE_CHUNKED = "chunked"
ROUTE_STREAMED = "streamed"
ROUTE_STREAMED_BLOCK = "streamed-block"
ROUTES = (ROUTE_IN_MEMORY, ROUTE_CHUNKED, ROUTE_STREAMED,
          ROUTE_STREAMED_BLOCK)

# planner fudge on analytic estimates: XLA temporaries, fusion buffers,
# and allocator slack that no shape formula sees.  Streamed estimates
# additionally carry the measured calibration factor (see record_plan).
_OVERHEAD = 1.25

# flat allowance for compiled programs + runtime structures per fit
_PROGRAM_BYTES = 64 << 20


class BudgetError(RuntimeError):
    """``scale_policy="strict"`` and the memory budget forced (or the
    pinned route demanded) a scale downgrade.  ``estimates`` carries
    every candidate's priced footprint so the operator sees exactly what
    was infeasible and why."""

    def __init__(self, algo: str, msg: str,
                 estimates: Optional[List["RouteEstimate"]] = None):
        self.algo = algo
        self.estimates = list(estimates or [])
        detail = "; ".join(
            f"{e.route}: hbm~{_fmt_bytes(e.hbm_bytes)} "
            f"host~{_fmt_bytes(e.host_bytes)}"
            + (f" ({e.reject})" if e.reject else "")
            for e in self.estimates
        )
        super().__init__(
            f"{algo}: {msg}" + (f" — candidates: {detail}" if detail else "")
        )


def _world() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:  # noqa: BLE001 — planning must work pre-backend
        return 1


def _fmt_bytes(n: int) -> str:
    if n <= 0:
        return "?"
    for unit in ("B", "K", "M", "G", "T"):
        if n < 1024 or unit == "T":
            return f"{n:.4g}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n:.4g}T"


_UNITS = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_budget(spec: str) -> Optional[int]:
    """Parse a budget knob: ``""`` -> None (auto-detect), ``"0"`` /
    ``"unlimited"`` -> 0 (unbounded), else bytes with an optional
    K/M/G/T suffix (``"4G"``, ``"512M"``, ``"1073741824"``).  A typo
    raises — a budget that silently parses to nothing defeats the
    planner (the fault_spec/kmeans_kernel contract)."""
    s = spec.strip().lower()
    if not s:
        return None
    if s in ("unlimited", "none", "inf"):
        return 0
    mult = 1
    if s[-1] in _UNITS:
        mult = _UNITS[s[-1]]
        s = s[:-1]
    try:
        v = float(s)
    except ValueError:
        raise ValueError(
            f"memory budget must be bytes with an optional K/M/G/T "
            f"suffix, '0'/'unlimited', or empty (auto-detect); got "
            f"{spec!r}"
        ) from None
    if v < 0:
        raise ValueError(f"memory budget must be >= 0, got {spec!r}")
    return int(v * mult)


def detect_hbm_bytes() -> int:
    """Per-device accelerator memory, from the backend's own accounting
    (``memory_stats()['bytes_limit']``).  0 = the backend reports none
    (CPU) — the HBM constraint is then unbounded unless pinned."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("bytes_limit", 0))
    except Exception:  # noqa: BLE001 — detection must never fail a fit
        pass
    return 0


def detect_host_bytes() -> int:
    """Physical host RAM (sysconf); 0 when undetectable = unbounded."""
    try:
        import os

        return int(os.sysconf("SC_PHYS_PAGES")) * int(
            os.sysconf("SC_PAGE_SIZE")
        )
    except (ValueError, OSError, AttributeError):
        return 0


@dataclasses.dataclass(frozen=True)
class Budgets:
    """Resolved budgets for one plan: 0 = unbounded.  ``*_source`` names
    where each number came from (``config`` vs ``detected``) so
    summary.route is self-explaining."""

    hbm: int
    host: int
    hbm_source: str
    host_source: str

    @classmethod
    def resolve(cls) -> "Budgets":
        cfg = get_config()
        hbm = parse_budget(cfg.memory_budget_hbm)
        host = parse_budget(cfg.memory_budget_host)
        return cls(
            hbm=detect_hbm_bytes() if hbm is None else hbm,
            host=detect_host_bytes() if host is None else host,
            hbm_source="detected" if hbm is None else "config",
            host_source="detected" if host is None else "config",
        )

    def as_dict(self) -> dict:
        return {
            "hbm": self.hbm, "host": self.host,
            "hbm_source": self.hbm_source,
            "host_source": self.host_source,
        }


def scale_policy_cfg() -> Tuple[str, Optional[str]]:
    """Validated ``Config.scale_policy`` -> (mode, pinned_route).  A
    typo raises at fit entry, not after a multi-minute pass (the
    kmeans_kernel contract)."""
    policy = get_config().scale_policy.strip()
    if policy in ("auto", "strict"):
        return policy, None
    if policy.startswith("pin:"):
        route = policy[4:]
        if route in ROUTES:
            return "pin", route
        raise ValueError(
            f"scale_policy pin route must be one of {', '.join(ROUTES)}; "
            f"got {policy!r}"
        )
    raise ValueError(
        f"scale_policy must be auto|strict|pin:<route>, got {policy!r}"
    )


@dataclasses.dataclass
class RouteEstimate:
    """One candidate route's priced footprint.  ``hbm_bytes`` /
    ``host_bytes`` <= 0 mean unknown (an un-sized generator source) —
    unknown fits any budget (the planner cannot reject what it cannot
    price; the estimate is still recorded as unknown)."""

    route: str
    hbm_bytes: int
    host_bytes: int
    reject: str = ""

    def fits(self, budgets: Budgets) -> bool:
        if budgets.hbm > 0 and self.hbm_bytes > budgets.hbm:
            return False
        if budgets.host > 0 and self.host_bytes > budgets.host:
            return False
        return True

    def why_rejected(self, budgets: Budgets) -> str:
        parts = []
        if budgets.hbm > 0 and self.hbm_bytes > budgets.hbm:
            parts.append(
                f"hbm estimate {_fmt_bytes(self.hbm_bytes)} > budget "
                f"{_fmt_bytes(budgets.hbm)}"
            )
        if budgets.host > 0 and self.host_bytes > budgets.host:
            parts.append(
                f"host estimate {_fmt_bytes(self.host_bytes)} > budget "
                f"{_fmt_bytes(budgets.host)}"
            )
        return "; ".join(parts)

    def as_dict(self) -> dict:
        out = {
            "route": self.route,
            "hbm_bytes": self.hbm_bytes,
            "host_bytes": self.host_bytes,
        }
        if self.reject:
            out["reject"] = self.reject
        return out


class RoutePlan:
    """The planner's decision for one fit: the chosen route, the natural
    (infinite-budget) route, every candidate's estimate, the budgets and
    policy that produced it, and the bookkeeping :func:`record_plan`
    turns into summary.route / span / metrics."""

    def __init__(self, algo: str, route: str, natural: str,
                 estimates: List[RouteEstimate], budgets: Budgets,
                 policy: str, *, chunk_rows: int = 0,
                 over_budget: bool = False, forced: bool = False):
        self.algo = algo
        self.route = route
        self.natural = natural
        self.estimates = estimates
        self.budgets = budgets
        self.policy = policy
        self.chunk_rows = chunk_rows  # suggested streamed chunk width
        self.over_budget = over_budget  # no candidate fit; loudest case
        self.forced = forced  # pin: override
        self.downgrades: List[str] = []
        # what the planner priced one staged row at (chunk width x dtype
        # + the mask/weight columns that ride along) — record_plan
        # cross-checks it against the observed bytes/row from the
        # pipeline's staging telemetry and folds the ratio into the
        # calibration EMA
        self.est_row_bytes = 0.0
        # staging-telemetry family totals at plan time: record_plan
        # subtracts them to isolate THIS fit's staged bytes/rows
        self.stream_marker = _tm.family_total("oap_stream_bytes_staged_total")
        self.rows_marker = _tm.family_total("oap_stream_rows_total")

    @property
    def degraded_scale(self) -> bool:
        """True when the budget (not the caller) moved the fit off its
        natural route — the case that must never be silent."""
        return self.route != self.natural and not self.forced

    def estimate_for(self, route: str) -> Optional[RouteEstimate]:
        for e in self.estimates:
            if e.route == route:
                return e
        return None

    def downgrade(self, route: str, why: str) -> None:
        """A post-plan scale downgrade the estimator was forced into
        (e.g. the ALS grouped guard rejecting a long-tail source ->
        in-memory COO).  Never silent: strict raises, auto warns and
        records."""
        mode, _ = scale_policy_cfg()
        if (mode == "strict" and _world() == 1
                and _scale_rank(route) < _scale_rank(self.route)):
            raise BudgetError(
                self.algo,
                f"scale_policy=strict forbids downgrading the planned "
                f"{self.route!r} route to {route!r} ({why})",
                self.estimates,
            )
        log.warning(
            "%s: route downgraded %s -> %s (%s)", self.algo, self.route,
            route, why,
        )
        self.downgrades.append(f"{self.route}->{route}: {why}")
        self.route = route

    def as_dict(self) -> dict:
        out = {
            "route": self.route,
            "natural": self.natural,
            "policy": self.policy,
            "budgets": self.budgets.as_dict(),
            "estimates": [e.as_dict() for e in self.estimates],
        }
        if self.chunk_rows:
            out["chunk_rows"] = self.chunk_rows
        if self.over_budget:
            out["over_budget"] = True
        if self.forced:
            out["forced"] = True
        if self.degraded_scale:
            out["degraded_scale"] = True
        if self.downgrades:
            out["downgrades"] = list(self.downgrades)
        return out


def _scale_rank(route: str) -> int:
    """Higher = handles more data per resident byte.  A move to a LOWER
    rank is a scale downgrade (the thing strict mode forbids)."""
    return {
        ROUTE_IN_MEMORY: 0, ROUTE_CHUNKED: 1, ROUTE_STREAMED: 2,
        ROUTE_STREAMED_BLOCK: 3,
    }[route]


def choose(algo: str, estimates: List[RouteEstimate],
           natural: Optional[str] = None) -> RoutePlan:
    """Pick a route from ``estimates`` (ordered fastest-first) under the
    configured budgets and scale policy.

    - ``pin:<route>``: that route, budgets advisory (must be a
      candidate; a pin naming an inapplicable route raises ValueError).
    - ``strict``: the natural route or :class:`BudgetError`.
    - ``auto``: the first candidate that fits both budgets; when none
      fits, the LAST (most scale-capable) candidate runs anyway with
      ``over_budget`` recorded and a loud warning — degrading scale
      further than streaming is impossible, and refusing to fit is
      strict mode's job.
    """
    if not estimates:
        raise ValueError(f"{algo}: no candidate routes to plan over")
    budgets = Budgets.resolve()
    mode, pinned = scale_policy_cfg()
    natural = natural or estimates[0].route
    for e in estimates:
        if not e.fits(budgets):
            e.reject = e.why_rejected(budgets)
    if _world() > 1:
        # multi-process worlds: estimates derive from RANK-LOCAL shard
        # shapes, so a borderline budget could pick different routes on
        # different ranks — a divergent collective schedule (hang).  The
        # planner stays ADVISORY there: the natural route runs, the
        # estimates and any budget breach are still recorded loudly in
        # summary.route, and strict/pin govern single-process fits only
        # (the static-world contract, docs/distributed.md).
        plan = RoutePlan(
            algo, natural, natural, estimates, budgets,
            f"{get_config().scale_policy}(advisory:multi-process)",
        )
        nat = plan.estimate_for(natural)
        if nat is not None and nat.reject:
            plan.over_budget = True
            log.warning(
                "%s: natural route %r exceeds the budget (%s) — "
                "multi-process worlds keep the natural route (planner "
                "advisory)", algo, natural, nat.reject,
            )
        return plan

    if mode == "pin":
        est = next((e for e in estimates if e.route == pinned), None)
        if est is None:
            raise ValueError(
                f"{algo}: scale_policy=pin:{pinned} does not apply to "
                f"this fit (candidates: "
                f"{', '.join(e.route for e in estimates)})"
            )
        plan = RoutePlan(algo, pinned, natural, estimates, budgets,
                         f"pin:{pinned}", forced=True)
        return plan

    chosen = next((e for e in estimates if not e.reject), None)
    if mode == "strict":
        nat = next(e for e in estimates if e.route == natural)
        if nat.reject:
            raise BudgetError(
                algo,
                f"scale_policy=strict and the natural {natural!r} route "
                f"exceeds the budget ({nat.reject})",
                estimates,
            )
        if chosen is None or chosen.route != natural:
            raise BudgetError(
                algo,
                f"scale_policy=strict forbids degrading scale off the "
                f"natural {natural!r} route",
                estimates,
            )
        return RoutePlan(algo, natural, natural, estimates, budgets,
                         "strict")

    over = chosen is None
    if over:
        chosen = estimates[-1]
        log.warning(
            "%s: NO candidate route fits the memory budget "
            "(hbm=%s host=%s) — running the most scale-capable route "
            "%r over budget; consider raising the budget or "
            "scale_policy=strict",
            algo, _fmt_bytes(budgets.hbm), _fmt_bytes(budgets.host),
            chosen.route,
        )
    plan = RoutePlan(algo, chosen.route, natural, estimates, budgets,
                     "auto", over_budget=over)
    if plan.degraded_scale:
        nat = plan.estimate_for(natural)
        log.warning(
            "%s: memory budget moved the fit off its natural %r route "
            "onto %r (%s)", algo, natural, chosen.route,
            nat.reject if nat is not None else "unpriceable",
        )
    return plan


# -- per-algorithm candidate pricing ------------------------------------------


def _dtype_bytes() -> int:
    return 8 if get_config().enable_x64 else 4


def _padded_rows(n: int) -> int:
    from oap_mllib_tpu.data.bucketing import bucket_rows

    return bucket_rows(max(int(n), 1), 256)


def _depth() -> int:
    from oap_mllib_tpu.data.prefetch import resolve_depth

    try:
        return resolve_depth()
    except ValueError:
        return 1


def suggest_chunk_rows(d: int, extra_width: int, budgets: Budgets,
                       default_rows: int) -> int:
    """Streamed chunk width: the default unless the HBM budget demands
    narrower — depth staged (rows, d) chunks plus the (rows,
    extra_width) working block must fit HALF the budget (the other half
    is accumulators/programs/slack), floored at the resilience ladder's
    OOM_CHUNK_FLOOR_ROWS."""
    from oap_mllib_tpu.utils.resilience import OOM_CHUNK_FLOOR_ROWS

    if budgets.hbm <= 0:
        return default_rows
    per_row = (d + extra_width + 1) * _dtype_bytes() * _depth()
    fit_rows = max(int(budgets.hbm // (2 * max(per_row, 1))),
                   OOM_CHUNK_FLOOR_ROWS)
    return max(min(default_rows, fit_rows), 1)


def _calibrated(algo: str, estimate: int) -> int:
    return int(estimate * calibration_factor(algo))


def plan_kmeans(n: Optional[int], d: int, k: int, *,
                source_backing: Optional[str] = None,
                chunk_rows: int = 0,
                row_chunks_hint: int = 1) -> RoutePlan:
    """Route plan for one K-Means fit.  ``source_backing`` None = array
    input (candidates: in-memory / chunked / streamed); a ChunkSource
    input passes its ``backing`` (natural route: streamed).  ``n`` None
    = un-sized source (footprints unknown; streams unconditionally)."""
    b = _dtype_bytes()
    budgets = Budgets.resolve()
    from oap_mllib_tpu.data.stream import DEFAULT_CHUNK_ROWS
    from oap_mllib_tpu.ops.kmeans_ops import SCORE_BUDGET_ELEMS

    centroids = 3 * k * d * b + _PROGRAM_BYTES
    # array inputs are free to pick their chunk width from the budget;
    # a ChunkSource keeps the width it was built with (the compiled
    # per-chunk programs are keyed on it) and is priced at that width
    rows = chunk_rows or suggest_chunk_rows(
        d, k, budgets, DEFAULT_CHUNK_ROWS
    )
    streamed_hbm = _calibrated(
        "kmeans",
        int((_depth() * rows * (d + k + 1) * b + centroids) * _OVERHEAD),
    )
    if source_backing is None:
        np_ = _padded_rows(n)
        table = np_ * (d + 1) * b
        host = n * d * b
        in_mem = RouteEstimate(
            ROUTE_IN_MEMORY,
            int((table + np_ * k * b + centroids) * _OVERHEAD), host)
        chunked = RouteEstimate(
            ROUTE_CHUNKED,
            int((table + SCORE_BUDGET_ELEMS * b + centroids)
                * _OVERHEAD), host)
        streamed = RouteEstimate(ROUTE_STREAMED, streamed_hbm, host)
        # the natural route is what the resident-table Lloyd actually
        # runs: an unchunked score buffer when auto_row_chunks needs no
        # scan ("in-memory"), else the scan-chunked program ("chunked")
        # — a shape auto_row_chunks already chunks never offers the
        # unbounded in-memory candidate
        if row_chunks_hint <= 1:
            ests = [in_mem, chunked, streamed]
            natural = ROUTE_IN_MEMORY
        else:
            ests = [chunked, streamed]
            natural = ROUTE_CHUNKED
        plan = choose("KMeans", ests, natural)
    else:
        host = (
            n * d * b
            if (n and source_backing == "memory")
            else rows * d * b * 2
        )
        ests = [RouteEstimate(ROUTE_STREAMED, streamed_hbm, host)]
        plan = choose("KMeans", ests, ROUTE_STREAMED)
    plan.chunk_rows = rows
    plan.est_row_bytes = (d + 1) * b  # data row + the mask/weight lane
    return plan


def plan_pca(n: Optional[int], d: int, *,
             source_backing: Optional[str] = None,
             chunk_rows: int = 0) -> RoutePlan:
    """Route plan for one PCA fit (candidates: in-memory covariance vs
    the two-pass streamed moments)."""
    b = _dtype_bytes()
    budgets = Budgets.resolve()
    from oap_mllib_tpu.data.stream import DEFAULT_CHUNK_ROWS

    gram = 2 * d * d * b + _PROGRAM_BYTES
    rows = chunk_rows or suggest_chunk_rows(
        d, 0, budgets, DEFAULT_CHUNK_ROWS
    )
    streamed_hbm = _calibrated(
        "pca", int((_depth() * rows * (d + 1) * b + 2 * gram) * _OVERHEAD)
    )
    if source_backing is None:
        np_ = _padded_rows(n)
        host = n * d * b
        ests = [
            RouteEstimate(
                ROUTE_IN_MEMORY,
                int((np_ * (d + 1) * b + gram) * _OVERHEAD), host),
            RouteEstimate(ROUTE_STREAMED, streamed_hbm, host),
        ]
        plan = choose("PCA", ests, ROUTE_IN_MEMORY)
    else:
        host = (
            n * d * b
            if (n and source_backing == "memory")
            else rows * d * b * 2
        )
        ests = [RouteEstimate(ROUTE_STREAMED, streamed_hbm, host)]
        plan = choose("PCA", ests, ROUTE_STREAMED)
    plan.chunk_rows = rows
    plan.est_row_bytes = (d + 1) * b
    return plan


# grouped-edge layouts: ~12 bytes/edge (idx + value + validity) per
# update direction, times the adaptive-group padding allowance the
# blowup guard enforces (ops/als_ops.GROUPED_MAX_BLOWUP)
_ALS_EDGE_BYTES = 12
_ALS_BLOWUP = 2.0


def plan_als(nnz: int, n_users: int, n_items: int, rank: int, *,
             world: int = 1,
             source_backing: Optional[str] = None) -> RoutePlan:
    """Route plan for one ALS fit.  Candidates: the fully-resident
    grouped/COO layouts (in-memory), host-resident edges with chunked
    uploads (streamed), and the mesh-composed streamed block layout
    (streamed-block, world > 1 — per-rank layouts shrink world-fold).
    Source inputs keep host O(nnz) on every route (the triples ingest
    to host arrays, like the reference's executor partitions) — the
    streamed property is DEVICE memory."""
    b = 4  # ALS is f32 like the reference
    factors = (n_users + n_items) * rank * b
    edges = int(2 * nnz * _ALS_EDGE_BYTES * _ALS_BLOWUP)
    moments = (n_users + n_items) * rank * (rank + 1) * b
    host_edges = edges + 3 * nnz * 8  # grouped layouts + the id triples
    upload = 64 << 20  # bounded per-step group-chunk upload
    in_mem = RouteEstimate(
        ROUTE_IN_MEMORY,
        int((edges + 3 * factors + moments + _PROGRAM_BYTES) * _OVERHEAD),
        host_edges,
    )
    streamed = RouteEstimate(
        ROUTE_STREAMED,
        _calibrated("als", int(
            (3 * factors + moments + upload + _PROGRAM_BYTES) * _OVERHEAD
        )),
        host_edges,
    )
    if world > 1:
        block = RouteEstimate(
            ROUTE_STREAMED_BLOCK,
            _calibrated("als", int(
                (3 * factors // world + moments // world + upload
                 + _PROGRAM_BYTES) * _OVERHEAD
            )),
            host_edges // world + 3 * nnz * 8,
        )
        # multi-device worlds have no single-device candidates: the
        # block layout IS the natural route (and the only one offered —
        # restricting the device set is the num_user_blocks knob's job)
        plan = choose("ALS", [block], ROUTE_STREAMED_BLOCK)
    else:
        natural = (
            ROUTE_STREAMED if source_backing is not None
            else ROUTE_IN_MEMORY
        )
        ests = (
            [streamed, in_mem] if source_backing is not None
            else [in_mem, streamed]
        )
        plan = choose("ALS", ests, natural)
    # triples stage as width-3 f64 chunks on the streamed ingest path
    plan.est_row_bytes = 3 * 8
    return plan


# -- spill: the resilience ladder's host-OOM rung -----------------------------


def spill_source(holder: Dict[str, object], algo: str) -> bool:
    """Stage ``holder["source"]`` (and the lockstep ``holder["weights"]``
    source, if any) to atomic disk spills and swap the holder onto the
    disk-backed replacements — the ladder re-runs its attempt reading
    from disk through the same prefetch pipeline.  Returns False (and
    warns) on any failure: the ladder falls through, the original
    source is untouched (SpillWriter never replaces a file it did not
    finish)."""
    try:
        src = holder["source"]
        spilled = src.spill_to_disk()
        w = holder.get("weights")
        if w is not None:
            holder["weights"] = w.spill_to_disk()
        holder["source"] = spilled
        holder["spilled"] = True
        _tm.counter(
            "oap_route_spills_total", {"algo": algo},
            help="Host-OOM spill rungs taken (table staged to disk)",
        ).inc()
        log.warning(
            "%s: spilled %s rows to %s", algo, spilled.n_rows,
            getattr(spilled, "backing", "disk"),
        )
        return True
    except Exception as e:  # noqa: BLE001 — the rung falls through
        log.warning("%s: spill to disk failed: %s", algo, e)
        return False


def spill_array(holder: Dict[str, object], x, weights, chunk_rows: int,
                algo: str) -> bool:
    """The in-memory route's spill hook: wrap the resident array (and
    optional per-row weights) as chunk sources, spill them, and leave
    the disk-backed sources in ``holder`` — the attempt closure re-reads
    the holder and re-enters the STREAMED route from disk."""
    from oap_mllib_tpu.data.stream import ChunkSource

    try:
        import numpy as np

        holder["source"] = ChunkSource.from_array(x, chunk_rows=chunk_rows)
        if weights is not None:
            holder["weights"] = ChunkSource.from_array(
                np.asarray(weights).reshape(-1, 1), chunk_rows=chunk_rows
            )
        return spill_source(holder, algo)
    except Exception as e:  # noqa: BLE001 — the rung falls through
        log.warning("%s: spill to disk failed: %s", algo, e)
        return False


# -- calibration: estimates learn from the bytes-staged telemetry ------------

_cal_lock = threading.Lock()
_cal: Dict[str, float] = {}
_CAL_ALPHA = 0.3  # EMA weight of the newest observation
_CAL_CLAMP = (0.25, 4.0)  # a wild ratio is a bug, not a calibration


def calibration_factor(algo: str) -> float:
    with _cal_lock:
        return _cal.get(algo, 1.0)


def reset_calibration() -> None:
    with _cal_lock:
        _cal.clear()


def _note_calibration(algo: str, estimated: float, actual: float) -> float:
    """Fold one fit's estimated-vs-observed staged bytes/row ratio into
    the per-algo EMA the next plan's streamed estimates are scaled by."""
    if estimated <= 0 or actual <= 0:
        return calibration_factor(algo)
    ratio = min(max(actual / estimated, _CAL_CLAMP[0]), _CAL_CLAMP[1])
    with _cal_lock:
        prev = _cal.get(algo, 1.0)
        _cal[algo] = prev + _CAL_ALPHA * (ratio - prev)
        return _cal[algo]


# -- exposure: summary.route + route span + oap_route_* metrics ---------------


def record_plan(summary, plan: Optional[RoutePlan], *,
                spilled: bool = False) -> None:
    """Attach the plan to the fit summary (``summary["route"]`` /
    ``summary.route`` — the merge_stats convention), annotate the span
    tree's ``route`` node, book the ``oap_route_*`` metrics, and fold
    the streamed estimate-vs-actual staged bytes into the calibration
    EMA.  Call BEFORE telemetry.finalize_fit so the exporters see it."""
    if summary is None or plan is None:
        return
    d = plan.as_dict()
    if spilled:
        d["spilled"] = True
    # estimate-vs-actual cross-check: the bytes/row the pipeline
    # actually staged this fit (the accounting telemetry already
    # collects per pass) against the bytes/row the planner priced —
    # the ratio calibrates the next plan's streamed estimates
    actual_b = _tm.family_total("oap_stream_bytes_staged_total") \
        - plan.stream_marker
    actual_r = _tm.family_total("oap_stream_rows_total") - plan.rows_marker
    if actual_b > 0:
        d["actual_bytes_staged"] = int(actual_b)
    if actual_b > 0 and actual_r > 0 and plan.est_row_bytes > 0:
        observed = actual_b / actual_r
        d["staged_bytes_per_row"] = round(observed, 2)
        d["estimated_bytes_per_row"] = round(plan.est_row_bytes, 2)
        d["calibration"] = round(
            _note_calibration(
                plan.algo.lower(), plan.est_row_bytes, observed
            ), 4,
        )
    labels = {"algo": plan.algo, "route": plan.route}
    _tm.counter(
        "oap_route_decisions_total", labels,
        help="Route-planner decisions by algorithm and chosen route",
    ).inc()
    chosen = plan.estimate_for(plan.route)
    if chosen is not None:
        _tm.gauge(
            "oap_route_estimated_hbm_bytes", labels,
            help="Planner HBM estimate of the chosen route",
        ).set(float(max(chosen.hbm_bytes, 0)))
        _tm.gauge(
            "oap_route_estimated_host_bytes", labels,
            help="Planner host-RAM estimate of the chosen route",
        ).set(float(max(chosen.host_bytes, 0)))
    if plan.over_budget:
        _tm.counter(
            "oap_route_over_budget_total", {"algo": plan.algo},
            help="Fits where no candidate route fit the budget",
        ).inc()
    if plan.degraded_scale or plan.downgrades:
        _tm.counter(
            "oap_route_downgrades_total", labels,
            help="Fits moved off their natural route (budget or guard)",
        ).inc()
    if isinstance(summary, dict):
        summary["route"] = d
        timings = summary.get("timings")
    else:
        summary.route = d
        timings = getattr(summary, "timings", None)
    if timings is not None and getattr(timings, "root", None) is not None:
        timings.root.node("route").attrs.update(d)
