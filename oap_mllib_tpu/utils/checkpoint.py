"""Elastic worlds: sharded iterate-state checkpoint/resume.

The resilience ladder (utils/resilience.py) is deliberately bypassed in
multi-process worlds under the static-world contract, so the
configuration production actually runs — pod slices on preemptible
capacity — had zero fault tolerance: one preempted host killed the whole
fit and every pass of work with it.  This module is the missing half:
**periodic sharded checkpoints of iterate state** (K-Means centroids,
ALS user/item factor shards, PCA streamed colsum/Gram moments, plus the
pass/iteration index and world layout), written per-rank with atomic
tmp+rename and a manifest, and **resume-from-checkpoint onto any world
size** — factor shards are redistributed through a collective resharding
pass (parallel/shuffle.reshard_factor_rows) when the world changed, so a
fleet that lost or gained hosts re-enters the iterate loop where it left
off instead of starting over.

On-disk layout (one directory per fit identity)::

    <checkpoint_dir>/<algo>-<sig12>/
        manifest.json                  # step, world, layout, signature
        step00000003.rank0.npz         # rank 0's shard at step 3
        step00000003.rank1.npz         # ...

Write protocol (the torn-write contract):

1. every rank writes its ``step<N>.rank<r>.npz`` shard via
   tmp+``os.replace`` (data/io.atomic_save_npz);
2. ranks agree the write landed everywhere (one tiny allgather in
   multi-process worlds — rank-uniform, fingerprinted by the collective
   sanitizer like every host collective);
3. rank 0 atomically replaces ``manifest.json``, which NAMES the step —
   a kill anywhere in 1–3 leaves the previous generation fully valid;
4. each rank garbage-collects its own shards older than the previous
   generation (two generations are kept so a failed manifest flip never
   strands the step it still points at).

Restore validates manifest version/signature and every needed shard's
embedded step; any failure is a *corrupt checkpoint*: a fresh fit (with
a warning) under ``Config.resume="auto"``, :class:`CheckpointError`
under ``resume="require"``.  Replicated state (centroids, moments,
replicated Y) restores onto any world directly; block-sharded factor
tables are re-read round-robin from the old rank shards and redistributed
collectively — no host ever materializes the full table.

Both ``ckpt.write`` and ``ckpt.restore`` are fault-injection sites
(utils/faults.py): a failed periodic write warns + counts and the fit
continues; an injected restore fault exercises the corrupt-checkpoint
tiers deterministically in CI (dev/checkpoint_gate.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import logging
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data import io as _io
from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import faults
from oap_mllib_tpu.utils.timing import phase_timer, tick, x64_scope

log = logging.getLogger("oap_mllib_tpu")

MANIFEST = "manifest.json"
_VERSION = 1
_KEEP_GENERATIONS = 2
_SHARD_RE = re.compile(r"step(\d{8})\.rank(\d+)\.npz$")

DECISION_FOUND = "found"
DECISION_FRESH = "fresh"
DECISION_RESHARDED = "resharded"

# newest step this process has durably committed or restored, across
# every Checkpointer in the process — the "last durable checkpoint
# step" field of recovery-plane crash records (utils/recovery.py), so a
# supervisor classifying an exit knows how much work a relaunch loses
_durable_lock = threading.Lock()
_LAST_DURABLE = {"step": -1}


def _note_durable(step: int) -> None:
    with _durable_lock:
        if step > _LAST_DURABLE["step"]:
            _LAST_DURABLE["step"] = int(step)


def last_durable_step() -> int:
    """The newest checkpoint step this process committed or restored
    (-1 when none) — stamped into crash records by the recovery plane."""
    with _durable_lock:
        return _LAST_DURABLE["step"]


class CheckpointError(RuntimeError):
    """A restore that ``Config.resume="require"`` cannot satisfy (no
    checkpoint, a corrupt manifest/shard, or a signature mismatch)."""


def resume_cfg(cfg=None) -> str:
    """Validated ``Config.resume`` — a typo must raise, not silently
    behave like any valid value (the als_kernel contract)."""
    cfg = cfg or get_config()
    policy = cfg.resume
    if policy not in ("auto", "require", "off"):
        raise ValueError(
            f"resume must be auto|require|off, got {policy!r}"
        )
    return policy


def _world() -> Tuple[int, int]:
    import jax

    return jax.process_count(), jax.process_index()


def _sig_hash(signature: Dict[str, Any]) -> str:
    blob = json.dumps(signature, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def fetch_replicated(arr) -> np.ndarray:
    """Host copy of a (logically) replicated device value.  Multi-process
    arrays that are not fully addressable (e.g. model-axis-sharded
    centers) first gather through a registry-cached replication program —
    the ALSModel._gather_blocks pattern; a COLLECTIVE, so every rank must
    checkpoint together (they do: writes fire at config-uniform steps)."""
    import jax

    if not hasattr(arr, "sharding") or getattr(
            arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oap_mllib_tpu.utils import progcache

    mesh = arr.sharding.mesh
    fn = progcache.get_or_build(
        "ckpt.gather_replicated",
        (progcache.mesh_fingerprint(mesh),),
        lambda: jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P())),
    )
    return np.asarray(fn(arr))


def local_factor_rows(arr, offsets, per: int) -> Tuple[np.ndarray, np.ndarray]:
    """(ids, vals) for THIS process's valid rows of a block-sharded
    ``(world * per, r)`` factor table: each addressable block shard
    contributes its rows below the block boundary (padding dropped),
    with their GLOBAL row ids — exactly the shard payload a different
    world size can re-bucket at restore.  Model-axis replicas dedupe by
    block start."""
    offsets = np.asarray(offsets)
    ids: List[np.ndarray] = []
    vals: List[np.ndarray] = []
    seen = set()
    for s in sorted(arr.addressable_shards,
                    key=lambda sh: sh.index[0].start or 0):
        start = s.index[0].start or 0
        if start in seen:
            continue
        seen.add(start)
        b = start // per
        lo, hi = int(offsets[b]), int(offsets[b + 1])
        data = np.asarray(s.data)
        ids.append(np.arange(lo, hi, dtype=np.int64))
        vals.append(data[: hi - lo])
    r = arr.shape[-1]
    if not ids:
        return np.zeros((0,), np.int64), np.zeros((0, r), np.float32)
    return np.concatenate(ids), np.concatenate(vals).astype(np.float32)


def factors_from_result(res: "RestoreResult", name: str,
                        n_rows: int) -> np.ndarray:
    """Full ``(n_rows, r)`` host factor table from either storage form —
    replicated (``arrays``) or block-sharded (``sharded``).  Single-
    device restores of a checkpoint written by a block-parallel world
    land here: the reading process holds every old shard (round-robin
    over a world of one), so assembly is exact; rows no shard carried
    stay zero (a shrunken id space's tail).  A GROWN axis (the manifest
    recorded fewer rows than ``n_rows`` — growable-axis restore) pads
    the tail with zeros either way; the caller's grown-fill pass seeds
    those rows with the deterministic init."""
    if name in res.arrays:
        arr = np.asarray(res.arrays[name], np.float32)
        if arr.ndim == 2 and arr.shape[0] < n_rows:
            arr = np.concatenate([
                arr,
                np.zeros((n_rows - arr.shape[0], arr.shape[1]),
                         np.float32),
            ])
        return arr
    ids, vals = res.sharded[name]
    r = vals.shape[1] if vals.ndim == 2 else 1
    out = np.zeros((n_rows, r), np.float32)
    keep = ids < n_rows
    out[ids[keep]] = vals[keep]
    return out


def replicated_from_result(res: "RestoreResult", name: str,
                           n_rows: int) -> np.ndarray:
    """Full replicated host table from either storage form, correct in
    multi-process worlds: a block-sharded checkpoint restored into a
    replicated layout gathers every rank's loaded rows first (each rank
    only read its round-robin subset of old shards).  The gathers are
    rank-uniform and ride the collective-sanitizer fingerprint plane
    like every host collective."""
    import jax

    if name in res.arrays or jax.process_count() == 1:
        return factors_from_result(res, name, n_rows)
    from jax.experimental import multihost_utils

    from oap_mllib_tpu.utils import sanitizers

    ids, vals = res.sharded[name]
    r = vals.shape[1] if vals.ndim == 2 else 1
    n_local = np.asarray([len(ids)], np.int64)
    sanitizers.note_collective("process_allgather", "host", ((1,),), "int64")
    with x64_scope(True):
        counts = np.asarray(multihost_utils.process_allgather(n_local))
    n_max = max(1, int(counts.max()))
    pid = np.full((n_max,), -1, np.int64)
    pid[: len(ids)] = ids
    pval = np.zeros((n_max, r), np.float32)
    pval[: len(ids)] = vals
    sanitizers.note_collective(
        "process_allgather", "host", ((n_max,), (n_max, r)),
        "int64,float32",
    )
    with x64_scope(True):
        gid, gval = multihost_utils.process_allgather([pid, pval])
    gid = np.asarray(gid).reshape(-1)
    gval = np.asarray(gval).reshape(-1, r)
    out = np.zeros((n_rows, r), np.float32)
    keep = (gid >= 0) & (gid < n_rows)
    out[gid[keep]] = gval[keep]
    return out


def sharded_rows_from_result(res: "RestoreResult", name: str,
                             world: int, rank: int):
    """(ids, vals) feed for the collective resharding pass from either
    storage form.  A replicated checkpoint (written by a single-device
    or replicated-Y fit) is strided ``rank::world`` so the live world's
    ranks contribute disjoint row sets — reshard_factor_rows requires
    every global row on exactly one process."""
    if name in res.sharded:
        return res.sharded[name]
    arr = np.asarray(res.arrays[name], np.float32)
    ids = np.arange(rank, arr.shape[0], world, dtype=np.int64)
    return ids, arr[ids]


@dataclasses.dataclass
class RestoreResult:
    """Outcome of one restore attempt; ``decision`` lands in the fit
    summary and the ``checkpoint`` span so operators can see whether a
    fit continued, started fresh (and why), or was resharded."""

    decision: str = DECISION_FRESH
    step: int = 0
    reason: str = ""
    old_world: int = 0
    new_world: int = 0
    arrays: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    sharded: Dict[str, Tuple[np.ndarray, np.ndarray]] = dataclasses.field(
        default_factory=dict
    )
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    layout: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # growable-axis restore (warm-start): signature key -> (old, new)
    # extent for every declared growable axis the manifest recorded
    # SMALLER than this fit — the restored state covers the old prefix,
    # the caller initializes the grown tail (ALS: init_factors_rows)
    grown: Dict[str, Tuple[int, int]] = dataclasses.field(
        default_factory=dict
    )

    @property
    def found(self) -> bool:
        return self.decision != DECISION_FRESH


class Checkpointer:
    """One fit's checkpoint channel: periodic sharded writes + restore.

    Built by :func:`maybe_open` (None when ``Config.checkpoint_dir`` is
    empty — the zero-overhead off path).  ``signature`` is the fit
    identity (algo, shapes, seed, solver params, dtype — NOT the world
    size, chunk geometry, or precision policy, which are all allowed to
    change across a preemption); it keys the directory name and is
    embedded in the manifest, so a restore can never consume state from
    a different problem.
    """

    def __init__(self, algo: str, signature: Dict[str, Any], *,
                 cfg=None, timings=None, growable: Tuple[str, ...] = ()):
        cfg = cfg or get_config()
        self.algo = algo
        self.signature = dict(signature)
        self.signature["algo"] = algo
        self.growable = tuple(growable)
        for key in self.growable:
            if key not in self.signature:
                raise ValueError(
                    f"growable axis {key!r} is not a signature key "
                    f"(have {sorted(self.signature)})"
                )
        self.resume = resume_cfg(cfg)
        self.interval = max(int(cfg.checkpoint_interval), 1)
        # growable axes are EXCLUDED from the directory hash (replaced
        # by the sorted axis-name set), so yesterday's fit and today's
        # grown one share a directory — the warm-start-is-restore
        # contract; the full signature still rides the manifest and is
        # checked key-by-key at restore (shape-prefix match)
        dir_sig = dict(self.signature)
        if self.growable:
            for key in self.growable:
                dir_sig.pop(key, None)
            dir_sig["__growable__"] = sorted(self.growable)
        self.dir = os.path.join(
            cfg.checkpoint_dir, f"{algo}-{_sig_hash(dir_sig)}"
        )
        self.timings = timings
        self.world, self.rank = _world()
        self.writes = 0
        self.bytes_written = 0
        self.write_s = 0.0
        self.last_step = -1
        self._result: Optional[RestoreResult] = None

    # -- write side ----------------------------------------------------------

    def due(self, step: int) -> bool:
        """True when ``step`` is a checkpoint boundary — callers whose
        state EXTRACTION is itself expensive (a sharded-factor host
        pull) gate on this before materializing anything."""
        return step % self.interval == 0

    def maybe_write(self, step: int, arrays: Dict[str, np.ndarray],
                    extra: Optional[Dict[str, Any]] = None,
                    sharded: Optional[Dict[str, tuple]] = None,
                    layout: Optional[Dict[str, Any]] = None,
                    force: bool = False) -> bool:
        """Checkpoint iterate state at ``step`` when the interval says so
        (or ``force``).  ``arrays`` is replicated state (identical on
        every rank — each writes its copy for redundancy); ``sharded``
        maps name -> (ids, vals) of THIS rank's factor rows (see
        :func:`local_factor_rows`); ``extra``/``layout`` are
        JSON-serializable world-uniform metadata (pass index, converged
        flag, block offsets).  Never raises: a failed write warns +
        counts — a checkpoint is insurance, not a liveness dependency."""
        if not force and step % self.interval:
            return False
        with self._phase():
            return self._write_guarded(step, arrays, extra or {},
                                       sharded or {}, layout or {})

    def _phase(self):
        """The ``checkpoint`` child span under the fit root (a no-op
        context when the caller attached no Timings)."""
        if self.timings is None:
            return contextlib.nullcontext()
        return phase_timer(self.timings, "checkpoint")

    def _write_guarded(self, step, arrays, extra, sharded, layout) -> bool:
        elapsed = tick()
        ok, err, nbytes = True, None, 0
        try:
            faults.maybe_fault("ckpt.write")
            nbytes = self._write_shard(step, arrays, sharded)
        except Exception as e:  # noqa: BLE001 — insurance must not kill
            ok, err = False, e
        # rank-uniform agreement BEFORE the manifest flip: the manifest
        # must never name a step some rank failed to persist.  Reached on
        # the failure path too, so a one-rank fault cannot desync the
        # world's collective schedule.
        all_ok = self._sync_ok(ok)
        if not all_ok:
            _tm.counter(
                "oap_checkpoint_write_failures_total", {"algo": self.algo},
                help="Checkpoint writes that failed (warned, fit continued)",
            ).inc()
            log.warning(
                "%s: checkpoint write at step %d failed (%s); fit "
                "continues without this checkpoint",
                self.algo, step,
                err if err is not None else "failure on a peer rank",
            )
            return False
        flip_ok = True
        if self.rank == 0:
            try:
                self._write_manifest(step, list(arrays), extra,
                                     sharded, layout)
            except Exception as e:  # noqa: BLE001
                flip_ok = False
                log.warning(
                    "%s: checkpoint manifest flip at step %d failed (%s); "
                    "the previous generation stays live",
                    self.algo, step, e,
                )
        # second rank-uniform agreement: the manifest flip is the commit
        # point, so a failed flip must look failed on EVERY rank — peers
        # must not count writes/last_step (and report a durable
        # checkpoint in metrics and the fit summary) while the manifest
        # still names the previous generation.
        if not self._sync_ok(flip_ok):
            _tm.counter(
                "oap_checkpoint_write_failures_total", {"algo": self.algo},
                help="Checkpoint writes that failed (warned, fit continued)",
            ).inc()
            if self.rank != 0:
                log.warning(
                    "%s: checkpoint manifest flip at step %d failed on "
                    "rank 0; the previous generation stays live",
                    self.algo, step,
                )
            return False
        self._gc()
        dt = elapsed()
        self.writes += 1
        self.bytes_written += nbytes
        self.write_s += dt
        self.last_step = step
        _note_durable(step)
        if flightrec.enabled():
            # the commit (manifest flip agreed world-wide) is the event a
            # post-mortem aligns against — "the crash was N events after
            # the last durable step" (telemetry/flightrec.py)
            flightrec.record("ckpt_commit", self.algo, f"step={step}")
        _tm.counter(
            "oap_checkpoint_writes_total", {"algo": self.algo},
            help="Checkpoint shard writes that landed durably",
        ).inc()
        _tm.counter(
            "oap_checkpoint_bytes_written_total",
            help="Bytes written to checkpoint shards",
        ).inc(nbytes)
        _tm.counter(
            "oap_checkpoint_shards_total",
            help="Checkpoint shard files written",
        ).inc()
        _tm.counter(
            "oap_checkpoint_write_seconds_total",
            help="Wall spent writing checkpoints",
        ).inc(dt)
        self._note_span()
        return True

    def _shard_name(self, step: int, rank: int) -> str:
        return f"step{step:08d}.rank{rank}.npz"

    def _write_shard(self, step, arrays, sharded) -> int:
        os.makedirs(self.dir, exist_ok=True)
        payload = {"__step__": np.asarray(step, np.int64)}
        for name, a in arrays.items():
            payload[f"a.{name}"] = np.asarray(a)
        for name, (ids, vals) in sharded.items():
            payload[f"s.{name}.ids"] = np.asarray(ids, np.int64)
            payload[f"s.{name}.vals"] = np.asarray(vals, np.float32)
        path = os.path.join(self.dir, self._shard_name(step, self.rank))
        return _io.atomic_save_npz(path, payload)

    def _write_manifest(self, step, array_names, extra, sharded,
                        layout) -> None:
        from oap_mllib_tpu.parallel.bootstrap import world_layout

        # per-name value width of the sharded state (``sharded`` is the
        # name -> (ids, vals) dict; a bare name list is accepted for
        # fabricated-manifest tests).  Recorded so a restoring rank that
        # was assigned NO old shards (the world grew) can still build its
        # empty (0, r) placeholder with the TRUE width — widths derived
        # per-rank from local data would be rank-divergent there, and
        # rank-divergent buffer shapes hang the restore collectives.
        widths = {}
        if isinstance(sharded, dict):
            for name, (_ids, vals) in sharded.items():
                v = np.asarray(vals)
                widths[name] = int(v.shape[1]) if v.ndim == 2 else 1
        wl = world_layout()
        manifest = {
            "version": _VERSION,
            "algo": self.algo,
            "step": int(step),
            "world": self.world,
            "devices": wl["devices"],
            "arrays": sorted(array_names),
            "sharded": sorted(sharded),
            "widths": widths,
            "extra": extra,
            "layout": layout,
            "signature": self.signature,
            "growable": list(self.growable),
            "interval": self.interval,
        }
        _io.atomic_write_json(os.path.join(self.dir, MANIFEST), manifest)

    def _sync_ok(self, ok: bool) -> bool:
        if self.world == 1:
            return ok
        from jax.experimental import multihost_utils

        from oap_mllib_tpu.utils import recovery, sanitizers

        flag = np.asarray([0 if ok else 1], np.int64)
        sanitizers.note_collective(
            "process_allgather", "host", ((1,),), "int64"
        )
        # the agreement gather is a host collective like any other: a
        # peer preempted mid-write must convert into a diagnosis on the
        # survivors, not a hang (utils/recovery.guarded_dispatch —
        # disarmed = one config check)
        with x64_scope(True):
            gathered = recovery.guarded_dispatch(
                "ckpt.sync", "host",
                lambda: multihost_utils.process_allgather(flag),
            )
        return int(np.asarray(gathered).sum()) == 0

    def _gc(self) -> None:
        """Drop THIS rank's shards beyond the newest _KEEP_GENERATIONS
        (best-effort; a racing reader already holds its data in memory —
        data/io.load_npz materializes eagerly).  Rank 0 additionally
        drops VANISHED ranks' shards — ranks >= the current world, left
        behind by a restore onto a smaller world — once their generation
        ages out of the kept set, so elastic cycles in a long-lived
        checkpoint_dir cannot accumulate orphans no live rank owns."""
        try:
            entries = []
            for f in os.listdir(self.dir):
                m = _SHARD_RE.match(f)
                if m:
                    entries.append((int(m.group(1)), int(m.group(2)), f))
            mine = sorted(f for step, rank, f in entries
                          if rank == self.rank)
            for f in mine[:-_KEEP_GENERATIONS]:
                os.unlink(os.path.join(self.dir, f))
            if self.rank == 0:
                kept = set(sorted({step for step, _, _ in entries})
                           [-_KEEP_GENERATIONS:])
                for step, rank, f in entries:
                    if rank >= self.world and step not in kept:
                        os.unlink(os.path.join(self.dir, f))
        except OSError:
            pass

    # -- restore side --------------------------------------------------------

    def restore(self) -> RestoreResult:
        """One restore attempt; the decision (found / fresh / resharded,
        old->new world) is remembered for :meth:`record`.  Corrupt or
        mismatched checkpoints follow ``Config.resume``: "auto" falls
        back to a fresh fit with a warning, "require" raises
        :class:`CheckpointError`, "off" never reads at all."""
        elapsed = tick()
        with self._phase():
            res = self._restore_guarded()
        self._result = res
        _tm.counter(
            "oap_checkpoint_restores_total",
            {"algo": self.algo, "decision": res.decision},
            help="Checkpoint restore attempts by outcome",
        ).inc()
        _tm.counter(
            "oap_checkpoint_restore_seconds_total",
            help="Wall spent in checkpoint restore attempts",
        ).inc(elapsed())
        self._note_span()
        return res

    def _restore_guarded(self) -> RestoreResult:
        if self.resume == "off":
            return RestoreResult(reason="resume=off", new_world=self.world)
        err: Optional[Exception] = None
        res = RestoreResult(new_world=self.world)
        try:
            faults.maybe_fault("ckpt.restore")
            res = self._load()
        except Exception as e:  # noqa: BLE001 — classified below
            err = e
        # rank-uniform outcome: one rank with a torn shard must not start
        # fresh while its peers resume mid-fit (divergent collective
        # schedules hang the world)
        if not self._sync_ok(err is None):
            err = err or CheckpointError(
                f"{self.algo}: checkpoint restore failed on a peer rank"
            )
            res = RestoreResult(new_world=self.world)
        if err is not None:
            if self.resume == "require":
                raise CheckpointError(
                    f"{self.algo}: resume='require' but no usable "
                    f"checkpoint under {self.dir}: {err}"
                ) from err
            if isinstance(err, FileNotFoundError):
                res.reason = "no checkpoint found"
            else:
                res.reason = f"corrupt checkpoint: {err}"
                log.warning(
                    "%s: falling back to a fresh fit (%s)",
                    self.algo, res.reason,
                )
        return res

    def _load(self) -> RestoreResult:
        mpath = os.path.join(self.dir, MANIFEST)
        if not os.path.exists(mpath):
            raise FileNotFoundError(f"no checkpoint manifest at {mpath}")
        manifest = _io.read_json(mpath)
        if manifest.get("version") != _VERSION:
            raise CheckpointError(
                f"manifest version {manifest.get('version')!r} != {_VERSION}"
            )
        grown = self._check_signature(manifest)
        step = int(manifest["step"])
        old_world = int(manifest["world"])
        decision = (
            DECISION_FOUND if old_world == self.world else DECISION_RESHARDED
        )
        # replicated arrays: read the old rank aligned with THIS rank
        # (any old shard carries them; aligned keeps same-world restores
        # reading each rank's own file)
        rep_shard = self._load_shard(step, self.rank % old_world)
        arrays = {
            name: rep_shard[f"a.{name}"] for name in manifest["arrays"]
        }
        # sharded state: partition the old shard files round-robin over
        # the NEW world so every old row is read exactly once, then the
        # caller reshards collectively (shuffle.reshard_factor_rows)
        sharded: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        if manifest["sharded"]:
            widths = {
                n: int(w)
                for n, w in dict(manifest.get("widths", {})).items()
            }
            per_name: Dict[str, Tuple[list, list]] = {
                n: ([], []) for n in manifest["sharded"]
            }
            for old_rank in range(old_world):
                if old_rank % self.world != self.rank:
                    continue
                shard = (
                    rep_shard if old_rank == self.rank % old_world
                    else self._load_shard(step, old_rank)
                )
                for name in manifest["sharded"]:
                    per_name[name][0].append(shard[f"s.{name}.ids"])
                    per_name[name][1].append(shard[f"s.{name}.vals"])
            for name, (ids, vals) in per_name.items():
                # a rank assigned no old shards (the world GREW past
                # old_world) still participates in the restore gathers
                # and the resharding all_to_all, whose record widths
                # every rank derives from vals.shape[1] — so the empty
                # placeholder must carry the manifest-recorded value
                # width, never a guessed one
                sharded[name] = (
                    np.concatenate(ids) if ids else np.zeros((0,), np.int64),
                    np.concatenate(vals) if vals else np.zeros(
                        (0, widths.get(name, 1)), np.float32),
                )
        self.last_step = step
        _note_durable(step)
        return RestoreResult(
            decision=decision, step=step, old_world=old_world,
            new_world=self.world, arrays=arrays, sharded=sharded,
            extra=dict(manifest.get("extra", {})),
            layout=dict(manifest.get("layout", {})),
            grown=grown,
        )

    def _check_signature(self, manifest) -> Dict[str, Tuple[int, int]]:
        """Fit-identity check with growable axes: every NON-growable
        signature key must match the manifest exactly (different
        problem otherwise); a growable key may be LARGER in this fit
        than the manifest recorded — the grown tail is the caller's to
        initialize — and the growth is returned (old, new) per axis.
        A shrunk axis (restored rows would silently truncate) and a
        changed growable declaration (the manifest's rows were bucketed
        under different axis semantics) both raise."""
        man_sig = manifest.get("signature")
        if not self.growable:
            if man_sig != self.signature:
                raise CheckpointError(
                    "checkpoint signature mismatch (different problem): "
                    f"manifest {man_sig!r} vs fit {self.signature!r}"
                )
            return {}
        man_growable = list(manifest.get("growable", []))
        if man_growable != list(self.growable):
            raise CheckpointError(
                "checkpoint growable-axis declaration mismatch "
                "(reordered or changed axes): manifest declares "
                f"{man_growable!r}, fit declares {list(self.growable)!r}"
            )
        if not isinstance(man_sig, dict):
            raise CheckpointError(
                "checkpoint signature mismatch (different problem): "
                f"manifest {man_sig!r} vs fit {self.signature!r}"
            )
        fixed_man = {
            k: v for k, v in man_sig.items() if k not in self.growable
        }
        fixed_fit = {
            k: v for k, v in self.signature.items()
            if k not in self.growable
        }
        if fixed_man != fixed_fit:
            raise CheckpointError(
                "checkpoint signature mismatch (different problem): "
                f"manifest {fixed_man!r} vs fit {fixed_fit!r}"
            )
        grown: Dict[str, Tuple[int, int]] = {}
        for key in self.growable:
            old = int(man_sig.get(key, -1))
            new = int(self.signature[key])
            if old == new:
                continue
            if old > new:
                raise CheckpointError(
                    f"checkpoint axis {key!r} shrank: manifest has "
                    f"{old}, fit has {new} — restored rows beyond the "
                    "new extent would be silently dropped; refit from "
                    "scratch (or restore into an axis >= the manifest's)"
                )
            grown[key] = (old, new)
        return grown

    def _load_shard(self, step: int, rank: int) -> Dict[str, np.ndarray]:
        path = os.path.join(self.dir, self._shard_name(step, rank))
        shard = _io.load_npz(path)
        got = int(shard.get("__step__", np.asarray(-1)))
        if got != step:
            raise CheckpointError(
                f"shard {path} records step {got}, manifest says {step}"
            )
        return shard

    def mark_resharded(self) -> None:
        """Upgrade a same-world restore to ``resharded`` when the caller
        redistributed state anyway (e.g. the block layout changed with
        the process count unchanged — a num_user_blocks re-cap)."""
        if self._result is not None and self._result.found:
            self._result.decision = DECISION_RESHARDED
            self._note_span()

    # -- summary / telemetry -------------------------------------------------

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "dir": self.dir,
            "interval": self.interval,
            "writes": self.writes,
            "bytes_written": self.bytes_written,
            "write_seconds": round(self.write_s, 6),
            "last_step": self.last_step,
        }
        res = self._result
        if res is not None:
            out["decision"] = res.decision
            out["restored_step"] = res.step
            if res.found:
                out["old_world"] = res.old_world
                out["new_world"] = res.new_world
                if res.grown:
                    # warm-start growth, per axis: [old, new] extents
                    out["grown"] = {
                        k: [int(o), int(n)]
                        for k, (o, n) in sorted(res.grown.items())
                    }
            elif res.reason:
                out["reason"] = res.reason
        return out

    def record(self, summary) -> None:
        """Attach the fit's checkpoint accounting + restore decision to
        its summary (dict key / object attribute — the merge_stats
        convention) so operators can see which fits resumed and from
        where."""
        if summary is None:
            return
        if isinstance(summary, dict):
            summary["checkpoint"] = self.as_dict()
        else:
            summary.checkpoint = self.as_dict()
        self._note_span()

    def _note_span(self) -> None:
        if self.timings is None:
            return
        self.timings.root.node("checkpoint").attrs.update(self.as_dict())


def maybe_open(algo: str, signature: Dict[str, Any], *,
               timings=None,
               growable: Tuple[str, ...] = ()) -> Optional[Checkpointer]:
    """The one checkpointing entry estimators call: None when
    ``Config.checkpoint_dir`` is empty (one string check — the
    checkpoint-off ~0% overhead contract, asserted by
    dev/checkpoint_gate.py), else a :class:`Checkpointer` rooted at the
    fit's signature directory.  ``growable`` names signature keys (e.g.
    ALS ``n_users``/``n_items``) allowed to GROW across restores — the
    warm-start path: the axes are excluded from the directory hash and
    checked prefix-wise at restore (see Checkpointer._check_signature),
    with growth recorded in ``RestoreResult.grown`` /
    ``summary.checkpoint["grown"]``."""
    cfg = get_config()
    if not cfg.checkpoint_dir:
        return None
    return Checkpointer(
        algo, signature, cfg=cfg, timings=timings, growable=growable
    )
