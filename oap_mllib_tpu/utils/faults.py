"""Deterministic fault-injection registry: every retry tier testable
without real hardware faults.

The resilience layer (utils/resilience.py) only earns its keep if every
rung — transient retry, the halved-chunk OOM rung, the CPU fallback —
can be driven in CI.  Real faults (a flaky disk, a device OOM mid-fit, a
coordinator that is not up yet) are not reproducible on demand, so this
module plants named *sites* at the runtime's fragile edges and arms them
from config:

====================  =====================================================
site                  fires at
====================  =====================================================
``stream.read``       every piece pulled from a ``ChunkSource`` iterator
                      (data/stream.py) — host I/O faults
``prefetch.stage``    every stage call of the prefetch pipeline
                      (data/prefetch.py), i.e. in the producer thread at
                      depth >= 2 — staging/transfer faults
``bootstrap.connect`` each coordinator-connection attempt in
                      ``initialize_distributed`` (parallel/bootstrap.py)
``fit.execute``       every jitted-program launch that goes through
                      ``progcache.launch`` (utils/progcache.py) — the
                      jitted-fit chokepoint, where a device OOM surfaces
``ckpt.write``        every periodic checkpoint write
                      (utils/checkpoint.Checkpointer.write) — a failed
                      write must warn + count, never kill a healthy fit
``ckpt.restore``      every checkpoint restore attempt
                      (utils/checkpoint.Checkpointer.restore) — a fault
                      here is a corrupt/unreadable checkpoint: fresh fit
                      under ``Config.resume="auto"``, CheckpointError
                      under ``resume="require"``
====================  =====================================================

Arming: ``Config.fault_spec`` / env ``OAP_MLLIB_TPU_FAULT_SPEC``, a
comma-separated list of ``site:kind=count`` entries::

    stream.read:fail=2,prefetch.stage:fail=1   # first 2 reads + first
                                               # stage call raise transient
    fit.execute:oom=*                          # EVERY launch raises OOM
                                               # (persistent fault)

Kinds: ``fail`` = transient (classified TRANSIENT — the retry tier),
``oom`` = device memory exhaustion (classified OOM — the halved-chunk
rung), ``nan`` = non-finite iterate (classified NONFINITE — drives the
precision-degradation rung and the ``nonfinite_policy`` tiers), ``err``
= permanent (classified as no fault — propagates raw).  ``count`` is a
positive int (the first N calls raise) or ``*`` (persistent).  The
registry is deterministic: same spec + same call sequence = same
faults, so gates can assert exact retry counters (dev/fault_gate.py,
dev/precision_gate.py).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from oap_mllib_tpu.config import get_config

SITES = (
    "stream.read", "prefetch.stage", "bootstrap.connect", "fit.execute",
    "ckpt.write", "ckpt.restore",
)

KIND_FAIL = "fail"
KIND_OOM = "oom"
KIND_NONFINITE = "nan"
KIND_ERR = "err"
_KINDS = (KIND_FAIL, KIND_OOM, KIND_NONFINITE, KIND_ERR)


class FaultInjected(Exception):
    """Marker base for injected faults (classify_fault checks it first,
    so injected faults never depend on message parsing)."""

    kind = KIND_ERR


class InjectedTransientError(FaultInjected, OSError):
    """Injected transient fault (an ``OSError`` — the host-I/O shape the
    classifier treats as retryable even without the marker)."""

    kind = KIND_FAIL


class InjectedOOMError(FaultInjected, MemoryError):
    """Injected device-OOM fault; the message carries the XLA
    ``RESOURCE_EXHAUSTED`` phrase the classifier keys on for real ones."""

    kind = KIND_OOM


class InjectedPermanentError(FaultInjected, RuntimeError):
    """Injected permanent fault — NOT classified as transient/OOM; the
    ladder must re-raise it unchanged."""

    kind = KIND_ERR


class InjectedNonFiniteError(FaultInjected, FloatingPointError):
    """Injected non-finite-iterate fault (classified NONFINITE, like a
    real :class:`~oap_mllib_tpu.utils.resilience.NonFiniteError` from a
    streamed guardrail) — drives the resilience ladder's
    precision-degradation rung and the ``nonfinite_policy`` tiers in CI
    without needing data that actually overflows."""

    kind = KIND_NONFINITE


def _make_fault(kind: str, site: str, nth: int) -> FaultInjected:
    if kind == KIND_OOM:
        return InjectedOOMError(
            f"RESOURCE_EXHAUSTED: injected device OOM at {site} (call {nth})"
        )
    if kind == KIND_FAIL:
        return InjectedTransientError(
            f"injected transient fault at {site} (call {nth})"
        )
    if kind == KIND_NONFINITE:
        return InjectedNonFiniteError(
            f"injected non-finite iterate at {site} (call {nth})"
        )
    return InjectedPermanentError(
        f"injected permanent fault at {site} (call {nth})"
    )


class _SiteState:
    __slots__ = ("kind", "limit", "calls", "fired")

    def __init__(self, kind: str, limit: int):
        self.kind = kind
        self.limit = limit  # -1 = persistent
        self.calls = 0
        self.fired = 0


def parse_spec(spec: str) -> Dict[str, _SiteState]:
    """Parse the fault-spec grammar; raises ValueError naming the valid
    sites/kinds on any malformed entry (a typo'd spec must fail loudly,
    not silently inject nothing)."""
    out: Dict[str, _SiteState] = {}
    for entry in spec.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, action = entry.split(":", 1)
            kind, count = action.split("=", 1)
        except ValueError:
            raise ValueError(
                f"malformed fault_spec entry {entry!r} — expected "
                "'site:kind=count' (e.g. 'stream.read:fail=2')"
            ) from None
        site, kind, count = site.strip(), kind.strip(), count.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; valid sites: {', '.join(SITES)}"
            )
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; valid kinds: {', '.join(_KINDS)}"
            )
        if count == "*":
            limit = -1
        else:
            try:
                limit = int(count)
            except ValueError:
                raise ValueError(
                    f"fault count must be an int or '*', got {count!r}"
                ) from None
            if limit < 0:
                raise ValueError(f"fault count must be >= 0, got {limit}")
        out[site] = _SiteState(kind, limit)
    return out


class FaultRegistry:
    """Process-wide armed-site table.  ``maybe_fault`` re-arms lazily
    whenever ``Config.fault_spec`` changes, so tests and services drive
    injection purely through config/env."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spec: Optional[str] = None
        self._sites: Dict[str, _SiteState] = {}

    def arm(self, spec: str) -> None:
        sites = parse_spec(spec)  # validate before swapping state
        with self._lock:
            self._spec = spec
            self._sites = sites

    def maybe_fault(self, site: str) -> None:
        spec = get_config().fault_spec
        if spec != self._spec:  # unlocked read: a racing double-arm is
            self.arm(spec)  # idempotent (same spec, fresh counters)
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return
            st.calls += 1
            if st.limit == -1 or st.fired < st.limit:
                st.fired += 1
                raise _make_fault(st.kind, site, st.fired)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-armed-site counters: calls seen, faults fired, the limit."""
        with self._lock:
            return {
                s: {"calls": st.calls, "fired": st.fired, "limit": st.limit,
                    "kind": st.kind}
                for s, st in self._sites.items()
            }

    def reset(self) -> None:
        """Re-arm the current spec with fresh counters (gates run the
        same injection sequence twice and need call counts to restart)."""
        with self._lock:
            spec = self._spec
        if spec is not None:
            self.arm(spec)


_REGISTRY = FaultRegistry()


def maybe_fault(site: str) -> None:
    """Raise the armed fault for ``site`` if its budget remains; no-op
    when the site is unarmed.  Call at every site the spec names."""
    _REGISTRY.maybe_fault(site)


def stats() -> Dict[str, Dict[str, int]]:
    return _REGISTRY.stats()


def reset() -> None:
    _REGISTRY.reset()
