"""Deterministic fault-injection registry: every retry tier testable
without real hardware faults.

The resilience layer (utils/resilience.py) only earns its keep if every
rung — transient retry, the halved-chunk OOM rung, the CPU fallback —
can be driven in CI.  Real faults (a flaky disk, a device OOM mid-fit, a
coordinator that is not up yet) are not reproducible on demand, so this
module plants named *sites* at the runtime's fragile edges and arms them
from config:

====================  =====================================================
site                  fires at
====================  =====================================================
``stream.read``       every piece pulled from a ``ChunkSource`` iterator
                      (data/stream.py) — host I/O faults
``prefetch.stage``    every stage call of the prefetch pipeline
                      (data/prefetch.py), i.e. in the producer thread at
                      depth >= 2 — staging/transfer faults
``bootstrap.connect`` each coordinator-connection attempt in
                      ``initialize_distributed`` (parallel/bootstrap.py)
``fit.execute``       every jitted-program launch that goes through
                      ``progcache.launch`` (utils/progcache.py) — the
                      jitted-fit chokepoint, where a device OOM surfaces
``ckpt.write``        every periodic checkpoint write
                      (utils/checkpoint.Checkpointer.write) — a failed
                      write must warn + count, never kill a healthy fit
``ckpt.restore``      every checkpoint restore attempt
                      (utils/checkpoint.Checkpointer.restore) — a fault
                      here is a corrupt/unreadable checkpoint: fresh fit
                      under ``Config.resume="auto"``, CheckpointError
                      under ``resume="require"``
``collective.dispatch``  every host-level collective dispatch (the eager
                      facade in parallel/collective.py and the
                      host-mediated ``process_allgather`` reductions in
                      ops/stream_ops.py) — where a dead peer, a network
                      partition, or a preemption surfaces; drives the
                      recovery plane's deadline/abort tiers
                      (utils/recovery.py)
``disk.read``         every piece pulled from a DISK-backed
                      ``ChunkSource`` (mmap'd ``.npy`` / parquet piece
                      readers, data/stream.py) — media faults on the
                      out-of-core read path
``spill.write``       every chunk written by the spill writer
                      (data/io.SpillWriter) — a failed spill write
                      warns + falls through the resilience ladder (the
                      tmp+``os.replace`` protocol means it can never
                      corrupt an existing spill)
``spill.read``        every piece pulled from a SPILL-backed
                      ``ChunkSource`` (a table the host-OOM rung staged
                      to disk) — drives the spilled-route read tiers
``serve.request``     every scoring batch booked by the serving
                      micro-batcher (serving/batcher.py ``_book``) —
                      request-path faults; a transient here drives the
                      traffic plane's durable-future retry envelope
``serve.dispatch``    every dispatch cycle of the async traffic queue
                      (serving/traffic.TrafficQueue.pump) — a
                      dispatcher-thread crash; the queue must fail
                      in-flight futures loudly and restart, never wedge
``serve.batch``       every coalesced flush of the serving registry
                      (serving/registry.ServedModel._flush_many) — a
                      poison batch; drives the log2-bisection isolation
                      path of the traffic plane
``serve.drain``       every graceful-drain entry
                      (serving/traffic.TrafficQueue.drain) — drain-path
                      faults during scale-in / shutdown
``delta.ingest``      every incremental-fit delta ingested by the online
                      paths (online/minibatch.py partial_fit chunks,
                      online/ipca.py updates, online/foldin.py rating
                      deltas) — a fault here must leave the base model
                      AND its served pin untouched (compute-then-swap)
``delta.solve``       every batched fold-in solve launch
                      (online/foldin.py — the one
                      ``als_ops.regularized_solve`` call per delta
                      commit); drives the failed-commit regression:
                      the old model version keeps answering
====================  =====================================================

Arming: ``Config.fault_spec`` / env ``OAP_MLLIB_TPU_FAULT_SPEC``, a
comma-separated list of ``site:kind=count`` entries::

    stream.read:fail=2,prefetch.stage:fail=1   # first 2 reads + first
                                               # stage call raise transient
    fit.execute:oom=*                          # EVERY launch raises OOM
                                               # (persistent fault)

Kinds: ``fail`` = transient (classified TRANSIENT — the retry tier),
``oom`` = device memory exhaustion (classified OOM — the geometric
halved-chunk rung), ``oomhost`` = HOST memory exhaustion (classified
OOM_HOST — drives the spill-to-disk rung: the staged table moves to a
disk-backed source and the fit re-enters the streamed route), ``nan`` =
non-finite iterate (classified NONFINITE — drives the
precision-degradation rung and the ``nonfinite_policy`` tiers), ``err``
= permanent (classified as no fault — propagates raw), ``kill`` = the
process is SIGKILLed on the spot (no exception, no cleanup — a
preemption; drives the live-world recovery drills).  ``count`` is a
positive int (the first N calls raise) or ``*`` (persistent).  The
registry is deterministic: same spec + same call sequence = same
faults, so gates can assert exact retry counters (dev/fault_gate.py,
dev/precision_gate.py).

**Chaos mode** (``Config.chaos`` / env ``OAP_MLLIB_TPU_CHAOS``) layers a
seeded *randomized* schedule over every registered site on top of any
explicit spec: ``seed:rate[:kinds[:budget]]`` fires a fault on ~``rate``
of site calls, cycling through ``kinds`` (``+``-separated, default
``fail``), capped at ``budget`` total fires (default unbounded).  The
decision is a pure hash of (seed, process index, site, call index) —
reproducible end to end, and DIFFERENT per rank, so one rank of a world
can be killed while its peers survive into the collective-deadline path
(dev/chaos_gate.py drills exactly that loop).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional

from oap_mllib_tpu.config import get_config

SITES = (
    "stream.read", "prefetch.stage", "bootstrap.connect", "fit.execute",
    "ckpt.write", "ckpt.restore", "collective.dispatch",
    "disk.read", "spill.write", "spill.read", "serve.request",
    "serve.dispatch", "serve.batch", "serve.drain",
    "delta.ingest", "delta.solve",
)

KIND_FAIL = "fail"
KIND_OOM = "oom"
KIND_HOST_OOM = "oomhost"
KIND_NONFINITE = "nan"
KIND_ERR = "err"
KIND_KILL = "kill"
_KINDS = (KIND_FAIL, KIND_OOM, KIND_HOST_OOM, KIND_NONFINITE, KIND_ERR,
          KIND_KILL)


class FaultInjected(Exception):
    """Marker base for injected faults (classify_fault checks it first,
    so injected faults never depend on message parsing)."""

    kind = KIND_ERR


class InjectedTransientError(FaultInjected, OSError):
    """Injected transient fault (an ``OSError`` — the host-I/O shape the
    classifier treats as retryable even without the marker)."""

    kind = KIND_FAIL


class InjectedOOMError(FaultInjected, MemoryError):
    """Injected device-OOM fault; the message carries the XLA
    ``RESOURCE_EXHAUSTED`` phrase the classifier keys on for real ones."""

    kind = KIND_OOM


class InjectedHostOOMError(FaultInjected, MemoryError):
    """Injected HOST-memory exhaustion (a bare ``MemoryError`` with no
    device marker — the shape a failed np allocation raises): classified
    OOM_HOST, driving the resilience ladder's spill-to-disk rung."""

    kind = KIND_HOST_OOM


class InjectedPermanentError(FaultInjected, RuntimeError):
    """Injected permanent fault — NOT classified as transient/OOM; the
    ladder must re-raise it unchanged."""

    kind = KIND_ERR


class InjectedNonFiniteError(FaultInjected, FloatingPointError):
    """Injected non-finite-iterate fault (classified NONFINITE, like a
    real :class:`~oap_mllib_tpu.utils.resilience.NonFiniteError` from a
    streamed guardrail) — drives the resilience ladder's
    precision-degradation rung and the ``nonfinite_policy`` tiers in CI
    without needing data that actually overflows."""

    kind = KIND_NONFINITE


def _hard_kill(site: str, nth: int) -> None:
    """The ``kill`` kind: SIGKILL this process on the spot — no
    exception, no atexit, no flushing beyond this warning.  The closest
    injectable analog of a preemption notice arriving mid-collective."""
    import logging
    import os
    import signal

    logging.getLogger("oap_mllib_tpu").warning(
        "fault injection: hard-killing process at %s (fire %d)", site, nth
    )
    os.kill(os.getpid(), signal.SIGKILL)


def _make_fault(kind: str, site: str, nth: int) -> FaultInjected:
    if kind == KIND_OOM:
        return InjectedOOMError(
            f"RESOURCE_EXHAUSTED: injected device OOM at {site} (call {nth})"
        )
    if kind == KIND_FAIL:
        return InjectedTransientError(
            f"injected transient fault at {site} (call {nth})"
        )
    if kind == KIND_HOST_OOM:
        return InjectedHostOOMError(
            f"injected host memory exhaustion at {site} (call {nth})"
        )
    if kind == KIND_NONFINITE:
        return InjectedNonFiniteError(
            f"injected non-finite iterate at {site} (call {nth})"
        )
    return InjectedPermanentError(
        f"injected permanent fault at {site} (call {nth})"
    )


class _SiteState:
    __slots__ = ("kind", "limit", "calls", "fired")

    def __init__(self, kind: str, limit: int):
        self.kind = kind
        self.limit = limit  # -1 = persistent
        self.calls = 0
        self.fired = 0


def parse_spec(spec: str) -> Dict[str, _SiteState]:
    """Parse the fault-spec grammar; raises ValueError naming the valid
    sites/kinds on any malformed entry (a typo'd spec must fail loudly,
    not silently inject nothing)."""
    out: Dict[str, _SiteState] = {}
    for entry in spec.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, action = entry.split(":", 1)
            kind, count = action.split("=", 1)
        except ValueError:
            raise ValueError(
                f"malformed fault_spec entry {entry!r} — expected "
                "'site:kind=count' (e.g. 'stream.read:fail=2')"
            ) from None
        site, kind, count = site.strip(), kind.strip(), count.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; valid sites: {', '.join(SITES)}"
            )
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; valid kinds: {', '.join(_KINDS)}"
            )
        if count == "*":
            limit = -1
        else:
            try:
                limit = int(count)
            except ValueError:
                raise ValueError(
                    f"fault count must be an int or '*', got {count!r}"
                ) from None
            if limit < 0:
                raise ValueError(f"fault count must be >= 0, got {limit}")
        out[site] = _SiteState(kind, limit)
    return out


class ChaosState:
    """Seeded randomized fault schedule over EVERY registered site.

    The fire decision for one site call is a pure function of
    (seed, process index, site, per-site call index): a crc32 hash
    mapped to [0, 1) and compared against ``rate``.  Including the
    process index makes ranks fail *independently* — the property the
    live-world drills need (one rank killed, peers surviving into the
    collective-deadline path) — while keeping every rank's schedule
    reproducible from the spec alone.  The fired-fault kind cycles
    deterministically through ``kinds``; ``budget`` caps total fires
    per process (-1 = unbounded)."""

    __slots__ = ("seed", "rate", "kinds", "budget", "calls", "fired")

    def __init__(self, seed: int, rate: float, kinds: List[str],
                 budget: int):
        self.seed = seed
        self.rate = rate
        self.kinds = list(kinds)
        self.budget = budget  # -1 = unbounded
        self.calls: Dict[str, int] = {}
        self.fired = 0

    def decide(self, site: str, call: int, rank: int) -> bool:
        """Pure fire decision (no state) — unit-testable determinism."""
        h = zlib.crc32(f"{self.seed}:{rank}:{site}:{call}".encode())
        return (h / 0xFFFFFFFF) < self.rate

    def maybe_fire(self, site: str, rank: int):
        """Advance this site's call counter; returns the fault kind to
        fire, or None."""
        call = self.calls.get(site, 0)
        self.calls[site] = call + 1
        if self.budget != -1 and self.fired >= self.budget:
            return None
        if not self.decide(site, call, rank):
            return None
        kind = self.kinds[self.fired % len(self.kinds)]
        self.fired += 1
        return kind

    def stats(self) -> Dict[str, object]:
        return {
            "seed": self.seed, "rate": self.rate, "kinds": list(self.kinds),
            "budget": self.budget, "fired": self.fired,
            "calls": dict(self.calls),
        }


def parse_chaos(spec: str) -> Optional[ChaosState]:
    """Parse ``Config.chaos`` (``seed:rate[:kinds[:budget]]``); None for
    the empty spec, ValueError naming the grammar on anything malformed
    (a chaos spec that silently arms nothing defeats the drill)."""
    spec = spec.strip()
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) < 2 or len(parts) > 4:
        raise ValueError(
            f"malformed chaos spec {spec!r} — expected "
            "'seed:rate[:kinds[:budget]]' (e.g. '7:0.02' or "
            "'7:0.01:fail+kill:3')"
        )
    try:
        seed = int(parts[0])
        rate = float(parts[1])
    except ValueError:
        raise ValueError(
            f"chaos seed must be an int and rate a float, got "
            f"{parts[0]!r}:{parts[1]!r}"
        ) from None
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"chaos rate must be in [0, 1], got {rate}")
    kinds = ["fail"]
    if len(parts) >= 3 and parts[2].strip():
        kinds = [k.strip() for k in parts[2].split("+") if k.strip()]
        bad = [k for k in kinds if k not in _KINDS]
        if bad:
            raise ValueError(
                f"unknown chaos kind(s) {bad}; valid kinds: "
                f"{', '.join(_KINDS)}"
            )
    budget = -1
    if len(parts) == 4 and parts[3].strip() not in ("", "*"):
        try:
            budget = int(parts[3])
        except ValueError:
            raise ValueError(
                f"chaos budget must be an int or '*', got {parts[3]!r}"
            ) from None
        if budget < 0:
            raise ValueError(f"chaos budget must be >= 0, got {budget}")
    return ChaosState(seed, rate, kinds, budget)


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # noqa: BLE001 — chaos must work before a backend
        return 0


class FaultRegistry:
    """Process-wide armed-site table.  ``maybe_fault`` re-arms lazily
    whenever ``Config.fault_spec`` or ``Config.chaos`` changes, so tests
    and services drive injection purely through config/env."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spec: Optional[str] = None
        self._sites: Dict[str, _SiteState] = {}
        self._chaos_spec: Optional[str] = None
        self._chaos: Optional[ChaosState] = None

    def arm(self, spec: str) -> None:
        sites = parse_spec(spec)  # validate before swapping state
        with self._lock:
            self._spec = spec
            self._sites = sites

    def arm_chaos(self, spec: str) -> None:
        chaos = parse_chaos(spec)  # validate before swapping state
        with self._lock:
            self._chaos_spec = spec
            self._chaos = chaos

    def maybe_fault(self, site: str) -> None:
        cfg = get_config()
        spec, chaos_spec = cfg.fault_spec, cfg.chaos
        if spec != self._spec:  # unlocked read: a racing double-arm is
            self.arm(spec)  # idempotent (same spec, fresh counters)
        if chaos_spec != self._chaos_spec:
            self.arm_chaos(chaos_spec)
        with self._lock:
            st = self._sites.get(site)
            if st is not None:
                st.calls += 1
                if st.limit == -1 or st.fired < st.limit:
                    st.fired += 1
                    if st.kind == KIND_KILL:
                        _hard_kill(site, st.fired)
                    raise _make_fault(st.kind, site, st.fired)
            if self._chaos is not None:
                kind = self._chaos.maybe_fire(site, _process_index())
                if kind is not None:
                    nth = self._chaos.fired
                    if kind == KIND_KILL:
                        _hard_kill(site, nth)
                    raise _make_fault(kind, site, nth)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-armed-site counters: calls seen, faults fired, the limit.
        The chaos schedule's counters ride under the ``"chaos"`` key."""
        with self._lock:
            out = {
                s: {"calls": st.calls, "fired": st.fired, "limit": st.limit,
                    "kind": st.kind}
                for s, st in self._sites.items()
            }
            if self._chaos is not None:
                out["chaos"] = self._chaos.stats()
            return out

    def reset(self) -> None:
        """Re-arm the current specs with fresh counters (gates run the
        same injection sequence twice and need call counts to restart)."""
        with self._lock:
            spec, chaos_spec = self._spec, self._chaos_spec
        if spec is not None:
            self.arm(spec)
        if chaos_spec is not None:
            self.arm_chaos(chaos_spec)


_REGISTRY = FaultRegistry()


def maybe_fault(site: str) -> None:
    """Raise the armed fault for ``site`` if its budget remains; no-op
    when the site is unarmed.  Call at every site the spec names."""
    _REGISTRY.maybe_fault(site)


def stats() -> Dict[str, Dict[str, int]]:
    return _REGISTRY.stats()


def reset() -> None:
    _REGISTRY.reset()
