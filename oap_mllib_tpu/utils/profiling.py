"""XLA-level profiling: the deep-trace layer the reference never had.

The reference's only observability is wall-time prints (survey §5);
utils/timing.py replicates that.  This module adds the TPU-native layer
beneath it: ``jax.profiler`` traces capture per-op device timelines,
HBM usage, and ICI collective timing, viewable in TensorBoard/XProf.

Usage::

    from oap_mllib_tpu.utils.profiling import trace
    with trace("/tmp/oap_trace"):
        KMeans(k=8).fit(x)

or set ``OAP_MLLIB_TPU_PROFILE_DIR`` and every estimator fit is traced.
"""

from __future__ import annotations

import contextlib
import logging
import os

log = logging.getLogger("oap_mllib_tpu")


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block."""
    import jax

    log.info("profiler trace -> %s", log_dir)
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def maybe_trace():
    """Trace if OAP_MLLIB_TPU_PROFILE_DIR is set; no-op otherwise."""
    log_dir = os.environ.get("OAP_MLLIB_TPU_PROFILE_DIR", "")
    if not log_dir:
        yield
        return
    with trace(log_dir):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
