"""XLA-level profiling: the deep-trace layer the reference never had.

The reference's only observability is wall-time prints (survey §5);
utils/timing.py replicates that.  This module adds the TPU-native layer
beneath it: ``jax.profiler`` traces capture per-op device timelines,
HBM usage, and ICI collective timing, viewable in TensorBoard/XProf.

Usage::

    from oap_mllib_tpu.utils.profiling import trace
    with trace("/tmp/oap_trace"):
        KMeans(k=8).fit(x)

or set ``Config.profile_dir`` (env ``OAP_MLLIB_TPU_PROFILE_DIR``) and
every estimator fit is traced.  While a trace is live the span tree
(telemetry/spans.py) emits a ``jax.profiler.TraceAnnotation`` per phase,
so the fit's named spans line up on the XProf timeline;
:func:`trace_active` is the one-bool guard that keeps that free when no
trace is running.
"""

from __future__ import annotations

import contextlib
import logging

from oap_mllib_tpu.config import get_config

log = logging.getLogger("oap_mllib_tpu")

# live jax.profiler.trace nesting depth — the cheap guard the span layer
# checks before paying for a TraceAnnotation
_active = 0


def trace_active() -> bool:
    return _active > 0


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block."""
    global _active
    import jax

    log.info("profiler trace -> %s", log_dir)
    with jax.profiler.trace(log_dir):
        _active += 1
        try:
            yield
        finally:
            _active -= 1


@contextlib.contextmanager
def maybe_trace():
    """Trace if ``Config.profile_dir`` is set; no-op otherwise.  The knob
    is env-coerced like every other config field (OAP_MLLIB_TPU_
    PROFILE_DIR), so ``Config.set``/scoped overrides work too — it used
    to read the raw env var only."""
    log_dir = get_config().profile_dir
    if not log_dir:
        yield
        return
    with trace(log_dir):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
