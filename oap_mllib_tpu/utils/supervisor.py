"""Supervised relaunch: the restart half of the live-world recovery loop.

utils/recovery.py converts a dead peer into a prompt, machine-readable
exit on every rank (collective deadlines + the crash-record sideband);
utils/checkpoint.py makes the lost work resumable.  This module closes
the loop: a :class:`Supervisor` launches the world's rank processes,
watches them, **classifies** the exit (crash records + exit codes +
signals), and relaunches under a bounded restart budget with exponential
backoff — shrinking the world by one when the same rank keeps failing
(``Config.shrink_after`` consecutive times), so a repeatedly bad host
stops taking the fleet down with it.  Relaunched worlds run with
``Config.resume="auto"``: same-world resumes are bit-identical
continuations, shrunken worlds redistribute factor shards through
``parallel/shuffle.reshard_factor_rows`` (the elastic-training pattern
of PAPERS.md arXiv:2112.01075).

The supervisor is deliberately jax-free: it spawns and reaps plain
subprocesses, reads JSON from the sideband, and never joins the world
itself — so it survives everything the workers can do to themselves,
including SIGKILL mid-collective.  ``dev/supervise.py`` is the CLI
driver; dev/chaos_gate.py drills the whole loop in CI.

Exit classification, per rank:

==================  =========================================================
classification      meaning
==================  =========================================================
``ok``              exit code 0, no crash record
``killed``          died on a signal (negative returncode) with no record —
                    a preemption; the prime relaunch candidate
``collective_timeout``  the rank's own deadline expired waiting for a peer
                    (a *victim*, not a culprit)
``peer_abort``      the rank aborted because a peer's record appeared
                    (also a victim)
<fault class>       the crash record's class (transient/oom/nonfinite/
                    unclassified) for ranks that faulted locally
``error``           nonzero exit with no record and no signal
==================  =========================================================

The *culprit* of a failed attempt is the first non-victim failure
(killed/faulted/errored rank); pure-victim attempts (every failure a
timeout/peer-abort — the dead rank left no trace, e.g. SIGKILL) fall
back to the first signal-killed rank, then the first failure.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
import time
from typing import Any, Callable, Dict, List, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import recovery

log = logging.getLogger("oap_mllib_tpu")

_VICTIM_CLASSES = (recovery.FAULT_TIMEOUT, recovery.FAULT_PEER_ABORT)


class SupervisorError(RuntimeError):
    """The restart budget ran out before a world completed."""


@dataclasses.dataclass
class RankExit:
    """One rank's exit from one attempt."""

    rank: int
    returncode: Optional[int]
    classification: str
    record: Optional[Dict[str, Any]] = None
    output: str = ""

    @property
    def ok(self) -> bool:
        return self.classification == "ok"

    @property
    def victim(self) -> bool:
        return self.classification in _VICTIM_CLASSES

    def as_dict(self) -> Dict[str, Any]:
        out = {
            "rank": self.rank,
            "returncode": self.returncode,
            "classification": self.classification,
        }
        if self.record is not None:
            out["record"] = {
                k: self.record.get(k)
                for k in ("fault_class", "site", "op", "last_checkpoint_step")
            }
        return out


@dataclasses.dataclass
class Attempt:
    """One launched world: its size, per-rank exits, and outcome."""

    index: int
    world: int
    exits: List[RankExit] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return bool(self.exits) and all(e.ok for e in self.exits)

    def culprit(self) -> Optional[int]:
        """The rank to blame for a failed attempt (None when ok)."""
        if self.ok:
            return None
        bad = [e for e in self.exits if not e.ok]
        for e in bad:  # a non-victim local failure names itself
            if not e.victim:
                return e.rank
        for e in bad:  # all victims: blame a signal death if any
            if e.returncode is not None and e.returncode < 0:
                return e.rank
        return bad[0].rank if bad else None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "world": self.world,
            "ok": self.ok,
            "culprit": self.culprit(),
            "exits": [e.as_dict() for e in self.exits],
        }


class Supervisor:
    """Launch → watch → classify → relaunch/shrink, under a budget.

    ``build_argv(rank, world, coord, attempt)`` returns the argv for one
    rank's process (``coord`` is a fresh ``host:port`` rendezvous per
    attempt — reusing a dead world's port races its TIME_WAIT sockets).
    The supervisor injects into every worker's environment:

    - ``OAP_MLLIB_TPU_CRASH_DIR`` — the shared sideband (and clears
      stale records between attempts);
    - ``OAP_MLLIB_TPU_RESUME=auto`` — relaunches resume the last durable
      checkpoint (callers arm ``OAP_MLLIB_TPU_CHECKPOINT_DIR`` in
      ``env``);
    - ``OAP_MLLIB_TPU_CHAOS`` — when a base ``chaos`` spec is given, its
      seed is re-seeded ``+attempt`` so a deterministic kill schedule
      does not re-kill the resumed world at the same point;
    - ``SUPERVISE_ATTEMPT`` — the attempt index (drill workers key
      one-shot faults off it);
    - ``OAP_MLLIB_TPU_PROBE_EPOCH`` — the attempt index as the
      capability-probe generation: every relaunch invalidates the
      probe caches (utils/dispatch.throughput_probe, parallel/balance
      .world_capabilities), so a relaunched rank re-measures its
      CURRENT capability instead of shard-planning from its
      pre-preemption value.

    Restart policy: at most ``restart_budget`` relaunches (Config
    default), backoff ``restart_backoff * 2^(n-1)`` seconds before
    relaunch *n*; ``shrink_after`` consecutive failures blamed on the
    same rank shrink the world by one (never below 1) and reset the
    blame counter — ``resume=auto`` reshards state onto the new layout.
    """

    def __init__(self, build_argv: Callable[[int, int, str, int], List[str]],
                 world: int, crash_dir: str, *,
                 env: Optional[Dict[str, str]] = None,
                 restart_budget: Optional[int] = None,
                 restart_backoff: Optional[float] = None,
                 shrink_after: Optional[int] = None,
                 chaos: str = "",
                 attempt_timeout: float = 600.0,
                 grace_s: float = 30.0,
                 poll_s: float = 0.2,
                 coord_host: str = "127.0.0.1"):
        cfg = get_config()
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.build_argv = build_argv
        self.world = world
        self.crash_dir = crash_dir
        self.env = dict(env or os.environ)
        self.restart_budget = (
            int(cfg.restart_budget) if restart_budget is None
            else int(restart_budget)
        )
        self.restart_backoff = (
            float(cfg.restart_backoff) if restart_backoff is None
            else float(restart_backoff)
        )
        self.shrink_after = (
            int(cfg.shrink_after) if shrink_after is None
            else int(shrink_after)
        )
        if self.restart_budget < 0 or self.restart_backoff < 0:
            raise ValueError(
                "restart_budget and restart_backoff must be >= 0, got "
                f"{self.restart_budget}/{self.restart_backoff}"
            )
        if self.shrink_after < 1:
            raise ValueError(
                f"shrink_after must be >= 1, got {self.shrink_after}"
            )
        self.chaos = chaos
        self.attempt_timeout = attempt_timeout
        self.grace_s = grace_s
        self.poll_s = poll_s
        self.coord_host = coord_host
        self.attempts: List[Attempt] = []
        self.relaunches = 0
        self.shrinks = 0
        self.balance_hints: List[Dict[str, Any]] = []
        self.scale_hints: List[Dict[str, Any]] = []
        self.drain_reports: List[Dict[str, Any]] = []
        self._blame_rank: Optional[int] = None
        self._blame_count = 0

    # -- world lifecycle -----------------------------------------------------

    def _coord(self) -> str:
        from oap_mllib_tpu.parallel.bootstrap import free_port

        return f"{self.coord_host}:{free_port(self.coord_host, 4000)}"

    def _worker_env(self, attempt: int) -> Dict[str, str]:
        env = dict(self.env)
        env["OAP_MLLIB_TPU_CRASH_DIR"] = self.crash_dir
        env["OAP_MLLIB_TPU_RESUME"] = "auto"
        env["SUPERVISE_ATTEMPT"] = str(attempt)
        # fresh capability generation per attempt: a relaunched rank
        # must re-probe, not trust its pre-preemption measurement
        env["OAP_MLLIB_TPU_PROBE_EPOCH"] = str(attempt)
        if self.chaos:
            from oap_mllib_tpu.utils.faults import parse_chaos

            base = parse_chaos(self.chaos)
            if base is not None:
                parts = self.chaos.split(":")
                parts[0] = str(base.seed + attempt)
                env["OAP_MLLIB_TPU_CHAOS"] = ":".join(parts)
        return env

    def _launch(self, attempt: int, world: int):
        os.makedirs(self.crash_dir, exist_ok=True)
        recovery.clear_crash_records(self.crash_dir)
        coord = self._coord()
        env = self._worker_env(attempt)
        procs = []
        for rank in range(world):
            procs.append(subprocess.Popen(
                self.build_argv(rank, world, coord, attempt),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            ))
        return procs

    def _reap(self, procs) -> List[str]:
        """Wait out the grace window for survivors of a failure, then
        SIGKILL stragglers; returns per-rank captured output."""
        deadline = time.monotonic() + self.grace_s
        while any(p.poll() is None for p in procs) \
                and time.monotonic() < deadline:
            time.sleep(self.poll_s)
        outs = []
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                out = ""
            outs.append(out or "")
        return outs

    def _watch(self, procs) -> bool:
        """Block until the world completes or fails.  Returns True when
        every rank exited 0 before the attempt timeout; False on the
        first nonzero exit (or timeout), leaving survivors to _reap."""
        deadline = time.monotonic() + self.attempt_timeout
        while time.monotonic() < deadline:
            codes = [p.poll() for p in procs]
            if any(c is not None and c != 0 for c in codes):
                return False
            if all(c == 0 for c in codes):
                return True
            time.sleep(self.poll_s)
        return False

    # -- classification ------------------------------------------------------

    def _classify(self, attempt: int, world: int, procs,
                  outs: List[str]) -> Attempt:
        att = Attempt(index=attempt, world=world)
        for rank, (p, out) in enumerate(zip(procs, outs)):
            code = p.poll()
            record = None
            path = recovery.crash_record_path(self.crash_dir, rank)
            if os.path.exists(path):
                try:
                    import json

                    with open(path) as f:
                        record = json.load(f)
                except Exception:  # noqa: BLE001 — torn record
                    record = {"rank": rank}
            if code == 0 and record is None:
                cls = "ok"
            elif record is not None and record.get("fault_class"):
                cls = str(record["fault_class"])
            elif code is not None and code < 0:
                cls = "killed"
            else:
                cls = "error"
            att.exits.append(RankExit(
                rank=rank, returncode=code, classification=cls,
                record=record, output=out,
            ))
        return att

    def _read_balance_hint(self) -> Optional[Dict[str, Any]]:
        """Consume the straggler controller's persistent-offender hint
        (parallel/balance.HINT_FILENAME — written by rank 0 when a rank
        stayed slowest despite re-planning).  Read-and-remove: a hint
        names ONE world's offender and must not carry into the next
        attempt's bookkeeping."""
        path = os.path.join(self.crash_dir, "balance.hint.json")
        if not os.path.exists(path):
            return None
        try:
            import json

            with open(path) as f:
                hint = json.load(f)
        except Exception:  # noqa: BLE001 — a torn hint is no hint
            hint = None
        try:
            os.remove(path)
        except OSError:
            pass
        return hint if isinstance(hint, dict) else None

    def _read_scale_hint(self) -> Optional[Dict[str, Any]]:
        """Consume the serving scale controller's replica decision
        (serving/traffic.SCALE_HINT_FILENAME — the fleet's queue-depth/
        p99 trends voting the world out or in).  Read-and-remove, like
        the balance hint: one decision sizes ONE relaunch."""
        path = os.path.join(self.crash_dir, "serve.scale.hint.json")
        if not os.path.exists(path):
            return None
        try:
            import json

            with open(path) as f:
                hint = json.load(f)
        except Exception:  # noqa: BLE001 — a torn hint is no hint
            hint = None
        try:
            os.remove(path)
        except OSError:
            pass
        if not isinstance(hint, dict) \
                or hint.get("action") not in ("out", "in"):
            return None
        return hint

    def _read_drain_reports(self) -> List[Dict[str, Any]]:
        """Consume graceful-drain reports (serving/traffic.py
        ``serve.drain.done.rank<r>.json`` — a released replica's proof
        that it flushed or loudly failed every accepted future before
        letting go).  Read-and-remove; the shrink path logs whether the
        shrunk replica drained clean or dropped futures on the floor."""
        reports: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(self.crash_dir))
        except OSError:
            return reports
        import json

        for name in names:
            if not (name.startswith("serve.drain.done.rank")
                    and name.endswith(".json")):
                continue
            path = os.path.join(self.crash_dir, name)
            try:
                with open(path) as f:
                    rep = json.load(f)
            except Exception:  # noqa: BLE001 — torn report
                rep = None
            try:
                os.remove(path)
            except OSError:
                pass
            if isinstance(rep, dict):
                reports.append(rep)
        return reports

    # -- the supervision loop ------------------------------------------------

    def run(self) -> Dict[str, Any]:
        """Supervise until a world completes or the budget runs out.

        Returns the machine-readable summary (``ok``, ``attempts``,
        ``relaunches``, ``shrinks``, ``final_world``, ``outputs`` — the
        final attempt's per-rank stdout).  Telemetry:
        ``oap_recovery_relaunches_total``,
        ``oap_recovery_restart_budget_spent_total``,
        ``oap_recovery_world_shrinks_total``, and the detect→respawn
        wall in the ``oap_recovery_time_to_recovery_seconds``
        histogram."""
        world = self.world
        attempt = 0
        outs: List[str] = []
        while True:
            log.info("supervisor: attempt %d, world %d", attempt, world)
            procs = self._launch(attempt, world)
            clean = self._watch(procs)
            t_detect = time.monotonic()
            outs = self._reap(procs)
            att = self._classify(attempt, world, procs, outs)
            self.attempts.append(att)
            hint = self._read_balance_hint()
            if hint is not None:
                self.balance_hints.append(hint)
                log.warning(
                    "supervisor: balance hint — rank %s was a persistent "
                    "straggler (skew %s over %s passes)",
                    hint.get("rank"), hint.get("skew_ratio"),
                    hint.get("streak_passes"),
                )
            scale_hint = self._read_scale_hint()
            if scale_hint is not None:
                self.scale_hints.append(scale_hint)
                log.warning(
                    "supervisor: serving scale hint — %s (%s)",
                    scale_hint.get("action"), scale_hint.get("reason"),
                )
            for rep in self._read_drain_reports():
                self.drain_reports.append(rep)
                log.warning(
                    "supervisor: replica rank %s drained before release "
                    "— answered=%s failed=%s",
                    rep.get("rank"), rep.get("answered"),
                    rep.get("failed"),
                )
            if att.ok and clean:
                return self._summary(True, world, outs)
            culprit = att.culprit()
            log.warning(
                "supervisor: attempt %d failed (world %d, culprit rank "
                "%s): %s", attempt, world, culprit,
                [e.as_dict() for e in att.exits if not e.ok],
            )
            if self.relaunches >= self.restart_budget:
                summary = self._summary(False, world, outs)
                log.error(
                    "supervisor: restart budget (%d) exhausted",
                    self.restart_budget,
                )
                return summary
            if culprit == self._blame_rank:
                self._blame_count += 1
            else:
                self._blame_rank, self._blame_count = culprit, 1
            # a balance hint naming the culprit counts as one more vote
            # toward the shrink threshold: the controller already proved
            # the rank was dragging the world BEFORE it died, so the
            # supervisor stops giving it relaunch benefit-of-the-doubt
            if (hint is not None and culprit is not None
                    and int(hint.get("rank", -1)) == culprit):
                self._blame_count += 1
                log.warning(
                    "supervisor: culprit rank %d matches the balance "
                    "hint — blame count now %d/%d",
                    culprit, self._blame_count, self.shrink_after,
                )
            if (self._blame_count >= self.shrink_after and world > 1
                    and culprit is not None):
                world -= 1
                self.shrinks += 1
                self._blame_rank, self._blame_count = None, 0
                _tm.counter(
                    "oap_recovery_world_shrinks_total",
                    help="Supervisor world-shrink decisions (a repeatedly "
                         "bad rank excluded)",
                ).inc()
                log.warning(
                    "supervisor: rank %s failed %d consecutive times — "
                    "shrinking world to %d (resume=auto reshards state)",
                    culprit, self.shrink_after, world,
                )
            if scale_hint is not None:
                want = world + (1 if scale_hint["action"] == "out" else -1)
                # replica count is the controlled variable, but the
                # supervisor bounds it: never above the initially
                # provisioned world (host resources were sized for it),
                # never below 1
                sized = max(1, min(want, self.world))
                if sized != world:
                    log.warning(
                        "supervisor: sizing next world %d -> %d per "
                        "serving scale hint (%s)",
                        world, sized, scale_hint["action"],
                    )
                    world = sized
            self.relaunches += 1
            _tm.counter(
                "oap_recovery_relaunches_total",
                help="Supervisor world relaunches",
            ).inc()
            _tm.counter(
                "oap_recovery_restart_budget_spent_total",
                help="Restart-budget units consumed",
            ).inc()
            backoff = self.restart_backoff * (2.0 ** (self.relaunches - 1))
            if backoff > 0:
                time.sleep(backoff)
            attempt += 1
            _tm.histogram(
                "oap_recovery_time_to_recovery_seconds",
                help="Wall from failure detection to the relaunched world "
                     "spawning (factor-4 log buckets)",
            ).observe(time.monotonic() - t_detect)

    def _summary(self, ok: bool, world: int,
                 outs: List[str]) -> Dict[str, Any]:
        return {
            "ok": ok,
            "final_world": world,
            "relaunches": self.relaunches,
            "restart_budget": self.restart_budget,
            "shrinks": self.shrinks,
            "balance_hints": list(self.balance_hints),
            "scale_hints": list(self.scale_hints),
            "drain_reports": list(self.drain_reports),
            "attempts": [a.as_dict() for a in self.attempts],
            "outputs": list(outs),
        }
