"""Fault classification, retry/backoff, and the degradation ladder.

The reference earns its "drop-in" claim by never surfacing accelerator
failures to user code: a failed platform gate silently falls back to
vanilla MLlib (Utils.scala:98-115).  ``utils/dispatch.should_accelerate``
replicates the *static* half of that contract — one decision, up front.
This module adds the dynamic half: any fault AFTER that point (a
transient chunk-read error, a device OOM mid-fit, a coordinator that is
not up yet) is classified, retried with backoff, degraded gracefully,
counted, and — via utils/faults.py — injectable in tests.

The ladder, per accelerated fit (single-process; see below)::

    accelerated fit
      │ transient fault (I/O error, Unavailable, connection refused)
      ├──> retry the attempt under RetryPolicy (exponential backoff +
      │    deterministic jitter, bounded by retries AND deadline)
      │ HOST-RAM OOM (a bare MemoryError with no device marker)
      ├──> the SPILL rung: stage the source to a disk-backed spill
      │    (data/io.SpillWriter, atomic) and re-enter the STREAMED route
      │    reading from disk — host RAM sheds O(table), the pass
      │    structure (and therefore the math) is unchanged.  A failed
      │    spill write warns and falls through to the rungs below.
      │ device OOM (XLA RESOURCE_EXHAUSTED)
      ├──> GEOMETRIC halved-chunk retries: chunk width halves per rung
      │    (streamed sources re-chunk at chunk_rows/2^level down to the
      │    OOM_CHUNK_FLOOR_ROWS floor; in-memory K-Means doubles its
      │    Lloyd chunk count per rung; streamed ALS halves its upload
      │    blocks), bounded by retry_limit AND the caller's halving
      │    headroom; the divisor sequence lands in
      │    ``ResilienceStats.halvings``
      │ non-finite iterate while a REDUCED compute-precision policy
      │ (bf16/tf32, utils/precision.py) was active
      ├──> the PRECISION rung: ONE retry with every policy pinned to f32
      │    (precision.force_f32) — a rounding-induced overflow/NaN must
      │    not fail a fit that is healthy at full precision
      │ still failing / retries exhausted / non-finite iterate at f32
      │ under nonfinite_policy="fallback"
      └──> the CPU/NumPy fallback path when Config.fallback is True;
           otherwise ResilienceError carrying the full fault history.

Non-faults (ValueError, TypeError, API misuse) are never retried or
masked — they propagate unchanged from the first attempt.

**Multi-process worlds bypass the ladder entirely** (the static-world
contract, docs/distributed.md): a rank-local retry would desync the
collective schedule and strand peers, so faults there keep the
fail-fast-together semantics of ``_PassGuard`` and recovery stays
restart-level.  With the recovery sideband armed (``Config.crash_dir``
— set by utils/supervisor for every rank it launches) that restart
level is *supervised*: a fatal fault writes a crash record that poisons
the peers out of their collectives, and the supervisor relaunches the
world with ``resume=auto`` restoring the last durable checkpoint; the
fit summary's ``resilience.ladder`` reads ``"supervised"`` instead of
``"bypassed(static-world)"``.

Per-fit :class:`ResilienceStats` (retries, degradations, faults seen,
history) merge into the fit summaries next to the ``progcache`` delta.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import zlib
from typing import Callable, List, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils.faults import FaultInjected

log = logging.getLogger("oap_mllib_tpu")

# fault kinds (classify_fault return values)
TRANSIENT = "transient"
OOM = "oom"  # device memory exhaustion (XLA RESOURCE_EXHAUSTED shapes)
OOM_HOST = "oom-host"  # host-RAM exhaustion (bare MemoryError) — spills
NONFINITE = "nonfinite"

# streamed chunk widths never halve below this floor: a sub-64-row chunk
# cannot OOM any real device, so further halving only multiplies pass
# overhead — the rung falls through to the CPU path instead
OOM_CHUNK_FLOOR_ROWS = 64


def halvings_available(chunk_rows: int,
                       floor: int = OOM_CHUNK_FLOOR_ROWS) -> int:
    """How many times ``chunk_rows`` can halve before crossing ``floor``
    — the per-fit bound streamed estimators hand the geometric OOM rung
    (further capped by ``retry_limit`` inside :func:`resilient_fit`)."""
    n = 0
    rows = int(chunk_rows)
    while rows // 2 >= floor:
        rows //= 2
        n += 1
    return max(n, 1)  # every path keeps at least the legacy single rung

# message markers for faults that only identify themselves textually
# (jaxlib's XlaRuntimeError carries gRPC/XLA status names in the string)
_OOM_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "allocation failure",
    "failed to allocate",
)
_TRANSIENT_MARKERS = (
    "unavailable",
    "connection refused",
    "connection reset",
    "deadline_exceeded",
    "deadline exceeded",
    "temporarily unavailable",
    "broken pipe",
    "socket closed",
)


class NonFiniteError(FloatingPointError):
    """NaN/Inf detected in a training iterate (K-Means centroids, ALS
    factors, the PCA Gram accumulator) by a streamed-path guardrail."""


class ResilienceError(RuntimeError):
    """A fit exhausted the degradation ladder with fallback disabled.
    ``history`` is the recorded fault sequence (site/kind/message)."""

    def __init__(self, algo: str, history: List[str]):
        self.history = list(history)
        trail = "; ".join(history) if history else "no faults recorded"
        super().__init__(
            f"{algo}: accelerated fit failed after exhausting the "
            f"degradation ladder and fallback is disabled — fault "
            f"history: {trail}"
        )


def classify_fault(exc: BaseException) -> Optional[str]:
    """Classify an exception into a fault kind, or None for non-faults.

    - Injected faults (utils/faults.py) carry their kind explicitly.
    - :class:`NonFiniteError` -> NONFINITE (guardrail detections).
    - XLA ``RESOURCE_EXHAUSTED``/OOM messages -> OOM (device memory).
    - A bare ``MemoryError`` with no device marker -> OOM_HOST (a failed
      host allocation — np buffers, staging copies): the ladder's SPILL
      rung, not the device halved-chunk rung.
    - ``ConnectionError``/``OSError`` (host I/O, refused sockets) and
      Unavailable/DeadlineExceeded-style messages -> TRANSIENT.
    - Everything else -> None (a programming error or bad input; the
      ladder must re-raise it unchanged, never mask it).
    """
    if isinstance(exc, FaultInjected):
        from oap_mllib_tpu.utils import faults

        return {
            faults.KIND_FAIL: TRANSIENT,
            faults.KIND_OOM: OOM,
            faults.KIND_HOST_OOM: OOM_HOST,
            faults.KIND_NONFINITE: NONFINITE,
        }.get(exc.kind)
    if isinstance(exc, NonFiniteError):
        return NONFINITE
    msg = str(exc).lower()
    if any(m in msg for m in _OOM_MARKERS):
        return OOM
    if isinstance(exc, MemoryError):
        return OOM_HOST
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    if isinstance(exc, OSError):
        return TRANSIENT
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return None


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter and a deadline.

    ``max_retries`` bounds the retry COUNT; ``deadline_s`` bounds the
    retry WALL (a fit that keeps failing slowly must not retry past its
    budget even with retries left).  Jitter is deterministic — a hash of
    (site, attempt) — so retry schedules are reproducible in tests while
    still de-synchronizing many concurrent fits retrying the same
    shared resource.
    """

    max_retries: int = 5
    backoff_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    deadline_s: float = 30.0
    jitter: float = 0.1

    @classmethod
    def from_config(cls) -> "RetryPolicy":
        cfg = get_config()
        return cls(
            max_retries=max(int(cfg.retry_limit), 0),
            backoff_s=max(float(cfg.retry_backoff), 0.0),
            deadline_s=max(float(cfg.retry_deadline), 0.0),
        )

    @classmethod
    def for_serving(cls) -> "RetryPolicy":
        """The traffic plane's durable-future envelope
        (``Config.serve_retry_limit`` / ``serve_retry_backoff``): same
        site-hashed deterministic jitter as the fit ladder, but a tight
        backoff cap — a serving retry must stay inside request-latency
        scales, not fit scales.  The deadline is per-REQUEST (each
        request carries its own), so the policy itself is unbounded."""
        cfg = get_config()
        return cls(
            max_retries=max(int(cfg.serve_retry_limit), 0),
            backoff_s=max(float(cfg.serve_retry_backoff), 0.0),
            max_backoff_s=0.5,
            deadline_s=float("inf"),
        )

    def delay_s(self, attempt: int, site: str = "") -> float:
        """Backoff before retry ``attempt`` (0-based), jittered."""
        base = min(
            self.backoff_s * (self.multiplier ** attempt), self.max_backoff_s
        )
        frac = zlib.crc32(f"{site}:{attempt}".encode()) / 0xFFFFFFFF
        return base * (1.0 + self.jitter * frac)


class ResilienceStats:
    """Per-fit fault accounting, merged into fit summaries next to the
    ``progcache`` delta (see :func:`merge_stats`)."""

    __slots__ = ("retries", "degradations", "faults", "backoff_s", "history",
                 "ladder", "halvings", "spilled")

    def __init__(self) -> None:
        self.retries = 0  # transient retries taken
        self.degradations = 0  # ladder rungs stepped (spill, halved, fallback)
        self.faults = 0  # faults observed (classified exceptions)
        self.backoff_s = 0.0  # total wall slept in backoff
        self.history: List[str] = []  # "<site>[<kind>]: <message>" entries
        # geometric OOM-rung trail: the chunk DIVISOR of each halving
        # rung stepped (2, 4, 8, ...), so a summary shows not just that
        # the fit degraded but how far the chunk width walked down
        self.halvings: List[int] = []
        # the host-OOM spill rung fired and the fit re-entered the
        # streamed route from a disk spill (summary.route carries the
        # spill source details)
        self.spilled = False
        # which protections were live for this fit: "active" (the full
        # single-process ladder) vs "bypassed(static-world)" (multi-
        # process worlds keep fail-fast-together semantics; recovery
        # there is restart-level — utils/checkpoint.py resume).  Stamped
        # by resilient_fit so operators can read a fit summary and know
        # WHY no rung fired, not just that none did.
        self.ladder = "active"

    def record(self, site: str, kind: Optional[str], exc: BaseException) -> None:
        self.faults += 1
        self.history.append(f"{site}[{kind or 'unclassified'}]: {exc}")
        if flightrec.enabled():
            flightrec.record("fault", site, kind or "unclassified")
        _tm.counter(
            "oap_resilience_faults_total",
            {"kind": kind or "unclassified"},
            help="Classified exceptions observed by the resilience layer",
        ).inc()

    def note_retry(self, delay_s: float) -> None:
        """Book one transient retry + its backoff, here AND in the
        process metrics registry."""
        self.retries += 1
        self.backoff_s += delay_s
        if flightrec.enabled():
            flightrec.record("retry", "transient", f"{delay_s:.3f}s")
        _tm.counter(
            "oap_resilience_retries_total",
            help="Transient-fault retries taken",
        ).inc()
        _tm.counter(
            "oap_resilience_backoff_seconds_total",
            help="Wall slept in retry backoff",
        ).inc(delay_s)

    def note_degradation(self) -> None:
        """Book one ladder rung stepped (halved-chunk or CPU fallback)."""
        self.degradations += 1
        if flightrec.enabled():
            flightrec.record("degrade", "ladder")
        _tm.counter(
            "oap_resilience_degradations_total",
            help="Degradation-ladder rungs stepped",
        ).inc()

    def as_dict(self) -> dict:
        return {
            "retries": self.retries,
            "degradations": self.degradations,
            "faults": self.faults,
            "backoff_s": self.backoff_s,
            "history": list(self.history),
            "ladder": self.ladder,
            "halvings": list(self.halvings),
            "spilled": self.spilled,
        }


def merge_stats(summary, stats: ResilienceStats) -> None:
    """Attach a fit's resilience counters to its summary — dict summaries
    (PCA/ALS) get a ``"resilience"`` key, object summaries (KMeansSummary)
    a ``.resilience`` attribute; both sit next to the ``progcache`` delta."""
    if summary is None:
        return
    if isinstance(summary, dict):
        summary["resilience"] = stats.as_dict()
    else:
        summary.resilience = stats.as_dict()


def nonfinite_policy_cfg() -> str:
    """Validated ``Config.nonfinite_policy`` — a typo must raise, not
    silently behave like either valid value (the als_kernel contract)."""
    policy = get_config().nonfinite_policy
    if policy not in ("raise", "fallback"):
        raise ValueError(
            f"nonfinite_policy must be raise|fallback, got {policy!r}"
        )
    return policy


def check_finite(value, what: str) -> None:
    """Iterate-level numerical guardrail: raise :class:`NonFiniteError`
    if ``value`` contains NaN/Inf.  Works on np and jax arrays (one
    device->host bool sync for the latter); the ladder (or the caller's
    configured ``nonfinite_policy``) decides raise-vs-fallback."""
    import numpy as np

    nonfinite_policy_cfg()  # fail fast on a typo'd policy
    if bool(np.all(np.isfinite(np.asarray(value)))):
        return
    raise NonFiniteError(
        f"non-finite values detected in {what} "
        "(nonfinite_policy governs whether this raises or degrades "
        "to the CPU fallback path)"
    )


def _world() -> int:
    import jax

    return jax.process_count()


def run_with_retry(
    fn: Callable[[], object],
    *,
    policy: Optional[RetryPolicy] = None,
    stats: Optional[ResilienceStats] = None,
    site: str = "",
):
    """Run ``fn`` retrying TRANSIENT faults under ``policy``; any other
    exception propagates immediately.  The single-tier helper for edges
    that sit outside a fit ladder (source ingestion, port probes);
    multi-process worlds run ``fn`` once (static-world contract)."""
    policy = policy or RetryPolicy.from_config()
    stats = stats or ResilienceStats()
    if _world() > 1:
        return fn()
    deadline = time.monotonic() + policy.deadline_s
    while True:
        try:
            return fn()
        except Exception as e:
            kind = classify_fault(e)
            stats.record(site, kind, e)
            delay = policy.delay_s(stats.retries, site)
            if (
                kind != TRANSIENT
                or stats.retries >= policy.max_retries
                or time.monotonic() + delay > deadline
            ):
                raise
            stats.note_retry(delay)
            log.warning(
                "%s: transient fault (%s); retry %d/%d in %.2fs",
                site or "retry", e, stats.retries, policy.max_retries, delay,
            )
            time.sleep(delay)


def resilient_fit(
    algo: str,
    attempt: Callable[[int], object],
    fallback: Optional[Callable[[], object]],
    *,
    stats: Optional[ResilienceStats] = None,
    policy: Optional[RetryPolicy] = None,
    spill: Optional[Callable[[], bool]] = None,
    max_halvings: Optional[int] = None,
):
    """Run an accelerated fit under the full degradation ladder.

    ``attempt(degraded)`` runs the accelerated fit; ``degraded`` is the
    halved-chunk rung LEVEL (0 = full chunks, level n = chunk width
    divided by 2^n — estimators map it to their chunk knob with the
    OOM_CHUNK_FLOOR_ROWS floor; paths without a knob run the same
    program again, and a persistent fault then falls through to the next
    rung).  Legacy boolean callbacks keep working — level 0 is falsy.
    ``max_halvings`` bounds the geometric walk (use
    :func:`halvings_available` for chunked sources; None keeps the
    legacy single rung), further capped by ``policy.max_retries``.
    ``spill()`` is the host-OOM rung: stage the fit's source to disk and
    swap the attempt onto the disk-backed streamed route (return True on
    success; False/raise warns and falls through to the rungs below —
    never corrupts, the SpillWriter atomic protocol).  ``fallback()`` is
    the CPU/NumPy path, consulted only when ``Config.fallback`` is True
    (via ``dispatch.allow_fallback``, the same gate the static predicate
    uses).  Multi-process worlds run ``attempt(0)`` once — the ladder is
    a single-process facility (module docstring).

    Fault routing: TRANSIENT retries under ``policy`` (count + deadline
    bounded); an OOM_HOST fault steps the SPILL rung once (then behaves
    like OOM); each device OOM steps one geometric halving rung while
    headroom remains (transient retries still available there); a
    NONFINITE fault raised while the attempt resolved a REDUCED
    compute-precision policy (bf16/tf32 —
    utils/precision.reduced_active) first steps the PRECISION rung: one
    retry with every policy pinned to f32, BEFORE the
    ``nonfinite_policy`` decision, so a rounding-induced NaN degrades to
    full precision instead of failing the fit; NONFINITE at f32 honors
    ``Config.nonfinite_policy`` (``raise`` propagates immediately,
    ``fallback`` escalates straight to the CPU rung); unclassified
    exceptions propagate unchanged.  Exhausted ladders raise
    :class:`ResilienceError` with the recorded history when fallback is
    unavailable.
    """
    from oap_mllib_tpu.utils import precision as _precision

    stats = stats or ResilienceStats()
    if _world() > 1:
        # the static-world contract: no rank-local rung may fire.  But
        # when the recovery sideband is armed (Config.crash_dir — the
        # supervisor sets it for every rank it launches), recovery is
        # SUPERVISED rather than absent: a fatal fault here poisons the
        # peers (they abort their collectives promptly instead of
        # hanging) and the supervisor relaunches the world with
        # resume=auto restoring the last durable checkpoint
        # (utils/recovery.py, utils/supervisor.py).
        if get_config().crash_dir:
            stats.ladder = "supervised"
        else:
            stats.ladder = "bypassed(static-world)"
        try:
            return attempt(0)
        except Exception as e:
            from oap_mllib_tpu.utils import recovery

            recovery.record_fatal(f"{algo}.fit", e)
            raise
    stats.ladder = "active"
    policy = policy or RetryPolicy.from_config()
    deadline = time.monotonic() + policy.deadline_s
    halving_limit = min(
        1 if max_halvings is None else max(int(max_halvings), 0),
        max(policy.max_retries, 1),
    )
    degraded = 0  # halving level: chunk width / 2^degraded
    precision_degraded = False
    spilled = False
    while True:
        try:
            _precision.begin_attempt()
            if precision_degraded:
                with _precision.force_f32():
                    return attempt(degraded)
            return attempt(degraded)
        except Exception as e:
            kind = classify_fault(e)
            if kind is None:
                raise  # not a fault: API misuse/bugs are never masked
            site = f"{algo}.fit" + (".degraded" if degraded else "")
            stats.record(site, kind, e)
            if kind == TRANSIENT and stats.retries < policy.max_retries:
                delay = policy.delay_s(stats.retries, site)
                if time.monotonic() + delay <= deadline:
                    stats.note_retry(delay)
                    log.warning(
                        "%s: transient fault (%s); retry %d/%d in %.2fs",
                        site, e, stats.retries, policy.max_retries, delay,
                    )
                    time.sleep(delay)
                    continue
            if kind == OOM_HOST and spill is not None and not spilled:
                # the spill rung: stage the table to disk and re-enter
                # the streamed route — the ONLY rung that sheds host
                # RAM.  A failed spill warns and falls through (the
                # halving rungs below also shrink host staging buffers).
                spilled = True
                stats.note_degradation()
                ok = False
                try:
                    ok = bool(spill())
                except Exception as spill_err:  # noqa: BLE001 — rung must
                    log.warning(  # fall through, never mask the ladder
                        "%s: spill to disk raised (%s); falling through "
                        "the ladder", site, spill_err,
                    )
                if ok:
                    stats.spilled = True
                    log.warning(
                        "%s: host OOM (%s); spilled the staged table to "
                        "disk and re-entering the streamed route", site, e,
                    )
                    continue
                log.warning(
                    "%s: host OOM (%s) and the spill rung failed; "
                    "continuing down the ladder", site, e,
                )
            if kind in (OOM, OOM_HOST) and degraded < halving_limit:
                degraded += 1
                stats.note_degradation()
                stats.halvings.append(2 ** degraded)
                log.warning(
                    "%s: OOM (%s); retrying at chunk width /%d "
                    "(halving %d/%d)",
                    site, e, 2 ** degraded, degraded, halving_limit,
                )
                continue
            if (
                kind == NONFINITE
                and not precision_degraded
                and _precision.reduced_active()
            ):
                # the precision rung: the fit ran bf16/tf32 — pin every
                # policy to f32 for one retry before the nonfinite_policy
                # decision (fits already at f32 skip straight past this,
                # keeping the exact pre-policy fault semantics)
                precision_degraded = True
                stats.note_degradation()
                log.warning(
                    "%s: non-finite iterate under a reduced compute-"
                    "precision policy (%s); retrying once at f32",
                    site, e,
                )
                continue
            if kind == NONFINITE and nonfinite_policy_cfg() == "raise":
                raise
            # final rung: the CPU/NumPy reference path
            from oap_mllib_tpu.utils.dispatch import allow_fallback

            why = f"{kind} fault: {e}"
            if fallback is not None and allow_fallback(algo, why):
                stats.note_degradation()
                return fallback()
            raise ResilienceError(algo, stats.history) from e
