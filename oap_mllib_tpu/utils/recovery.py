"""Live-world recovery plane: collective deadlines + coordinated abort.

The sanitizer plane (utils/sanitizers.py) catches the *rank-divergent*
collective — every rank is alive, they just disagree.  This module
catches the other multi-rank failure mode: a peer that is **gone** (a
preempted host, a SIGKILLed worker, a hung device).  Without it every
survivor blocks inside ``process_allgather``/the facade dispatch until
the distributed runtime's own timeout kills the world minutes later,
with no diagnosis and nothing machine-readable for a supervisor to act
on.  Two mechanisms close the gap, both off by default:

- **Collective deadlines** (``Config.collective_timeout`` > 0): every
  host-level collective dispatch runs under :func:`guarded_dispatch` —
  the blocking call moves to a daemon thread and the caller waits with a
  deadline.  Expiry raises :class:`CollectiveTimeoutError` on every
  surviving rank, naming the op, axis, elapsed wall, and the
  last-completed dispatch fingerprint (plus the collective sanitizer's
  sequence digest when armed) so the hang converts into a diagnosis.
  Disarmed (the default) the seam is one config check per dispatch.

- **Coordinated abort** (``Config.crash_dir`` non-empty): a rank's
  fatal fault writes a machine-readable *crash record*
  (``crash.rank<r>.json`` — rank, site, fault class, last durable
  checkpoint step, final telemetry snapshot) into the shared sideband
  directory.  Ranks waiting inside a deadline-armed collective poll the
  sideband and raise :class:`PeerAbortError` promptly when a peer's
  record appears — the generalization of the streamed pass's riding
  error flag (ops/stream_ops._PassGuard) to faults that never reach a
  common reduction.  The supervisor (utils/supervisor.py) reads the
  records to classify the exit and decide relaunch/shrink.

This is the detect half of the detect → abort → relaunch →
resharded-resume loop (the elastic-training pattern of PAPERS.md
arXiv:2112.01075); utils/checkpoint.py owns the resume half and
utils/supervisor.py the relaunch half.  The reference framework cannot
express any of it: its oneCCL communicator is static — one lost rank
wedges the world (survey §7.3).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.telemetry.spans import current_span

log = logging.getLogger("oap_mllib_tpu")

# v2 (ISSUE 11): records gained the ``flight_recorder`` field — the
# tail of the per-rank event ring (telemetry/flightrec.py, [] when the
# recorder is off), so every post-mortem shows the last N events on
# every rank, not just a final snapshot.
CRASH_RECORD_VERSION = 2
_CRASH_PREFIX = "crash.rank"

# sideband poll cadence while blocked inside a guarded dispatch: fast
# enough that a poisoned world aborts in well under a second, slow
# enough that the listdir cost is invisible next to any real collective
_POLL_S = 0.05

FAULT_TIMEOUT = "collective_timeout"
FAULT_PEER_ABORT = "peer_abort"


class RecoveryError(RuntimeError):
    """Base class for recovery-plane aborts."""


class CollectiveTimeoutError(RecoveryError):
    """A peer never arrived at a collective within the deadline.

    ``op``/``axis``/``elapsed_s`` carry the dispatch that expired;
    ``last_completed`` is (count, signature) of the newest dispatch this
    rank finished — the point up to which the world was provably in
    step."""

    def __init__(self, msg: str, *, op: str = "", axis: str = "",
                 elapsed_s: float = 0.0, last_completed=None):
        super().__init__(msg)
        self.op = op
        self.axis = axis
        self.elapsed_s = elapsed_s
        self.last_completed = last_completed


class PeerAbortError(RecoveryError):
    """A peer's crash record appeared while this rank was blocked in a
    collective; ``record`` is the peer's parsed crash record."""

    def __init__(self, msg: str, record: Optional[Dict[str, Any]] = None):
        super().__init__(msg)
        self.record = dict(record or {})


def collective_timeout_cfg(cfg=None) -> float:
    """Validated ``Config.collective_timeout`` — negative must raise,
    not silently disarm (the kmeans_kernel/fault_spec contract)."""
    timeout = float((cfg or get_config()).collective_timeout)
    if timeout < 0:
        raise ValueError(
            f"collective_timeout must be >= 0 seconds (0 = disarmed), "
            f"got {timeout}"
        )
    return timeout


def _world() -> int:
    import jax

    return jax.process_count()


def _rank() -> int:
    import jax

    return jax.process_index()


# -- last-completed dispatch fingerprint --------------------------------------
# Updated on every guarded dispatch that finishes, whatever the armed
# state of the sanitizer plane — the timeout diagnosis must be able to
# say "the world was in step through dispatch #N [sig]" even when
# fingerprint cross-checking is off.

_fp_lock = threading.Lock()
_completed = {"count": 0, "last": ""}


def _note_completed(sig: str) -> None:
    with _fp_lock:
        _completed["count"] += 1
        _completed["last"] = sig


def last_completed() -> Dict[str, Any]:
    """(count, signature) of the newest host-level dispatch this rank
    completed under the watchdog."""
    with _fp_lock:
        return dict(_completed)


def _sanitizer_digest() -> str:
    """The collective sanitizer's fit-window fingerprint when armed
    ('' otherwise) — the richer sequence digest rides the diagnosis."""
    try:
        from oap_mllib_tpu.utils import sanitizers

        if sanitizers.enabled("collective"):
            count, digest = sanitizers.fingerprint()
            return f"{count}:{digest}"
    except Exception:  # noqa: BLE001 — diagnosis must never mask the fault
        pass
    return ""


# -- crash records + poison sideband ------------------------------------------


def crash_record_path(crash_dir: str, rank: int) -> str:
    return os.path.join(crash_dir, f"{_CRASH_PREFIX}{rank}.json")


def write_crash_record(site: str, fault_class: str, error: str, *,
                       op: str = "", elapsed_s: float = 0.0) -> Optional[str]:
    """Write this rank's machine-readable crash record into the sideband
    (atomic tmp+rename, so peers and the supervisor never read a torn
    file); no-op returning None when ``Config.crash_dir`` is empty.
    Never raises — the record is the diagnosis channel for a fault
    already in flight, and a second failure here must not mask it."""
    cfg = get_config()
    if not cfg.crash_dir:
        return None
    try:
        from oap_mllib_tpu.data import io as _io
        from oap_mllib_tpu.telemetry import flightrec
        from oap_mllib_tpu.utils import checkpoint as _ckpt

        # the crash itself becomes the ring's final event, so the
        # embedded tail always ends with what killed this rank
        if flightrec.enabled():
            flightrec.record("crash", site, fault_class)
        rank = _rank()
        record = {
            "version": CRASH_RECORD_VERSION,
            "rank": rank,
            "world": _world(),
            "site": site,
            "fault_class": fault_class,
            "error": str(error)[:4000],
            "op": op,
            "elapsed_s": round(float(elapsed_s), 3),
            "last_completed": last_completed(),
            "sanitizer_fingerprint": _sanitizer_digest(),
            "last_checkpoint_step": _ckpt.last_durable_step(),
            "flight_recorder": flightrec.tail(
                flightrec.CRASH_TAIL_EVENTS
            ),
            "telemetry": _tm.snapshot(),
        }
        os.makedirs(cfg.crash_dir, exist_ok=True)
        path = crash_record_path(cfg.crash_dir, rank)
        _io.atomic_write_json(path, record)
        _tm.counter(
            "oap_recovery_aborts_total", {"cause": fault_class},
            help="Coordinated aborts by fault class (crash records written)",
        ).inc()
        sp = current_span()
        if sp is not None:
            sp.node("recovery").attrs.update({
                "fault_class": fault_class, "site": site, "op": op,
            })
        return path
    except Exception as e:  # noqa: BLE001
        log.warning("recovery: failed to write crash record (%s)", e)
        return None


def check_poison(crash_dir: str, my_rank: int) -> Optional[Dict[str, Any]]:
    """The first PEER crash record in the sideband, parsed (an unparsable
    record still counts — a half-dead peer is still dead; it returns
    with only the rank filled in), or None when the world looks
    healthy."""
    try:
        names = os.listdir(crash_dir)
    except OSError:
        return None
    for name in sorted(names):
        if not (name.startswith(_CRASH_PREFIX) and name.endswith(".json")):
            continue
        try:
            rank = int(name[len(_CRASH_PREFIX):-len(".json")])
        except ValueError:
            continue
        if rank == my_rank:
            continue
        try:
            with open(os.path.join(crash_dir, name)) as f:
                return json.load(f)
        except Exception:  # noqa: BLE001 — torn/corrupt: peer is dead anyway
            return {"rank": rank}
    return None


def clear_crash_records(crash_dir: str) -> int:
    """Remove every crash record in the sideband (the supervisor calls
    this between attempts so a stale record cannot poison the relaunched
    world); returns how many were removed."""
    removed = 0
    try:
        for name in os.listdir(crash_dir):
            if name.startswith(_CRASH_PREFIX) and name.endswith(".json"):
                os.unlink(os.path.join(crash_dir, name))
                removed += 1
    except OSError:
        pass
    return removed


def list_crash_records(crash_dir: str) -> list:
    """Paths of every crash record currently in the sideband, sorted by
    filename (i.e. by rank).  The serving plane uses this to NAME the
    culprit when accepted work cannot complete after an eviction — a
    ``ServeError(reason="eviction")`` carries these paths so the
    operator lands on the exact crash record, not a generic timeout."""
    try:
        names = os.listdir(crash_dir)
    except OSError:
        return []
    return [
        os.path.join(crash_dir, name)
        for name in sorted(names)
        if name.startswith(_CRASH_PREFIX) and name.endswith(".json")
    ]


def record_fatal(site: str, exc: BaseException) -> None:
    """Coordinated-abort hook for a fatal fault outside any collective:
    classify it (utils/resilience.classify_fault) and poison the world
    via the sideband.  Called by ``resilient_fit``'s multi-process path
    before the exception propagates; no-op when ``Config.crash_dir`` is
    empty or the world is single-process (the ladder owns recovery
    there)."""
    if not get_config().crash_dir or _world() <= 1:
        return
    if isinstance(exc, RecoveryError):
        return  # the watchdog already wrote this rank's record
    from oap_mllib_tpu.utils.resilience import classify_fault

    kind = classify_fault(exc) or "unclassified"
    write_crash_record(site, kind, repr(exc))


# -- the collective watchdog ---------------------------------------------------


def guarded_dispatch(op: str, axis: str, fn):
    """Run one host-level collective dispatch under the recovery plane.

    Disarmed (``collective_timeout == 0``, the default) or
    single-process, this is ``fn()`` behind one config check — the
    <1%-overhead contract dev/chaos_gate.py asserts.  Armed in a
    multi-process world, ``fn`` runs in a daemon thread while this
    thread waits with a deadline, polling the crash sideband: the
    dispatch completing wins; a peer crash record raises
    :class:`PeerAbortError`; deadline expiry writes this rank's crash
    record and raises :class:`CollectiveTimeoutError` naming
    op/axis/elapsed/last-completed-fingerprint.  The blocked worker
    thread is abandoned (daemon) — after a timeout the process is
    expected to exit and be relaunched by the supervisor."""
    cfg = get_config()
    if cfg.collective_timeout == 0 or _world() <= 1:
        if cfg.collective_timeout:  # validate only when armed at all
            collective_timeout_cfg(cfg)
        return fn()
    timeout = collective_timeout_cfg(cfg)
    crash_dir = cfg.crash_dir
    my_rank = _rank()

    done = threading.Event()
    box: Dict[str, Any] = {"out": None, "exc": None}

    def _run():
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            box["exc"] = e
        finally:
            done.set()

    worker = threading.Thread(
        target=_run, daemon=True, name=f"oap-collective-{op}"
    )
    t0 = time.monotonic()
    worker.start()
    while not done.wait(_POLL_S):
        elapsed = time.monotonic() - t0
        if crash_dir:
            peer = check_poison(crash_dir, my_rank)
            if peer is not None:
                _tm.counter(
                    "oap_recovery_peer_aborts_total",
                    help="Dispatches aborted because a peer's crash "
                         "record appeared in the sideband",
                ).inc()
                write_crash_record(
                    "collective.dispatch", FAULT_PEER_ABORT,
                    f"peer rank {peer.get('rank')} aborted: "
                    f"{peer.get('fault_class', '?')} at "
                    f"{peer.get('site', '?')}",
                    op=op, elapsed_s=elapsed,
                )
                raise PeerAbortError(
                    f"collective '{op}' over axis '{axis}' aborted after "
                    f"{elapsed:.1f}s: rank {peer.get('rank')} poisoned the "
                    f"world ({peer.get('fault_class', 'unknown fault')} at "
                    f"{peer.get('site', '?')}: "
                    f"{peer.get('error', 'no detail')[:500]}); its last "
                    "durable checkpoint step was "
                    f"{peer.get('last_checkpoint_step', -1)}",
                    record=peer,
                )
        if elapsed >= timeout:
            _tm.counter(
                "oap_recovery_timeouts_total", {"op": op},
                help="Collective dispatches that expired the deadline "
                     "(a peer never arrived)",
            ).inc()
            last = last_completed()
            digest = _sanitizer_digest()
            write_crash_record(
                "collective.dispatch", FAULT_TIMEOUT,
                f"{op} over '{axis}' exceeded collective_timeout="
                f"{timeout}s", op=op, elapsed_s=elapsed,
            )
            raise CollectiveTimeoutError(
                f"collective '{op}' over axis '{axis}' did not complete "
                f"within collective_timeout={timeout}s (elapsed "
                f"{elapsed:.1f}s, rank {my_rank} of {_world()}): a peer "
                "likely died or hung.  Last completed dispatch on this "
                f"rank: #{last['count']}"
                + (f" [{last['last']}]" if last["last"] else " (none)")
                + (f"; collective-sanitizer fingerprint {digest}"
                   if digest else "")
                + ".  Recovery: relaunch under utils/supervisor (resume="
                "auto restores the last durable checkpoint — docs/"
                "distributed.md 'Recovery runbook').",
                op=op, axis=axis, elapsed_s=elapsed, last_completed=last,
            )
    if box["exc"] is not None:
        raise box["exc"]
    _note_completed(f"{op}|{axis}")
    return box["out"]
