"""Debug table printers — the Service.java helpers (reference
ml/util/Service.java:377-578 printNumericTable family), off the hot path.

The reference ships Intel-sample pretty-printers used when debugging the
JNI data plane.  The analogs here format (sharded) device tables without
forcing a full-table transfer: only the printed head is fetched.
"""

from __future__ import annotations

import numpy as np


def _fetch_head(arr, n: int) -> np.ndarray:
    """First ``n`` rows of a host or device array; device transfers are
    bounded to the head (a sharded array is gathered via one jitted slice
    so multi-host tables print without materializing everywhere)."""
    try:
        import jax

        if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
            from jax.sharding import NamedSharding, PartitionSpec

            mesh = getattr(arr.sharding, "mesh", None)
            if mesh is None:
                # non-named shardings (GSPMD/positional) carry no mesh to
                # express a replicated gather on — fail with a usable
                # message instead of an AttributeError deep in jax
                raise TypeError(
                    "cannot fetch the head of a non-addressable array "
                    f"with {type(arr.sharding).__name__}; pass a "
                    "NamedSharding array or a host array"
                )
            from oap_mllib_tpu.utils import progcache

            head = progcache.get_or_build(
                "debug.fetch_head",
                (progcache.mesh_fingerprint(mesh), n),
                lambda: jax.jit(
                    lambda a: a[:n],
                    out_shardings=NamedSharding(mesh, PartitionSpec()),
                ),
            )(arr)
            return np.asarray(head)
    except ImportError:
        pass
    return np.asarray(arr[:n])


def format_table(data, title: str = "", max_rows: int = 10,
                 max_cols: int = 20, precision: int = 6) -> str:
    """Format a 2-D table like Service.printNumericTable: a title line with
    shape, then the first rows/cols with aligned fixed-point values."""
    head = _fetch_head(data, max_rows)
    if head.ndim == 1:
        head = head[:, None]
    full_shape = tuple(getattr(data, "shape", head.shape))
    n_rows = full_shape[0] if full_shape else 0
    n_cols = full_shape[1] if len(full_shape) > 1 else 1
    lines = [f"{title or 'table'} ({n_rows} x {n_cols})"]
    shown = head[:, :max_cols]
    for r in shown:
        lines.append("  " + " ".join(f"{v: .{precision}f}" for v in r))
    trailer = []
    if head.shape[0] < n_rows:
        trailer.append(f"{n_rows - head.shape[0]} more rows")
    if head.shape[1] > max_cols:
        trailer.append(f"{head.shape[1] - max_cols} more cols")
    if trailer:
        lines.append(f"  ... ({', '.join(trailer)})")
    return "\n".join(lines)


def print_table(data, title: str = "", max_rows: int = 10,
                max_cols: int = 20, precision: int = 6) -> None:
    print(format_table(data, title, max_rows, max_cols, precision))


def format_csr(table, title: str = "", max_rows: int = 10,
               precision: int = 4) -> str:
    """Format a CSRTable row-wise (Service.printCSRNumericTable analog):
    one line per row with its (col, value) pairs from the CSR offsets.
    ``precision`` controls the value decimals like ``format_table``'s
    (default keeps the historical 4).  Transfers are bounded to the
    printed head: only max_rows+1 offsets and the nnz they span are
    fetched (so device/sharded tables print cheaply)."""
    offsets = _fetch_head(table.row_offsets, min(max_rows, table.n_rows) + 1)
    head_nnz = int(offsets[-1])
    cols = _fetch_head(table.cols, head_nnz)
    vals = _fetch_head(table.values, head_nnz)
    lines = [
        f"{title or 'csr'} ({table.n_rows} x {table.n_cols}, nnz={table.nnz})"
    ]
    for r in range(min(max_rows, table.n_rows)):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        pairs = " ".join(
            f"{int(c)}:{v:.{precision}f}"
            for c, v in zip(cols[lo:hi], vals[lo:hi])
        )
        lines.append(f"  [{r}] {pairs}")
    if table.n_rows > max_rows:
        lines.append(f"  ... ({table.n_rows - max_rows} more rows)")
    return "\n".join(lines)


def print_csr(table, title: str = "", max_rows: int = 10,
              precision: int = 4) -> None:
    print(format_csr(table, title, max_rows, precision))
