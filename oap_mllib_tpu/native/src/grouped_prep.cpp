// ALS grouped-edge layout prep (~ the reference's host-side data prep in
// ALSDALImpl.cpp:184-230, which built per-rank CSR tables before handing
// off to the device kernels).  The NumPy path (ops/als_ops.py
// build_grouped_edges) is argsort-bound — O(nnz log nnz) plus several
// full-size temporaries; this is a stable counting sort by destination,
// O(nnz + n_dst), filling the padded (G, P) blocks in one pass.
//
// Error contract (shared by both entry points): -1 = bad input (P<=0,
// n_dst<=0, or a destination id outside [0, n_dst)); -2 = allocation
// failure (the O(n_dst) counts buffer — callers fall back to the NumPy
// path).  No exception ever crosses the extern "C" boundary.

#include <algorithm>
#include <cstdint>
#include <new>
#include <vector>

namespace {

// counts per destination; returns false on out-of-range ids
bool count_dsts(const int64_t* dst, int64_t nnz, int64_t n_dst,
                std::vector<int64_t>& counts) {
  counts.assign(static_cast<size_t>(n_dst), 0);
  for (int64_t e = 0; e < nnz; ++e) {
    int64_t d = dst[e];
    if (d < 0 || d >= n_dst) return false;
    counts[static_cast<size_t>(d)]++;
  }
  return true;
}

}  // namespace

extern "C" {

// Total padded edge count the grouped layout produces for one side:
// each destination's edge list rounds up to a multiple of P.  Also the
// native fast path for the COO-fallback blowup guard
// (ops/als_ops.py grouped_padded_edges).
int64_t oap_als_grouped_total(const int64_t* dst, int64_t nnz, int64_t n_dst,
                              int64_t P) {
  if (P <= 0 || n_dst <= 0 || nnz < 0) return -1;
  try {
    std::vector<int64_t> counts;
    if (!count_dsts(dst, nnz, n_dst, counts)) return -1;
    int64_t total = 0;
    for (int64_t d = 0; d < n_dst; ++d)
      total += ((counts[static_cast<size_t>(d)] + P - 1) / P) * P;
    return total;
  } catch (const std::bad_alloc&) {
    return -2;
  } catch (...) {
    return -2;
  }
}

// Fill the padded grouped layout.  Outputs are caller-allocated with
// capacity `total` (= oap_als_grouped_total) for src_g/conf_g/valid_g and
// total/P for group_dst, and MUST be pre-zeroed (pad slots keep src=0,
// conf=0, valid=0).  The capacity is validated BEFORE any output write,
// so a stale/mismatched capacity returns -1 without touching the
// buffers.  Edges keep their input order within each destination
// (stable, matching the NumPy path's stable argsort).  Returns total.
int64_t oap_als_group_edges(const int64_t* dst, const int64_t* src,
                            const float* conf, int64_t nnz, int64_t n_dst,
                            int64_t P, int64_t capacity, int32_t* src_g,
                            float* conf_g, float* valid_g,
                            int32_t* group_dst) {
  if (P <= 0 || n_dst <= 0 || nnz < 0) return -1;
  try {
    std::vector<int64_t> counts;
    if (!count_dsts(dst, nnz, n_dst, counts)) return -1;
    // per-destination padded start offsets; validate capacity before
    // writing a single output element
    std::vector<int64_t> start(static_cast<size_t>(n_dst), 0);
    int64_t total = 0;
    for (int64_t d = 0; d < n_dst; ++d) {
      start[static_cast<size_t>(d)] = total;
      total += ((counts[static_cast<size_t>(d)] + P - 1) / P) * P;
    }
    if (total != capacity) return -1;
    int64_t gidx = 0;
    for (int64_t d = 0; d < n_dst; ++d) {
      int64_t padded =
          ((counts[static_cast<size_t>(d)] + P - 1) / P) * P;
      for (int64_t g = 0; g < padded / P; ++g)
        group_dst[gidx++] = static_cast<int32_t>(d);
    }
    // stable scatter: slot = start[d] + (running fill of d)
    std::vector<int64_t>& fill = counts;  // reuse as fill cursors
    std::fill(fill.begin(), fill.end(), 0);
    for (int64_t e = 0; e < nnz; ++e) {
      int64_t d = dst[e];
      int64_t slot =
          start[static_cast<size_t>(d)] + fill[static_cast<size_t>(d)]++;
      src_g[slot] = static_cast<int32_t>(src[e]);
      conf_g[slot] = conf[e];
      valid_g[slot] = 1.0f;
    }
    return total;
  } catch (const std::bad_alloc&) {
    return -2;
  } catch (...) {
    return -2;
  }
}

}  // extern "C"
