// Host-side table store: handle-based registry of dense row-major buffers.
//
// TPU-native analog of the reference's native table layer
// (mllib-dal/src/main/native/OneDAL.cpp): where that code memcpy'd JVM
// double[] batches into oneDAL HomogenNumericTables (cSetDoubleBatch,
// OneDAL.cpp:50-60), appended tables into a RowMergedNumericTable
// (cAddNumericTable, :67-76) and freed native memory explicitly
// (cFreeDataMemory, :83-89), this store owns aligned host buffers that are
// staged row-batch by row-batch and then handed to the device runtime in
// one zero-copy view (jax/dlpack reads the pointer via ctypes).
//
// Handles are process-global ints; all calls are thread-safe.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>

namespace {

struct DenseTable {
  double* data = nullptr;   // row-major, 64-byte aligned
  int64_t rows = 0;         // valid rows
  int64_t capacity = 0;     // allocated rows
  int64_t cols = 0;
};

std::mutex g_mu;
std::map<int64_t, DenseTable> g_tables;
int64_t g_next_handle = 1;

double* aligned_alloc_rows(int64_t rows, int64_t cols) {
  void* p = nullptr;
  size_t bytes = static_cast<size_t>(rows) * cols * sizeof(double);
  if (bytes == 0) bytes = 64;
  if (posix_memalign(&p, 64, bytes) != 0) return nullptr;
  return static_cast<double*>(p);
}

// Append while g_mu is already held. Returns new row count or -1.
int64_t append_locked(DenseTable& t, const double* batch, int64_t n_rows) {
  if (n_rows < 0) return -1;
  if (t.rows + n_rows > t.capacity) {
    int64_t new_cap = t.capacity ? t.capacity : 64;
    while (new_cap < t.rows + n_rows) new_cap *= 2;
    double* nb = aligned_alloc_rows(new_cap, t.cols);
    if (!nb) return -1;
    memcpy(nb, t.data, static_cast<size_t>(t.rows) * t.cols * sizeof(double));
    free(t.data);
    t.data = nb;
    t.capacity = new_cap;
  }
  memcpy(t.data + t.rows * t.cols, batch,
         static_cast<size_t>(n_rows) * t.cols * sizeof(double));
  t.rows += n_rows;
  return t.rows;
}

}  // namespace

extern "C" {

// Create an empty table with given capacity; returns handle or -1.
int64_t oap_table_create(int64_t capacity_rows, int64_t cols) {
  if (capacity_rows < 0 || cols <= 0) return -1;
  double* buf = aligned_alloc_rows(capacity_rows, cols);
  if (!buf) return -1;
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t h = g_next_handle++;
  g_tables[h] = DenseTable{buf, 0, capacity_rows, cols};
  return h;
}

// Append a batch of rows (row-major doubles). Grows if needed.
// Returns new row count or -1. (~ cSetDoubleBatch, OneDAL.cpp:50-60)
int64_t oap_table_append(int64_t handle, const double* batch, int64_t n_rows) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  if (it == g_tables.end()) return -1;
  return append_locked(it->second, batch, n_rows);
}

// Merge src into dst (row concat); frees src. Atomic under the registry
// lock, so concurrent free/copy_out on either handle cannot interleave.
// (~ cAddNumericTable + merge)
int64_t oap_table_merge(int64_t dst, int64_t src) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (dst == src) return -1;
  auto it = g_tables.find(src);
  auto jt = g_tables.find(dst);
  if (it == g_tables.end() || jt == g_tables.end()) return -1;
  if (jt->second.cols != it->second.cols) return -1;
  int64_t r = append_locked(jt->second, it->second.data, it->second.rows);
  if (r < 0) return -1;
  free(it->second.data);
  g_tables.erase(it);
  return r;
}

int64_t oap_table_rows(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  return it == g_tables.end() ? -1 : it->second.rows;
}

int64_t oap_table_cols(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  return it == g_tables.end() ? -1 : it->second.cols;
}

// Raw data pointer for zero-copy numpy views (caller must keep table alive).
double* oap_table_data(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  return it == g_tables.end() ? nullptr : it->second.data;
}

// Copy out valid rows into caller buffer; returns rows copied or -1.
int64_t oap_table_copy_out(int64_t handle, double* out, int64_t max_rows) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  if (it == g_tables.end()) return -1;
  DenseTable& t = it->second;
  int64_t n = t.rows < max_rows ? t.rows : max_rows;
  memcpy(out, t.data, static_cast<size_t>(n) * t.cols * sizeof(double));
  return n;
}

// Free table memory. (~ cFreeDataMemory, OneDAL.cpp:83-89)
int64_t oap_table_free(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_tables.find(handle);
  if (it == g_tables.end()) return -1;
  free(it->second.data);
  g_tables.erase(it);
  return 0;
}

// Number of live tables (leak checking in tests).
int64_t oap_table_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return static_cast<int64_t>(g_tables.size());
}

}  // extern "C"
