// Host-side ratings shuffle prep: block bucketing, sort, distinct counts.
//
// TPU-native analog of the reference's native ALS shuffle
// (mllib-dal/src/main/native/ALSShuffle.cpp): there, each rank buckets its
// packed Rating{i64 user, i64 item, f32 rating} records by user block
// (getPartiton, :30-35), exchanges them via oneCCL alltoall/alltoallv
// (:92-109), sorts by (user, item) (:111) and counts distinct users for
// the CSR row count (:50-60).
//
// On TPU the exchange itself is an XLA all_to_all of padded fixed-shape
// tensors compiled into the program (parallel/shuffle.py); what stays on
// the host is the O(nnz log nnz) bucket/sort/count prep, which this file
// does in C++ for throughput.  Records are struct-of-arrays (three parallel
// arrays) rather than the reference's packed 20-byte struct — SoA is what
// both numpy and the device runtime want.

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// Assign each record to a block: min(key / keys_per_block, n_blocks-1).
// (~ getPartiton, ALSShuffle.cpp:30-35)
void oap_shuffle_block_ids(const int64_t* keys, int64_t n, int64_t keys_per_block,
                           int64_t n_blocks, int32_t* out_block_ids) {
  if (keys_per_block <= 0 || n_blocks <= 0) {  // avoid SIGFPE; caller validates too
    for (int64_t i = 0; i < n; ++i) out_block_ids[i] = 0;
    return;
  }
  for (int64_t i = 0; i < n; ++i) {
    int64_t b = keys[i] / keys_per_block;
    out_block_ids[i] = static_cast<int32_t>(b < n_blocks - 1 ? b : n_blocks - 1);
  }
}

// Counts per block (for the alltoall size exchange).
void oap_shuffle_block_counts(const int32_t* block_ids, int64_t n,
                              int64_t n_blocks, int64_t* out_counts) {
  std::fill(out_counts, out_counts + n_blocks, 0);
  for (int64_t i = 0; i < n; ++i) ++out_counts[block_ids[i]];
}

// Sort records by (block, user, item): writes a permutation into out_perm
// such that records[out_perm] is block-grouped and (user, item)-sorted
// within each block. (~ the sort at ALSShuffle.cpp:111)
void oap_shuffle_sort_perm(const int32_t* block_ids, const int64_t* users,
                           const int64_t* items, int64_t n, int64_t* out_perm) {
  std::iota(out_perm, out_perm + n, 0);
  std::stable_sort(out_perm, out_perm + n, [&](int64_t a, int64_t b) {
    if (block_ids[a] != block_ids[b]) return block_ids[a] < block_ids[b];
    if (users[a] != users[b]) return users[a] < users[b];
    return items[a] < items[b];
  });
}

// Distinct consecutive keys in a sorted array — the CSR row count.
// (~ distinct_count, ALSShuffle.cpp:50-60)
int64_t oap_distinct_count(const int64_t* sorted_keys, int64_t n) {
  if (n == 0) return 0;
  int64_t count = 1;
  for (int64_t i = 1; i < n; ++i) {
    if (sorted_keys[i] != sorted_keys[i - 1]) ++count;
  }
  return count;
}

}  // extern "C"
