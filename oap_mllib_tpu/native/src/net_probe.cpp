// Multi-host bootstrap helpers: local IP discovery + free-port probing.
//
// TPU-native analog of the reference's oneCCL KVS rendezvous plumbing
// (mllib-dal/src/main/native/OneCCL.cpp): fill_local_host_ip enumerates
// non-loopback interfaces via getifaddrs (:141-200), and the free-port
// scanner binds successive ports starting at 3000 (:207-247).  Here the
// discovered ip:port seeds jax.distributed.initialize (the KVS analog,
// survey §2.6) instead of a oneCCL KVS.

#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <ifaddrs.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" {

// First non-loopback IPv4 address, written as dotted quad into out
// (at least 16 bytes). Returns 0 on success, -1 if none found.
// (~ fill_local_host_ip, OneCCL.cpp:141-200 — which likewise excludes "lo")
int oap_local_ip(char* out, int out_len) {
  if (!out || out_len < INET_ADDRSTRLEN) return -1;
  struct ifaddrs* ifaddr = nullptr;
  if (getifaddrs(&ifaddr) != 0) return -1;
  int rc = -1;
  for (struct ifaddrs* ifa = ifaddr; ifa; ifa = ifa->ifa_next) {
    if (!ifa->ifa_addr || ifa->ifa_addr->sa_family != AF_INET) continue;
    if (strcmp(ifa->ifa_name, "lo") == 0) continue;
    auto* sin = reinterpret_cast<struct sockaddr_in*>(ifa->ifa_addr);
    if (inet_ntop(AF_INET, &sin->sin_addr, out, out_len)) {
      rc = 0;
      break;
    }
  }
  freeifaddrs(ifaddr);
  return rc;
}

// Scan for a bindable TCP port on `ip` starting at `start_port`
// (reference starts at 3000, OneCCL.cpp:213). Returns the port or -1.
int oap_free_port(const char* ip, int start_port, int max_tries) {
  if (start_port <= 0 || start_port > 65535) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  if (!ip || !*ip) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    return -1;
  }
  for (int port = start_port;
       port <= 65535 && port < start_port + max_tries; ++port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    addr.sin_port = htons(static_cast<uint16_t>(port));
    int rc = bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr));
    close(fd);
    if (rc == 0) return port;
  }
  return -1;
}

}  // extern "C"
