// Fast file parsers: libsvm, dense CSV, and "user::item::rating" files.
//
// Native data-loader layer: the reference reads example data through Spark
// (libsvm via spark.read.format, CSV, MovieLens-style ratings parsed in
// examples/als/.../ALSExample.scala); its Java-side debug readers live in
// Service.java.  Here parsing is C++ for throughput and the result lands
// in the table store (table_store.cpp) for zero-copy numpy views.
//
// All parsers return a table handle (dense row-major doubles) or -1.

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int64_t oap_table_create(int64_t capacity_rows, int64_t cols);
int64_t oap_table_append(int64_t handle, const double* batch, int64_t n_rows);
int64_t oap_table_free(int64_t handle);
}

namespace {

// Read a whole file into a string; returns false on error.
bool slurp(const char* path, std::string* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  if (sz < 0) {
    fclose(f);
    return false;
  }
  fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(sz));
  size_t rd = sz ? fread(&(*out)[0], 1, static_cast<size_t>(sz), f) : 0;
  fclose(f);
  return rd == static_cast<size_t>(sz);
}

}  // namespace

extern "C" {

// Parse libsvm ("label idx:val ..." with 1-based indices) into a dense
// table of n_features columns (0 => auto-detect max index).
// Labels are returned in a separate 1-column table via *labels_handle.
int64_t oap_parse_libsvm(const char* path, int64_t n_features,
                         int64_t* labels_handle) {
  std::string buf;
  if (!slurp(path, &buf)) return -1;

  struct Row {
    double label;
    std::vector<std::pair<int64_t, double>> feats;
  };
  std::vector<Row> rows;
  int64_t max_idx = 0;

  const char* p = buf.c_str();
  const char* end = p + buf.size();
  while (p < end) {
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '#') {  // comment line
      while (p < end && *p != '\n') ++p;
      continue;
    }
    Row row;
    char* next = nullptr;
    row.label = strtod(p, &next);
    if (next == p) {  // blank/garbage line
      while (p < end && *p != '\n') ++p;
      continue;
    }
    p = next;
    while (p < end && *p != '\n') {
      while (p < end && (*p == ' ' || *p == '\t')) ++p;
      if (p >= end || *p == '\n' || *p == '\r' || *p == '#') break;
      int64_t idx = strtoll(p, &next, 10);
      if (next == p || *next != ':') return -1;  // malformed token
      p = next + 1;
      double val = strtod(p, &next);
      if (next == p) return -1;
      p = next;
      row.feats.emplace_back(idx, val);
      if (idx > max_idx) max_idx = idx;
    }
    rows.push_back(std::move(row));
  }

  int64_t d = n_features > 0 ? n_features : max_idx;
  if (d <= 0) return -1;
  // explicit n_features with an out-of-range index is an error, not a
  // silent truncation (keeps native and Python paths equivalent)
  if (n_features > 0 && max_idx > n_features) return -1;
  int64_t h = oap_table_create(static_cast<int64_t>(rows.size()), d);
  int64_t lh = oap_table_create(static_cast<int64_t>(rows.size()), 1);
  if (h < 0 || lh < 0) {
    if (h >= 0) oap_table_free(h);
    if (lh >= 0) oap_table_free(lh);
    return -1;
  }
  std::vector<double> dense(static_cast<size_t>(d));
  for (const Row& row : rows) {
    std::fill(dense.begin(), dense.end(), 0.0);
    for (auto& kv : row.feats) {
      if (kv.first >= 1 && kv.first <= d) dense[kv.first - 1] = kv.second;
    }
    oap_table_append(h, dense.data(), 1);
    oap_table_append(lh, &row.label, 1);
  }
  if (labels_handle) *labels_handle = lh;
  else oap_table_free(lh);
  return h;
}

// Parse dense numeric CSV (no header). Returns table handle or -1.
int64_t oap_parse_csv(const char* path, char delimiter) {
  std::string buf;
  if (!slurp(path, &buf)) return -1;
  const char* p = buf.c_str();
  const char* end = p + buf.size();

  int64_t h = -1, cols = 0;
  std::vector<double> row;
  while (p < end) {
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    if (*p == '#') {  // comment line (np.loadtxt-compatible)
      while (p < end && *p != '\n') ++p;
      continue;
    }
    row.clear();
    while (p < end && *p != '\n' && *p != '\r') {
      char* next = nullptr;
      double v = strtod(p, &next);
      if (next == p) {  // non-numeric cell
        if (h >= 0) oap_table_free(h);
        return -1;
      }
      row.push_back(v);
      p = next;
      while (p < end && (*p == ' ' || *p == '\t')) ++p;
      // strict: after a value only the delimiter or end-of-line may follow
      // (matches the np.loadtxt fallback, which rejects stray separators)
      if (p < end && *p == delimiter) {
        ++p;
      } else if (p < end && *p != '\n' && *p != '\r') {
        if (h >= 0) oap_table_free(h);
        return -1;
      }
    }
    if (row.empty()) continue;
    if (h < 0) {
      cols = static_cast<int64_t>(row.size());
      h = oap_table_create(64, cols);
      if (h < 0) return -1;
    } else if (static_cast<int64_t>(row.size()) != cols) {
      oap_table_free(h);
      return -1;  // ragged rows
    }
    oap_table_append(h, row.data(), 1);
  }
  return h;
}

// Parse "user<sep>item<sep>rating" lines (sep = "::" or any single char
// string). Returns a 3-column table (user, item, rating) or -1.
int64_t oap_parse_ratings(const char* path, const char* sep) {
  std::string buf;
  if (!slurp(path, &buf)) return -1;
  size_t seplen = strlen(sep);
  if (seplen == 0) return -1;
  const char* p = buf.c_str();
  const char* end = p + buf.size();
  int64_t h = oap_table_create(64, 3);
  if (h < 0) return -1;

  while (p < end) {
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    char* next = nullptr;
    double vals[3];
    bool ok = true;
    for (int k = 0; k < 3; ++k) {
      if (k < 2) {
        // ids are strict integers (the Python path uses int()); strtod
        // would silently truncate "1.5" -> 1
        int64_t id = strtoll(p, &next, 10);
        if (next == p) {
          ok = false;
          break;
        }
        vals[k] = static_cast<double>(id);
      } else {
        vals[k] = strtod(p, &next);
        if (next == p) {
          ok = false;
          break;
        }
      }
      p = next;
      if (k < 2) {
        if (p + seplen <= end && strncmp(p, sep, seplen) == 0) {
          p += seplen;
        } else {
          ok = false;
          break;
        }
      }
    }
    // nothing but whitespace may follow the rating on the line
    while (ok && p < end && (*p == ' ' || *p == '\t')) ++p;
    if (ok && p < end && *p != '\n' && *p != '\r') ok = false;
    if (!ok) {
      oap_table_free(h);
      return -1;
    }
    oap_table_append(h, vals, 1);
    while (p < end && *p != '\n') ++p;
  }
  return h;
}

}  // extern "C"
