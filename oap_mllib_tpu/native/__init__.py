"""Native runtime bindings (ctypes).

The C++ layer (src/) replaces the reference's native components that are
not device compute: the table store (~ OneDAL.cpp), file parsers (~ the
Spark readers / Service.java helpers), bootstrap network probing
(~ OneCCL.cpp's interface/port scanning), and the ALS shuffle prep
(~ ALSShuffle.cpp).  Loading mirrors the reference's LibLoader
(LibLoader.java: extract + System.load at first use): the .so is built
on demand with `make` the first time it's needed and cached under
native/build/.  Every entry point has a pure-NumPy fallback, so the
framework works without a toolchain (the capability-fallback contract).

Use ``available()`` to check, or call the wrappers — they fall back
silently.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("oap_mllib_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "build", "liboapmllibtpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(force: bool = False) -> bool:
    """Run make; ``force`` rebuilds unconditionally (-B) for the stale-.so
    retry.  The Makefile links to a temp file and renames it over the
    target, so concurrent ranks sharing this checkout always dlopen a
    complete .so (old or new), never a missing or half-written one."""
    try:
        cmd = ["make", "-C", _HERE, "-j4"]
        if force:
            cmd.insert(1, "-B")
        # oaplint: disable=blocking-while-locked -- one-shot dlopen init: the lock IS the once guard
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except (subprocess.SubprocessError, OSError) as e:
        log.info("native build failed (using NumPy fallbacks): %s", e)
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare every entry point's signature.  Raises AttributeError when
    the .so predates a symbol — the caller rebuilds and retries once
    (stale build caches must degrade to the NumPy fallbacks, never crash
    the whole native layer)."""
    i64, i32, f64p = ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_double)
    lib.oap_table_create.restype = i64
    lib.oap_table_create.argtypes = [i64, i64]
    lib.oap_table_append.restype = i64
    lib.oap_table_append.argtypes = [i64, f64p, i64]
    lib.oap_table_merge.restype = i64
    lib.oap_table_merge.argtypes = [i64, i64]
    lib.oap_table_rows.restype = i64
    lib.oap_table_rows.argtypes = [i64]
    lib.oap_table_cols.restype = i64
    lib.oap_table_cols.argtypes = [i64]
    lib.oap_table_copy_out.restype = i64
    lib.oap_table_copy_out.argtypes = [i64, f64p, i64]
    lib.oap_table_data.restype = f64p
    lib.oap_table_data.argtypes = [i64]
    lib.oap_table_free.restype = i64
    lib.oap_table_free.argtypes = [i64]
    lib.oap_table_count.restype = i64
    lib.oap_table_count.argtypes = []
    lib.oap_parse_libsvm.restype = i64
    lib.oap_parse_libsvm.argtypes = [ctypes.c_char_p, i64, ctypes.POINTER(i64)]
    lib.oap_parse_csv.restype = i64
    lib.oap_parse_csv.argtypes = [ctypes.c_char_p, ctypes.c_char]
    lib.oap_parse_ratings.restype = i64
    lib.oap_parse_ratings.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.oap_local_ip.restype = ctypes.c_int
    lib.oap_local_ip.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.oap_free_port.restype = ctypes.c_int
    lib.oap_free_port.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.oap_shuffle_block_ids.restype = None
    lib.oap_shuffle_block_ids.argtypes = [
        ctypes.POINTER(i64), i64, i64, i64, ctypes.POINTER(i32)]
    lib.oap_shuffle_block_counts.restype = None
    lib.oap_shuffle_block_counts.argtypes = [
        ctypes.POINTER(i32), i64, i64, ctypes.POINTER(i64)]
    lib.oap_shuffle_sort_perm.restype = None
    lib.oap_shuffle_sort_perm.argtypes = [
        ctypes.POINTER(i32), ctypes.POINTER(i64), ctypes.POINTER(i64),
        i64, ctypes.POINTER(i64)]
    lib.oap_distinct_count.restype = i64
    lib.oap_distinct_count.argtypes = [ctypes.POINTER(i64), i64]
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.oap_als_grouped_total.restype = i64
    lib.oap_als_grouped_total.argtypes = [ctypes.POINTER(i64), i64, i64, i64]
    lib.oap_als_group_edges.restype = i64
    lib.oap_als_group_edges.argtypes = [
        ctypes.POINTER(i64), ctypes.POINTER(i64), f32p, i64, i64, i64,
        i64, ctypes.POINTER(i32), f32p, f32p, ctypes.POINTER(i32)]
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # oaplint: disable=blocking-while-locked -- one-shot dlopen init: the lock IS the once guard
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        load_path = _SO_PATH
        for attempt in (0, 1):
            try:
                lib = ctypes.CDLL(load_path)
            except OSError as e:
                log.info("native load failed (using NumPy fallbacks): %s", e)
                return None
            finally:
                if load_path != _SO_PATH:
                    # the dlopen mapping outlives the unlink (Linux); never
                    # leave the retry's temp copy behind
                    try:
                        os.remove(load_path)
                    except OSError:
                        pass
            try:
                _lib = _bind(lib)
                return _lib
            except AttributeError as e:
                # stale .so from before a symbol existed: force-rebuild
                # (make -B; never remove-then-rebuild — peers sharing this
                # checkout must not see a missing .so) and retry through a
                # unique temp copy — dlopen caches the stale handle for
                # the original path within this process
                if attempt == 0:
                    # oaplint: disable=blocking-while-locked -- stale-.so rebuild in one-shot init
                    if _build(force=True):
                        import shutil
                        import tempfile

                        fd, load_path = tempfile.mkstemp(suffix=".so")
                        os.close(fd)
                        shutil.copy(_SO_PATH, load_path)
                        continue
                log.info(
                    "native library is stale and rebuild failed "
                    "(using NumPy fallbacks): %s", e,
                )
                return None
        return None


def available() -> bool:
    return _load() is not None


def table_view(handle: int) -> np.ndarray:
    """Zero-copy numpy view of a live native table (no copy; the caller
    must keep the table alive and not free it while the view exists).
    This is the handoff point to the device runtime: jnp.asarray /
    jax.device_put consume the view directly."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rows = lib.oap_table_rows(handle)
    cols = lib.oap_table_cols(handle)
    ptr = lib.oap_table_data(handle)
    if rows < 0 or cols < 0 or not ptr:
        raise RuntimeError("invalid native table handle")
    return np.ctypeslib.as_array(ptr, shape=(rows, cols))


def _table_to_numpy(lib, handle: int) -> np.ndarray:
    rows = lib.oap_table_rows(handle)
    cols = lib.oap_table_cols(handle)
    if rows < 0 or cols < 0:
        raise RuntimeError("invalid native table handle")
    out = np.empty((rows, cols), dtype=np.float64)
    got = lib.oap_table_copy_out(
        handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), rows
    )
    if got != rows:
        raise RuntimeError("native table copy_out failed")
    return out


# -- parsers ----------------------------------------------------------------

def parse_libsvm(path: str, n_features: int = 0) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native libsvm parse; returns (labels, X) or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    lh = ctypes.c_int64(-1)
    h = lib.oap_parse_libsvm(path.encode(), n_features, ctypes.byref(lh))
    if h < 0:
        raise ValueError(f"native libsvm parse failed: {path}")
    try:
        x = _table_to_numpy(lib, h)
        labels = _table_to_numpy(lib, lh.value)[:, 0]
    finally:
        lib.oap_table_free(h)
        if lh.value >= 0:
            lib.oap_table_free(lh.value)
    return labels, x


def parse_csv(path: str, delimiter: str = ",") -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    h = lib.oap_parse_csv(path.encode(), delimiter.encode()[:1])
    if h < 0:
        raise ValueError(f"native csv parse failed: {path}")
    try:
        return _table_to_numpy(lib, h)
    finally:
        lib.oap_table_free(h)


def parse_ratings(
    path: str, sep: str = "::"
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    lib = _load()
    if lib is None:
        return None
    h = lib.oap_parse_ratings(path.encode(), sep.encode())
    if h < 0:
        raise ValueError(f"native ratings parse failed: {path}")
    try:
        t = _table_to_numpy(lib, h)
    finally:
        lib.oap_table_free(h)
    return (
        t[:, 0].astype(np.int64),
        t[:, 1].astype(np.int64),
        t[:, 2].astype(np.float32),
    )


# -- bootstrap probing ------------------------------------------------------

def local_ip() -> Optional[str]:
    """First non-loopback IPv4 (~ Utils.sparkFirstExecutorIP analog's
    native side). None if native lib unavailable or no interface."""
    lib = _load()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(64)
    if lib.oap_local_ip(buf, 64) != 0:
        return None
    return buf.value.decode()


def free_port(ip: str = "", start: int = 3000, max_tries: int = 1000) -> Optional[int]:
    """Scan for a bindable TCP port (~ OneCCL.cpp:207-247)."""
    lib = _load()
    if lib is None:
        return None
    port = lib.oap_free_port(ip.encode(), start, max_tries)
    return port if port > 0 else None


# -- shuffle prep -----------------------------------------------------------

def shuffle_prep(
    users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
    keys_per_block: int, n_blocks: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Bucket + sort ratings by (user block, user, item).

    Returns (users, items, ratings, block_counts, perm) with records
    reordered block-grouped, per-block counts for the alltoall size
    exchange, and the permutation applied.  Falls back to NumPy.
    """
    if keys_per_block <= 0:
        raise ValueError(f"keys_per_block must be > 0, got {keys_per_block}")
    if n_blocks <= 0:
        raise ValueError(f"n_blocks must be > 0, got {n_blocks}")
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    ratings = np.asarray(ratings)
    n = len(users)
    lib = _load()
    if lib is None:
        block = np.minimum(users // keys_per_block, n_blocks - 1).astype(np.int32)
        perm = np.lexsort((items, users, block))
        counts = np.bincount(block, minlength=n_blocks).astype(np.int64)
        return users[perm], items[perm], ratings[perm], counts, perm
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    block = np.empty((n,), dtype=np.int32)
    lib.oap_shuffle_block_ids(
        users.ctypes.data_as(i64p), n, keys_per_block, n_blocks,
        block.ctypes.data_as(i32p))
    counts = np.empty((n_blocks,), dtype=np.int64)
    lib.oap_shuffle_block_counts(
        block.ctypes.data_as(i32p), n, n_blocks, counts.ctypes.data_as(i64p))
    perm = np.empty((n,), dtype=np.int64)
    lib.oap_shuffle_sort_perm(
        block.ctypes.data_as(i32p), users.ctypes.data_as(i64p),
        items.ctypes.data_as(i64p), n, perm.ctypes.data_as(i64p))
    return users[perm], items[perm], ratings[perm], counts, perm


def shuffle_prep_offsets(
    users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
    offsets: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Bucket + sort ratings by (user block, user, item) under EXPLICIT
    block boundaries — the uneven-offset variant of :func:`shuffle_prep`
    for capability-weighted block layouts (parallel/balance
    .plan_block_offsets).  ``offsets`` is the ``(n_blocks + 1,)``
    monotone key-boundary array; block b owns users in
    ``[offsets[b], offsets[b+1])``.  Same return contract as
    shuffle_prep.  Pure NumPy (searchsorted replaces the C library's
    uniform-width division; the uneven layout only engages on
    heterogeneous worlds, where the shuffle is not the bottleneck)."""
    offsets = np.asarray(offsets, np.int64)
    n_blocks = len(offsets) - 1
    if n_blocks < 1:
        raise ValueError("offsets must have >= 2 entries")
    if np.any(np.diff(offsets) < 0):
        raise ValueError("offsets must be monotone non-decreasing")
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    ratings = np.asarray(ratings)
    block = np.clip(
        np.searchsorted(offsets, users, side="right") - 1, 0, n_blocks - 1
    ).astype(np.int32)
    perm = np.lexsort((items, users, block))
    counts = np.bincount(block, minlength=n_blocks).astype(np.int64)
    return users[perm], items[perm], ratings[perm], counts, perm


def distinct_count(sorted_keys: np.ndarray) -> int:
    sorted_keys = np.ascontiguousarray(sorted_keys, dtype=np.int64)
    lib = _load()
    if lib is None:
        if len(sorted_keys) == 0:
            return 0
        return int(1 + np.count_nonzero(np.diff(sorted_keys)))
    return int(lib.oap_distinct_count(
        sorted_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(sorted_keys)))


# -- ALS grouped-edge prep --------------------------------------------------

def als_grouped_total(dst: np.ndarray, n_dst: int, p: int) -> Optional[int]:
    """Padded edge total for one grouped side (blowup-guard fast path);
    None if the native lib is unavailable (or its O(n_dst) counts buffer
    cannot be allocated — callers fall back to NumPy)."""
    if n_dst <= 0 or len(dst) == 0:
        return 0  # empty side: no groups, matching the NumPy path
    lib = _load()
    if lib is None:
        return None
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    total = lib.oap_als_grouped_total(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(dst),
        n_dst, p,
    )
    if total == -2:
        return None  # allocation failure: NumPy fallback
    if total < 0:
        raise ValueError("destination id out of range for grouped layout")
    return int(total)


def als_group_edges(
    dst: np.ndarray, src: np.ndarray, conf: np.ndarray, n_dst: int, p: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Stable counting-sort build of the padded (G, P) grouped-edge layout
    (~ ops/als_ops.build_grouped_edges, O(nnz + n_dst) instead of the
    NumPy argsort path); None if the native lib is unavailable."""
    lib = _load()
    if lib is None or n_dst <= 0 or len(dst) == 0:
        return None  # empty/degenerate sides keep the NumPy path's behavior
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    src = np.ascontiguousarray(src, dtype=np.int64)
    conf = np.ascontiguousarray(conf, dtype=np.float32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    # one counting pass to size the buffers, one inside the builder — the
    # duplicate O(nnz) count is noise next to the argsort it replaces
    total = als_grouped_total(dst, n_dst, p)
    if total is None:
        return None
    src_g = np.zeros((total,), np.int32)
    conf_g = np.zeros((total,), np.float32)
    valid_g = np.zeros((total,), np.float32)
    group_dst = np.zeros((total // p,), np.int32)
    got = lib.oap_als_group_edges(
        dst.ctypes.data_as(i64p), src.ctypes.data_as(i64p),
        conf.ctypes.data_as(f32p), len(dst), n_dst, p, total,
        src_g.ctypes.data_as(i32p), conf_g.ctypes.data_as(f32p),
        valid_g.ctypes.data_as(f32p), group_dst.ctypes.data_as(i32p),
    )
    if got == -2:
        return None  # allocation failure: NumPy fallback
    if got != total:
        raise RuntimeError("native grouped-edge build failed")
    g = total // p
    return (
        src_g.reshape(g, p),
        conf_g.reshape(g, p),
        valid_g.reshape(g, p),
        group_dst,
    )
