"""Unified configuration system.

The reference scatters configuration across five channels (survey §5): Spark
conf keys under ``spark.oap.mllib.*`` (e.g. ``spark.oap.mllib.oneccl.kvs.ip`` /
``.port``, reference KMeansDALImpl.scala:40-44), Spark resource settings
(``spark.executor.cores``, Utils.scala:51-58), env vars set from code
(``CCL_ATL_TRANSPORT``, OneCCL.scala:26-30), build-time env, and a deployment
env script.  This framework unifies them into one dataclass with env-var
overrides under the ``OAP_MLLIB_TPU_*`` namespace (the ``spark.oap.mllib.*``
analog) plus a programmatic API.

Env mapping: config field ``foo_bar`` <- env ``OAP_MLLIB_TPU_FOO_BAR``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional

_ENV_PREFIX = "OAP_MLLIB_TPU_"


def _env_bool(val: str) -> bool:
    return val.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Config:
    """Global framework configuration.

    Fields mirror the reference's config surface:

    - ``device``: backend selector, the ``spark.oap.mllib.device=tpu`` analog
      (BASELINE.json north star). One of ``"tpu"``, ``"cpu"``, ``"auto"``.
      ``"auto"`` uses an accelerator when present, else the host platform.
    - ``coordinator_address`` / ``coordinator_port``: multi-host bootstrap
      rendezvous — the ``spark.oap.mllib.oneccl.kvs.ip``/``.port`` analog
      (reference KMeansDALImpl.scala:39-46). Empty means single-process.
    - ``num_processes`` / ``process_id``: collective world shape — the
      executor-count / rank pair (reference OneCCL.scala:32-42).
    - ``data_axis`` / ``model_axis``: mesh axis names for row sharding and
      feature/factor sharding.
    - ``model_parallel``: size of the model axis in meshes built by
      :func:`~oap_mllib_tpu.parallel.mesh.get_mesh` (devices are arranged
      (n // model_parallel, model_parallel)).  >1 enables mesh-sharded
      linalg — PCA shards its Gram/covariance rows over the model axis so
      the (d, d) accumulation outgrows one chip's HBM (survey §5; the
      reference has no analog because oneDAL kernels are single-node).
    - ``enable_x64``: run K-Means/PCA accumulation in float64 for parity with
      the reference's double kernels (KMeansDALImpl.cpp:32); ALS uses float32
      like the reference (ALSDALImpl.cpp:35).
    - ``fallback``: when True (default), estimators silently fall back to the
      CPU/NumPy reference path if the capability predicate fails — the
      "unmodified user code" contract (reference Utils.scala:98-115).
    - ``timing``: per-phase wall-time logging, the std::chrono log analog
      (reference KMeansDALImpl.cpp:202-222).
    """

    device: str = "auto"
    coordinator_address: str = ""
    coordinator_port: int = 0
    num_processes: int = 1
    process_id: int = 0
    data_axis: str = "data"
    model_axis: str = "model"
    model_parallel: int = 1
    enable_x64: bool = False
    fallback: bool = True
    timing: bool = False
    seed: int = 0
    # MXU precision tier for the K-Means hot loop AND the PCA covariance
    # Gram.  "highest" = full f32 (multi-pass) — the 1e-4 numerical-parity
    # contract.  "high" = bf16_3x: K-Means runs bf16_3x centroid sums +
    # bf16 assignment (within 1e-5 of highest; ~3x kernel steady-state,
    # ~2.6x end-to-end — BASELINE.md; see kmeans_ops._assign_prec), PCA
    # holds <=1e-4 on the centered Gram.
    # "default" = bf16 everywhere (K-Means ~1e-2, PCA ~1e-3); opt-in for
    # throughput-first workloads.  The x64 lane pins PCA to highest.
    # Per-tier bounds pinned on tests_tpu/; docs/configuration.md has the
    # full table.
    matmul_precision: str = "highest"
    # K-Means hot-loop kernel: "auto" picks the fastest measured path per
    # shape/tier (BASELINE.md kernel table, v5e): the fused Pallas kernel
    # at the f32-accurate tiers (it won every profiled shape once the
    # loop-mode assignment landed), the chunked XLA Lloyd at "default" or
    # when (k, d) overflows the kernel's VMEM blocks.  "xla"/"pallas"
    # force a path; "pallas" requires TPU + single-device + f32 and falls
    # back otherwise.
    kmeans_kernel: str = "auto"
    # ALS normal-equation layout: "auto" uses the scatter-free grouped-edge
    # programs (12x the COO path at MovieLens-1M scale on v5e, BASELINE.md)
    # unless the degree distribution's padding blowup exceeds the guard, in
    # which case the COO segment-sum programs run; "grouped"/"coo" force a
    # layout.  Applies to both the single-device and the block-parallel
    # paths.
    als_kernel: str = "auto"
    # PCA covariance kernel: "auto" runs the fused Pallas moments kernel
    # (ops/pallas/pca_kernel: center + mask + Gram + colsum per row tile
    # in VMEM, no HBM centered temp) when its preconditions hold — TPU,
    # single device, f32, and the (d, d) Gram block fits the kernel's
    # VMEM budget (d <= ~2048) — at every precision tier (the kernel
    # ships the same hand-rolled bf16 hi/lo-split tiers as the K-Means
    # kernel, so the bf16 policy prices ON Pallas).  "xla"/"pallas"
    # force a path; "pallas" still requires the preconditions and falls
    # back otherwise.  Applies to the in-memory AND streamed covariance
    # passes (the model-sharded Gram stays on the shard_map XLA path).
    pca_kernel: str = "auto"
    # ALS normal-equation solve kernel: "auto" runs the batched Pallas
    # assembly+solve kernel (ops/pallas/als_kernel: per-user Gram
    # assembly — moments + ALS-WR regularization + implicit Gram term —
    # and the unrolled rank-r Cholesky in one fused program, batch on
    # the 128-lane axis) when on TPU with f32 factors and rank <= 32;
    # "xla" keeps the batch-wide unrolled XLA solve
    # (ops/als_ops._chol_solve_unrolled); "pallas" forces the kernel
    # (same preconditions, falls back otherwise).  Applies wherever
    # moments meet regularized_solve: single-device grouped/COO and the
    # block-parallel runners.
    als_solve_kernel: str = "auto"
    # Cross-device reduction of per-pass moments (K-Means centroid
    # sums/counts/cost over the data axis, and the streamed multi-host
    # per-pass reductions): "auto"/"on" replace the post-pass psums with
    # the ring reduction (ops/pallas/ring_reduce: reduce-scatter +
    # all-gather rotating fixed segments around the mesh ring —
    # pltpu.make_async_remote_copy DMA on TPU, the identical-schedule
    # ppermute program elsewhere), falling back to the psum path when
    # the mesh has fewer than 2 devices on the reduce axis; "off" keeps
    # the psum path everywhere.  "on" and "auto" are synonyms today
    # (the auto rule may grow shape bounds as TPU measurements land).
    ring_reduction: str = "auto"
    # ALS item-factor layout on the block-parallel path.  "replicated"
    # keeps Y on every device and psums full (n_items, r, r+1) item
    # partials each iteration — one collective, best at small n_items.
    # "sharded" completes the 2-D user x item grid (the reference's
    # per-rank transposed item blocks, ALSDALImpl.cpp:192-214,301-316):
    # Y block-sharded over the data axis, per-iteration collectives are
    # two factor all_gathers — ~(r+1)x less traffic — and the per-rank
    # item partials and resident Y shrink world-fold.  "auto" shards once
    # the replicated psum bytes/iteration exceed
    # ops.als_block.ITEM_SHARD_AUTO_BYTES AND the sharded all_gather
    # traffic is actually lower (user-dominated id spaces stay
    # replicated — ops.als_block.item_layout_sharded).
    als_item_layout: str = "auto"
    # PCA eigensolver.  "eigh" (and "auto", today's resolution of it) =
    # the full d x d factorization — the parity contract, exact for any
    # spectrum.  "randomized" = top-k subspace iteration
    # (ops/pca_ops.topk_eigh_randomized): replaces the O(d^3) eigh that
    # owns 66% of the large-d wall (BASELINE.md row 5) with a few
    # (d, d) x (d, k+16) MXU matmuls — opt-in because accuracy is
    # spectral-gap-dependent (decaying spectra ~1e-4 vs eigh; a flat
    # spectrum biases values ~5% low and its eigenvectors are
    # ill-defined).  The fit summary records which solver ran.
    pca_solver: str = "auto"
    # Randomized-solver tuning: probe width = k + pca_rand_oversample,
    # subspace iterations = pca_rand_iters.  The defaults hold ~1e-4 on
    # decaying spectra; weakly-gapped spectra tighten with more of both
    # (measured d=2048 Wishart edge: ~5% value bias at 8/16, ~0.3% at
    # 16/64 — BASELINE.md row 5).  Ignored unless pca_solver="randomized".
    pca_rand_oversample: int = 16
    pca_rand_iters: int = 8
    # Shape bucketing (data/bucketing.py): round padded row counts up to
    # geometric buckets so one compiled program serves a RANGE of input
    # sizes — a service fitting many differently-sized datasets stops
    # paying seconds of XLA compile per request shape.  "on" (default) =
    # x2 steps anchored at the shard multiple; "off" = exact padding
    # (today's shapes); a numeric string (e.g. "1.25") = gentler growth.
    # Padding rows carry mask/weight 0, so per-fit results match the
    # unbucketed path (k-means|| init draws are the one shape-dependent
    # RNG — docs/user-guide.md "Compile amortization" has the caveat and
    # the memory/FLOP cost table).
    shape_bucketing: str = "on"
    # Persistent XLA compilation cache directory (jax
    # compilation_cache_dir, wired by utils/progcache
    # .ensure_persistent_cache at dispatch time).  Non-empty = compiled
    # executables serialize to this dir and a warm process skips XLA
    # compilation entirely — the cross-process half of compile
    # amortization.  Empty (default) = no persistence.
    compilation_cache_dir: str = ""
    # Kernel-geometry autotuner (ops/pallas/autotune.py): tile rows,
    # VMEM rotation depth, solve batch, ring segment counts per
    # (backend, shape-bucket, dtype-tier).  "auto" = launch cached or
    # pinned tuned geometry when available, otherwise the hand-picked
    # defaults — never sweeps, zero overhead.  "on" = sweep the
    # candidate grid on a cache miss (deterministic measured best-of-N;
    # winners persist) so the SECOND fit on the same backend/bucket
    # launches pre-tuned with zero sweep overhead.  "off" = defaults
    # always, cache ignored.  "pin:<json>" = per-kernel geometry pinned
    # verbatim (e.g. 'pin:{"kmeans": {"tile_rows": 1024}}'); unknown
    # kernels/fields raise, like every typo here.
    tuning: str = "auto"
    # Persistent tuning-cache directory: swept winners serialize here
    # (one JSON file per (backend, kernel, shape-bucket, dtype-tier)
    # key) so a FRESH process — or a second fit anywhere on the same
    # backend — launches pre-tuned without re-sweeping.  Empty
    # (default) = in-process memory only.
    tuning_cache_dir: str = ""
    # Streamed-path prefetch depth: how many chunks the background staging
    # thread may hold ahead of the consumer (data/prefetch.py).  2 =
    # double buffering — chunk N+1 is padded/converted/device_put while
    # chunk N's step executes, hiding host->device transfer behind
    # compute.  1 = today's strictly serial stage->transfer->compute loop
    # (no thread; bit-identical results — depth never changes the math,
    # only the overlap).  Each unit of depth holds one extra staged chunk
    # in device memory, so HBM grows by chunk_bytes * (depth - 1).
    prefetch_depth: int = 2
    # -- memory-budget planner (utils/membudget.py) --------------------------
    # HBM budget consulted by the route planner on every accelerated fit:
    # the per-device accelerator memory the fit's working set may occupy.
    # "" (default) = auto-detect (jax device memory_stats bytes_limit;
    # a conservative host-RAM-derived bound on backends that report
    # none); a size string ("4G", "512M", "1073741824") pins it; "0" or
    # "unlimited" disables the HBM constraint.  Budgets only steer ROUTE
    # selection (in-memory / chunked / streamed / streamed-block) — they
    # never reject a fit outright unless scale_policy="strict".
    memory_budget_hbm: str = ""
    # Host-RAM budget for staged tables (same grammar): the planner
    # routes fits whose staged host footprint exceeds it onto
    # disk-backed streaming, and the resilience ladder's spill rung
    # re-enters the streamed route from disk after a host-classified
    # OOM.  "" = auto-detect from the machine's physical memory.
    memory_budget_host: str = ""
    # What the planner does when the budget forces a route below the
    # fit's natural one: "auto" (default) picks the cheapest route that
    # fits the budgets, degrading loudly (warning log + the full
    # decision in summary.route) but never silently; "strict" raises
    # BudgetError instead of degrading scale (operators who must never
    # absorb a slow route without knowing); "pin:<route>" forces one of
    # in-memory|chunked|streamed|streamed-block, budgets advisory.  A
    # typo raises at fit entry (the kmeans_kernel contract).
    scale_policy: str = "auto"
    # Directory for spilled tables (the resilience ladder's host-OOM
    # rung stages the source to disk here and re-enters the streamed
    # route; utils/membudget.spill_source).  "" = the platform temp dir.
    spill_dir: str = ""
    # -- resilience layer (utils/resilience.py, utils/faults.py) ------------
    # Fault-injection spec: comma-separated "site:kind=count" entries
    # arming deterministic faults at named runtime sites (stream.read,
    # prefetch.stage, bootstrap.connect, fit.execute) — e.g.
    # "stream.read:fail=2" makes the first two chunk reads raise a
    # transient error.  Empty = no injection.  Grammar and sites:
    # utils/faults.py; CI drives every retry tier through this
    # (dev/fault_gate.py).
    fault_spec: str = ""
    # What a streamed-path numerical guardrail does when it detects
    # NaN/Inf in a training iterate (K-Means centroids, ALS factors, the
    # PCA Gram accumulator, checked after each pass): "raise" surfaces a
    # NonFiniteError immediately; "fallback" degrades to the CPU/NumPy
    # reference path (subject to Config.fallback).
    nonfinite_policy: str = "raise"
    # Max transient-fault retries per fit attempt ladder (exponential
    # backoff with deterministic jitter; utils/resilience.RetryPolicy).
    retry_limit: int = 5
    # Backoff base in seconds: retry n sleeps ~ retry_backoff * 2^n,
    # capped at 2 s, jittered deterministically.
    retry_backoff: float = 0.05
    # Retry wall-clock budget in seconds: retries stop when the next
    # backoff would cross this deadline, even with retries left.
    retry_deadline: float = 30.0
    # Coordinator-connection budget for initialize_distributed, in
    # seconds: connection attempts retry with backoff until this
    # deadline, then fail with an error naming coordinator/rank/elapsed.
    bootstrap_timeout: float = 60.0
    # -- live-world recovery plane (utils/recovery.py, utils/supervisor.py) --
    # Collective deadline in seconds: > 0 arms a watchdog on every
    # host-level collective dispatch (the eager facade in
    # parallel/collective.py, the host-mediated reductions in
    # ops/stream_ops.py, the checkpoint agreement gathers, the sanitizer
    # cross-check) in multi-process worlds.  A peer that never shows up
    # raises CollectiveTimeoutError on every surviving rank — naming
    # op/axis/elapsed and the last-completed dispatch fingerprint —
    # instead of hanging until the distributed timeout.  0 (default) =
    # disarmed: the hot path is one config check per dispatch.  Negative
    # values raise.
    collective_timeout: float = 0.0
    # Recovery sideband directory: non-empty arms coordinated abort — a
    # rank's fatal fault writes a machine-readable crash record
    # (crash.rank<r>.json: rank, site, fault class, last durable
    # checkpoint step, telemetry snapshot) that poisons its peers: ranks
    # waiting inside a deadline-armed collective see the record and
    # raise PeerAbortError promptly instead of timing out.  The
    # supervisor (utils/supervisor.py) sets this for every rank it
    # launches and classifies the records at exit.  Multi-process worlds
    # need a filesystem shared by every rank.  Empty (default) = off.
    crash_dir: str = ""
    # Supervisor restart budget: how many relaunches
    # utils/supervisor.Supervisor may spend before giving up on a world.
    restart_budget: int = 3
    # Supervisor relaunch backoff base in seconds: relaunch n sleeps
    # restart_backoff * 2^(n-1) before spawning the new world.
    restart_backoff: float = 1.0
    # How many CONSECUTIVE failures attributed to the same rank before
    # the supervisor shrinks the world by one (excluding the repeatedly
    # bad slot) and lets resume=auto reshard state onto the new layout.
    shrink_after: int = 2
    # Seeded randomized chaos schedule over every registered fault site
    # (utils/faults.py): "seed:rate[:kinds[:budget]]" — e.g. "7:0.02"
    # fires a transient fault on ~2% of site calls, "7:0.01:kill:1"
    # hard-kills the process (SIGKILL — a preemption) at most once.
    # kinds is a "+"-separated subset of fail|oom|nan|err|kill (cycled
    # deterministically); budget caps total fired faults ("*" =
    # unbounded).  The schedule is a pure function of
    # (seed, process index, site, call index), so drills are
    # reproducible and ranks fail independently.  Empty = off.
    chaos: str = ""
    # -- elastic worlds: sharded checkpoint/resume (utils/checkpoint.py) -----
    # Checkpoint directory for iterate-state checkpoints.  Non-empty arms
    # periodic per-rank sharded checkpoints on every fit path (K-Means
    # centroids, ALS factor shards, PCA streamed moments, plus the
    # pass/iteration index and world layout), written atomically
    # (tmp+rename, manifest last) so a preempted worker can be relaunched
    # and resume mid-fit — in a DIFFERENT world size if needed (factor
    # shards are redistributed through a collective resharding pass at
    # restore).  Multi-process worlds require this to be a filesystem
    # shared by every rank.  Empty (default) = checkpointing off, zero
    # overhead (one string check per fit).
    checkpoint_dir: str = ""
    # How often to checkpoint, in iterate-loop steps (streamed passes /
    # ALS iterations; in-memory fits run their compiled loops in
    # interval-sized segments and checkpoint between segments).  1
    # (default) = every step.
    checkpoint_interval: int = 1
    # Restore policy when checkpoint_dir is armed: "auto" (default)
    # resumes from a matching checkpoint when one exists and silently
    # starts fresh otherwise (a corrupt or mismatched checkpoint also
    # falls back to fresh, with a warning); "require" raises
    # CheckpointError unless a valid checkpoint was restored (operators
    # who must never silently recompute); "off" never restores but still
    # writes (produce checkpoints without consuming them).
    resume: str = "auto"
    # -- mixed-precision compute policy (utils/precision.py) -----------------
    # Process-wide input/accumulation precision for the matmul-dominated
    # hot paths (K-Means Lloyd distances + centroid sums, PCA
    # Gram/colsum, ALS normal-equation moments), in-memory AND streamed:
    # "f32" = today's behavior, bit-compatible (operands stay f32, dots
    # run at matmul_precision); "tf32" = f32 operands, bf16_3x dots
    # (lax.Precision.HIGH — the TPU analog of TF32, ~1e-5 of full f32);
    # "bf16" = operands cast to bfloat16 (at STAGING time on streamed
    # paths, halving host->device bytes) with f32 accumulators — solves,
    # norms, and convergence state stay f32; "auto" = bf16 where a
    # parity bound is registered for the algorithm and the backend has
    # fast bf16 MXUs, else f32.  enable_x64 pins every fit to f32.  A
    # non-finite iterate under a reduced policy degrades the fit to f32
    # via the resilience ladder's precision rung instead of failing.
    # Parity bounds + gate: utils/precision.py, dev/precision_gate.py.
    compute_precision: str = "f32"
    # Per-algorithm overrides of compute_precision (same vocabulary,
    # including "auto"); empty = inherit.  E.g. kmeans_precision="bf16"
    # runs only K-Means reduced while PCA/ALS stay at the global policy.
    kmeans_precision: str = ""
    pca_precision: str = ""
    als_precision: str = ""
    # -- serving plane (oap_mllib_tpu/serving/) ------------------------------
    # Compute policy for serving-time scoring matmuls (the registry /
    # micro-batcher request paths and the full-sweep top-k).  "" (the
    # default) inherits the algorithm's resolved compute policy
    # (compute_precision + per-algo overrides) — f32 stays
    # bit-compatible with direct model calls; "f32"|"tf32"|"bf16"|
    # "auto" override it for serving only (a bf16 serving tier halves
    # the request staging bytes while fits keep f32).  A typo raises at
    # request time.
    serving_precision: str = ""
    # Row-chunk width of the full-sweep top-k (serving/sweep.py): how
    # many query (user) rows score per compiled step while the sweep
    # streams the factor table through the prefetch pipeline.  0 (the
    # default) derives the width from the shared scoring live-buffer
    # budget (ops/kmeans_ops.rows_per_chunk — the same bound the
    # models' chunked top-k uses), so the (chunk, n_items) score block
    # stays bounded whatever the table sizes.  Negative raises.
    sweep_chunk_rows: int = 0
    # -- traffic plane (serving/traffic.py): async ingestion, admission,
    #    replica scaling ------------------------------------------------------
    # Max pending async requests a TrafficQueue holds before submit
    # sheds with ShedError(reason="queue_full") + oap_serve_shed_total.
    # The bound is requests (not rows): it caps dispatcher latency per
    # pump cycle.  Must be >= 1; a typo raises at submit time.
    serve_queue_depth: int = 256
    # Default per-request deadline in milliseconds for submits that
    # don't pass deadline_ms explicitly.  Requests still pending past
    # their deadline are shed before dispatch (their future raises
    # ShedError(reason="deadline")) — never scored dead.  0 (default) =
    # no deadline; negative raises.
    serve_deadline_ms: float = 0.0
    # Fraction of the resolved HBM budget (utils/membudget.Budgets —
    # memory_budget_hbm or auto-detect) the traffic queue's staged
    # working set may claim before submit sheds with
    # ShedError(reason="budget"): pending + incoming request bytes x
    # the planner's overhead fudge > hbm_budget x this headroom =>
    # shed instead of OOM.  Only armed when the budget resolves > 0
    # (an unbounded budget prices nothing).  Must be in (0, 1]; a typo
    # raises at submit time.
    serve_shed_headroom: float = 0.5
    # Scale-out trigger for the serving replica controller
    # (serving/traffic.ScaleController): windowed mean queue depth PER
    # REPLICA above this (with a non-falling depth trend) votes one
    # replica out, booked in oap_serve_scale_out_total and the
    # supervisor sideband hint.  Must be > 0.
    serve_scale_high: float = 32.0
    # Scale-in trigger: a fleet idle (zero queue depth, no new
    # requests) for this many seconds sheds one replica down to the
    # controller's floor.  Must be > 0.
    serve_scale_idle_s: float = 30.0
    # Durable-future retry envelope (serving/traffic.py): how many times
    # an ADMITTED request may be re-enqueued after a transient scoring
    # fault before its future fails with a classified ServeError
    # (reason="retries-exhausted").  Re-enqueued requests keep their
    # original deadline and arrival order, so retries never jump the
    # deadline priority.  0 = fail on the first transient fault; must
    # be >= 0 (a typo raises at submit time).
    serve_retry_limit: int = 2
    # Backoff base in seconds for re-enqueued requests: retry n waits
    # ~ serve_retry_backoff * 2^n before redispatch, jittered
    # deterministically per (site, attempt) like
    # utils/resilience.RetryPolicy.  Must be >= 0; a typo raises at
    # submit time.
    serve_retry_backoff: float = 0.01
    # Brownout degradation ladder (serving/traffic.BrownoutController):
    # what the traffic plane does under SUSTAINED over-budget pressure
    # (membudget-priced admission, fleet-trend-gated like the scale
    # controller) before it sheds.  "auto" (default) steps through the
    # recorded rungs — "topk" (halved top-k depth), "bf16" (serving
    # precision drops to bf16 where a parity bound is registered),
    # "stale" (stale-pin answering during model re-pin) — absorbing
    # over-budget requests while rungs remain; each step is LOUD
    # (serving_summary()["brownout"], span attrs,
    # oap_serve_brownout_rung, the flight recorder).  "off" disarms the
    # ladder (over-budget requests shed immediately, today's
    # behavior); "pin:<rung>" holds a fixed rung (off|topk|bf16|stale)
    # without automatic stepping.  A typo raises at submit time.
    serve_brownout: str = "auto"
    # Request-lifecycle tracing (serving/reqtrace.py): > 0 arms a trace
    # context on every ADMITTED request — a deterministic id plus a
    # fixed-schema deadline-budget ledger (admission / queue_wait /
    # batch_form / bucket_pad / compile / execute / dispatch stage
    # walls that sum to the measured request wall by construction),
    # attached to the answered future (serving.ledger_of), booked into
    # the oap_serve_stage_seconds{stage=} histograms, and folded into
    # serving_summary()["attribution"].  The value is the SAMPLING
    # fraction for heavy emission (flight-recorder request events,
    # JSONL "request" records, /metrics exemplars): a request is
    # sampled when crc32(trace_id)/2^32 < serve_trace_sample — a pure
    # hash, no RNG, so every process of a world samples the same ids.
    # 0 (default) = off, one config check per submit; must be in
    # [0, 1], a typo raises at submit time.
    serve_trace_sample: float = 0.0
    # Serving latency SLO target in milliseconds (serving/slo.py): > 0
    # arms the multi-window burn-rate error-budget engine — a request
    # is "bad" when it fails/sheds or its wall exceeds this p99 target;
    # burn rates over the fast (serve_slo_window_s / 12) and slow
    # (serve_slo_window_s) windows land in oap_slo_burn_rate{window=},
    # oap_slo_error_budget_remaining, serving_summary()["slo"], the
    # /sloz endpoint, and every scale/brownout decision (observe-only:
    # the SLO state is RECORDED with the decision, it never makes one).
    # 0 (default) = disarmed; must be >= 0.
    serve_slo_p99_ms: float = 0.0
    # Availability objective for the error-budget engine: the target
    # fraction of requests answered within SLO (e.g. 0.999 = a 0.1%
    # error budget).  Burn rate 1.0 means bad requests arrive exactly
    # at the rate that exhausts the budget over the window.  Must be in
    # (0, 1); a typo raises when the engine is consulted.
    serve_slo_availability: float = 0.999
    # Slow burn-rate window in seconds (the error-budget accounting
    # horizon); the fast window is this / 12 (the SRE 5m/1h pairing).
    # Must be > 0.
    serve_slo_window_s: float = 3600.0
    # -- online / incremental fits (oap_mllib_tpu/online/) -------------------
    # Count-decay factor for mini-batch Lloyd (online/minibatch.py
    # KMeansModel.partial_fit): each partial_fit multiplies the
    # accumulated per-center counts by this BEFORE folding the new
    # mini-batch in, so the per-center learning rate
    # counts_new / (decay * counts_old + counts_new) forgets old data
    # geometrically.  1.0 (default) = no forgetting — the streaming
    # average converges to the full-batch Lloyd step over the union of
    # all chunks seen; values in (0, 1) track drifting distributions.
    # Must be in (0, 1]; a typo raises at partial_fit entry.
    online_decay: float = 1.0
    # Row batching for the ALS fold-in solve (online/foldin.py): how
    # many touched user/item rows solve per normal-equation launch.  0
    # (default) solves every touched row in ONE batched launch — the
    # fold-in contract (the per-delta cost is one edge pass + one
    # solve, never a full refit); > 0 chunks huge deltas so the
    # (batch, r, r) moment block stays bounded.  Negative raises.
    online_foldin_batch: int = 0
    # In-place serving re-pin on delta commit (online/delta.py): "auto"
    # (default) re-pins every registry handle serving the committed
    # model — version bump + fresh device pins under the registry lock,
    # in-flight requests keep answering, zero new XLA compiles while
    # shapes stay in-bucket — and resets the
    # oap_serve_model_staleness_seconds gauge; "off" leaves served
    # handles on the old pin (they go stale, LOUD via the staleness
    # gauge) until the caller re-serves explicitly.  A typo raises at
    # commit time.
    online_repin: str = "auto"
    # -- telemetry layer (oap_mllib_tpu/telemetry/) --------------------------
    # jax.profiler trace directory: non-empty wraps every estimator fit
    # in a profiler trace written there (utils/profiling.maybe_trace),
    # and the span tree emits a TraceAnnotation per phase while the
    # trace is live.  Promoted from the raw OAP_MLLIB_TPU_PROFILE_DIR
    # env read so Config.set/scoped overrides work like every other
    # knob; the env var still applies through the standard coercion.
    profile_dir: str = ""
    # Runtime sanitizer plane (utils/sanitizers.py): comma-set of
    # "collective", "transfer", "retrace", "locks"; empty (default) =
    # all off.  "collective" fingerprints every host-level collective
    # dispatch as (op, axis, shape, dtype) and cross-checks the
    # signature across ranks BEFORE dispatch (plus a per-fit
    # fingerprint check at finalization), so a rank-divergent
    # collective raises a diagnostic naming the mismatching op on every
    # rank instead of hanging the world.  "transfer" runs streamed
    # per-chunk consumer bodies under jax.transfer_guard("disallow") —
    # implicit device<->host syncs in the hot loop fail loudly (the
    # runtime ground truth behind oaplint R4).  "retrace" asserts zero
    # new XLA compiles after warmup in steady-state chunk loops (and
    # via sanitizers.steady_state scopes).  "locks" arms the tracked-
    # lock seams (utils/locktrace.py): a live lock-order inversion
    # raises LockOrderError naming both witness stacks instead of
    # deadlocking, hold times feed the oap_lock_hold_seconds histogram,
    # and holds exceeding collective_timeout are flagged (never killed)
    # — the runtime half of the oaplint concurrency pass (R19-R22).
    # Off = one cached string check per seam (~0% overhead,
    # dev/sanitizer_gate.py and dev/concurrency_gate.py assert it); on
    # adds one tiny allgather per host collective under "collective".
    # docs/distributed.md "Sanitizers" has the when/why table.
    sanitizers: str = ""
    # JSON-lines telemetry sink: non-empty appends one record per span
    # close plus a registry snapshot at every fit finalization (and a
    # final snapshot at process exit).  Multi-process worlds write
    # per-rank files (<path>.rank<r>), each record rank-tagged, so a
    # world's files concatenate into one mergeable stream
    # (telemetry/export.py; docs/observability.md).  Empty = off (the
    # near-zero-overhead default: no file is ever opened).
    telemetry_log: str = ""
    # -- fleet observability control plane (telemetry/fleet.py,
    #    telemetry/flightrec.py) --------------------------------------------
    # Live metrics exposition port: > 0 starts one stdlib http.server
    # daemon thread per rank serving GET /metrics (the Prometheus text
    # exposition of the process registry) and GET /healthz (fit root,
    # step, last-collective fingerprint, resilience ladder state) on
    # port metrics_port + process_id — every rank of a co-hosted
    # pseudo-cluster world gets its own scrape surface.  0 (default) =
    # no server, zero overhead; negative raises.
    metrics_port: int = 0
    # Cross-rank fleet rollups: "auto" (default) arms per-pass rollups
    # only in multi-process worlds (single-process fits pay one config
    # check); "on" arms them everywhere (a 1-rank world folds its own
    # frame — useful for tests and single-host dashboards); "off"
    # disarms them.  Armed, every streamed pass allgathers one
    # fixed-shape per-rank stat frame (pass wall, stage/transfer/compute
    # split, bytes staged, retries, kernel dispatch wall) over the host
    # collective plane (deadline-watchdog guarded), folds it into
    # oap_fleet_* gauges/histograms on rank 0, and lands a `fleet` block
    # (slowest rank, skew ratio, imbalance trend) in the fit summary.
    # A typo raises.
    fleet_stats: str = "auto"
    # -- heterogeneous fleets: capability-weighted sharding
    #    (parallel/balance.py, utils/dispatch.throughput_probe) ---------------
    # Capability-weighted shard planning: "auto" (default) arms the
    # balance plane in multi-process worlds — per-rank capability
    # weights (probed or pinned) convert into uneven per-rank row
    # extents for streamed fits built through balance.local_source and
    # uneven user-block offsets for replicated-layout block ALS, so a
    # mixed or degraded world finishes passes together instead of at
    # the slowest rank's pace; "on" arms it everywhere (a 1-rank world
    # degenerates to the equal plan — tests, dashboards); "off" keeps
    # equal shards (the planner still runs where consulted, with
    # origin="equal").  A typo raises.
    capability_sharding: str = "auto"
    # Per-rank capability override.  "" (default) = measure: a tiny
    # deterministic-seeded matmul + host->device stream microbench
    # (utils/dispatch.throughput_probe), cached per process.  A bare
    # float ("0.25") pins THIS rank's capability; a comma map keyed by
    # rank ("0:1.0,1:0.25") pins per rank (tests / known-heterogeneous
    # deployments — ranks absent from the map fall back to the probe).
    # Values must be > 0; a typo raises.
    rank_capability: str = ""
    # Capability-probe generation.  The probe cache
    # (utils/dispatch.throughput_probe, parallel/balance
    # .world_capabilities) is keyed by this epoch: bumping it
    # invalidates every cached measurement so the next consult
    # re-probes.  The supervisor (utils/supervisor.py) sets
    # OAP_MLLIB_TPU_PROBE_EPOCH to the attempt number on every
    # (re)launch, so a relaunched rank measures its CURRENT capability
    # instead of trusting its pre-preemption value.  Default 0.
    probe_epoch: int = 0
    # Live straggler rebalancing trigger (parallel/balance.py, riding
    # the fleet rollups): when a pass's skew ratio (max/mean per-rank
    # pass wall) exceeds this for rebalance_patience consecutive passes
    # and the imbalance trend is not falling, the controller re-plans
    # extents at the next pass boundary from the measured per-rank
    # throughput.  Must be > 1.0; rebalancing also requires
    # Config.fleet_stats armed (the rollups are its measurement layer).
    rebalance_threshold: float = 1.5
    # How many CONSECUTIVE over-threshold passes before a re-plan (>= 1).
    rebalance_patience: int = 3
    # Flight recorder ring size, in event slots: > 0 arms a
    # constant-memory per-rank ring buffer (telemetry/flightrec.py) of
    # recent events — span open/close, host-collective dispatch
    # fingerprints, fault/retry/degradation events, checkpoint commits —
    # each stamped with a monotonic seq.  Crash records
    # (utils/recovery.py) embed the tail, so every post-mortem shows the
    # last N events on every rank; the JSONL telemetry sink drains new
    # events at each fit finalization (dev/oaptrace.py merges them into
    # a Perfetto-loadable timeline).  0 (default) = off, one config
    # check per would-be event; negative raises.
    flight_recorder: int = 0

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key not in os.environ:
                continue
            raw = os.environ[env_key]
            if f.type in ("bool", bool):
                setattr(cfg, f.name, _env_bool(raw))
            elif f.type in ("int", int):
                setattr(cfg, f.name, int(raw))
            elif f.type in ("float", float):
                setattr(cfg, f.name, float(raw))
            else:
                setattr(cfg, f.name, raw)
        return cfg


_lock = threading.Lock()
_config: Optional[Config] = None


def get_config() -> Config:
    """Return the process-global config, initializing from env on first use."""
    global _config
    with _lock:
        if _config is None:
            _config = Config.from_env()
        return _config


def set_config(**updates) -> Config:
    """Update the process-global config in place; returns it."""
    cfg = get_config()
    with _lock:
        for k, v in updates.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown config field: {k!r}")
            setattr(cfg, k, v)
    return cfg
