"""oap-mllib-tpu: a TPU-native distributed classical-ML framework.

A brand-new framework with the capabilities of OAP MLlib (the reference at
/root/reference): accelerated K-Means, PCA, and implicit ALS with
Spark-MLlib-compatible parameters, numerical parity, and transparent fallback
to a CPU reference path — redesigned TPU-first:

- Compute kernels are JAX/XLA programs (MXU matmuls, fused elementwise),
  jitted over a `jax.sharding.Mesh` (rows sharded over the ``data`` axis,
  features/factors optionally over ``model``), replacing the reference's
  oneDAL distributed step1Local/step2Master kernels
  (reference: mllib-dal/src/main/native/{KMeans,PCA,ALS}DALImpl.cpp).
- Cross-device sync is XLA collectives (psum / all_gather / all_to_all) over
  ICI/DCN compiled into the program, replacing oneCCL
  broadcast/allgatherv/alltoallv of serialized byte blobs
  (reference: mllib-dal/src/main/native/OneCCL.cpp).
- Multi-host bootstrap is the JAX distributed runtime (coordinator ip:port),
  replacing the oneCCL TCP-KVS rendezvous (reference: OneCCL.cpp:47-86).
- The native runtime layer (host tables, parsers, port probing) is C++
  loaded via ctypes, replacing the JNI/oneDAL table layer
  (reference: mllib-dal/src/main/native/OneDAL.cpp, LibLoader.java).

Public API::

    from oap_mllib_tpu import KMeans, PCA, ALS
    model = KMeans(k=8, max_iter=20).fit(X)
"""

__version__ = "0.1.0"

from oap_mllib_tpu.config import Config, get_config, set_config
from oap_mllib_tpu import telemetry
from oap_mllib_tpu import online
from oap_mllib_tpu.models.kmeans import KMeans, KMeansModel
from oap_mllib_tpu.models.pca import PCA, PCAModel
from oap_mllib_tpu.models.als import ALS, ALSModel

__all__ = [
    "telemetry",
    "online",
    "KMeans",
    "KMeansModel",
    "PCA",
    "PCAModel",
    "ALS",
    "ALSModel",
    "Config",
    "get_config",
    "set_config",
]
