"""On-device model registry: serve fitted models without re-uploading.

The eager model surface used to pay a host->device weight upload per
call (``models/kmeans.py`` predict re-staged the centers, ``models/als
.py`` re-staged whole factor tables) — at serving QPS that is the
dominant cost and it scales with the MODEL, not the request.
:func:`serve` pins a fitted model's state on-device ONCE, keyed like
the program cache (serving the same model twice returns the same
handle, no re-pin), and every request then routes through the
micro-batcher (:mod:`oap_mllib_tpu.serving.batcher`) against the
pinned weights.

Per-request telemetry lands in the process registry —
``oap_serve_requests_total`` / ``_batches_total`` / ``_pad_rows_total``
/ ``_queue_depth`` plus the ``oap_serve_request_seconds`` factor-4
log-bucket latency histogram (telemetry/metrics.py) — so the PR 11
``/metrics`` endpoint exposes the serving plane live, and
:func:`serving_summary` renders the "serving" block (request totals +
p50/p99) for benches and reports.

The :func:`pin` helper is also the models' own device-copy cache (the
eager-path fix): identity-keyed on the HOST array object, so a refit —
which constructs a fresh model/array — naturally invalidates it, and
repeated calls against one model never re-upload.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

# tracked (utils/locktrace.py): the serving registry lock nests the
# telemetry registry lock (gauge bookings under registration) — the
# prime cross-subsystem ordering seam the "locks" sanitizer watches
_LOCK = locktrace.TrackedLock("serving.registry", threading.RLock())
_SERVED: Dict[tuple, "ServedModel"] = {}

# oap_serve_queue_depth is written from BOTH sides of the traffic
# plane — submitters increment, the dispatcher thread and coalesced
# flushes decrement — so the gauge is maintained as a delta-summed
# counter under its own tracked lock: concurrent set() calls from two
# threads would clobber each other (the race the "locks" sanitizer
# watches this seam for); delta folding under the lock cannot.
_DEPTH_LOCK = locktrace.TrackedLock("serving.queue_depth")
_queue_depth = 0


def note_queue_depth(delta: int) -> int:
    """Fold ``delta`` into the live queue-depth gauge (pending async
    requests + requests coalesced into an in-flight flush), race-safe
    under the dispatcher thread.  Returns the new depth."""
    global _queue_depth
    with _DEPTH_LOCK:
        _queue_depth = max(0, _queue_depth + int(delta))
        depth = _queue_depth
        _tm.gauge(
            "oap_serve_queue_depth",
            help="Serving requests pending in the traffic queue or "
                 "coalesced into the in-flight batch",
        ).set(depth)
    return depth


def queue_depth() -> int:
    """The live traffic-queue depth (pending + coalesced in-flight) —
    the /healthz serving block reads this without touching the gauge
    registry."""
    with _DEPTH_LOCK:
        return _queue_depth


def pin(cache: dict, name: str, host_array, *,
        allow_stale: bool = False) -> Any:
    """Device copy of ``host_array`` cached in ``cache[name]``, keyed by
    the host array's IDENTITY: the same object returns the same device
    buffer (zero re-uploads), a replaced array (a refit, a mutated
    model) re-stages exactly once.  Staging is an explicit
    ``jax.device_put`` (transfer-sanitizer clean).

    ``allow_stale``: at the brownout ladder's ``stale`` rung
    (``traffic.brownout_stale_ok``), a re-pin in flight (the identity
    key changed — a refit replaced the host table) answers from the
    PREVIOUS device pin instead of blocking the request on the fresh
    transfer — LOUD via ``oap_serve_stale_pins_total``; the fresh table
    pins on the next un-browned-out call."""
    import jax

    ent = cache.get(name)
    if ent is not None and ent[0] is host_array:
        return ent[1]
    if ent is not None and allow_stale:
        from oap_mllib_tpu.serving import traffic

        if traffic.brownout_stale_ok():
            _tm.counter(
                "oap_serve_stale_pins_total",
                help="Requests answered from a stale device pin under "
                     "the brownout ladder's stale rung",
            ).inc()
            return ent[1]
    dev = jax.device_put(np.asarray(host_array))
    cache[name] = (host_array, dev)
    return dev


def _observe_request(kind: str, wall_s: float, rows: int) -> None:
    lab = {"model": kind}
    _tm.counter(
        "oap_serve_requests_total", lab,
        help="Serving requests answered by model kind",
    ).inc()
    _tm.counter(
        "oap_serve_rows_total", lab,
        help="Request rows scored by the serving plane",
    ).inc(rows)
    # a traced coalesced flush pins one of its sampled trace ids to the
    # latency bucket as an OpenMetrics exemplar — a dashboard's slow
    # bucket links to a concrete request ledger
    from oap_mllib_tpu.serving import reqtrace

    tid = reqtrace.exemplar_trace_id()
    _tm.histogram(
        "oap_serve_request_seconds", lab,
        help="Per-request serving latency (staging + scoring + fetch)",
    ).observe(
        wall_s, exemplar={"trace_id": tid} if tid is not None else None
    )


class ServedModel:
    """One pinned model + its request accounting.  Subclasses expose the
    estimator's scoring surface; every public request runs under
    :meth:`_request`, which books the latency histogram and counters.

    Handles carry a ``model_version`` (bumped by :meth:`repin` on every
    delta commit — online/delta.py) and a staleness clock (seconds since
    the pinned state last changed), so serving freshness is a METRIC
    (``oap_serve_model_staleness_seconds``), not a cron job."""

    kind = "model"

    def __init__(self, model):
        self.model = model
        self._cache: dict = {}
        self.requests = 0
        # in-place update plane: version 1 is the initial pin; every
        # repin() (a committed delta fit) bumps it and resets the
        # staleness clock — the HANDLE object never changes, so
        # in-flight requests keep answering through it
        self.model_version = 1
        self._committed_at = time.monotonic()

    # -- request accounting ---------------------------------------------------
    def _request(self, rows: int, fn):
        t0 = time.perf_counter()
        out = fn()
        _observe_request(self.kind, time.perf_counter() - t0, rows)
        self.requests += 1
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "kind": self.kind, "requests": self.requests,
            "model_version": self.model_version,
            "staleness_seconds": round(self.staleness_seconds(), 3),
        }

    # -- in-place update (delta commits) --------------------------------------
    def staleness_seconds(self) -> float:
        """Seconds since this handle's pinned state last changed (the
        initial pin or the newest delta commit's re-pin)."""
        return max(0.0, time.monotonic() - self._committed_at)

    def touch_staleness(self) -> float:
        """Refresh + return the staleness gauge for this handle — called
        by serving_summary()/the /healthz serving block so a scrape
        always sees the CURRENT age of the pinned state."""
        s = self.staleness_seconds()
        _tm.gauge(
            "oap_serve_model_staleness_seconds", {"model": self.kind},
            help="Seconds since the served model's pinned state last "
                 "changed (a delta commit re-pin resets it)",
        ).set(s)
        return s

    def repin(self) -> int:
        """Refresh the device pins from the model's CURRENT host state
        and bump the version — the in-place half of a delta commit
        (online/delta.py).  The pin refresh runs OUTSIDE the registry
        lock — ``pin`` can consult the traffic plane's brownout state,
        whose lock is also taken while calling back into the registry
        (observe -> clear), so holding ``_LOCK`` across it would invert
        the lock order; the version/clock bump alone runs under
        ``_LOCK`` so it stays atomic against serve()/unserve().
        In-flight requests are NEVER evicted — they hold the handle
        (and at worst the previous device buffer, which stays valid
        until they drop it; a request racing the refresh answers
        through whichever pin generation it grabbed, both of which are
        committed states).  Zero new XLA compiles by construction: the
        pinned array SHAPES are unchanged (same centers (k, d), same
        item table), so every bucketed scoring program re-binds to the
        fresh buffers without recompiling (dev/online_gate.py asserts
        it against xla_compile_count)."""
        self._repin_pins()
        with _LOCK:
            self.model_version += 1
            self._committed_at = time.monotonic()
            version = self.model_version
        _tm.gauge(
            "oap_serve_model_version", {"model": self.kind},
            help="Version of the served model's pinned state (bumped "
                 "by every committed delta fit)",
        ).set(version)
        self.touch_staleness()
        _tm.counter(
            "oap_serve_repins_total", {"model": self.kind},
            help="In-place serving re-pins (committed delta fits)",
        ).inc()
        if flightrec.enabled():
            flightrec.record(
                "serve", "repin", f"kind={self.kind} version={version}"
            )
        return version

    def _repin_pins(self) -> None:
        """Refresh the subclass's device pins (identity-keyed ``pin``
        calls: a commit that swapped a host array re-stages exactly
        once; unchanged arrays are free)."""

    # -- micro-batch coalescing ----------------------------------------------
    def _flush_many(self, batches, score_rows):
        """Coalesce a queue of small requests into ONE bucketed launch:
        concatenate rows, score once, split results back per request.
        ``oap_serve_queue_depth`` tracks the coalesced depth while the
        flush is in flight — the micro-batching win is 1 launch (and at
        most one bucket's padding) for N requests."""
        batches = [np.atleast_2d(np.asarray(b)) for b in batches]
        if not batches:
            return []
        from oap_mllib_tpu.utils import faults

        # the coalesced-flush fault site: drives the traffic plane's
        # poison-batch bisection (a classified fault here splits the
        # group, never fails innocents)
        faults.maybe_fault("serve.batch")
        # delta-folded, not set(): the dispatcher thread and concurrent
        # flushes all move the same gauge (see note_queue_depth)
        note_queue_depth(len(batches))
        try:
            joined = np.concatenate(batches, axis=0)
            if (np.issubdtype(joined.dtype, np.floating)
                    and not np.isfinite(joined).all()):
                # a poison payload faults DETERMINISTICALLY in
                # whichever bisection half contains it — that's what
                # lets the traffic plane isolate the request
                from oap_mllib_tpu.utils.resilience import NonFiniteError

                raise NonFiniteError(
                    "coalesced serving flush contains nonfinite input "
                    "rows (poison request in the batch)"
                )
            out = score_rows(joined)
        finally:
            note_queue_depth(-len(batches))
        parts = []
        lo = 0
        for b in batches:
            parts.append(out[lo : lo + b.shape[0]])
            lo += b.shape[0]
        # each coalesced entry is a REQUEST (the batcher booked one
        # batch, the caller's _request books the shared flush wall and
        # the summed rows); count the remaining requests here
        for _ in batches[1:]:
            _observe_request(self.kind, 0.0, 0)
            self.requests += 1
        return parts

    # -- compile pre-warm -----------------------------------------------------
    def warmup(self, max_rows: int) -> int:
        """Compile the scoring-program bucket family for request sizes
        up to ``max_rows`` (one launch per geometric bucket).  After
        warmup, a storm of ANY sizes <= max_rows compiles zero new XLA
        programs — the steady-state serving contract
        (dev/serve_gate.py asserts it against xla_compile_count)."""
        from oap_mllib_tpu.serving import batcher

        sizes = batcher.warm_sizes(max_rows)
        for b in sizes:
            self._warm_one(b)
        return len(sizes)

    def _warm_one(self, rows: int) -> None:
        raise NotImplementedError


class ServedKMeans(ServedModel):
    kind = "kmeans"

    def __init__(self, model):
        super().__init__(model)
        # pin now: the handle's reason to exist
        self.centers_dev = pin(
            self._cache, "centers", model.cluster_centers_
        )

    def _repin_pins(self) -> None:
        self.centers_dev = pin(
            self._cache, "centers", self.model.cluster_centers_
        )

    def predict(self, x) -> np.ndarray:
        from oap_mllib_tpu.serving import batcher

        x = np.atleast_2d(np.asarray(x))
        return self._request(
            x.shape[0],
            lambda: batcher.assign_kmeans(self.centers_dev, x, self.kind),
        )

    transform = predict

    def predict_many(self, batches):
        """Answer a queue of requests with one coalesced launch (see
        :meth:`ServedModel._flush_many`)."""
        from oap_mllib_tpu.serving import batcher

        return self._request(
            sum(np.atleast_2d(np.asarray(b)).shape[0] for b in batches),
            lambda: self._flush_many(
                batches,
                lambda x: batcher.assign_kmeans(
                    self.centers_dev, x, self.kind
                ),
            ),
        )

    def _warm_one(self, rows: int) -> None:
        from oap_mllib_tpu.serving import batcher

        d = int(self.model.cluster_centers_.shape[1])
        batcher.assign_kmeans(
            self.centers_dev, np.zeros((rows, d), np.float32), self.kind
        )


class ServedPCA(ServedModel):
    kind = "pca"

    def __init__(self, model):
        super().__init__(model)
        self.components_dev = pin(
            self._cache, "components", model.components_
        )

    def _repin_pins(self) -> None:
        self.components_dev = pin(
            self._cache, "components", self.model.components_
        )

    def transform(self, x) -> np.ndarray:
        from oap_mllib_tpu.serving import batcher

        x = np.atleast_2d(np.asarray(x))
        return self._request(
            x.shape[0],
            lambda: batcher.project_pca(self.components_dev, x, self.kind),
        )

    def _warm_one(self, rows: int) -> None:
        from oap_mllib_tpu.serving import batcher

        d = int(self.model.components_.shape[0])
        batcher.project_pca(
            self.components_dev, np.zeros((rows, d), np.float32), self.kind
        )


class ServedALS(ServedModel):
    """Pinned ALS factors.  Block-sharded fits keep their LIVE device
    layout (``sweep`` serves straight from it — no host gather); host
    -factor models pin both tables once."""

    kind = "als"

    def __init__(self, model):
        super().__init__(model)
        self.sharded = model._sharded_user is not None
        if not self.sharded:
            self.user_dev = pin(
                self._cache, "user", model.user_factors_
            )
            self.item_dev = pin(
                self._cache, "item", model.item_factors_
            )

    def _repin_pins(self) -> None:
        # sharded layouts serve straight from the live device blocks —
        # nothing host-pinned to refresh (the fold-in paths update the
        # host-factor form; sharded models re-serve after a refit)
        if not self.sharded:
            self.user_dev = pin(
                self._cache, "user", self.model.user_factors_
            )
            self.item_dev = pin(
                self._cache, "item", self.model.item_factors_
            )

    def predict(self, users, items) -> np.ndarray:
        return self._request(
            len(np.atleast_1d(users)),
            lambda: self.model.predict(users, items),
        )

    def recommend_for_users(self, user_ids, num_items: int,
                            with_scores: bool = False):
        """Subset recommendation against the pinned item table (the
        bucketed request surface; ids validated by the model)."""
        from oap_mllib_tpu.serving import batcher

        user_ids = np.asarray(user_ids, np.int64)
        if self.sharded:
            # sharded layouts answer subset requests through the model
            # (factor gather is the model's documented collective)
            return self._request(
                len(user_ids),
                lambda: self.model.recommend_for_users(
                    user_ids, num_items, with_scores
                ),
            )

        def run():
            q = self.model.user_factors_[user_ids]
            ids, scores = batcher.topk_scores(
                q, self.item_dev, num_items, self.kind
            )
            return (ids, scores) if with_scores else ids

        return self._request(len(user_ids), run)

    def recommend_for_all_users(self, num_items: int,
                                with_scores: bool = False,
                                chunk_rows: int = 0):
        """Full-sweep top-k (serving/sweep.py): streamed + prefetched
        over the whole user base, factor-sharded when the model's live
        layout is — never materializing the quadratic score matrix."""
        from oap_mllib_tpu.serving import sweep

        n_users = (
            int(self.model._sharded_user[1][-1]) if self.sharded
            else int(self.model.user_factors_.shape[0])
        )
        return self._request(
            n_users,
            lambda: sweep.recommend_for_all_users(
                self.model, num_items, with_scores=with_scores,
                chunk_rows=chunk_rows, handle=self,
            ),
        )

    def _warm_one(self, rows: int) -> None:
        from oap_mllib_tpu.serving import batcher

        if self.sharded:
            return
        r = int(self.model.user_factors_.shape[1])
        batcher.topk_scores(
            np.zeros((rows, r), np.float32), self.item_dev, 1, self.kind
        )


def serve(model, key: Optional[str] = None) -> ServedModel:
    """Pin ``model`` on-device and return its serving handle.

    Keyed like the program cache: serving the SAME model object again
    returns the existing handle (weights stay pinned, nothing
    re-uploads); an explicit ``key`` names the entry so callers can
    address it across call sites.  Dispatch is structural (centers /
    components / factors), so compat-layer proxies serve too."""
    with _LOCK:
        reg_key = (key,) if key is not None else ("id", id(model))
        existing = _SERVED.get(reg_key)
        if existing is not None and existing.model is model:
            return existing
    if hasattr(model, "cluster_centers_"):
        handle: ServedModel = ServedKMeans(model)
    elif hasattr(model, "components_"):
        handle = ServedPCA(model)
    elif hasattr(model, "rank") and (
        getattr(model, "_sharded_user", None) is not None
        or getattr(model, "_user_factors", None) is not None
    ):
        handle = ServedALS(model)
    else:
        raise TypeError(
            f"cannot serve {type(model).__name__}: expected a fitted "
            "KMeansModel, PCAModel, or ALSModel surface"
        )
    with _LOCK:
        _SERVED[reg_key] = handle
        _tm.gauge(
            "oap_serve_models_pinned",
            help="Models currently pinned in the serving registry",
        ).set(len(_SERVED))
    return handle


def unserve(model_or_key) -> bool:
    """Drop a served model from the registry (its pinned buffers free
    with the handle).  Accepts the model object or the explicit key."""
    with _LOCK:
        for k in (("id", id(model_or_key)), (model_or_key,)):
            if k in _SERVED:
                del _SERVED[k]
                _tm.gauge("oap_serve_models_pinned").set(len(_SERVED))
                return True
    return False


def served_models() -> Dict[tuple, ServedModel]:
    with _LOCK:
        return dict(_SERVED)


def repin_model(model) -> int:
    """Re-pin every registry handle serving ``model`` (in-place delta
    commit — online/delta.py): each handle's device pins refresh from
    the model's current host arrays, its ``model_version`` bumps, and
    its staleness clock resets, WITHOUT evicting the handle (in-flight
    requests keep answering through it).  Returns the number of handles
    re-pinned (0 when the model is not served — commits on unserved
    models are free)."""
    with _LOCK:
        handles = [h for h in _SERVED.values() if h.model is model]
    for h in handles:
        h.repin()
    return len(handles)


def clear() -> None:
    """Tests: drop every handle (per-model pins die with them)."""
    global _queue_depth
    with _LOCK:
        _SERVED.clear()
        _tm.gauge("oap_serve_models_pinned").set(0)
    with _DEPTH_LOCK:
        _queue_depth = 0


def serving_summary() -> Dict[str, Any]:
    """The ``serving`` summary block: request/batch/pad totals plus
    p50/p99 latency estimated from the factor-4 log-bucket histogram
    (upper-bound bucket quantiles — telemetry/metrics.py)."""
    reqs = _tm.family_total("oap_serve_requests_total")
    block: Dict[str, Any] = {
        "models_pinned": len(_SERVED),
        "requests": int(reqs),
        "batches": int(_tm.family_total("oap_serve_batches_total")),
        "pad_rows": int(_tm.family_total("oap_serve_pad_rows_total")),
        "rows": int(_tm.family_total("oap_serve_rows_total")),
        "evictions": int(_tm.family_total("oap_serve_evictions_total")),
    }
    if reqs:
        p50, p99 = _latency_quantiles()
        block["latency_p50_s"] = p50
        block["latency_p99_s"] = p99
    with _LOCK:
        handles = list(_SERVED.values())
    if handles:
        # per-handle freshness: version + staleness (gauge refreshed on
        # the way out, so a summary/scrape always sees the current age)
        block["models"] = [
            {
                "kind": h.kind,
                "model_version": h.model_version,
                "staleness_seconds": round(h.touch_staleness(), 3),
                "requests": h.requests,
            }
            for h in handles
        ]
    with _DEPTH_LOCK:
        block["queue_depth"] = _queue_depth
    from oap_mllib_tpu.serving import traffic

    block.update(traffic.summary_block())
    return block


def _latency_quantiles() -> tuple:
    """(p50, p99) across every model kind's request-latency histogram —
    merged bucket-wise (same fixed bounds) then read via
    metrics.histogram_quantile."""
    reg = _tm.registry()
    merged: Optional[_tm.Histogram] = None
    with _tm._LOCK:
        series = [
            m for (name, _), m in reg._metrics.items()
            if name == "oap_serve_request_seconds"
        ]
    for h in series:
        if merged is None:
            merged = _tm.Histogram(h.bounds)
        for i, c in enumerate(h.counts):
            merged.counts[i] += c
        merged.sum += h.sum
        merged.count += h.count
    if merged is None or merged.count == 0:
        return (0.0, 0.0)
    return (
        _tm.histogram_quantile(merged, 0.50),
        _tm.histogram_quantile(merged, 0.99),
    )
