"""SLO / error-budget plane: multi-window burn rates over the request
ledger stream.

The reqtrace ledger (serving/reqtrace.py) says where each request's
wall went; this module says whether the fleet is keeping its promise.
Armed by ``Config.serve_slo_p99_ms`` > 0, every finalized ledger feeds
one observation — a request is **bad** when it failed/shed or its wall
exceeded the p99 target — into a pair of sliding windows (the SRE
fast/slow multi-window pattern: the slow window is
``Config.serve_slo_window_s``, the fast window is slow/12, the classic
5m/1h pairing), and the engine maintains:

- **burn rate** per window: (bad fraction) / (1 - ``Config.serve_slo_
  availability``) — 1.0 means bad requests arrive exactly at the rate
  that exhausts the error budget over the window; >1 burns faster;
- **error budget remaining** over the slow window: 1 - consumed,
  floored at 0;
- a **breach** flag when BOTH windows burn above 1.0 (the
  multi-window page condition: fast alone is noise, slow alone is
  stale).

All of it is OBSERVE-ONLY state: live gauges
(``oap_slo_burn_rate{window=}``, ``oap_slo_error_budget_remaining``),
an ``slo`` block in ``serving_summary()``, the ``/sloz`` endpoint next
to ``/metrics`` (telemetry/fleet.py), and a ``brief()`` dict that the
ScaleController and BrownoutController RECORD with every decision —
the decisions stay where they are (queue-depth/pressure trends); the
SLO state that justified them becomes part of the record.

The engine clock is injectable (tests drive windows deterministically)
and the singleton rebuilds when the SLO knobs change (the
traffic.brownout() pattern).  Disarmed, ``observe_request`` is one
config check.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

_LOCK = locktrace.TrackedLock("serving.slo")
_ENGINE: Optional["SLOEngine"] = None

# the fast window is the slow window / 12 — the SRE 5m-against-1h
# multi-window ratio
FAST_DIVISOR = 12.0


def slo_cfg(cfg=None) -> Dict[str, float]:
    """Validated SLO knobs — a typo raises when the engine is
    consulted, not after a day of silent mis-accounting (the
    kmeans_kernel/fault_spec contract)."""
    cfg = cfg or get_config()
    p99_ms = float(cfg.serve_slo_p99_ms)
    if p99_ms < 0:
        raise ValueError(
            f"serve_slo_p99_ms must be >= 0 (0 = SLO engine off), got "
            f"{p99_ms}"
        )
    availability = float(cfg.serve_slo_availability)
    if not 0.0 < availability < 1.0:
        raise ValueError(
            f"serve_slo_availability must be in (0, 1), got "
            f"{availability}"
        )
    window_s = float(cfg.serve_slo_window_s)
    if window_s <= 0:
        raise ValueError(
            f"serve_slo_window_s must be > 0, got {window_s}"
        )
    return {
        "p99_ms": p99_ms,
        "availability": availability,
        "window_s": window_s,
    }


def armed() -> bool:
    """One config check — the off-path cost at ledger finalization."""
    return get_config().serve_slo_p99_ms > 0


class SLOEngine:
    """Sliding-window burn-rate accounting over (t, good) samples.

    Windows prune lazily on observe/read; memory is bounded by the
    request rate times the slow window (each sample is one tuple).
    All mutation runs under the engine lock — ledger finalization on
    the dispatcher thread races scrapes from the /sloz handler."""

    def __init__(self, p99_ms: float, availability: float,
                 window_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.p99_ms = float(p99_ms)
        self.availability = float(availability)
        self.window_s = float(window_s)
        self.fast_window_s = self.window_s / FAST_DIVISOR
        self.budget_frac = 1.0 - self.availability
        self._clock = clock
        self._lock = locktrace.TrackedLock("serving.slo.engine")
        self._samples: deque = deque()  # (t, bad) — slow-window pruned
        self.total = 0
        self.bad = 0

    # -- ingestion ------------------------------------------------------------

    def observe(self, wall_s: float, ok: bool,
                t: Optional[float] = None) -> None:
        """Fold one finished request: bad when it failed OR blew the
        p99 target."""
        now = self._clock() if t is None else float(t)
        bad = (not ok) or (
            self.p99_ms > 0 and float(wall_s) * 1e3 > self.p99_ms
        )
        with self._lock:
            self._samples.append((now, bad))
            self.total += 1
            if bad:
                self.bad += 1
            self._prune(now)
        self._gauges(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        samples = self._samples
        while samples and samples[0][0] < cutoff:
            samples.popleft()

    # -- reads ----------------------------------------------------------------

    def _window_counts(self, now: float, window_s: float) -> tuple:
        cutoff = now - window_s
        total = bad = 0
        for t, b in self._samples:
            if t >= cutoff:
                total += 1
                if b:
                    bad += 1
        return total, bad

    def burn_rate(self, window_s: float,
                  t: Optional[float] = None) -> float:
        """(bad fraction over the window) / (error-budget fraction)."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._prune(now)
            total, bad = self._window_counts(now, window_s)
        if total == 0:
            return 0.0
        return (bad / total) / self.budget_frac

    def budget_remaining(self, t: Optional[float] = None) -> float:
        """Fraction of the slow window's error budget left, floored at
        0: 1 - bad / (total * budget_frac)."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            self._prune(now)
            total, bad = self._window_counts(now, self.window_s)
        if total == 0:
            return 1.0
        allowed = total * self.budget_frac
        return max(0.0, 1.0 - bad / allowed) if allowed > 0 else 0.0

    def state(self, t: Optional[float] = None) -> Dict[str, Any]:
        """The full ``/sloz`` / summary payload."""
        now = self._clock() if t is None else float(t)
        fast = self.burn_rate(self.fast_window_s, t=now)
        slow = self.burn_rate(self.window_s, t=now)
        with self._lock:
            total, bad = self._window_counts(now, self.window_s)
            lifetime_total, lifetime_bad = self.total, self.bad
        return {
            "armed": True,
            "p99_ms": self.p99_ms,
            "availability": self.availability,
            "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "requests": total,
            "bad": bad,
            "lifetime_requests": lifetime_total,
            "lifetime_bad": lifetime_bad,
            "burn_rate_fast": round(fast, 4),
            "burn_rate_slow": round(slow, 4),
            "error_budget_remaining": round(
                self.budget_remaining(t=now), 4
            ),
            # the multi-window page condition: both windows burning
            # above 1.0 — fast alone is a blip, slow alone is history
            "breach": fast > 1.0 and slow > 1.0,
        }

    def brief(self, t: Optional[float] = None) -> Dict[str, Any]:
        """The compact dict scale/brownout decisions RECORD (observe-
        only: the SLO never makes the decision, it witnesses it)."""
        s = self.state(t=t)
        return {
            "burn_rate_fast": s["burn_rate_fast"],
            "burn_rate_slow": s["burn_rate_slow"],
            "error_budget_remaining": s["error_budget_remaining"],
            "breach": s["breach"],
        }

    def _gauges(self, now: float) -> None:
        _tm.gauge(
            "oap_slo_burn_rate", {"window": "fast"},
            help="Error-budget burn rate (bad fraction / budget "
                 "fraction) over the fast window",
        ).set(self.burn_rate(self.fast_window_s, t=now))
        _tm.gauge(
            "oap_slo_burn_rate", {"window": "slow"},
            help="Error-budget burn rate over the slow window "
                 "(serve_slo_window_s)",
        ).set(self.burn_rate(self.window_s, t=now))
        _tm.gauge(
            "oap_slo_error_budget_remaining",
            help="Fraction of the slow window's error budget left "
                 "(0 = exhausted)",
        ).set(self.budget_remaining(t=now))


def engine() -> Optional[SLOEngine]:
    """The process-wide engine, (re)built when the SLO knobs change
    (the traffic.brownout() singleton pattern); None when disarmed."""
    global _ENGINE
    if not armed():
        return None
    knobs = slo_cfg()
    key = (knobs["p99_ms"], knobs["availability"], knobs["window_s"])
    with _LOCK:
        e = _ENGINE
        if (e is None or (e.p99_ms, e.availability, e.window_s) != key):
            e = SLOEngine(*key)
            _ENGINE = e
    return e


def observe_request(wall_s: float, ok: bool,
                    t: Optional[float] = None) -> None:
    """Ledger-finalization hook (serving/reqtrace.finalize): one
    config check when disarmed."""
    e = engine()
    if e is not None:
        e.observe(wall_s, ok, t=t)


def brief() -> Dict[str, Any]:
    """The decision-record dict ({} when disarmed) — what every
    scale/brownout decision stores as its witnessed SLO state."""
    e = engine()
    return e.brief() if e is not None else {}


def state() -> Dict[str, Any]:
    """The ``/sloz`` payload ({"armed": False} when disarmed)."""
    e = engine()
    return e.state() if e is not None else {"armed": False}


def summary_block() -> Dict[str, Any]:
    """The ``serving_summary()["slo"]`` block ({} when disarmed)."""
    e = engine()
    return e.state() if e is not None else {}


# package-level spelling (serving.slo_state — "state" alone is too
# generic to re-export)
slo_state = state


def _reset_for_tests() -> None:
    global _ENGINE
    with _LOCK:
        _ENGINE = None
