"""Request-lifecycle tracing: the deadline-budget ledger every admitted
request carries through the traffic plane.

The traffic plane (ISSUE 13/16/18) exposes only aggregates — an
operator can watch ``oap_serve_shed_total`` rise or p99 drift, but
cannot answer "where did THIS request's deadline go?".  This module is
the per-request answer: when ``Config.serve_trace_sample`` > 0, every
ADMITTED request gets a :class:`TraceContext` (deterministic id — no
RNG — plus a sampled flag from a pure hash of that id) and a
:class:`Ledger` that rides the request's future through its whole
lifecycle, recording a FIXED-schema stage breakdown:

========== ==================================================
stage       what the wall covers
========== ==================================================
admission   ``submit`` entry -> admitted (pricing, brownout,
            queue checks under the admission lock)
queue_wait  admitted -> popped by a dispatch cycle (includes
            retry backoff waits — requeues re-enter here)
batch_form  popped -> its coalesced group's scoring call
            begins (shed triage, deadline sort, group slicing)
bucket_pad  inside the flush: rounding the joined batch onto
            its geometric bucket (batcher.bucket_batch wall)
compile     inside the flush: XLA backend compile wall
            attributed to the flush (progcache ground truth —
            zero in the warmed steady state)
execute     inside the flush: the remainder of the scoring
            call (staging + device execute + fetch)
dispatch    scoring returned -> future resolved (result
            split, landing)
========== ==================================================

The stages sum to the measured request wall BY CONSTRUCTION: every
boundary cut accumulates the full interval since the previous cut
(:meth:`Ledger.cut`), and the within-flush split
(:meth:`Ledger.cut_flush`) clamps its parts to the flush interval.
Lifecycle events that are not stages — retry/requeue, poison
quarantine, brownout rung steps, drain, shed, per-hop ring-sweep
rotations — append to the ledger's event list (and the flight
recorder) instead.

Where the ledger lands:

- attached to the answered/failed future (:func:`ledger_of`);
- ``oap_serve_stage_seconds{stage=}`` histograms (with trace-id
  exemplars on sampled requests — telemetry/metrics.py);
- ``serving_summary()["attribution"]`` (p50/p99 per stage + the
  stage-sum vs request-wall coverage ratio);
- flight-recorder ``request`` events + JSONL ``type: "request"``
  records for SAMPLED requests — dev/oaptrace.py merges them into
  Perfetto request flows (one lane per replica, ring-hop arrows);
- the SLO engine (serving/slo.py) observes every finalized ledger.

Disarmed (``serve_trace_sample == 0``, the default) the whole plane is
one config check per submit — ``begin()`` returns None and every other
hook is a None/thread-local-miss check (dev/slo_gate.py bounds the
seam at <1% of the serving microbench).
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Dict, List, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

# the fixed stage schema, in lifecycle order (the JSONL record, the
# attribution block, and the oaptrace lanes all render this order)
STAGES = (
    "admission",
    "queue_wait",
    "batch_form",
    "bucket_pad",
    "compile",
    "execute",
    "dispatch",
)

# terminal outcomes a ledger finalizes with
OUTCOMES = ("answered", "shed", "failed", "cancelled")

_STATE_LOCK = locktrace.TrackedLock("serving.reqtrace")
_wall_sum = 0.0   # finalized request walls (coverage denominator)
_stage_sum = 0.0  # finalized stage sums (coverage numerator)
_finalized = 0

_tls = threading.local()


def trace_sample_cfg(cfg=None) -> float:
    """Validated ``Config.serve_trace_sample`` — out of [0, 1] must
    raise, not silently disarm (the kmeans_kernel/fault_spec
    contract)."""
    cfg = cfg or get_config()
    sample = float(cfg.serve_trace_sample)
    if not 0.0 <= sample <= 1.0:
        raise ValueError(
            f"serve_trace_sample must be in [0, 1] (0 = tracing off), "
            f"got {sample}"
        )
    return sample


def armed() -> bool:
    """One config check — the off-path cost at the submit seam."""
    return get_config().serve_trace_sample != 0


def is_sampled(trace_id: str, sample: float) -> bool:
    """Deterministic sampling decision: a pure hash of the trace id
    against the sampling fraction — NO RNG, so every process of a
    world (and every rerun) samples the same ids."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 2**32 < sample


def make_trace_id(rank: int, seq: int) -> str:
    """Deterministic per-request id: rank + admission seq (unique
    within a process lifetime, stable across reruns of a deterministic
    storm)."""
    return f"{rank:02x}-{seq:08x}"


class TraceContext:
    """The identity half of a traced request: who it is (trace id,
    rank, admission seq), what it promised (deadline), and whether the
    heavy emission paths fire for it (``sampled``)."""

    __slots__ = ("trace_id", "rank", "seq", "deadline_ms", "sampled")

    def __init__(self, rank: int, seq: int, deadline_ms: float,
                 sample: float):
        self.rank = int(rank)
        self.seq = int(seq)
        self.deadline_ms = float(deadline_ms)
        self.trace_id = make_trace_id(self.rank, self.seq)
        self.sampled = is_sampled(self.trace_id, sample)


class Ledger:
    """The budget half: where this request's wall went, stage by
    stage, plus the lifecycle events that are not stages.

    All stamps use the OWNING QUEUE's clock (injectable — fake-clock
    tests stay deterministic); ``cut`` accumulates the full interval
    since the previous boundary into one stage, so the stages sum to
    ``t_end - t0`` exactly, retries and all."""

    __slots__ = ("ctx", "t0", "stages", "events", "outcome", "model",
                 "wall_s", "retries", "_last")

    def __init__(self, ctx: TraceContext, t0: float):
        self.ctx = ctx
        self.t0 = float(t0)
        self.stages: Dict[str, float] = {s: 0.0 for s in STAGES}
        self.events: List[Dict[str, Any]] = []
        self.outcome = ""
        self.model = ""
        self.wall_s = 0.0
        self.retries = 0
        self._last = float(t0)

    def cut(self, stage: str, now: float) -> None:
        """Close the interval since the last boundary into ``stage``."""
        self.stages[stage] += max(0.0, float(now) - self._last)
        self._last = float(now)

    def cut_flush(self, now: float, pad_s: float, compile_s: float) -> None:
        """Close the scoring-flush interval, split three ways: bucket
        padding (measured in the batcher), XLA compile (the progcache
        ground-truth delta across the flush), execute (the remainder).
        Parts are clamped to the interval so the ledger's sum-to-wall
        invariant survives measurement skew (or a fake clock)."""
        flush = max(0.0, float(now) - self._last)
        pad = min(max(0.0, float(pad_s)), flush)
        comp = min(max(0.0, float(compile_s)), flush - pad)
        self.stages["bucket_pad"] += pad
        self.stages["compile"] += comp
        self.stages["execute"] += flush - pad - comp
        self._last = float(now)

    def event(self, kind: str, detail: str, t: float) -> None:
        """Append one non-stage lifecycle event (retry, poison,
        brownout, drain, shed, ring_hop, ...)."""
        self.events.append(
            {"kind": str(kind), "t": float(t), "detail": str(detail)}
        )

    def stage_sum(self) -> float:
        return sum(self.stages.values())

    def as_record(self) -> Dict[str, Any]:
        """The JSONL ``type: "request"`` payload (rank-tagged by the
        sink caller)."""
        return {
            "trace_id": self.ctx.trace_id,
            "seq": self.ctx.seq,
            "rank": self.ctx.rank,
            "deadline_ms": self.ctx.deadline_ms,
            "sampled": self.ctx.sampled,
            "t0": self.t0,
            "wall_s": self.wall_s,
            "outcome": self.outcome,
            "model": self.model,
            "retries": self.retries,
            "stages": {s: self.stages[s] for s in STAGES},
            "events": list(self.events),
        }


def begin(queue_clock_now: float, rank: int, seq: int,
          deadline_ms: float) -> Optional[Ledger]:
    """Open a ledger for one admission attempt, or None when tracing
    is disarmed (the one-config-check off path)."""
    sample = trace_sample_cfg()
    if sample == 0.0:
        return None
    ctx = TraceContext(rank, seq, deadline_ms, sample)
    return Ledger(ctx, queue_clock_now)


def finalize(ledger: Optional[Ledger], outcome: str, now: float,
             model: str = "") -> None:
    """Close a ledger: stamp the outcome and wall, book the per-stage
    histograms (+ exemplars when sampled), feed the SLO engine, and —
    for SAMPLED requests — emit the flight-recorder request event and
    the JSONL ``request`` record.  Idempotent: a ledger finalizes
    exactly once (the future-resolution race goes to whoever lands the
    future)."""
    if ledger is None:
        return
    if ledger.outcome:
        return
    ledger.outcome = outcome if outcome in OUTCOMES else "failed"
    if model:
        ledger.model = model
    # close any open interval into dispatch: the final boundary is the
    # future landing, whatever path got here
    ledger.cut("dispatch", now)
    ledger.wall_s = max(0.0, float(now) - ledger.t0)
    exemplar = (
        {"trace_id": ledger.ctx.trace_id} if ledger.ctx.sampled else None
    )
    for stage in STAGES:
        v = ledger.stages[stage]
        if v > 0.0 or stage in ("queue_wait", "execute"):
            _tm.histogram(
                "oap_serve_stage_seconds", {"stage": stage},
                help="Per-request wall attributed to each traffic-plane "
                     "lifecycle stage (serving/reqtrace.py; stages sum "
                     "to the request wall)",
            ).observe(v, exemplar=exemplar)
    global _wall_sum, _stage_sum, _finalized
    with _STATE_LOCK:
        _wall_sum += ledger.wall_s
        _stage_sum += ledger.stage_sum()
        _finalized += 1
    _tm.counter(
        "oap_serve_traced_total", {"outcome": ledger.outcome},
        help="Traced requests finalized, by outcome",
    ).inc()
    from oap_mllib_tpu.serving import slo

    slo.observe_request(
        ledger.wall_s, ok=ledger.outcome == "answered", t=now
    )
    if ledger.ctx.sampled:
        from oap_mllib_tpu.telemetry import flightrec

        flightrec.record(
            "request", ledger.ctx.trace_id,
            f"outcome={ledger.outcome} wall_ms="
            f"{ledger.wall_s * 1e3:.3f} retries={ledger.retries}",
        )
        _emit_request_record(ledger)


def _emit_request_record(ledger: Ledger) -> None:
    """Append one JSONL ``type: "request"`` record to the telemetry
    sink (no-op when ``Config.telemetry_log`` is off).  Request ``t0``
    and flight-recorder event times share the monotonic clock family,
    so dev/oaptrace.py can lay both on one timeline."""
    from oap_mllib_tpu.telemetry import export

    export.emit_requests([ledger.as_record()])


# -- thread-local attach: flush-internal notes + ring-hop fan-in --------------


class attach:
    """Context manager binding the ledgers of an in-flight coalesced
    flush to the scoring thread, so seams BELOW the traffic plane
    (batcher pad timing, sharded-sweep ring hops) can fold into them
    without plumbing arguments through ``predict_many``."""

    def __init__(self, ledgers: List[Ledger]):
        self._ledgers = [lg for lg in ledgers if lg is not None]

    def __enter__(self):
        _tls.ledgers = self._ledgers
        _tls.flush = {}
        return self

    def __exit__(self, *exc) -> None:
        _tls.ledgers = None
        _tls.flush = None

    def flush_notes(self) -> Dict[str, float]:
        return dict(getattr(_tls, "flush", None) or {})


def current_ledgers() -> List[Ledger]:
    """The ledgers attached to this thread's in-flight flush ([] when
    none — the common, un-traced case)."""
    return list(getattr(_tls, "ledgers", None) or [])


def exemplar_trace_id() -> Optional[str]:
    """A sampled trace id from the attached flush (the exemplar the
    request-latency histogram pins to its bucket), or None."""
    for lg in getattr(_tls, "ledgers", None) or ():
        if lg.ctx.sampled:
            return lg.ctx.trace_id
    return None


def note_flush(stage: str, seconds: float) -> None:
    """Accumulate a within-flush measurement (today: ``bucket_pad``
    from batcher.bucket_batch) into the attached flush's note dict.
    A thread-local miss when no traced flush is in flight — the
    disarmed seam."""
    acc = getattr(_tls, "flush", None)
    if acc is not None:
        acc[stage] = acc.get(stage, 0.0) + float(seconds)


def note_event(kind: str, detail: str, t: float) -> None:
    """Append a lifecycle event to every attached ledger (ring-hop
    rotations from serving/sweep.py ride this)."""
    for lg in getattr(_tls, "ledgers", None) or ():
        lg.event(kind, detail, t)


# -- attribution rollup --------------------------------------------------------


def stage_quantiles() -> Dict[str, Dict[str, float]]:
    """Per-stage p50/p99 from the ``oap_serve_stage_seconds``
    histograms (upper-bound bucket estimates, the
    registry._latency_quantiles convention)."""
    reg = _tm.registry()
    out: Dict[str, Dict[str, float]] = {}
    with _tm._LOCK:
        series = [
            (dict(labels).get("stage", ""), m)
            for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_stage_seconds"
        ]
    for stage, h in series:
        if h.count == 0:
            continue
        out[stage] = {
            "p50_s": _tm.histogram_quantile(h, 0.50),
            "p99_s": _tm.histogram_quantile(h, 0.99),
            "count": int(h.count),
            "sum_s": round(float(h.sum), 6),
        }
    return out


def attribution_block() -> Dict[str, Any]:
    """The ``serving_summary()["attribution"]`` block: per-stage
    p50/p99 plus the stage-sum vs request-wall coverage ratio (1.0 by
    construction — the slo_gate contract asserts the 5% tolerance on
    per-request ledgers).  {} when nothing was traced."""
    with _STATE_LOCK:
        finalized, wall, stages = _finalized, _wall_sum, _stage_sum
    if finalized == 0:
        return {}
    return {
        "traced": finalized,
        "wall_s": round(wall, 6),
        "stage_s": round(stages, 6),
        "coverage": round(stages / wall, 4) if wall > 0 else 1.0,
        "stages": stage_quantiles(),
    }


def ledger_of(future) -> Optional[Ledger]:
    """The ledger attached to an answered/failed traffic future, or
    None (tracing disarmed when the request was admitted)."""
    return getattr(future, "ledger", None)


def _reset_for_tests() -> None:
    global _wall_sum, _stage_sum, _finalized
    with _STATE_LOCK:
        _wall_sum = 0.0
        _stage_sum = 0.0
        _finalized = 0
    _tls.ledgers = None
    _tls.flush = None
