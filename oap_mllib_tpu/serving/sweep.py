"""Full-sweep top-k at scale: streamed, prefetched, factor-sharded.

``recommend_for_all_users`` over 10M+ users is the serving plane's
batch workload: one (n_users, r) x (r, n_items) scoring sweep whose
NAIVE form materializes the quadratic (n_users, n_items) score matrix
(40 TB at 10M x 1M).  This module keeps every form of the sweep inside
the chunked-top-k contract the models established
(``models/als.py _top_k_scores``; the reference blockifies its
recommendForAll the same way, ALS.scala:383-401) and composes it with
the platform's scale machinery:

- **Streamed sweep** (host-factor models): the user table walks through
  the prefetch pipeline (``data/prefetch.py``) in bucketed row chunks —
  chunk N+1 stages/uploads while chunk N's top-k executes — against the
  PINNED item table; results land in a preallocated (n_users, k)
  output, so host memory is O(output + chunk) however large the user
  base (``Config.sweep_chunk_rows`` overrides the live-buffer-budget
  chunk width).
- **Factor-sharded ring sweep** (block-sharded fits): the model serves
  from its LIVE device layout — no host gather.  Each rank keeps its
  user block; item blocks rotate around the mesh ring (the PR 9 ring
  schedule: ``collective.ppermute`` steps, partial results stay put)
  while each rank folds a running top-k.  The cross-block merge is an
  EXACT lexicographic (score desc, global id asc) two-key sort, so the
  sharded sweep matches the single-device reference's ``lax.top_k``
  tie-breaking bit-for-bit on the id side.
- :func:`shard_factors` places a host factor table onto the live mesh
  block layout through ``parallel/shuffle.reshard_factor_rows`` (the
  elastic-worlds redistribution pass) — serving a loaded model sharded
  without any rank ever holding peers' rows.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import progcache

# row chunk inside the sharded per-rank programs: bounds the live
# (chunk, items_block) score buffer like rows_per_chunk does for the
# streamed sweep
_SHARD_ROW_CHUNK = 4096


def _sweep_chunk_rows(n_targets: int, r: int) -> int:
    """Rows per sweep chunk: ``Config.sweep_chunk_rows`` when set
    (> 0), else the shared scoring live-buffer budget
    (ops/kmeans_ops.rows_per_chunk over the score block + the query
    chunk — the models' chunked top-k uses the same bound).  Negative
    values raise (the kmeans_kernel contract)."""
    from oap_mllib_tpu.ops.kmeans_ops import rows_per_chunk

    cfg_rows = int(get_config().sweep_chunk_rows)
    if cfg_rows < 0:
        raise ValueError(
            f"sweep_chunk_rows must be >= 0, got {cfg_rows}"
        )
    return cfg_rows or rows_per_chunk(n_targets, r)


def recommend_for_all_users(model, num_items: int, *,
                            with_scores: bool = False, chunk_rows: int = 0,
                            handle=None, reform=None):
    """Top-``num_items`` item ids (and optionally scores) for EVERY
    user — the serving-plane sweep.  Sharded fits sweep their live
    factor layout; host-factor models run the streamed chunked sweep.
    Results match ``model.recommend_for_all_users`` exactly.

    ``reform`` is the eviction-failover hook (the durable-future
    contract for in-flight sharded work): when a replica dies
    mid-sweep — a recovery-plane error (``CollectiveTimeoutError`` /
    ``PeerAbortError``), or the pre-launch eviction check refusing a
    mesh that spans a dead peer — ``reform(exc)`` must return a
    REPLACEMENT model on the survivors' live layout (e.g. re-shard the
    host factor tables across local devices with
    :func:`shard_factors_local`); the sweep then re-runs ONCE on it
    (``oap_serve_sweep_reforms_total`` booked).  Without a hook the
    sweep fails loudly: ``traffic.ServeError(reason="eviction")``
    naming the culprit crash record(s) on the sideband."""
    from oap_mllib_tpu.utils import recovery

    if num_items < 0:
        raise ValueError(f"top-k count must be >= 0, got {num_items}")
    if getattr(model, "_sharded_user", None) is not None:
        try:
            ids, scores = _sweep_sharded(
                model, int(num_items), with_scores
            )
        except recovery.RecoveryError as exc:
            if reform is None:
                from oap_mllib_tpu.serving import traffic

                raise traffic.ServeError(
                    "eviction",
                    "sharded sweep lost a replica mid-flight and no "
                    "reform hook was provided; the mesh spans a dead "
                    "peer",
                    crash_records=recovery.list_crash_records(
                        str(get_config().crash_dir or "")
                    ),
                    cause=exc,
                ) from exc
            _tm.counter(
                "oap_serve_sweep_reforms_total",
                help="Sharded sweeps re-formed on the survivors' "
                     "layout after a replica eviction",
            ).inc()
            new_model = reform(exc)
            return recommend_for_all_users(
                new_model, num_items, with_scores=with_scores,
                chunk_rows=chunk_rows, handle=handle, reform=None,
            )
    else:
        ids, scores = sweep_streamed(
            model.user_factors_, _pinned_targets(model, handle),
            int(num_items), with_scores=with_scores,
            chunk_rows=chunk_rows,
        )
    return (ids, scores) if with_scores else ids


def _pinned_targets(model, handle):
    """The pinned device item table: through the serving handle's pin
    when one exists, else a model-cache pin (both identity-keyed — the
    table uploads once per model lifetime either way)."""
    if handle is not None and getattr(handle, "item_dev", None) is not None:
        return handle.item_dev
    from oap_mllib_tpu.serving.registry import pin

    cache = getattr(model, "_dev_cache", None)
    if cache is None:
        cache = model._dev_cache = {}
    # allow_stale: at the brownout ladder's stale rung a refit-in-
    # flight answers from the previous pin instead of blocking
    return pin(cache, "targets:item", model.item_factors_,
               allow_stale=True)


# -- streamed (host-factor) sweep --------------------------------------------


def sweep_streamed(query: np.ndarray, targets_dev, n: int, *,
                   with_scores: bool = False, chunk_rows: int = 0,
                   kind: str = "als") -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Chunked, prefetch-pipelined top-``n`` of ``query @ targets.T``
    per query row.  The user table streams through the prefetch
    pipeline in bucketed fixed-width chunks (two compiled shapes: the
    full chunk and the tail's bucket) while the device folds top-k per
    chunk — the (n_query, n_targets) score matrix never exists, host
    memory is the preallocated (n_query, n) output plus O(chunk)."""
    import jax

    from oap_mllib_tpu.data.bucketing import bucket_rows
    from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats
    from oap_mllib_tpu.serving import batcher

    query = np.ascontiguousarray(np.asarray(query, np.float32))
    m = int(targets_dev.shape[0])
    n = min(int(n), m)
    n_query = query.shape[0]
    out_ids = np.empty((n_query, n), np.int32)
    out_scores = np.empty((n_query, n), np.float32) if with_scores else None
    if n_query == 0 or n == 0:
        return out_ids[:, :n], out_scores
    rows = int(chunk_rows) or _sweep_chunk_rows(m, query.shape[1])
    rows = max(1, min(rows, n_query))

    def staged_chunks():
        for lo in range(0, n_query, rows):
            chunk = query[lo : lo + rows]
            nv = chunk.shape[0]
            if nv < rows:
                # the tail rounds onto its own bucket — at most one
                # extra compiled shape however the sweep is sized
                pad = bucket_rows(nv, batcher.SERVE_ROW_MULTIPLE) - nv
                chunk = np.concatenate(
                    [chunk, np.zeros((pad, chunk.shape[1]), chunk.dtype)]
                )
            yield lo, nv, chunk

    stats = PrefetchStats()

    def stage(item):
        lo, nv, chunk = item
        return lo, nv, batcher.stage(chunk)

    with Prefetcher(staged_chunks(), stage, stats=stats) as pf:
        for lo, nv, chunk_dev in pf:
            s, i = batcher.topk_pairs(chunk_dev, targets_dev, n, kind=kind)
            out_ids[lo : lo + nv] = jax.device_get(i)[:nv]
            if with_scores:
                out_scores[lo : lo + nv] = jax.device_get(s)[:nv]
    _tm.counter(
        "oap_serve_sweep_rows_total", {"model": kind},
        help="Query rows swept by full-sweep top-k",
    ).inc(n_query)
    return out_ids, out_scores


# -- factor-sharded ring sweep ------------------------------------------------


def _ring_steps(world: int):
    """The ring rotation schedule: each step every rank hands its item
    block to the PREVIOUS rank, so after t steps rank b holds block
    (b + t) mod world — the PR 9 ring-reduction walk with top-k merges
    in place of segment sums."""
    return [(i, (i - 1) % world) for i in range(world)]


def _build_sharded_sweep(mesh, axis: str, upb: int, n: int, world: int,
                         item_sharded: bool, ipb: int, policy: str,
                         tier: str, row_chunk: int):
    """Compiled per-rank sweep program (registry-cached by the caller).

    Per rank: fold top-``n`` of this rank's user block against every
    item block.  Item-sharded models rotate the blocks around the mesh
    ring; replicated items fold the one full table.  The merge is the
    exact lexicographic (-score, id) two-key sort, so sharded results
    match the single-device ``lax.top_k`` (ties -> lowest global id)."""
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.parallel import collective
    from oap_mllib_tpu.utils import precision as psn
    from oap_mllib_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    steps = world if item_sharded else 1
    # pad the user block to a whole number of row chunks (static); the
    # pad rows' results are garbage and never leave the valid slice
    chunk = max(1, min(row_chunk, upb))
    n_chunks = -(-upb // chunk)
    pad_rows = n_chunks * chunk - upb

    def merge(best_s, best_i, cand_s, cand_i):
        s = jnp.concatenate([best_s, cand_s], axis=1)
        i = jnp.concatenate([best_i, cand_i], axis=1)
        # exact global tie-breaking: ascending (-score, id) two-key sort
        # == descending score, lowest global id first among equals —
        # lax.top_k's documented tie rule on the unsharded reference
        neg_s, i_sorted = jax.lax.sort((-s, i), dimension=1, num_keys=2)
        return -neg_s[:, :n], i_sorted[:, :n]

    def block_topk(x_rows, y_blk, id_lo, valid):
        """Top-n of one user-row chunk against the currently held item
        block; padded item rows sort last (score -inf, id int32 max)."""
        scores = psn.pdot(x_rows, y_blk.T, policy, tier)
        local = jnp.arange(y_blk.shape[0], dtype=jnp.int32)
        ok = local < valid
        scores = jnp.where(ok[None, :], scores, -jnp.inf)
        gids = jnp.where(ok, id_lo + local, jnp.int32(2**31 - 1))
        kc = min(n, y_blk.shape[0])
        s, li = jax.lax.top_k(scores, kc)
        return s, jnp.take(gids, li)

    def rank_program(x_blk, y0, offsets):
        b = jax.lax.axis_index(axis)
        xp = jnp.concatenate(
            [x_blk, jnp.zeros((pad_rows, x_blk.shape[1]), x_blk.dtype)]
        ) if pad_rows else x_blk
        xc = xp.reshape(n_chunks, chunk, x_blk.shape[1])
        best_s = jnp.full((n_chunks, chunk, n), -jnp.inf, jnp.float32)
        best_i = jnp.full((n_chunks, chunk, n), 2**31 - 1, jnp.int32)
        y = y0
        for t in range(steps):
            if item_sharded:
                cur = jax.lax.rem(b + t, world)
                id_lo = offsets[cur]
                valid = offsets[cur + 1] - id_lo
            else:
                id_lo = jnp.int32(0)
                valid = jnp.int32(y0.shape[0])

            def scan_body(c, xs, y=y, id_lo=id_lo, valid=valid):
                bs, bi, xi = xs
                cs, ci = block_topk(xi, y, id_lo, valid)
                return c, merge(bs, bi, cs, ci)

            _, (best_s, best_i) = jax.lax.scan(
                scan_body, None, (best_s, best_i, xc)
            )
            if item_sharded and t + 1 < steps:
                y = collective.ppermute(y, axis, _ring_steps(world))
        out_s = best_s.reshape(n_chunks * chunk, n)[:upb]
        out_i = best_i.reshape(n_chunks * chunk, n)[:upb]
        return out_s, out_i

    y_spec = P(axis, None) if item_sharded else P()
    return jax.jit(
        shard_map(
            rank_program, mesh=mesh,
            in_specs=(P(axis, None), y_spec, P()),
            out_specs=(P(axis, None), P(axis, None)),
            check_vma=False,
        )
    )


def _sweep_sharded(model, n: int, with_scores: bool):
    """Serve the sweep straight from a block-sharded fit's live layout:
    per-rank fold + ring-rotated item blocks, then one replicated
    fetch of the (world*upb, n) RESULT (k ids per user — not the factor
    tables, which never gather)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    xb, offsets_u, upb = model._sharded_user
    mesh = xb.sharding.mesh
    cfg = get_config()
    axis = cfg.data_axis
    if not xb.is_fully_addressable:
        from oap_mllib_tpu.serving import ha
        from oap_mllib_tpu.utils import recovery as _rec

        if ha.fleet_evicted():
            # the mesh spans an evicted peer: an XLA collective on it
            # would hang with no watchdog — refuse BEFORE launch so the
            # caller's reform hook re-plans on the survivors' layout
            raise _rec.PeerAbortError(
                "sharded sweep refused: the factor mesh spans an "
                "evicted replica (fleet is local-only); re-form the "
                "shards on the survivors before sweeping"
            )
    world = mesh.shape[axis]
    pol = _serving_policy_als()
    item_sharded = model._sharded_item is not None
    repl = NamedSharding(mesh, P())
    if item_sharded:
        yb, offsets_i, ipb = model._sharded_item
        m = int(np.asarray(offsets_i)[-1])
        offs_dev = jax.device_put(
            np.asarray(offsets_i, np.int32), repl
        )
    else:
        from oap_mllib_tpu.serving.registry import pin

        cache = getattr(model, "_dev_cache", None)
        if cache is None:
            cache = model._dev_cache = {}
        y_host = model.item_factors_
        m = int(y_host.shape[0])
        yb = pin(cache, "targets:item", y_host)
        ipb = m
        offs_dev = jax.device_put(
            np.zeros((world + 1,), np.int32), repl
        )
    n = min(int(n), m)
    if n == 0:
        n_users = int(np.asarray(offsets_u)[-1])
        return (np.zeros((n_users, 0), np.int32),
                np.zeros((n_users, 0), np.float32) if with_scores else None)
    fn = progcache.get_or_build(
        "serve.sweep_sharded",
        (progcache.mesh_fingerprint(mesh), axis, int(upb), int(ipb),
         int(n), bool(item_sharded), pol.name, pol.dot_tier,
         progcache.array_key(xb, yb)),
        lambda: _build_sharded_sweep(
            mesh, axis, int(upb), int(n), int(world), item_sharded,
            int(ipb), pol.name, pol.dot_tier, _SHARD_ROW_CHUNK,
        ),
    )
    with progcache.launch(
        "serve.sweep_sharded",
        (pol.name, int(n), progcache.array_key(xb, yb)),
    ):
        s_blk, i_blk = fn(xb, yb, offs_dev)
    if item_sharded and world > 1:
        _note_ring_hops(mesh, axis, int(world))
    # replicate the RESULT blocks (k per user, not the factors) and
    # reassemble valid rows per block — the _gather_blocks offset
    # bookkeeping; multi-process worlds make this fetch a collective
    s_host = _fetch_replicated(s_blk, mesh)
    i_host = _fetch_replicated(i_blk, mesh)
    offsets = np.asarray(offsets_u)
    n_users = int(offsets[-1])
    out_i = np.zeros((n_users, n), np.int32)
    out_s = np.zeros((n_users, n), np.float32)
    for b in range(len(offsets) - 1):
        lo, hi = int(offsets[b]), int(offsets[b + 1])
        out_i[lo:hi] = i_host[b * upb : b * upb + (hi - lo)]
        out_s[lo:hi] = s_host[b * upb : b * upb + (hi - lo)]
    _tm.counter(
        "oap_serve_sweep_rows_total", {"model": "als"},
        help="Query rows swept by full-sweep top-k",
    ).inc(n_users)
    return out_i, (out_s if with_scores else None)


def _note_ring_hops(mesh, axis: str, world: int) -> None:
    """Host-side trace of the ring schedule the sharded sweep just ran:
    one ``ring_hop`` flight-recorder event (and request-ledger event,
    when a traced flush is attached) per rotation step.  The schedule
    is deterministic — item block ``b`` is resident on rank
    ``(b - t) mod world`` at hop ``t`` — so dev/oaptrace.py can draw
    cross-replica flow arrows per block from these stamps alone; the
    device ring itself (collective.ppermute inside the jit) is never
    perturbed."""
    import time as _time

    import jax

    from oap_mllib_tpu.serving import reqtrace
    from oap_mllib_tpu.telemetry import flightrec

    rank = int(jax.process_index())
    for t in range(world):
        detail = (
            f"rank={rank} hop={t} block={(rank + t) % world} "
            f"world={world}"
        )
        flightrec.record("ring_hop", f"hop{t}", detail)
        reqtrace.note_event("ring_hop", detail, _time.perf_counter())
    _tm.counter(
        "oap_serve_ring_hops_total",
        help="Ring-rotation hops traced by the sharded sweep",
    ).inc(world)


def _serving_policy_als():
    from oap_mllib_tpu.serving.batcher import resolve_policy

    return resolve_policy("als")


def _fetch_replicated(x, mesh) -> np.ndarray:
    """Host copy of a block-sharded result array; a registry-cached
    replicating identity when shards span processes (the
    ALSModel._gather_blocks pattern)."""
    import jax

    if not x.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec as P

        fn = progcache.get_or_build(
            "serve.gather_result",
            (progcache.mesh_fingerprint(mesh),),
            lambda: jax.jit(
                lambda a: a, out_shardings=NamedSharding(mesh, P())
            ),
        )
        x = fn(x)
    return jax.device_get(x)


def shard_factors(factors: np.ndarray, mesh) -> tuple:
    """Place a HOST factor table onto the mesh's block layout through
    the elastic-worlds redistribution pass
    (``parallel/shuffle.reshard_factor_rows``) — even row blocks, each
    process contributing only its local slice of rows.  Returns the
    ``(blocks, offsets, per_block)`` triple the sharded model surface
    and the ring sweep consume — a loaded/host model can then serve
    factor-sharded without ever gathering on one host."""
    import jax

    from oap_mllib_tpu.parallel.shuffle import reshard_factor_rows

    cfg = get_config()
    world = mesh.shape[cfg.data_axis]
    n = int(factors.shape[0])
    per = -(-n // world)
    offsets = np.minimum(np.arange(world + 1, dtype=np.int64) * per, n)
    nproc = jax.process_count()
    rank = jax.process_index()
    # each process contributes an even slice of the host rows — the
    # exchange routes every row to its destination block
    lo = (n * rank) // nproc
    hi = (n * (rank + 1)) // nproc
    ids = np.arange(lo, hi, dtype=np.int64)
    blocks = reshard_factor_rows(
        ids, np.asarray(factors[lo:hi], np.float32), mesh, offsets, per
    )
    return blocks, offsets, per


def shard_factors_local(factors: np.ndarray) -> tuple:
    """Block a HOST factor table across THIS process's devices only —
    the eviction-failover layout.  :func:`shard_factors` routes rows
    through the cross-process exchange sized by ``jax.process_count``,
    which is exactly what a survivor must NOT do after a peer died (the
    dead rank never arrives).  This variant builds a fresh local mesh
    over ``jax.local_devices()`` and places even row blocks with a
    plain ``device_put`` — no collective, usable the instant the fleet
    flips local-only.  Returns the same ``(blocks, offsets, per_block)``
    triple, so a re-formed model drops straight into the ring sweep
    (which now rotates over the local mesh)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = get_config()
    axis = cfg.data_axis
    devs = jax.local_devices()
    world = len(devs)
    mesh = Mesh(np.asarray(devs), (axis,))
    factors = np.asarray(factors, np.float32)
    n = int(factors.shape[0])
    per = -(-n // world)
    offsets = np.minimum(
        np.arange(world + 1, dtype=np.int64) * per, n
    )
    padded = factors
    if world * per != n:
        padded = np.concatenate([
            factors,
            np.zeros((world * per - n, factors.shape[1]), np.float32),
        ])
    blocks = jax.device_put(
        padded, NamedSharding(mesh, P(axis, None))
    )
    return blocks, offsets, per
