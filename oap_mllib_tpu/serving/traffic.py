"""Traffic plane: async ingestion, deadline-aware admission, scaling.

The registry/batcher machinery (ISSUE 13) answers requests fast — but
synchronously: ``predict``/``predict_many`` block their caller, batches
form in arrival order, and nothing stands between a request storm and
the replica's memory.  This module is the production front the serving
plane dispatches through (the map-reduce request-path decomposition of
PAPERS.md arXiv:2403.07128 applied to serving):

- :class:`TrafficQueue` — ``submit`` returns a ``concurrent.futures
  .Future`` immediately; a dispatcher thread coalesces pending requests
  into flushes ordered by **deadline** (not arrival order) on the
  existing geometric buckets (``ServedModel.predict_many`` →
  ``_flush_many`` → one bucketed launch per flush), so the request
  nearest its deadline is always scored first whatever order the storm
  arrived in.  Requests whose deadline expires before dispatch are shed
  (their future raises), never scored dead.
- **Admission control** — ``submit`` bounds the queue
  (``Config.serve_queue_depth``) and prices the projected staged
  working set against the memory-budget planner
  (``utils/membudget.Budgets`` × ``Config.serve_shed_headroom``), so a
  request storm can never OOM a replica.  Shedding is LOUD, the
  ``scale_policy`` contract: a :class:`ShedError` naming queue depth /
  deadline / priced bytes / budget, ``oap_serve_shed_total{reason=}``
  booked — never silent.
- :class:`ScaleController` — replica count as a controlled variable:
  consumes queue-depth/p99 samples (fleet heartbeat views /
  ``telemetry/fleet`` rollups), votes scale-out on sustained
  queue-depth-per-replica over ``Config.serve_scale_high`` with a
  non-falling trend, scale-in after ``Config.serve_scale_idle_s`` of
  idleness.  Decisions land in ``summary.serving`` +
  ``oap_serve_scale_*`` metrics, and :func:`write_scale_hint` posts
  them on the supervisor's sideband (``serve.scale.hint.json`` — the
  ``balance.hint.json`` pattern) so ``utils/supervisor.Supervisor``
  sizes the next relaunch from live traffic instead of a static world.

Concurrency contract (oaplint R19-R22 / the ``locks`` sanitizer): the
queue lock is a :class:`~oap_mllib_tpu.utils.locktrace.TrackedLock`
held only around list surgery — scoring, future resolution, and event
waits all run OUTSIDE it (detach-then-act); the dispatcher thread is
daemonized AND joined by :meth:`TrafficQueue.close`.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

# the supervisor sideband file the scale controller posts decisions to
# (crash_dir/<SCALE_HINT_FILENAME>; read-and-removed per attempt like
# parallel/balance.py's balance.hint.json)
SCALE_HINT_FILENAME = "serve.scale.hint.json"

# module scale-decision state for serving_summary (written under the
# tracked lock below — the dispatcher thread and fit threads both read)
_STATE_LOCK = locktrace.TrackedLock("serving.scale")
_scale_state: Dict[str, Any] = {}


class ShedError(RuntimeError):
    """A request the traffic plane refused (admission) or dropped
    (deadline expiry) — LOUDLY, the ``scale_policy`` contract: the
    message names the queue depth, the deadline, and the priced
    bytes-vs-budget so the operator sees exactly why, and every shed
    counts ``oap_serve_shed_total{reason=}``.  ``reason`` is one of
    ``"queue_full"`` / ``"budget"`` / ``"deadline"``."""

    def __init__(self, reason: str, msg: str, *,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 priced_bytes: Optional[int] = None,
                 budget_bytes: Optional[int] = None):
        self.reason = reason
        self.queue_depth = queue_depth
        self.deadline_ms = deadline_ms
        self.priced_bytes = priced_bytes
        self.budget_bytes = budget_bytes
        parts = []
        if queue_depth is not None:
            parts.append(f"queue depth {queue_depth}")
        if deadline_ms is not None and math.isfinite(deadline_ms):
            parts.append(f"deadline {deadline_ms:.1f} ms")
        if priced_bytes is not None:
            parts.append(
                f"priced ~{_fmt_bytes(priced_bytes)} vs budget "
                f"{_fmt_bytes(budget_bytes or 0)}"
            )
        detail = ", ".join(parts)
        super().__init__(
            f"serving traffic: request shed ({reason}) — {msg}"
            + (f" [{detail}]" if detail else "")
        )


def _fmt_bytes(n: int) -> str:
    from oap_mllib_tpu.utils.membudget import _fmt_bytes as fmt

    return fmt(int(n))


def _shed(reason: str, msg: str, **ctx) -> ShedError:
    """Build a ShedError and book the shed counter — every shed is
    visible on the metrics plane whether it raises at submit or lands
    on a future at dispatch."""
    _tm.counter(
        "oap_serve_shed_total", {"reason": reason},
        help="Requests shed by traffic-plane admission control / "
             "deadline expiry, by reason",
    ).inc()
    return ShedError(reason, msg, **ctx)


# -- validated traffic knobs --------------------------------------------------


def traffic_cfg() -> Dict[str, float]:
    """Validated traffic-plane knobs.  A typo raises at submit time,
    not after a storm already queued (the kmeans_kernel/fault_spec
    contract)."""
    cfg = get_config()
    depth = int(cfg.serve_queue_depth)
    if depth < 1:
        raise ValueError(
            f"serve_queue_depth must be >= 1, got {depth}"
        )
    deadline_ms = float(cfg.serve_deadline_ms)
    if deadline_ms < 0:
        raise ValueError(
            f"serve_deadline_ms must be >= 0 (0 = no deadline), got "
            f"{deadline_ms}"
        )
    headroom = float(cfg.serve_shed_headroom)
    if not 0.0 < headroom <= 1.0:
        raise ValueError(
            f"serve_shed_headroom must be in (0, 1], got {headroom}"
        )
    return {
        "queue_depth": depth,
        "deadline_ms": deadline_ms,
        "headroom": headroom,
    }


class _Request:
    __slots__ = ("x", "rows", "deadline", "deadline_ms", "seq", "future",
                 "submitted")

    def __init__(self, x: np.ndarray, deadline: float, deadline_ms: float,
                 seq: int, submitted: float):
        self.x = x
        self.rows = int(x.shape[0])
        self.deadline = deadline  # absolute clock seconds; inf = none
        self.deadline_ms = deadline_ms
        self.seq = seq
        self.submitted = submitted
        self.future: Future = Future()


class TrafficQueue:
    """Async request front for one serving handle.

    ::

        q = serving.TrafficQueue(handle)
        futs = [q.submit(batch, deadline_ms=50.0) for batch in storm]
        ids = [f.result() for f in futs]
        q.close()

    ``submit`` admits (or sheds) under the queue lock and returns a
    future; the dispatcher thread pops the whole pending set, sheds
    expired requests, sorts the rest by absolute deadline, slices them
    into flushes of at most ``max_batch_rows`` request rows, and
    answers each flush through ``handle.predict_many`` (one coalesced
    bucketed launch per flush — zero steady-state compiles after
    warmup).  Futures resolve (or raise) exactly once.

    ``clock`` is injectable (tests drive deadline logic with a fake
    monotonic clock + :meth:`pump`, no thread, fully deterministic);
    ``start=False`` skips the dispatcher thread so :meth:`pump` is the
    only dispatch path."""

    def __init__(self, handle, *, max_batch_rows: int = 1024,
                 poll_s: float = 0.01,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        if not callable(getattr(handle, "predict_many", None)):
            raise TypeError(
                f"TrafficQueue needs a handle with predict_many (got "
                f"{type(handle).__name__}); serve() the model first"
            )
        if max_batch_rows < 1:
            raise ValueError(
                f"max_batch_rows must be >= 1, got {max_batch_rows}"
            )
        self._handle = handle
        self._max_batch_rows = int(max_batch_rows)
        self._poll_s = float(poll_s)
        self._clock = clock
        self._lock = locktrace.TrackedLock("serving.traffic")
        self._pending: List[_Request] = []
        self._seq = 0
        self._closed = False
        self._budget_cache: Optional[tuple] = None
        self.submitted = 0
        self.answered = 0
        self.shed = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            t = threading.Thread(
                target=self._run, name="oap-serve-dispatch", daemon=True
            )
            self._thread = t
            t.start()

    # -- admission -----------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Admit one request; returns its future.  Sheds (raising
        :class:`ShedError`) when the queue is at ``serve_queue_depth``
        or the projected staged bytes would breach the serving memory
        allowance — the storm backs off HERE, not in the allocator."""
        knobs = traffic_cfg()
        if deadline_ms is None:
            deadline_ms = knobs["deadline_ms"]
        deadline_ms = float(deadline_ms)
        if deadline_ms < 0:
            raise ValueError(
                f"deadline_ms must be >= 0 (0 = no deadline), got "
                f"{deadline_ms}"
            )
        x = np.atleast_2d(np.asarray(x))
        allowance = self._allowance(knobs["headroom"])
        req_bytes = int(x.size * x.itemsize)
        now = self._clock()
        deadline = (
            now + deadline_ms / 1e3 if deadline_ms > 0 else math.inf
        )
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "TrafficQueue is closed; no further submissions"
                )
            depth = len(self._pending)
            if depth >= knobs["queue_depth"]:
                raise _shed(
                    "queue_full",
                    f"pending queue at serve_queue_depth="
                    f"{knobs['queue_depth']}; retry after the dispatcher "
                    "drains or scale out",
                    queue_depth=depth, deadline_ms=deadline_ms,
                )
            if allowance > 0:
                from oap_mllib_tpu.utils.membudget import _OVERHEAD

                pending_bytes = sum(
                    int(r.x.size * r.x.itemsize) for r in self._pending
                )
                priced = int((pending_bytes + req_bytes) * _OVERHEAD)
                if priced > allowance:
                    raise _shed(
                        "budget",
                        "projected staged working set exceeds the "
                        "serving allowance (hbm budget x "
                        "serve_shed_headroom); shed instead of OOM",
                        queue_depth=depth, deadline_ms=deadline_ms,
                        priced_bytes=priced, budget_bytes=allowance,
                    )
            req = _Request(x, deadline, deadline_ms, self._seq, now)
            self._seq += 1
            self._pending.append(req)
            self.submitted += 1
        from oap_mllib_tpu.serving import registry

        registry.note_queue_depth(1)
        self._wake.set()
        return req.future

    def _allowance(self, headroom: float) -> int:
        """The serving working-set allowance in bytes (0 = unbounded):
        the resolved HBM budget scaled by ``serve_shed_headroom``.
        Resolution is cached per budget-knob value — admission must not
        pay a device query per request."""
        cfg = get_config()
        key = (cfg.memory_budget_hbm, cfg.memory_budget_host)
        cached = self._budget_cache
        if cached is None or cached[0] != key:
            from oap_mllib_tpu.utils.membudget import Budgets

            cached = (key, Budgets.resolve())
            self._budget_cache = cached
        hbm = cached[1].hbm
        return int(hbm * headroom) if hbm > 0 else 0

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- dispatch ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._poll_s)
            self._wake.clear()
            self.pump()

    def pump(self) -> int:
        """One dispatch cycle: pop everything pending, shed the
        expired, deadline-order the rest, flush in row-bounded groups.
        Returns the number of requests resolved (answered + shed).
        Safe to call concurrently with the dispatcher thread — the pop
        is atomic and each request belongs to exactly one cycle."""
        with self._lock:
            batch = self._pending
            self._pending = []
        if not batch:
            return 0
        from oap_mllib_tpu.serving import registry

        registry.note_queue_depth(-len(batch))
        now = self._clock()
        live: List[_Request] = []
        resolved = 0
        for r in batch:
            if not r.future.set_running_or_notify_cancel():
                resolved += 1  # caller cancelled before dispatch
                continue
            if r.deadline <= now:
                late_ms = (now - r.deadline) * 1e3
                r.future.set_exception(_shed(
                    "deadline",
                    f"request expired {late_ms:.1f} ms past its "
                    "deadline before dispatch (queue wait exceeded the "
                    "budget); shed un-scored",
                    queue_depth=len(batch),
                    deadline_ms=r.deadline_ms,
                ))
                with self._lock:
                    self.shed += 1
                resolved += 1
                continue
            live.append(r)
        live.sort(key=lambda r: (r.deadline, r.seq))
        group: List[_Request] = []
        rows = 0
        groups: List[List[_Request]] = []
        for r in live:
            if group and rows + r.rows > self._max_batch_rows:
                groups.append(group)
                group, rows = [], 0
            group.append(r)
            rows += r.rows
        if group:
            groups.append(group)
        for g in groups:
            try:
                parts = self._handle.predict_many([r.x for r in g])
            except Exception as exc:  # noqa: BLE001 — lands on futures
                for r in g:
                    r.future.set_exception(exc)
            else:
                for r, out in zip(g, parts):
                    r.future.set_result(out)
                with self._lock:
                    self.answered += len(g)
            resolved += len(g)
        return resolved

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop admissions, join the dispatcher (R22), drain leftovers
        through one final :meth:`pump` so every future resolves."""
        with self._lock:
            self._closed = True
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        self.pump()

    def __enter__(self) -> "TrafficQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- replica-count control ----------------------------------------------------


class ScaleController:
    """Replica count as a controlled variable.

    Feed it queue-depth/p99 samples — from :func:`serving.heartbeat`
    fleet views (:meth:`observe_view`) or straight numbers
    (:meth:`observe`) — and it votes: **out** when the windowed mean
    queue depth per replica exceeds ``Config.serve_scale_high`` and the
    depth trend (``telemetry/fleet._trend``) is not falling; **in**
    when the fleet sat idle (zero depth, no new requests) for
    ``Config.serve_scale_idle_s``; **hold** otherwise.  Decisions book
    ``oap_serve_scale_out_total`` / ``oap_serve_scale_in_total`` / the
    ``oap_serve_scale_replicas`` gauge, surface in
    ``serving_summary()['scale']``, and :func:`write_scale_hint` posts
    them to the supervisor sideband so the next relaunch is sized by
    live traffic."""

    WINDOW = 4  # samples per decision window (fleet._trend's minimum)

    def __init__(self, replicas: int, *, min_replicas: int = 1,
                 max_replicas: int = 0,
                 high: Optional[float] = None,
                 idle_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        cfg = get_config()
        self.high = float(cfg.serve_scale_high if high is None else high)
        self.idle_s = float(
            cfg.serve_scale_idle_s if idle_s is None else idle_s
        )
        if self.high <= 0:
            raise ValueError(
                f"serve_scale_high must be > 0, got {self.high}"
            )
        if self.idle_s <= 0:
            raise ValueError(
                f"serve_scale_idle_s must be > 0, got {self.idle_s}"
            )
        if replicas < 1 or min_replicas < 1:
            raise ValueError(
                f"replicas/min_replicas must be >= 1, got "
                f"{replicas}/{min_replicas}"
            )
        self.replicas = int(replicas)
        self.min_replicas = int(min_replicas)
        # 0 = unbounded growth is never sane for a supervisor-run
        # fleet: default cap is the starting size x2
        self.max_replicas = int(max_replicas) or 2 * int(replicas)
        self._clock = clock
        self._depths: deque = deque(maxlen=self.WINDOW)
        self._p99s: deque = deque(maxlen=self.WINDOW)
        self._last_busy = clock()
        self._last_requests: Optional[int] = None
        self.decisions: List[Dict[str, Any]] = []

    def observe_view(self, view: Dict[str, Any],
                     p99_s: float = 0.0) -> Dict[str, Any]:
        """One observation from a :func:`serving.heartbeat` fleet view
        (fleet-wide queue depth = sum across replicas; replica count
        tracks the view's world)."""
        self.replicas = max(self.min_replicas, int(view.get("world", 1)))
        return self.observe(
            queue_depth=int(sum(view.get("queue_depth", []) or [0])),
            p99_s=p99_s,
            requests=int(sum(view.get("requests", []) or [0])),
        )

    def observe(self, queue_depth: int, p99_s: float = 0.0,
                requests: Optional[int] = None) -> Dict[str, Any]:
        """Fold one sample, return the decision dict (action out/in/
        hold, replicas, reason, the sample, the trends)."""
        from oap_mllib_tpu.telemetry.fleet import _trend

        now = self._clock()
        self._depths.append(float(queue_depth))
        self._p99s.append(float(p99_s))
        busy = queue_depth > 0 or (
            requests is not None and requests != self._last_requests
        )
        if requests is not None:
            self._last_requests = requests
        if busy:
            self._last_busy = now
        depth_trend = _trend(list(self._depths))
        p99_trend = _trend(list(self._p99s))
        per_replica = (
            float(np.mean(self._depths)) / max(1, self.replicas)
        )
        action, reason = "hold", ""
        if (len(self._depths) == self.WINDOW
                and per_replica > self.high
                and depth_trend != "falling"
                and self.replicas < self.max_replicas):
            action = "out"
            self.replicas += 1
            reason = (
                f"queue depth/replica {per_replica:.1f} > "
                f"serve_scale_high={self.high:g} (depth {depth_trend}, "
                f"p99 {p99_trend})"
            )
            self._depths.clear()
            self._p99s.clear()
            _tm.counter(
                "oap_serve_scale_out_total",
                help="Scale-out decisions by the serving replica "
                     "controller",
            ).inc()
        elif (now - self._last_busy >= self.idle_s
                and self.replicas > self.min_replicas):
            action = "in"
            self.replicas -= 1
            reason = (
                f"idle {now - self._last_busy:.1f}s >= "
                f"serve_scale_idle_s={self.idle_s:g}"
            )
            self._last_busy = now
            _tm.counter(
                "oap_serve_scale_in_total",
                help="Scale-in decisions by the serving replica "
                     "controller",
            ).inc()
        _tm.gauge(
            "oap_serve_scale_replicas",
            help="Replica count the serving scale controller currently "
                 "wants",
        ).set(self.replicas)
        decision = {
            "action": action,
            "replicas": self.replicas,
            "reason": reason,
            "queue_depth": int(queue_depth),
            "queue_depth_per_replica": round(per_replica, 3),
            "p99_s": float(p99_s),
            "depth_trend": depth_trend,
            "p99_trend": p99_trend,
        }
        self.decisions.append(decision)
        with _STATE_LOCK:
            _scale_state.clear()
            _scale_state.update(decision)
        return decision


def write_scale_hint(crash_dir: str,
                     decision: Dict[str, Any]) -> Optional[str]:
    """Post a non-hold scale decision on the supervisor sideband
    (``crash_dir/serve.scale.hint.json``, atomic tmp+rename — the
    balance.hint.json pattern).  The supervisor consumes it
    read-and-remove when sizing the next relaunch.  Returns the path
    (None for hold decisions or an unarmed sideband)."""
    import json
    import os

    if not crash_dir or decision.get("action") not in ("out", "in"):
        return None
    os.makedirs(crash_dir, exist_ok=True)
    path = os.path.join(crash_dir, SCALE_HINT_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(decision, f)
    os.replace(tmp, path)
    return path


def summary_block() -> Dict[str, Any]:
    """The traffic-plane additions to ``serving_summary()``: shed
    totals by reason, plus the scale controller's last decision."""
    out: Dict[str, Any] = {}
    reg = _tm.registry()
    with _tm._LOCK:
        sheds = {
            dict(labels).get("reason", ""): int(m.value)
            for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_shed_total"
        }
    if sheds:
        out["shed"] = {"total": sum(sheds.values()), **sheds}
    with _STATE_LOCK:
        if _scale_state:
            out["scale"] = dict(_scale_state)
    return out


def _reset_for_tests() -> None:
    with _STATE_LOCK:
        _scale_state.clear()
