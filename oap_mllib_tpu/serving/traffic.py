"""Traffic plane: async ingestion, deadline-aware admission, scaling.

The registry/batcher machinery (ISSUE 13) answers requests fast — but
synchronously: ``predict``/``predict_many`` block their caller, batches
form in arrival order, and nothing stands between a request storm and
the replica's memory.  This module is the production front the serving
plane dispatches through (the map-reduce request-path decomposition of
PAPERS.md arXiv:2403.07128 applied to serving):

- :class:`TrafficQueue` — ``submit`` returns a ``concurrent.futures
  .Future`` immediately; a dispatcher thread coalesces pending requests
  into flushes ordered by **deadline** (not arrival order) on the
  existing geometric buckets (``ServedModel.predict_many`` →
  ``_flush_many`` → one bucketed launch per flush), so the request
  nearest its deadline is always scored first whatever order the storm
  arrived in.  Requests whose deadline expires before dispatch are shed
  (their future raises), never scored dead.
- **Admission control** — ``submit`` bounds the queue
  (``Config.serve_queue_depth``) and prices the projected staged
  working set against the memory-budget planner
  (``utils/membudget.Budgets`` × ``Config.serve_shed_headroom``), so a
  request storm can never OOM a replica.  Shedding is LOUD, the
  ``scale_policy`` contract: a :class:`ShedError` naming queue depth /
  deadline / priced bytes / budget, ``oap_serve_shed_total{reason=}``
  booked — never silent.
- :class:`ScaleController` — replica count as a controlled variable:
  consumes queue-depth/p99 samples (fleet heartbeat views /
  ``telemetry/fleet`` rollups), votes scale-out on sustained
  queue-depth-per-replica over ``Config.serve_scale_high`` with a
  non-falling trend, scale-in after ``Config.serve_scale_idle_s`` of
  idleness.  Decisions land in ``summary.serving`` +
  ``oap_serve_scale_*`` metrics, and :func:`write_scale_hint` posts
  them on the supervisor's sideband (``serve.scale.hint.json`` — the
  ``balance.hint.json`` pattern) so ``utils/supervisor.Supervisor``
  sizes the next relaunch from live traffic instead of a static world.

Request-lifecycle fault tolerance (ISSUE 18) — the *degrade, never
fail* ladder the fits got, applied to every ACCEPTED request:

- **Durable futures** — each admitted request carries a retry envelope
  (``Config.serve_retry_limit`` / ``serve_retry_backoff``, the
  site-hashed deterministic jitter of ``utils/resilience.RetryPolicy``).
  A transient scoring fault re-enqueues the request at its ORIGINAL
  deadline priority instead of failing the future; a dispatcher-thread
  crash (fault site ``serve.dispatch``) fails the in-cycle futures with
  a classified :class:`ServeError` and restarts the dispatch loop — the
  queue never wedges, and no admitted future is ever silently dropped.
- **Poison-batch bisection** — a classified fault inside a coalesced
  flush triggers log₂ bisection of the group: halves re-coalesce onto
  the same geometric bucket family (zero new XLA compiles) until the
  poison request(s) are isolated, quarantined
  (``oap_serve_poison_total`` + a payload digest in the flight
  recorder), and every innocent request is answered.
- **Graceful drain** — :meth:`TrafficQueue.drain` stops admission
  (``ShedError(reason="draining")``), flushes pending + retrying
  futures until a wall deadline, fails leftovers loudly
  (``reason="drain-deadline"``), and posts a
  ``serve.drain.done.rank<r>.json`` report on the supervisor sideband;
  wired into ``ScaleController`` scale-in and ``ReplicaGuard.release``.
- **Brownout ladder** — :class:`BrownoutController`
  (``Config.serve_brownout`` = auto|off|pin:<rung>) steps recorded
  degradation rungs (reduced top-k depth → bf16 serving precision where
  a parity bound exists → stale-pin answering) under sustained
  over-budget pressure, fleet-trend-gated like the scale controller —
  each rung LOUD in ``serving_summary()["brownout"]``, span attrs, and
  ``oap_serve_brownout_rung``, absorbing pressure before requests shed.

Concurrency contract (oaplint R19-R22 / the ``locks`` sanitizer): the
queue lock is a :class:`~oap_mllib_tpu.utils.locktrace.TrackedLock`
held only around list surgery — scoring, future resolution, and event
waits all run OUTSIDE it (detach-then-act); the dispatcher thread is
daemonized AND joined by :meth:`TrafficQueue.close`.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
import weakref
import zlib
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.serving import reqtrace
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import faults, locktrace

# the supervisor sideband file the scale controller posts decisions to
# (crash_dir/<SCALE_HINT_FILENAME>; read-and-removed per attempt like
# parallel/balance.py's balance.hint.json)
SCALE_HINT_FILENAME = "serve.scale.hint.json"

# module scale-decision state for serving_summary (written under the
# tracked lock below — the dispatcher thread and fit threads both read)
_STATE_LOCK = locktrace.TrackedLock("serving.scale")
_scale_state: Dict[str, Any] = {}

# the last request shed by this process: (reason, monotonic time) — the
# /healthz serving block reports reason + age so a scrape of a shedding
# replica names why without grepping counters
_last_shed: Optional[tuple] = None

# live queues (weakly held) — the /healthz serving block sums their
# in-flight sets without keeping a closed queue alive
_QUEUES: "weakref.WeakSet[TrafficQueue]" = weakref.WeakSet()


class ShedError(RuntimeError):
    """A request the traffic plane refused (admission) or dropped
    (deadline expiry) — LOUDLY, the ``scale_policy`` contract: the
    message names the queue depth, the deadline, and the priced
    bytes-vs-budget so the operator sees exactly why, and every shed
    counts ``oap_serve_shed_total{reason=}``.  ``reason`` is one of
    ``"queue_full"`` / ``"budget"`` / ``"deadline"`` / ``"draining"``
    (the queue is flushing for scale-in/shutdown — resubmit to a live
    replica)."""

    def __init__(self, reason: str, msg: str, *,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 priced_bytes: Optional[int] = None,
                 budget_bytes: Optional[int] = None):
        self.reason = reason
        self.queue_depth = queue_depth
        self.deadline_ms = deadline_ms
        self.priced_bytes = priced_bytes
        self.budget_bytes = budget_bytes
        parts = []
        if queue_depth is not None:
            parts.append(f"queue depth {queue_depth}")
        if deadline_ms is not None and math.isfinite(deadline_ms):
            parts.append(f"deadline {deadline_ms:.1f} ms")
        if priced_bytes is not None:
            parts.append(
                f"priced ~{_fmt_bytes(priced_bytes)} vs budget "
                f"{_fmt_bytes(budget_bytes or 0)}"
            )
        detail = ", ".join(parts)
        super().__init__(
            f"serving traffic: request shed ({reason}) — {msg}"
            + (f" [{detail}]" if detail else "")
        )


class ServeError(RuntimeError):
    """A request the traffic plane ACCEPTED but could not answer — the
    loud half of the durable-future contract: accepted work completes
    exactly-once or fails naming exactly why.  ``reason`` is one of

    - ``"retries-exhausted"`` — transient scoring faults outlasted the
      ``serve_retry_limit`` envelope (``retries`` says how many ran);
    - ``"poison"`` — bisection isolated this request as the poison in
      its coalesced batch (``oap_serve_poison_total`` booked, payload
      digest in the flight recorder); innocents were answered;
    - ``"fault"`` — a classified non-retriable fault (``fault_class``
      names the kind, e.g. oom);
    - ``"dispatcher-crash"`` — the dispatch cycle scoring this request
      crashed; the dispatcher restarted but this future fails loudly
      rather than hang;
    - ``"drain-deadline"`` — unresolved when a graceful drain's wall
      deadline expired;
    - ``"shutdown"`` — the queue closed with the request unresolved
      (close() fail-or-flushes every future; nothing leaks);
    - ``"eviction"`` — a replica died mid-flight and the work could not
      re-form on the survivors; ``crash_records`` names the culprit
      crash record path(s) on the sideband.

    Every construction books
    ``oap_serve_request_failures_total{reason=}`` so classified
    failures are visible on the metrics plane wherever they land."""

    def __init__(self, reason: str, msg: str, *,
                 fault_class: Optional[str] = None,
                 retries: int = 0,
                 cause: Optional[BaseException] = None,
                 crash_records=()):
        self.reason = reason
        self.fault_class = fault_class
        self.retries = int(retries)
        self.crash_records = tuple(crash_records)
        _tm.counter(
            "oap_serve_request_failures_total", {"reason": reason},
            help="Accepted requests the traffic plane failed loudly, "
                 "by classified reason",
        ).inc()
        parts = []
        if fault_class:
            parts.append(f"class={fault_class}")
        if retries:
            parts.append(f"retries={retries}")
        if self.crash_records:
            parts.append("crash records: "
                         + ", ".join(self.crash_records))
        detail = ", ".join(parts)
        super().__init__(
            f"serving traffic: request failed ({reason}) — {msg}"
            + (f" [{detail}]" if detail else "")
        )
        if cause is not None:
            self.__cause__ = cause


def _fmt_bytes(n: int) -> str:
    from oap_mllib_tpu.utils.membudget import _fmt_bytes as fmt

    return fmt(int(n))


def _shed(reason: str, msg: str, **ctx) -> ShedError:
    """Build a ShedError and book the shed counter — every shed is
    visible on the metrics plane whether it raises at submit or lands
    on a future at dispatch.  Also stamps the /healthz last-shed state
    and drops a flight-recorder instant (shed instants land on the
    oaptrace request timeline)."""
    global _last_shed
    _tm.counter(
        "oap_serve_shed_total", {"reason": reason},
        help="Requests shed by traffic-plane admission control / "
             "deadline expiry, by reason",
    ).inc()
    with _STATE_LOCK:
        _last_shed = (reason, time.monotonic())
    from oap_mllib_tpu.telemetry import flightrec

    flightrec.record("serve", "shed", f"reason={reason}")
    return ShedError(reason, msg, **ctx)


# -- validated traffic knobs --------------------------------------------------


def traffic_cfg() -> Dict[str, float]:
    """Validated traffic-plane knobs.  A typo raises at submit time,
    not after a storm already queued (the kmeans_kernel/fault_spec
    contract)."""
    cfg = get_config()
    depth = int(cfg.serve_queue_depth)
    if depth < 1:
        raise ValueError(
            f"serve_queue_depth must be >= 1, got {depth}"
        )
    deadline_ms = float(cfg.serve_deadline_ms)
    if deadline_ms < 0:
        raise ValueError(
            f"serve_deadline_ms must be >= 0 (0 = no deadline), got "
            f"{deadline_ms}"
        )
    headroom = float(cfg.serve_shed_headroom)
    if not 0.0 < headroom <= 1.0:
        raise ValueError(
            f"serve_shed_headroom must be in (0, 1], got {headroom}"
        )
    retry_limit = int(cfg.serve_retry_limit)
    if retry_limit < 0:
        raise ValueError(
            f"serve_retry_limit must be >= 0, got {retry_limit}"
        )
    retry_backoff = float(cfg.serve_retry_backoff)
    if retry_backoff < 0:
        raise ValueError(
            f"serve_retry_backoff must be >= 0, got {retry_backoff}"
        )
    brownout = str(cfg.serve_brownout).strip().lower()
    _parse_brownout(brownout)  # a typo raises here, at submit time
    # a serve_trace_sample typo raises here too — before a storm queues
    trace_sample = reqtrace.trace_sample_cfg(cfg)
    return {
        "queue_depth": depth,
        "deadline_ms": deadline_ms,
        "headroom": headroom,
        "retry_limit": retry_limit,
        "retry_backoff": retry_backoff,
        "brownout": brownout,
        "trace_sample": trace_sample,
    }


# ordered degradation rungs the brownout ladder steps through: each is
# cheaper than the last, every step is recorded/LOUD (see
# BrownoutController)
BROWNOUT_RUNGS = ("off", "topk", "bf16", "stale")


def _parse_brownout(raw: str) -> Optional[int]:
    """Parse ``Config.serve_brownout`` (auto|off|pin:<rung>): the
    pinned rung index for ``pin:``, None for auto/off; a typo raises
    ValueError (the kmeans_kernel/fault_spec contract)."""
    if raw in ("auto", "off"):
        return None
    if raw.startswith("pin:"):
        rung = raw[len("pin:"):]
        if rung in BROWNOUT_RUNGS:
            return BROWNOUT_RUNGS.index(rung)
    raise ValueError(
        f"serve_brownout must be auto, off, or pin:<rung> with rung in "
        f"{'|'.join(BROWNOUT_RUNGS)}; got {raw!r}"
    )


class _Request:
    __slots__ = ("x", "rows", "deadline", "deadline_ms", "seq", "future",
                 "submitted", "retries", "not_before", "running", "trace")

    def __init__(self, x: np.ndarray, deadline: float, deadline_ms: float,
                 seq: int, submitted: float):
        self.x = x
        self.rows = int(x.shape[0])
        self.deadline = deadline  # absolute clock seconds; inf = none
        self.deadline_ms = deadline_ms
        self.seq = seq
        self.submitted = submitted
        self.future: Future = Future()
        # durable-future envelope: retries spent so far, the earliest
        # clock second the next attempt may dispatch (backoff), and
        # whether set_running_or_notify_cancel already ran (a future
        # transitions PENDING->RUNNING exactly once; a requeued request
        # is already RUNNING)
        self.retries = 0
        self.not_before = 0.0
        self.running = False
        # the request's deadline-budget ledger (serving/reqtrace.py),
        # or None when tracing is disarmed
        self.trace: Optional[reqtrace.Ledger] = None


class TrafficQueue:
    """Async request front for one serving handle.

    ::

        q = serving.TrafficQueue(handle)
        futs = [q.submit(batch, deadline_ms=50.0) for batch in storm]
        ids = [f.result() for f in futs]
        q.close()

    ``submit`` admits (or sheds) under the queue lock and returns a
    future; the dispatcher thread pops the whole pending set, sheds
    expired requests, sorts the rest by absolute deadline, slices them
    into flushes of at most ``max_batch_rows`` request rows, and
    answers each flush through ``handle.predict_many`` (one coalesced
    bucketed launch per flush — zero steady-state compiles after
    warmup).  Futures resolve (or raise) exactly once.

    ``clock`` is injectable (tests drive deadline logic with a fake
    monotonic clock + :meth:`pump`, no thread, fully deterministic);
    ``start=False`` skips the dispatcher thread so :meth:`pump` is the
    only dispatch path."""

    def __init__(self, handle, *, max_batch_rows: int = 1024,
                 poll_s: float = 0.01,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        if not callable(getattr(handle, "predict_many", None)):
            raise TypeError(
                f"TrafficQueue needs a handle with predict_many (got "
                f"{type(handle).__name__}); serve() the model first"
            )
        if max_batch_rows < 1:
            raise ValueError(
                f"max_batch_rows must be >= 1, got {max_batch_rows}"
            )
        self._handle = handle
        self._kind = str(getattr(handle, "kind", ""))
        self._max_batch_rows = int(max_batch_rows)
        self._poll_s = float(poll_s)
        self._clock = clock
        self._lock = locktrace.TrackedLock("serving.traffic")
        self._pending: List[_Request] = []
        self._inflight: Dict[int, _Request] = {}
        self._seq = 0
        self._closed = False
        self._draining = False
        self._budget_cache: Optional[tuple] = None
        self.submitted = 0
        self.answered = 0
        self.shed = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _QUEUES.add(self)
        if start:
            t = threading.Thread(
                target=self._run, name="oap-serve-dispatch", daemon=True
            )
            self._thread = t
            t.start()

    # -- admission -----------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Admit one request; returns its future.  Sheds (raising
        :class:`ShedError`) when the queue is at ``serve_queue_depth``
        or the projected staged bytes would breach the serving memory
        allowance — the storm backs off HERE, not in the allocator."""
        knobs = traffic_cfg()
        if deadline_ms is None:
            deadline_ms = knobs["deadline_ms"]
        deadline_ms = float(deadline_ms)
        if deadline_ms < 0:
            raise ValueError(
                f"deadline_ms must be >= 0 (0 = no deadline), got "
                f"{deadline_ms}"
            )
        x = np.atleast_2d(np.asarray(x))
        allowance = self._allowance(knobs["headroom"])
        req_bytes = int(x.size * x.itemsize)
        now = self._clock()
        deadline = (
            now + deadline_ms / 1e3 if deadline_ms > 0 else math.inf
        )
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "TrafficQueue is closed; no further submissions"
                )
            depth = len(self._pending)
            if self._draining:
                raise _shed(
                    "draining",
                    "queue is draining for scale-in/shutdown; resubmit "
                    "to a live replica",
                    queue_depth=depth, deadline_ms=deadline_ms,
                )
            if depth >= knobs["queue_depth"]:
                raise _shed(
                    "queue_full",
                    f"pending queue at serve_queue_depth="
                    f"{knobs['queue_depth']}; retry after the dispatcher "
                    "drains or scale out",
                    queue_depth=depth, deadline_ms=deadline_ms,
                )
            bo: Optional[Dict[str, Any]] = None
            if allowance > 0:
                from oap_mllib_tpu.utils.membudget import _OVERHEAD

                pending_bytes = sum(
                    int(r.x.size * r.x.itemsize) for r in self._pending
                )
                priced = int((pending_bytes + req_bytes) * _OVERHEAD)
                # the brownout ladder sees every priced admission: over
                # budget it may step a rung and ABSORB the breach
                # (degrade before shed); under budget it steps back down
                bo = brownout().observe_admission(priced, allowance)
                if priced > allowance and not bo["absorb"]:
                    raise _shed(
                        "budget",
                        "projected staged working set exceeds the "
                        "serving allowance (hbm budget x "
                        "serve_shed_headroom); shed instead of OOM",
                        queue_depth=depth, deadline_ms=deadline_ms,
                        priced_bytes=priced, budget_bytes=allowance,
                    )
            req = _Request(x, deadline, deadline_ms, self._seq, now)
            self._seq += 1
            if knobs["trace_sample"] > 0:
                # the deadline-budget ledger opens at submit entry (t0 =
                # now, stamped before the lock) and closes its first
                # stage — admission — here, still under the lock so the
                # dispatcher can never pop an un-traced request
                lg = reqtrace.begin(
                    now, int(get_config().process_id), req.seq,
                    deadline_ms,
                )
                if lg is not None:
                    t_adm = self._clock()
                    lg.cut("admission", t_adm)
                    if bo is not None and (bo["stepped"] or bo["rung"]):
                        lg.event(
                            "brownout",
                            f"rung={bo['rung_name']} "
                            f"stepped={bo['stepped']}",
                            t_adm,
                        )
                    req.trace = lg
                    # the future carries the ledger to the caller
                    # (reqtrace.ledger_of) — answered or failed alike
                    req.future.ledger = lg  # type: ignore[attr-defined]
            self._pending.append(req)
            self.submitted += 1
        from oap_mllib_tpu.serving import registry

        registry.note_queue_depth(1)
        self._wake.set()
        return req.future

    def _allowance(self, headroom: float) -> int:
        """The serving working-set allowance in bytes (0 = unbounded):
        the resolved HBM budget scaled by ``serve_shed_headroom``.
        Resolution is cached per budget-knob value — admission must not
        pay a device query per request."""
        cfg = get_config()
        key = (cfg.memory_budget_hbm, cfg.memory_budget_host)
        cached = self._budget_cache
        if cached is None or cached[0] != key:
            from oap_mllib_tpu.utils.membudget import Budgets

            cached = (key, Budgets.resolve())
            self._budget_cache = cached
        hbm = cached[1].hbm
        return int(hbm * headroom) if hbm > 0 else 0

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- dispatch ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._poll_s)
            self._wake.clear()
            try:
                self.pump()
            except Exception as exc:  # noqa: BLE001 — crash survived
                # the never-wedge contract: pump already failed or
                # requeued every in-cycle future (see
                # _dispatcher_crash); the loop restarts and keeps
                # draining — LOUD, never silent
                warnings.warn(
                    "serving traffic: dispatcher crashed and restarted "
                    f"— in-cycle futures were failed/requeued ({exc!r})",
                    RuntimeWarning, stacklevel=2,
                )

    # -- future resolution (exactly-once, close/drain-race safe) -------------

    def _land(self, r: _Request, out) -> bool:
        self._inflight.pop(id(r), None)
        try:
            r.future.set_result(out)
        except Exception:  # InvalidStateError: close()/drain() beat us
            return False
        self._finalize_trace(r, "answered")
        return True

    def _land_exc(self, r: _Request, exc: BaseException) -> bool:
        self._inflight.pop(id(r), None)
        try:
            r.future.set_exception(exc)
        except Exception:  # InvalidStateError: close()/drain() beat us
            return False
        self._finalize_trace(
            r, "shed" if isinstance(exc, ShedError) else "failed"
        )
        return True

    def _finalize_trace(self, r: _Request, outcome: str) -> None:
        """Close the request's ledger on whichever path landed its
        future — answered, shed, failed, or cancelled."""
        lg = r.trace
        if lg is None:
            return
        lg.retries = r.retries
        reqtrace.finalize(lg, outcome, self._clock(), model=self._kind)

    def pump(self) -> int:
        """One dispatch cycle: pop every pending request whose retry
        backoff has elapsed, shed the expired, deadline-order the rest,
        flush in row-bounded groups.  Returns the number of requests
        resolved (answered + shed + failed).  Safe to call concurrently
        with the dispatcher thread — the pop is atomic and each request
        belongs to exactly one cycle.  A crash in the cycle itself
        (fault site ``serve.dispatch``) fails or requeues every
        unresolved in-cycle future before re-raising — accepted work is
        never silently dropped."""
        now = self._clock()
        with self._lock:
            if not self._pending:
                return 0
            ready = [r for r in self._pending if r.not_before <= now]
            if not ready:
                return 0
            if len(ready) == len(self._pending):
                self._pending = []
            else:
                self._pending = [
                    r for r in self._pending if r.not_before > now
                ]
            for r in ready:
                self._inflight[id(r)] = r
        for r in ready:
            if r.trace is not None:
                # admitted (or requeued) -> popped by this cycle; retry
                # backoff waits accumulate here too, by construction
                r.trace.cut("queue_wait", now)
        from oap_mllib_tpu.serving import registry

        registry.note_queue_depth(-len(ready))
        try:
            faults.maybe_fault("serve.dispatch")
            return self._dispatch(ready, now)
        except Exception as exc:  # noqa: BLE001 — classified below
            self._dispatcher_crash(ready, exc)
            raise

    def _dispatch(self, ready: List[_Request], now: float) -> int:
        resolved = 0
        live: List[_Request] = []
        for r in ready:
            if not r.running:
                if not r.future.set_running_or_notify_cancel():
                    self._inflight.pop(id(r), None)
                    self._finalize_trace(r, "cancelled")
                    resolved += 1  # caller cancelled before dispatch
                    continue
                r.running = True
            if r.deadline <= now:
                late_ms = (now - r.deadline) * 1e3
                if self._land_exc(r, _shed(
                    "deadline",
                    f"request expired {late_ms:.1f} ms past its "
                    "deadline before dispatch (queue wait exceeded the "
                    "budget); shed un-scored",
                    queue_depth=len(ready),
                    deadline_ms=r.deadline_ms,
                )):
                    with self._lock:
                        self.shed += 1
                resolved += 1
                continue
            live.append(r)
        live.sort(key=lambda r: (r.deadline, r.seq))
        group: List[_Request] = []
        rows = 0
        groups: List[List[_Request]] = []
        for r in live:
            if group and rows + r.rows > self._max_batch_rows:
                groups.append(group)
                group, rows = [], 0
            group.append(r)
            rows += r.rows
        if group:
            groups.append(group)
        for g in groups:
            resolved += self._dispatch_group(g, now)
        return resolved

    def _dispatch_group(self, g: List[_Request], now: float) -> int:
        """Score one deadline-ordered group; on a fault, classify and
        either retry (transient), bisect (classified fault in a
        coalesced group — halves re-coalesce on the same geometric
        bucket family, no new compiles), quarantine (isolated poison),
        or land the raw exception (unclassified: a programming error
        must propagate unchanged, never masked)."""
        ledgers = [r.trace for r in g if r.trace is not None]
        if not ledgers:
            try:
                parts = self._handle.predict_many([r.x for r in g])
            except Exception as exc:  # noqa: BLE001 — classified below
                return self._group_fault(g, exc, now)
        else:
            # popped -> this group's scoring call begins: deadline
            # triage, sorting, and group slicing all land in batch_form
            t_score = self._clock()
            for lg in ledgers:
                lg.cut("batch_form", t_score)
            from oap_mllib_tpu.utils import progcache

            compile0 = progcache.xla_compile_secs()
            try:
                # bind the group's ledgers to the scoring thread so the
                # batcher's pad timing and the sharded sweep's ring-hop
                # events fold in without plumbing predict_many
                with reqtrace.attach(ledgers) as att:
                    parts = self._handle.predict_many([r.x for r in g])
            except Exception as exc:  # noqa: BLE001 — classified below
                t_fault = self._clock()
                for lg in ledgers:
                    lg.cut("execute", t_fault)
                    lg.event("fault", type(exc).__name__, t_fault)
                return self._group_fault(g, exc, now)
            t_done = self._clock()
            pad_s = att.flush_notes().get("bucket_pad", 0.0)
            comp_s = progcache.xla_compile_secs() - compile0
            # each request's flush interval splits pad/compile/execute;
            # the shared pad/compile walls are attributed per-request
            # (every rider of the flush paid them)
            for lg in ledgers:
                lg.cut_flush(t_done, pad_s, comp_s)
        resolved = 0
        for r, out in zip(g, parts):
            if self._land(r, out):
                resolved += 1
        with self._lock:
            self.answered += resolved
        return resolved

    def _group_fault(self, g: List[_Request], exc: BaseException,
                     now: float) -> int:
        from oap_mllib_tpu.utils import resilience

        kind = resilience.classify_fault(exc)
        if kind == resilience.TRANSIENT:
            policy = resilience.RetryPolicy.for_serving()
            retriable = [r for r in g if r.retries < policy.max_retries]
            spent = [r for r in g if r.retries >= policy.max_retries]
            if retriable:
                self._requeue(retriable, now, policy)
            n = 0
            for r in spent:
                if self._land_exc(r, ServeError(
                    "retries-exhausted",
                    f"request seq={r.seq} kept hitting transient "
                    f"scoring faults past serve_retry_limit="
                    f"{policy.max_retries}",
                    fault_class=kind, retries=r.retries, cause=exc,
                )):
                    n += 1
            return n
        if len(g) > 1 and kind is not None:
            # poison-batch bisection: a CLASSIFIED fault in a coalesced
            # group — split and rescore; each half re-buckets onto the
            # already-warm geometric family, so isolation costs zero
            # new XLA compiles
            _tm.counter(
                "oap_serve_bisect_total",
                help="Coalesced-batch bisection rounds triggered by a "
                     "classified scoring fault",
            ).inc()
            mid = len(g) // 2
            return (self._dispatch_group(g[:mid], now)
                    + self._dispatch_group(g[mid:], now))
        if len(g) > 1:
            # unclassified: land the RAW exception on every future of
            # the flush (identity preserved — never masked, never
            # rescored: a programming error is deterministic)
            return sum(1 for r in g if self._land_exc(r, exc))
        return self._quarantine(g[0], exc, kind)

    def _quarantine(self, r: _Request, exc: BaseException,
                    kind: Optional[str]) -> int:
        from oap_mllib_tpu.utils import resilience

        if kind is None:
            # raw identity preserved for unclassified singletons too
            return 1 if self._land_exc(r, exc) else 0
        if kind == resilience.NONFINITE:
            from oap_mllib_tpu.telemetry import flightrec

            digest = zlib.crc32(
                np.ascontiguousarray(r.x).tobytes()
            ) & 0xFFFFFFFF
            _tm.counter(
                "oap_serve_poison_total",
                help="Requests quarantined as poison by coalesced-"
                     "batch bisection",
            ).inc()
            flightrec.record(
                "serve", "poison",
                f"seq={r.seq} rows={r.rows} digest={digest:08x}: {exc}",
            )
            if r.trace is not None:
                r.trace.event(
                    "poison", f"digest={digest:08x}", self._clock()
                )
            err = ServeError(
                "poison",
                f"request seq={r.seq} quarantined: scoring it produces "
                f"a nonfinite outcome (payload digest {digest:08x}); "
                "innocents in its batch were answered",
                fault_class=kind, retries=r.retries, cause=exc,
            )
        else:
            err = ServeError(
                "fault",
                f"request seq={r.seq} failed a non-retriable {kind} "
                "scoring fault",
                fault_class=kind, retries=r.retries, cause=exc,
            )
        return 1 if self._land_exc(r, err) else 0

    def _requeue(self, rs: List[_Request], now: float, policy) -> None:
        """Re-enqueue transiently-faulted requests: seq and deadline
        are PRESERVED, so the retry dispatches at its original deadline
        priority; ``not_before`` applies the policy's jittered
        backoff."""
        for r in rs:
            r.not_before = now + policy.delay_s(r.retries,
                                                site="serve.batch")
            r.retries += 1
            if r.trace is not None:
                r.trace.retries = r.retries
                r.trace.event("retry", f"retries={r.retries}", now)
        from oap_mllib_tpu.telemetry import flightrec

        flightrec.record("serve", "retry", f"n={len(rs)}")
        _tm.counter(
            "oap_serve_retries_total",
            help="Transient scoring faults re-enqueued by the durable-"
                 "future retry envelope",
        ).inc(len(rs))
        with self._lock:
            closed = self._closed
            if not closed:
                self._pending.extend(rs)
                for r in rs:
                    self._inflight.pop(id(r), None)
        if closed:
            for r in rs:
                self._land_exc(r, ServeError(
                    "shutdown",
                    f"request seq={r.seq} had retries left but the "
                    "queue is closing; resubmit to a live replica",
                    retries=r.retries,
                ))
            return
        from oap_mllib_tpu.serving import registry

        registry.note_queue_depth(len(rs))
        self._wake.set()

    def _dispatcher_crash(self, ready: List[_Request],
                          exc: BaseException) -> None:
        """A crash in the dispatch cycle OUTSIDE the scoring call
        (fault site ``serve.dispatch`` or a bug): classify it, requeue
        transient survivors with retries left, fail everything else
        with ``ServeError(reason="dispatcher-crash")`` — the loop
        restarts (see ``_run``) and the queue never wedges."""
        from oap_mllib_tpu.utils import resilience

        _tm.counter(
            "oap_serve_dispatch_crashes_total",
            help="Dispatcher-thread crashes survived by the traffic "
                 "plane (futures failed/requeued, dispatch restarted)",
        ).inc()
        with self._lock:
            pending_ids = {id(r) for r in self._pending}
        leftover = [
            r for r in ready
            if not r.future.done() and id(r) not in pending_ids
        ]
        kind = resilience.classify_fault(exc)
        if kind == resilience.TRANSIENT:
            policy = resilience.RetryPolicy.for_serving()
            retriable = [
                r for r in leftover if r.retries < policy.max_retries
            ]
            leftover = [
                r for r in leftover if r.retries >= policy.max_retries
            ]
            if retriable:
                self._requeue(retriable, self._clock(), policy)
        for r in leftover:
            self._land_exc(r, ServeError(
                "dispatcher-crash",
                f"the dispatch cycle scoring request seq={r.seq} "
                "crashed; the dispatcher restarts but this future "
                "fails loudly rather than hang",
                fault_class=kind, retries=r.retries, cause=exc,
            ))

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        """Graceful release of this replica's queue: stop admission
        (subsequent submits shed with ``reason="draining"``), flush
        pending + retrying futures until the queue is empty or the
        WALL deadline (``timeout_s``) expires, then fail leftovers
        loudly with ``ServeError(reason="drain-deadline")`` — every
        accepted future resolves before the replica releases.  Books
        ``oap_serve_drains_total``, posts a
        ``serve.drain.done.rank<r>.json`` report on the crash sideband
        when armed, and returns the stats dict.  Wired into
        ``ScaleController`` scale-in decisions and
        ``ha.ReplicaGuard.release``."""
        faults.maybe_fault("serve.drain")
        from oap_mllib_tpu.telemetry import flightrec

        with self._lock:
            self._draining = True
            start_pending = len(self._pending) + len(self._inflight)
            for r in self._pending:
                if r.trace is not None:
                    r.trace.event(
                        "drain", f"pending={start_pending}",
                        self._clock(),
                    )
        flightrec.record(
            "serve", "drain", f"pending={start_pending}"
        )
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        answered0 = self.answered
        while True:
            try:
                self.pump()
            except Exception:  # noqa: BLE001 — crash path already
                pass           # failed/requeued the cycle's futures
            with self._lock:
                left = len(self._pending) + len(self._inflight)
            if left == 0 or time.monotonic() >= deadline:
                break
            self._wake.set()
            time.sleep(min(self._poll_s, 0.005))
        with self._lock:
            leftovers = list(self._pending)
            self._pending = []
            stuck = [
                r for r in self._inflight.values()
                if not r.future.done()
            ]
        if leftovers:
            from oap_mllib_tpu.serving import registry

            registry.note_queue_depth(-len(leftovers))
        failed = 0
        for r in leftovers + stuck:
            if self._land_exc(r, ServeError(
                "drain-deadline",
                f"request seq={r.seq} unresolved when the drain "
                f"deadline ({timeout_s:g}s) expired; resubmit to a "
                "live replica",
                retries=r.retries,
            )):
                failed += 1
        stats = {
            "pending_at_drain": start_pending,
            "answered": self.answered - answered0,
            "failed": failed,
            "drained": failed == 0,
            "timeout_s": float(timeout_s),
        }
        _tm.counter(
            "oap_serve_drains_total",
            help="Graceful drains of the traffic queue (scale-in / "
                 "shutdown)",
        ).inc()
        self._write_drain_report(stats)
        return stats

    def _write_drain_report(self, stats: Dict[str, Any]) -> Optional[str]:
        """Post the drain outcome on the supervisor sideband (atomic
        tmp+rename, the scale-hint pattern) so the supervisor's shrink
        path can confirm the released replica flushed its futures."""
        crash_dir = str(get_config().crash_dir or "")
        if not crash_dir:
            return None
        try:
            import jax

            rank = int(jax.process_index())
        except Exception:  # noqa: BLE001 — sidebandless single host
            rank = 0
        os.makedirs(crash_dir, exist_ok=True)
        path = os.path.join(crash_dir, f"serve.drain.done.rank{rank}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rank": rank, **stats}, f)
        os.replace(tmp, path)
        return path

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Stop admissions, join the dispatcher (R22), drain leftovers
        through one final :meth:`pump`, then FAIL-or-flush: any future
        still unresolved (a retry whose backoff never elapsed, a
        scoring callable that wedged the dispatcher past ``timeout_s``)
        raises ``ServeError(reason="shutdown")`` — close never leaks a
        pending future, wedged or not."""
        with self._lock:
            self._closed = True
            for r in self._pending:
                # final pump dispatches retries immediately: their
                # backoff is moot once the queue is closing
                r.not_before = 0.0
        self._stop.set()
        self._wake.set()
        t = self._thread
        wedged = False
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                # the scoring callable wedged the dispatcher: the
                # daemon flag alone would silently strand every pending
                # future — fail them explicitly instead
                wedged = True
                _tm.counter(
                    "oap_serve_close_wedged_total",
                    help="close() calls that found the dispatcher "
                         "wedged in a scoring call past the join "
                         "timeout (pending futures failed explicitly)",
                ).inc()
                warnings.warn(
                    "serving traffic: dispatcher did not join within "
                    f"{timeout_s}s at close (scoring callable wedged); "
                    "failing every unresolved future loudly",
                    RuntimeWarning, stacklevel=2,
                )
            else:
                self._thread = None
        if not wedged:
            try:
                self.pump()
            except Exception:  # noqa: BLE001 — crash path already
                pass           # failed/requeued the cycle's futures
        with self._lock:
            leftovers = list(self._pending)
            self._pending = []
            stuck = [
                r for r in self._inflight.values()
                if not r.future.done()
            ]
        if leftovers:
            from oap_mllib_tpu.serving import registry

            registry.note_queue_depth(-len(leftovers))
        for r in leftovers + stuck:
            self._land_exc(r, ServeError(
                "shutdown",
                f"request seq={r.seq} unresolved at TrafficQueue "
                "close; resubmit to a live replica",
                retries=r.retries,
            ))

    def __enter__(self) -> "TrafficQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- brownout degradation ladder ----------------------------------------------


class BrownoutController:
    """Degrade before you shed: under sustained over-budget admission
    pressure (priced bytes vs the serving allowance — the same pricing
    the budget shed uses), step through recorded degradation rungs
    instead of immediately refusing work.  Rungs, in order:

    0. ``off``   — no degradation (the steady state);
    1. ``topk``  — recommendation top-k depth halves
       (:func:`brownout_topk`); score work shrinks, answers shorten;
    2. ``bf16``  — serving precision drops to bf16 for algorithms with
       a recorded parity bound (:func:`brownout_precision_override`,
       consumed by ``batcher.resolve_policy``; an explicit
       ``serving_precision`` pin always wins);
    3. ``stale`` — model re-pins may answer from the previous (stale)
       device pin instead of blocking (:func:`brownout_stale_ok`,
       consumed by ``registry.pin``).

    Stepping is gated like ``ScaleController``: a FULL window of
    samples whose mean pressure ratio exceeds 1.0 with a non-falling
    trend (``telemetry/fleet._trend``) steps up; mean below 0.5 with a
    non-rising trend steps down.  ``pin:<rung>`` holds a rung
    unconditionally; ``off`` disables the ladder.  Every step is LOUD:
    ``oap_serve_brownout_rung`` gauge, ``oap_serve_brownout_steps_
    total{direction=}``, a flight-recorder entry, the enclosing span's
    ``brownout`` attr, and ``serving_summary()["brownout"]``.

    A breach is ABSORBED (admitted over budget) when the ladder just
    stepped or holds an intermediate rung — the degradation buys back
    the working set.  At the top rung with pressure still sustained,
    the budget shed resumes as the backstop: brownout delays shedding,
    it never disables the OOM guard."""

    RUNGS = BROWNOUT_RUNGS
    WINDOW = 4  # samples per step decision (fleet._trend's minimum)

    def __init__(self, policy: Optional[str] = None):
        raw = str(
            get_config().serve_brownout if policy is None else policy
        ).strip().lower()
        self.policy = raw
        self.pinned = _parse_brownout(raw)
        self.rung = self.pinned or 0
        self.absorbed = 0
        self._ratios: deque = deque(maxlen=self.WINDOW)
        self.steps: List[Dict[str, Any]] = []
        self._gauge()

    def _gauge(self) -> None:
        _tm.gauge(
            "oap_serve_brownout_rung",
            help="Current brownout degradation rung (0=off, 1=topk, "
                 "2=bf16, 3=stale)",
        ).set(self.rung)

    def _step(self, direction: int, ratio: float, trend: str) -> None:
        old = self.rung
        self.rung += direction
        step = {
            "from": self.RUNGS[old],
            "to": self.RUNGS[self.rung],
            "ratio": round(float(ratio), 3),
            "trend": trend,
        }
        # observe-only SLO wiring: the step stays pressure-driven, but
        # it RECORDS the burn-rate state that witnessed it
        from oap_mllib_tpu.serving import slo

        slo_brief = slo.brief()
        if slo_brief:
            step["slo"] = slo_brief
        self.steps.append(step)
        self._ratios.clear()  # each rung needs fresh sustained samples
        self._gauge()
        _tm.counter(
            "oap_serve_brownout_steps_total",
            {"direction": "up" if direction > 0 else "down"},
            help="Brownout ladder rung steps, by direction",
        ).inc()
        from oap_mllib_tpu.telemetry import flightrec

        flightrec.record(
            "serve", "brownout",
            f"rung {self.RUNGS[old]}->{self.RUNGS[self.rung]} "
            f"(pressure {ratio:.2f}, {trend})",
        )
        from oap_mllib_tpu.telemetry.spans import current_span

        sp = current_span()
        if sp is not None:
            sp.attrs["brownout"] = self.RUNGS[self.rung]

    def observe_admission(self, priced: int, budget: int) -> Dict[str, Any]:
        """Fold one priced admission; returns the decision dict (rung,
        whether THIS breach is absorbed, the pressure ratio/trend).
        Never blocks: called under the admission lock in ``submit``."""
        ratio = float(priced) / float(budget) if budget > 0 else 0.0
        self._ratios.append(ratio)
        if self.policy == "off" or self.pinned is not None:
            # pinned rungs degrade but never absorb a breach silently:
            # the operator pinned quality, not the admission contract
            return {
                "rung": self.rung, "rung_name": self.RUNGS[self.rung],
                "absorb": False, "ratio": ratio, "stepped": 0,
            }
        from oap_mllib_tpu.telemetry.fleet import _trend

        trend = _trend(list(self._ratios))
        mean = float(np.mean(self._ratios))
        stepped = 0
        if (len(self._ratios) == self.WINDOW
                and mean > 1.0
                and trend != "falling"
                and self.rung < len(self.RUNGS) - 1):
            self._step(+1, ratio, trend)
            stepped = 1
        elif (len(self._ratios) == self.WINDOW
                and mean < 0.5
                and trend != "rising"
                and self.rung > 0):
            self._step(-1, ratio, trend)
            stepped = -1
        absorb = ratio > 1.0 and (
            stepped > 0 or 0 < self.rung < len(self.RUNGS) - 1
        )
        if absorb:
            self.absorbed += 1
            _tm.counter(
                "oap_serve_brownout_absorbed_total",
                help="Over-budget admissions absorbed by an active "
                     "brownout rung instead of shed",
            ).inc()
        return {
            "rung": self.rung, "rung_name": self.RUNGS[self.rung],
            "absorb": absorb, "ratio": ratio, "stepped": stepped,
        }

    # public spelling; the ladder "observes" pressure like the
    # ScaleController observes the queue
    observe = observe_admission

    def summary(self) -> Dict[str, Any]:
        out = {
            "policy": self.policy,
            "rung": self.RUNGS[self.rung],
            "rung_index": self.rung,
            "steps": len(self.steps),
            "absorbed": self.absorbed,
        }
        if self.steps:
            out["last_step"] = dict(self.steps[-1])
        return out


_BROWNOUT: Optional[BrownoutController] = None


def brownout() -> BrownoutController:
    """The process-wide brownout ladder, lazily (re)built whenever
    ``Config.serve_brownout`` changes — the progcache/registry
    singleton pattern."""
    global _BROWNOUT
    raw = str(get_config().serve_brownout).strip().lower()
    with _STATE_LOCK:
        b = _BROWNOUT
        if b is None or b.policy != raw:
            b = BrownoutController(raw)
            _BROWNOUT = b
    return b


def brownout_rung() -> int:
    """Current rung index (0 = off) without forcing a rebuild cycle —
    cross-module consumers (batcher, registry, sweep) key off this."""
    return brownout().rung


def brownout_topk(k: int) -> int:
    """Rung >= topk: halve the requested recommendation depth (floor
    1).  NOTE the reduced-k program is a new static shape — warm it
    (``warmup``) before relying on the zero-compile steady state at
    this rung."""
    if brownout_rung() >= BROWNOUT_RUNGS.index("topk"):
        reduced = max(1, int(k) // 2)
        if reduced < int(k):
            _tm.counter(
                "oap_serve_brownout_topk_reduced_total",
                help="Recommendation requests answered at reduced "
                     "top-k depth under brownout",
            ).inc()
        return reduced
    return int(k)


def brownout_precision_override(algo: str) -> str:
    """Rung >= bf16 AND the algorithm has a recorded parity bound:
    return "bf16" for ``batcher.resolve_policy`` to fold in (an
    explicit ``serving_precision`` pin always wins); else ""."""
    if brownout_rung() >= BROWNOUT_RUNGS.index("bf16"):
        from oap_mllib_tpu.utils.precision import PARITY_BOUNDS

        if algo in PARITY_BOUNDS:
            return "bf16"
    return ""


def brownout_stale_ok() -> bool:
    """Rung >= stale: ``registry.pin`` may answer from the previous
    (stale) device pin during a model re-pin instead of blocking on
    the fresh transfer."""
    return brownout_rung() >= BROWNOUT_RUNGS.index("stale")


# -- replica-count control ----------------------------------------------------


class ScaleController:
    """Replica count as a controlled variable.

    Feed it queue-depth/p99 samples — from :func:`serving.heartbeat`
    fleet views (:meth:`observe_view`) or straight numbers
    (:meth:`observe`) — and it votes: **out** when the windowed mean
    queue depth per replica exceeds ``Config.serve_scale_high`` and the
    depth trend (``telemetry/fleet._trend``) is not falling; **in**
    when the fleet sat idle (zero depth, no new requests) for
    ``Config.serve_scale_idle_s``; **hold** otherwise.  Decisions book
    ``oap_serve_scale_out_total`` / ``oap_serve_scale_in_total`` / the
    ``oap_serve_scale_replicas`` gauge, surface in
    ``serving_summary()['scale']``, and :func:`write_scale_hint` posts
    them to the supervisor sideband so the next relaunch is sized by
    live traffic."""

    WINDOW = 4  # samples per decision window (fleet._trend's minimum)

    def __init__(self, replicas: int, *, min_replicas: int = 1,
                 max_replicas: int = 0,
                 high: Optional[float] = None,
                 idle_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 queue: Optional[TrafficQueue] = None):
        cfg = get_config()
        self.high = float(cfg.serve_scale_high if high is None else high)
        self.idle_s = float(
            cfg.serve_scale_idle_s if idle_s is None else idle_s
        )
        if self.high <= 0:
            raise ValueError(
                f"serve_scale_high must be > 0, got {self.high}"
            )
        if self.idle_s <= 0:
            raise ValueError(
                f"serve_scale_idle_s must be > 0, got {self.idle_s}"
            )
        if replicas < 1 or min_replicas < 1:
            raise ValueError(
                f"replicas/min_replicas must be >= 1, got "
                f"{replicas}/{min_replicas}"
            )
        self.replicas = int(replicas)
        self.min_replicas = int(min_replicas)
        # 0 = unbounded growth is never sane for a supervisor-run
        # fleet: default cap is the starting size x2
        self.max_replicas = int(max_replicas) or 2 * int(replicas)
        self._clock = clock
        # the local replica's queue, when attached: a scale-IN decision
        # gracefully drains it (stop admission, flush futures) before
        # the replica releases — no future dies with the shrink
        self._queue = queue
        self._depths: deque = deque(maxlen=self.WINDOW)
        self._p99s: deque = deque(maxlen=self.WINDOW)
        self._last_busy = clock()
        self._last_requests: Optional[int] = None
        self.decisions: List[Dict[str, Any]] = []

    def observe_view(self, view: Dict[str, Any],
                     p99_s: float = 0.0) -> Dict[str, Any]:
        """One observation from a :func:`serving.heartbeat` fleet view
        (fleet-wide queue depth = sum across replicas; replica count
        tracks the view's world)."""
        self.replicas = max(self.min_replicas, int(view.get("world", 1)))
        return self.observe(
            queue_depth=int(sum(view.get("queue_depth", []) or [0])),
            p99_s=p99_s,
            requests=int(sum(view.get("requests", []) or [0])),
        )

    def observe(self, queue_depth: int, p99_s: float = 0.0,
                requests: Optional[int] = None) -> Dict[str, Any]:
        """Fold one sample, return the decision dict (action out/in/
        hold, replicas, reason, the sample, the trends)."""
        from oap_mllib_tpu.telemetry.fleet import _trend

        now = self._clock()
        self._depths.append(float(queue_depth))
        self._p99s.append(float(p99_s))
        busy = queue_depth > 0 or (
            requests is not None and requests != self._last_requests
        )
        if requests is not None:
            self._last_requests = requests
        if busy:
            self._last_busy = now
        depth_trend = _trend(list(self._depths))
        p99_trend = _trend(list(self._p99s))
        per_replica = (
            float(np.mean(self._depths)) / max(1, self.replicas)
        )
        action, reason = "hold", ""
        if (len(self._depths) == self.WINDOW
                and per_replica > self.high
                and depth_trend != "falling"
                and self.replicas < self.max_replicas):
            action = "out"
            self.replicas += 1
            reason = (
                f"queue depth/replica {per_replica:.1f} > "
                f"serve_scale_high={self.high:g} (depth {depth_trend}, "
                f"p99 {p99_trend})"
            )
            self._depths.clear()
            self._p99s.clear()
            _tm.counter(
                "oap_serve_scale_out_total",
                help="Scale-out decisions by the serving replica "
                     "controller",
            ).inc()
        elif (now - self._last_busy >= self.idle_s
                and self.replicas > self.min_replicas):
            action = "in"
            self.replicas -= 1
            reason = (
                f"idle {now - self._last_busy:.1f}s >= "
                f"serve_scale_idle_s={self.idle_s:g}"
            )
            self._last_busy = now
            _tm.counter(
                "oap_serve_scale_in_total",
                help="Scale-in decisions by the serving replica "
                     "controller",
            ).inc()
        _tm.gauge(
            "oap_serve_scale_replicas",
            help="Replica count the serving scale controller currently "
                 "wants",
        ).set(self.replicas)
        decision = {
            "action": action,
            "replicas": self.replicas,
            "reason": reason,
            "queue_depth": int(queue_depth),
            "queue_depth_per_replica": round(per_replica, 3),
            "p99_s": float(p99_s),
            "depth_trend": depth_trend,
            "p99_trend": p99_trend,
        }
        # observe-only SLO wiring: the decision stays queue-driven, but
        # it RECORDS the burn-rate state that witnessed it
        from oap_mllib_tpu.serving import slo

        slo_brief = slo.brief()
        if slo_brief:
            decision["slo"] = slo_brief
        if action == "in" and self._queue is not None:
            # graceful shrink: the released replica stops admission and
            # flushes every accepted future BEFORE the world resizes
            decision["drained"] = self._queue.drain()
        self.decisions.append(decision)
        with _STATE_LOCK:
            _scale_state.clear()
            _scale_state.update(decision)
        return decision


def write_scale_hint(crash_dir: str,
                     decision: Dict[str, Any]) -> Optional[str]:
    """Post a non-hold scale decision on the supervisor sideband
    (``crash_dir/serve.scale.hint.json``, atomic tmp+rename — the
    balance.hint.json pattern).  The supervisor consumes it
    read-and-remove when sizing the next relaunch.  Returns the path
    (None for hold decisions or an unarmed sideband)."""
    import json
    import os

    if not crash_dir or decision.get("action") not in ("out", "in"):
        return None
    os.makedirs(crash_dir, exist_ok=True)
    path = os.path.join(crash_dir, SCALE_HINT_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(decision, f)
    os.replace(tmp, path)
    return path


def summary_block() -> Dict[str, Any]:
    """The traffic-plane additions to ``serving_summary()``: shed and
    request-failure totals by reason, durable-future counters, the
    brownout ladder state, plus the scale controller's last
    decision."""
    out: Dict[str, Any] = {}
    reg = _tm.registry()
    with _tm._LOCK:
        sheds = {
            dict(labels).get("reason", ""): int(m.value)
            for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_shed_total"
        }
        fails = {
            dict(labels).get("reason", ""): int(m.value)
            for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_request_failures_total"
        }
    if sheds:
        out["shed"] = {"total": sum(sheds.values()), **sheds}
    futures = {
        "retries": int(_tm.family_total("oap_serve_retries_total")),
        "poison": int(_tm.family_total("oap_serve_poison_total")),
        "bisections": int(_tm.family_total("oap_serve_bisect_total")),
        "dispatcher_crashes": int(
            _tm.family_total("oap_serve_dispatch_crashes_total")
        ),
        "drains": int(_tm.family_total("oap_serve_drains_total")),
    }
    if fails:
        futures["failed"] = {"total": sum(fails.values()), **fails}
    if fails or any(futures[k] for k in
                    ("retries", "poison", "bisections",
                     "dispatcher_crashes", "drains")):
        out["futures"] = futures
    b = _BROWNOUT
    if b is not None and (b.rung or b.steps or b.policy != "auto"
                          or b.absorbed):
        out["brownout"] = b.summary()
    with _STATE_LOCK:
        if _scale_state:
            out["scale"] = dict(_scale_state)
    attr = reqtrace.attribution_block()
    if attr:
        out["attribution"] = attr
    from oap_mllib_tpu.serving import slo

    s = slo.summary_block()
    if s:
        out["slo"] = s
    return out


def serving_health_block() -> Dict[str, Any]:
    """The ``serving`` block of ``/healthz`` (telemetry/fleet.py): what
    a pure-serving replica is DOING — queue depth, in-flight count,
    pinned models, the brownout rung, the last shed (reason + age), and
    the SLO burn state — so a scrape is no longer empty of the thing
    the replica exists for."""
    from oap_mllib_tpu.serving import registry, slo

    out: Dict[str, Any] = {
        "queue_depth": registry.queue_depth(),
        "in_flight": sum(
            len(q._inflight) for q in list(_QUEUES)
        ),
        "pinned_models": len(registry.served_models()),
    }
    handles = list(registry.served_models().values())
    if handles:
        # model freshness (online/delta.py commits): the staleness
        # gauge refreshes on every scrape through touch_staleness
        out["models"] = [
            {
                "kind": h.kind,
                "model_version": h.model_version,
                "staleness_seconds": round(h.touch_staleness(), 3),
            }
            for h in handles
        ]
    b = _BROWNOUT
    out["brownout_rung"] = BROWNOUT_RUNGS[b.rung] if b is not None \
        else "off"
    with _STATE_LOCK:
        last = _last_shed
    if last is not None:
        out["last_shed"] = {
            "reason": last[0],
            "age_s": round(max(0.0, time.monotonic() - last[1]), 3),
        }
    s = slo.brief()
    if s:
        out["slo"] = s
    return out


def _reset_for_tests() -> None:
    global _BROWNOUT, _last_shed
    with _STATE_LOCK:
        _scale_state.clear()
        _BROWNOUT = None
        _last_shed = None
