"""Request micro-batching: shape-bucketed, progcache-launched scoring.

XLA specializes every program on its input shapes, so a serving plane
answering jittered request sizes would recompile the scoring program
per distinct batch size — seconds of XLA latency injected into random
requests.  This module is the serving half of the compile-amortization
contract (data/bucketing.py + utils/progcache.py): every incoming
batch rounds UP onto the geometric row buckets (padding rows are
sliced back off the result — they are dead weight, never aggregated,
so results are identical to the exact-shape launch), and every scoring
program dispatches through the program-cache registry.  Steady state —
after :func:`~oap_mllib_tpu.serving.registry.ServedModel.warmup` or
one storm through the bucket family — compiles ZERO new XLA programs
(``dev/serve_gate.py`` asserts this against ``xla_compile_count``
ground truth).

Inputs are staged with an EXPLICIT ``jax.device_put`` (serving request
paths stay clean under the ``transfer`` sanitizer's disallow guard)
and the staged buffer is donated to the scoring program off-CPU — the
pad+score+top-k chain reuses the request's own HBM.  Scoring matmuls
route through ``precision.pdot`` under the serving dtype policy
(``Config.serving_precision``; empty inherits the per-algorithm
compute policy — the f32 default is bit-compatible with the direct
model calls).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data.bucketing import bucket_rows
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.faults import maybe_fault

# bucket anchor for request row counts: buckets are the x2 geometric
# series over multiples of 8 (vector-lane friendly, and small requests
# round to at most 8 rows of masked padding)
SERVE_ROW_MULTIPLE = 8

_SERVING_CHOICES = ("", "f32", "tf32", "bf16", "auto")


def resolve_policy(algo: str) -> psn.PrecisionPolicy:
    """The serving-time compute policy for ``algo``'s scoring matmuls.

    ``Config.serving_precision`` empty inherits the algorithm's resolved
    compute policy (``precision.resolve`` — so a bf16-fit service scores
    bf16 without a second knob); a non-empty value overrides it with the
    same vocabulary, re-using resolve's auto/x64 pins by resolving
    against a config copy whose global policy is the override.  A typo
    raises at request time (the kmeans_kernel contract).

    The brownout ladder's ``bf16`` rung
    (``traffic.brownout_precision_override``) folds in HERE — but only
    when no explicit ``serving_precision`` pin exists and the algorithm
    has a recorded parity bound: an operator pin always beats a
    degradation rung."""
    cfg = get_config()
    raw = cfg.serving_precision
    if raw not in _SERVING_CHOICES:
        raise ValueError(
            "serving_precision must be one of "
            f"{'|'.join(v or '<empty>' for v in _SERVING_CHOICES)}, "
            f"got {raw!r}"
        )
    if not raw:
        from oap_mllib_tpu.serving import traffic

        browned = traffic.brownout_precision_override(algo)
        if browned:
            return psn.resolve(
                algo,
                dataclasses.replace(
                    cfg, compute_precision=browned,
                    kmeans_precision="", pca_precision="",
                    als_precision="",
                ),
            )
        return psn.resolve(algo)
    return psn.resolve(
        algo,
        dataclasses.replace(
            cfg, compute_precision=raw,
            kmeans_precision="", pca_precision="", als_precision="",
        ),
    )


def bucket_batch(x: np.ndarray,
                 multiple: int = SERVE_ROW_MULTIPLE) -> Tuple[np.ndarray, int]:
    """Round a request batch up to its geometric row bucket.

    Returns ``(padded, n)`` — padded has ``bucket_rows(n)`` rows (zero
    rows appended; every consumer slices the result back to ``n``).
    ``Config.shape_bucketing`` governs the series exactly as it does
    for fits ("off" = exact padding to the multiple)."""
    t0 = time.perf_counter()
    x = np.ascontiguousarray(np.atleast_2d(x))
    n = x.shape[0]
    b = bucket_rows(max(n, 1), multiple)
    if b != n:
        x = np.concatenate(
            [x, np.zeros((b - n, x.shape[1]), x.dtype)], axis=0
        )
    # fold the pad wall into any attached request ledgers (a thread-
    # local miss when no traced flush is in flight — the disarmed seam)
    from oap_mllib_tpu.serving import reqtrace

    reqtrace.note_flush("bucket_pad", time.perf_counter() - t0)
    return x, n


def stage(x: np.ndarray):
    """Explicit host->device staging of one request payload.  Explicit
    (``jax.device_put``) so serving request paths run clean under the
    ``transfer`` sanitizer's disallow guard — any OTHER transfer in the
    hot path is then a caught bug, not noise."""
    import jax

    return jax.device_put(np.asarray(x))


def _donate_args() -> tuple:
    """Donate the staged request buffer to the scoring program — the
    pad/score chain reuses the request's own device memory.  CPU keeps
    buffers (XLA CPU does not implement donation; donating there only
    logs a warning per compile)."""
    import jax

    return (0,) if jax.default_backend() != "cpu" else ()


def _book(kind: str, pad: int) -> None:
    # every scoring batch is a fault-injection site ("serve.request",
    # utils/faults.py) so request-path faults are drillable like every
    # other runtime seam; unarmed, maybe_fault is a dict miss
    maybe_fault("serve.request")
    lab = {"model": kind}
    _tm.counter(
        "oap_serve_batches_total", lab,
        help="Scoring batches launched by the serving plane",
    ).inc()
    _tm.counter(
        "oap_serve_pad_rows_total", lab,
        help="Bucket-padding rows added to serving batches "
             "(masked, sliced off results)",
    ).inc(pad)


# -- scoring programs (one jitted family per op, progcache-registered) --------


def _build_assign(tier: str, policy: str):
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import kmeans_ops

    def kernel(xb, centers):
        d2 = kmeans_ops.pairwise_sq_dists(xb, centers, tier, policy)
        return jnp.argmin(d2, axis=1).astype(jnp.int32)

    return jax.jit(kernel, donate_argnums=_donate_args())


def assign_kmeans(centers_dev, x: np.ndarray, kind: str = "kmeans"):
    """Bucketed nearest-center assignment: pad ``x`` to its row bucket,
    launch the registry-cached assignment program against the PINNED
    centers, slice ids back to the request rows."""
    import jax

    pol = resolve_policy("kmeans")
    xb, n = bucket_batch(np.asarray(x, dtype=np.dtype(centers_dev.dtype)))
    _book(kind, xb.shape[0] - n)
    fn = progcache.get_or_build(
        "serve.assign",
        (progcache.backend_fingerprint(), pol.name, pol.dot_tier),
        lambda: _build_assign(pol.dot_tier, pol.name),
    )
    staged = stage(xb)
    progcache.note(
        "serve.assign",
        (pol.name, pol.dot_tier,
         progcache.array_key(staged, centers_dev)),
    )
    out = fn(staged, centers_dev)
    return jax.device_get(out)[:n]


def _build_project(tier: str, policy: str):
    import jax

    def kernel(xb, components):
        return psn.pdot(xb, components, policy, tier)

    return jax.jit(kernel, donate_argnums=_donate_args())


def project_pca(components_dev, x: np.ndarray, kind: str = "pca"):
    """Bucketed principal-component projection against the pinned
    (d, k) component matrix (no centering — Spark parity)."""
    import jax

    pol = resolve_policy("pca")
    xb, n = bucket_batch(np.asarray(x, dtype=components_dev.dtype))
    _book(kind, xb.shape[0] - n)
    fn = progcache.get_or_build(
        "serve.project",
        (progcache.backend_fingerprint(), pol.name, pol.dot_tier),
        lambda: _build_project(pol.dot_tier, pol.name),
    )
    staged = stage(xb)
    progcache.note(
        "serve.project",
        (pol.name, pol.dot_tier,
         progcache.array_key(staged, components_dev)),
    )
    out = fn(staged, components_dev)
    return jax.device_get(out)[:n]


def _build_topk(tier: str, policy: str):
    import jax

    def kernel(q, targets, n):
        scores = psn.pdot(q, targets.T, policy, tier)
        return jax.lax.top_k(scores, n)

    return jax.jit(kernel, static_argnames=("n",),
                   donate_argnums=_donate_args())


def topk_pairs(q_dev, targets_dev, n: int, kind: str = "als"):
    """Top-``n`` (scores, ids) per query row against the pinned target
    factors — the serving analog of models/als ``_top_k_pairs``, shared
    by the subset recommenders and the full-sweep chunks.  Returns
    DEVICE arrays (sweep consumers fetch explicitly)."""
    pol = resolve_policy("als")
    fn = progcache.get_or_build(
        "serve.topk",
        (progcache.backend_fingerprint(), pol.name, pol.dot_tier),
        lambda: _build_topk(pol.dot_tier, pol.name),
    )
    progcache.note(
        "serve.topk",
        (pol.name, pol.dot_tier, int(n),
         progcache.array_key(q_dev, targets_dev)),
    )
    return fn(q_dev, targets_dev, int(n))


def topk_scores(query: np.ndarray, targets_dev, n: int,
                kind: str = "als") -> Tuple[np.ndarray, np.ndarray]:
    """Bucketed one-shot top-k for a REQUEST batch of query rows (the
    subset-recommender surface).  The full-user-base sweep lives in
    :mod:`oap_mllib_tpu.serving.sweep` (streamed + sharded)."""
    import jax

    n = min(int(n), int(targets_dev.shape[0]))
    qb, rows = bucket_batch(np.asarray(query, np.float32))
    _book(kind, qb.shape[0] - rows)
    s, i = topk_pairs(stage(qb), targets_dev, n, kind=kind)
    return (
        jax.device_get(i)[:rows].astype(np.int32),
        jax.device_get(s)[:rows],
    )


def warm_sizes(max_rows: int,
               multiple: int = SERVE_ROW_MULTIPLE) -> list:
    """The bucket family covering request sizes up to ``max_rows`` —
    one warmup launch per entry compiles every program a steady-state
    storm of sizes <= max_rows can ever need."""
    out = []
    n = 1
    while True:
        b = bucket_rows(n, multiple)
        if not out or b != out[-1]:
            out.append(b)
        if b >= max_rows:
            break
        n = b + 1
    return out


def xla_snapshot() -> Optional[int]:
    """XLA compile count snapshot helper for gates/benches: the current
    ground-truth backend-compile count (``progcache.xla_compile_count``)
    so callers can assert a ZERO delta across a steady-state storm."""
    return progcache.xla_compile_count()
