"""Serving plane: fitted models as a high-availability, high-QPS workload.

Everything through the fit planes optimizes ``fit()``; production value
at the ROADMAP's "millions of users" scale is dominated by the FITTED
model surface — ``predict`` / ``transform`` / ``recommend_for_all_*``
(the reference's blockified recommendForAll, ALS.scala:383-401).  This
package makes that surface a first-class workload composed from the
existing subsystems instead of a per-call eager afterthought:

- :mod:`~oap_mllib_tpu.serving.registry` — ``serve(model)`` pins fitted
  state (centers / components / factor tables) on-device ONCE, keyed
  like the program cache, so no scoring call ever re-uploads weights;
  per-request telemetry (``oap_serve_*`` counters + factor-4 log-bucket
  latency histograms) rides the PR 11 ``/metrics`` endpoint.
- :mod:`~oap_mllib_tpu.serving.batcher` — request micro-batching:
  incoming batches round up onto the ``data/bucketing.py`` geometric
  buckets (pad rows are sliced back off — mask/weight-0 contract), and
  every scoring program launches through ``utils/progcache.py``, so a
  steady-state request storm of jittered sizes compiles ZERO new XLA
  programs; scoring matmuls take staged (donated off-CPU) buffers and
  route through ``precision.pdot`` under the serving dtype policy
  (``Config.serving_precision``).
- :mod:`~oap_mllib_tpu.serving.sweep` — full-sweep top-k at scale:
  ``recommend_for_all_users`` as a streamed, prefetch-pipelined
  (``data/prefetch.py``) sweep over 10M+ users that never materializes
  the quadratic score matrix, and a factor-sharded ring sweep (the
  PR 9 ring schedule: item blocks rotate around the mesh while partial
  top-k merges stay put) serving block-sharded fits from their LIVE
  layout instead of gathering factors to one host.
- :mod:`~oap_mllib_tpu.serving.ha` — serving availability: replica
  heartbeats over the deadline-watchdogged host collective plane
  (utils/recovery.py); a replica that misses its deadline is EVICTED —
  survivors keep answering in local mode and the supervisor
  (utils/supervisor.py) relaunches the lost replica.
- :mod:`~oap_mllib_tpu.serving.traffic` — the production front:
  ``TrafficQueue.submit`` returns a future while a dispatcher thread
  forms flushes by DEADLINE (not arrival order) over ``predict_many``;
  admission control prices the staged working set against the
  ``utils/membudget.py`` planner and sheds LOUDLY (:class:`ShedError`
  + ``oap_serve_shed_total``) instead of letting a storm OOM a
  replica; :class:`ScaleController` turns replica count into a
  controlled variable (queue-depth/p99 trends -> ``oap_serve_scale_*``
  + the supervisor's ``serve.scale.hint.json`` sideband).  Accepted
  requests are DURABLE (ISSUE 18): a retry envelope re-enqueues
  transient scoring faults at original deadline priority, poison
  batches bisect on the warm bucket family until the poison request is
  quarantined (:class:`ServeError` + ``oap_serve_poison_total``),
  ``TrafficQueue.drain`` / ``ReplicaGuard.release`` flush every future
  before a replica lets go, and the :class:`BrownoutController` ladder
  (``Config.serve_brownout``) degrades top-k depth / precision /
  pin freshness under sustained pressure before anything sheds.
- :mod:`~oap_mllib_tpu.serving.reqtrace` — request-lifecycle tracing
  (ISSUE 19): ``Config.serve_trace_sample`` > 0 gives every admitted
  request a deadline-budget :class:`~oap_mllib_tpu.serving.reqtrace
  .Ledger` (admission / queue wait / batch formation / bucket pad /
  compile / execute / dispatch — stages sum to the request wall by
  construction) attached to its future (:func:`ledger_of`), booked as
  ``oap_serve_stage_seconds{stage=}`` histograms with trace-id
  exemplars, rolled into ``serving_summary()["attribution"]``, and —
  for sampled requests — emitted as flight-recorder events + JSONL
  records that ``dev/oaptrace.py`` merges into Perfetto request flows.
- :mod:`~oap_mllib_tpu.serving.slo` — the error-budget plane over the
  same ledger stream: a multi-window burn-rate engine
  (``Config.serve_slo_p99_ms`` / ``serve_slo_availability``) behind
  ``oap_slo_*`` gauges, ``serving_summary()["slo"]``, the ``/sloz``
  endpoint, and observe-only SLO state recorded with every
  scale/brownout decision.

Usage (docs/user-guide.md "Serving")::

    handle = serving.serve(model)        # pins weights on-device once
    handle.warmup(4096)                  # pre-compile the bucket family
    ids = handle.predict(batch)          # zero steady-state compiles

    with serving.TrafficQueue(handle) as q:          # async front
        futs = [q.submit(b, deadline_ms=50) for b in storm]
        ids = [f.result() for f in futs]             # or ShedError
"""

from oap_mllib_tpu.serving.registry import (  # noqa: F401
    ServedALS,
    ServedKMeans,
    ServedModel,
    ServedPCA,
    serve,
    served_models,
    serving_summary,
    unserve,
)
from oap_mllib_tpu.serving.ha import (  # noqa: F401
    ReplicaGuard,
    fleet_evicted,
    heartbeat,
)
from oap_mllib_tpu.serving.traffic import (  # noqa: F401
    BrownoutController,
    ScaleController,
    ServeError,
    ShedError,
    TrafficQueue,
    brownout,
    brownout_stale_ok,
    brownout_topk,
    serving_health_block,
    write_scale_hint,
)
from oap_mllib_tpu.serving.reqtrace import (  # noqa: F401
    Ledger,
    TraceContext,
    attribution_block,
    is_sampled,
    ledger_of,
    make_trace_id,
)
from oap_mllib_tpu.serving.slo import (  # noqa: F401
    SLOEngine,
    slo_state,
)
