"""Serving availability: replica heartbeats, eviction, relaunch.

A serving fleet is a set of replicas answering the same model's
requests (replicated weights) or cooperating on sharded sweeps.  The
failure mode that matters is the one PR 10 solved for fits: a replica
that silently stops arriving at the rendezvous would wedge every
healthy peer.  This module composes the same recovery plane for the
serving side:

- :func:`heartbeat` — a FIXED-shape per-rank stat frame (rank,
  requests answered, queue depth) allgathered over the host collective
  plane (``ops/stream_ops._allgather_host``), which inherits the
  deadline watchdog (``Config.collective_timeout``), the crash-record
  poison check, and the collective sanitizer's fingerprinting.  A
  replica that misses the deadline converts every survivor's wait into
  a ``CollectiveTimeoutError`` naming the op.
- :class:`ReplicaGuard` — the eviction policy: serving legs run under
  :meth:`ReplicaGuard.leg`; a recovery-plane error records the fatal
  fault (crash record into ``Config.crash_dir`` when armed), EVICTS
  the fleet view (survivors flip to local-only mode and keep
  answering), and counts ``oap_serve_evictions_total``.  The
  supervisor (``utils/supervisor.py`` / ``dev/supervise.py``) then
  classifies the crash records and relaunches the lost replica while
  the survivors never stopped serving.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import recovery

# process-wide eviction flag: once ANY ReplicaGuard evicts, sharded
# collectives spanning the old mesh are doomed (a dead peer never
# arrives) — serving/sweep.py checks this BEFORE launching so the
# failure becomes a classified re-form instead of a watchdog-less hang
_EVICTED = False


def fleet_evicted() -> bool:
    """True once a replica has been evicted in this process — the
    signal that the pre-eviction multi-process mesh must not be
    dispatched onto again (re-form on the survivors' layout instead)."""
    return _EVICTED


def _reset_for_tests() -> None:
    global _EVICTED
    _EVICTED = False


def heartbeat(requests: Optional[int] = None,
              queue_depth: Optional[int] = None) -> Dict[str, Any]:
    """One fleet heartbeat: allgather (rank, requests, queue_depth)
    across the serving world and return the fleet view.  Single-process
    worlds return the local view without a collective.  Riding the
    sanctioned host-collective seam means a dead replica surfaces here
    as ``CollectiveTimeoutError`` / ``PeerAbortError`` (when the
    deadline / sideband are armed) instead of a silent wedge."""
    import jax

    from oap_mllib_tpu.ops.stream_ops import _allgather_host

    if requests is None:
        requests = int(_tm.family_total("oap_serve_requests_total"))
    if queue_depth is None:
        # default to the live traffic-queue depth (pending + coalesced
        # in-flight) so fleet views and the scale controller see real
        # backlog without every call site plumbing it
        from oap_mllib_tpu.serving import registry

        queue_depth = registry.queue_depth()
    rank = jax.process_index()
    frame = np.asarray(
        [float(rank), float(requests), float(queue_depth)], np.float64
    )
    # _allgather_host adds the rank axis single-process too, so the
    # fleet view is shape-stable at any world size
    (stacked,) = _allgather_host([frame])
    stacked = np.asarray(stacked).reshape(-1, 3)
    view = {
        "world": stacked.shape[0],
        "rank": rank,
        "requests": [int(v) for v in stacked[:, 1]],
        "queue_depth": [int(v) for v in stacked[:, 2]],
    }
    _tm.counter(
        "oap_serve_heartbeats_total",
        help="Serving fleet heartbeats completed",
    ).inc()
    return view


class ReplicaGuard:
    """Eviction wrapper for serving legs.

    ::

        guard = ReplicaGuard()
        for batch in requests:
            with guard.leg():
                answer(batch)          # local scoring
                ha.heartbeat()         # fleet rendezvous (skipped once
                                       # local_only)

    A recovery-plane error inside a leg evicts the fleet: the fault is
    recorded (machine-readable crash record when ``Config.crash_dir``
    is armed — the supervisor's classification input), the guard flips
    to ``local_only``, and the leg RETURNS instead of raising — the
    survivor keeps answering requests with identical results (the
    weights are local; only the fleet view shrank).

    ``queue``: attach the replica's ``traffic.TrafficQueue`` and
    :meth:`release` gracefully drains it (stop admission, flush every
    accepted future, fail leftovers loudly) before the replica lets go
    — the scale-in/shutdown half of the request-lifecycle contract."""

    def __init__(self, queue=None):
        self.local_only = False
        self.evictions = 0
        self.last_error: Optional[BaseException] = None
        self.queue = queue

    def leg(self):
        return _Leg(self)

    def release(self, timeout_s: float = 5.0) -> Optional[Dict[str, Any]]:
        """Graceful replica release: drain + close the attached traffic
        queue so no accepted future dies with the replica; returns the
        drain stats (None when no queue is attached)."""
        stats = None
        q = self.queue
        if q is not None:
            stats = q.drain(timeout_s)
            q.close()
            from oap_mllib_tpu.serving import slo
            from oap_mllib_tpu.telemetry import flightrec

            # the release record carries the SLO state it let go under
            # (observe-only — the release itself stays drain-driven)
            brief = slo.brief()
            if brief:
                stats["slo"] = brief
            flightrec.record(
                "serve", "release",
                f"replica released: answered={stats['answered']} "
                f"failed={stats['failed']}",
            )
        return stats

    def _evict(self, exc: BaseException) -> None:
        global _EVICTED
        self.local_only = True
        self.evictions += 1
        self.last_error = exc
        _EVICTED = True
        _tm.counter(
            "oap_serve_evictions_total",
            help="Serving replicas evicted after recovery-plane errors",
        ).inc()
        # the watchdog/poison path already wrote this rank's crash
        # record (recovery-plane errors are the only ones absorbed
        # here) — the sideband is the supervisor's relaunch signal;
        # record_fatal covers any future non-recovery classes
        recovery.record_fatal("serve.heartbeat", exc)


class _Leg:
    def __init__(self, guard: ReplicaGuard):
        self._g = guard

    def __enter__(self):
        return self._g

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and isinstance(exc, recovery.RecoveryError):
            self._g._evict(exc)
            return True  # absorbed: the survivor keeps serving
        return False
