"""Streamed (out-of-core) kernels: full-pass K-Means / PCA over a ChunkSource.

Device memory is bounded by O(chunk_rows x d) while the algorithms make
whole-table passes: each pass walks the source once, pushing fixed-shape
chunks through ONE compiled per-chunk program whose accumulators live on
device (donated, so XLA updates them in place).  This is the capability the
reference does not have — its executors must hold their whole partition as
a native table in RAM (OneDAL.scala:92-166) — and it is what lets a single
chip with 16 GB HBM fit the 100M x 256 north-star table (100 GB) streamed
from host RAM / disk.

Pass structure:
- K-Means: one pass per Lloyd iteration (loop-body mode: half-score
  assignment, no cost), one final pass at "highest" for cost/counts.
- k-means|| init: 1 reservoir pass + 1 distance pass + init_steps sampling
  passes + 1 ownership pass (the in-memory version's device state becomes a
  host-resident per-chunk dmin, updated lazily one round behind — Bahmani's
  oversampling is robust to the one-round-stale phi used for sampling).
- PCA: one pass for the column sums (mean), one for the centered Gram —
  the same two-pass mean-centered form as ops.pca_ops.covariance (the
  one-pass raw-moment form cancels catastrophically; see that docstring).
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.ops import kmeans_ops
from oap_mllib_tpu.telemetry import fleet, flightrec
from oap_mllib_tpu.utils import faults
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils import recovery
from oap_mllib_tpu.utils import sanitizers
from oap_mllib_tpu.utils.timing import tick


def _chunk_weights(n_valid: int, chunk_rows: int, dtype) -> np.ndarray:
    w = np.zeros((chunk_rows,), dtype)
    w[:n_valid] = 1.0
    return w


def _iter_weighted(source: ChunkSource, weights, dtype):
    """Yield (chunk, n_valid, w_vec) where w_vec is the row-weight vector
    with padding masked to 0.  ``weights`` is None (all-ones), or a width-1
    ChunkSource walked in lockstep (its per-chunk valid counts must match
    the data source's)."""
    if weights is None:
        for chunk, n_valid in source:
            yield chunk, n_valid, _chunk_weights(n_valid, source.chunk_rows, dtype)
        return
    # drive off the DATA iterator: a bare zip would silently drop the
    # data tail if the weight source ran out at a chunk boundary (its
    # n_rows may be unknown before a completed pass, so the up-front
    # row-count check cannot always catch a mismatch)
    wit = iter(weights)
    for chunk, n_valid in source:
        wpair = next(wit, None)
        if wpair is None:
            raise ValueError(
                "sample_weight source ran out of chunks before the data "
                "source — the two must be chunked identically"
            )
        wchunk, wn = wpair
        if wn != n_valid:
            raise ValueError(
                f"sample_weight source yielded {wn} valid rows where the "
                f"data source yielded {n_valid} — the two must be chunked "
                "identically"
            )
        w = np.asarray(wchunk, dtype).reshape(-1)[: source.chunk_rows].copy()
        w[n_valid:] = 0.0
        yield chunk, n_valid, w
    if next(wit, None) is not None:
        raise ValueError(
            "sample_weight source has more chunks than the data source — "
            "the two must be chunked identically"
        )


def _stage_to_device(dtype, stats: PrefetchStats, stage_dtype=None):
    """Stage callable for the prefetch pipeline: pad/convert the host
    chunk and weight vector and issue their device transfers.  Runs in
    the producer thread at depth >= 2 — chunk N+1 stages while chunk N's
    step executes.  The host halves ride along because the k-means||
    loops sample/inspect rows host-side after the device fold.

    ``stage_dtype`` is the DATA chunk's staging dtype — under the bf16
    compute policy (utils/precision.staging_dtype) the cast happens HERE,
    in the producer thread, so the pad/convert output and the
    host->device transfer both carry half the bytes; weights stay at the
    accumulation dtype (they weight f32 accumulators)."""
    stage_dtype = dtype if stage_dtype is None else stage_dtype

    def stage(item):
        chunk, n_valid, w = item
        hc = np.asarray(chunk, stage_dtype)
        hw = np.asarray(w, dtype)
        with stats.transfer():
            cj = jnp.asarray(hc)
            wj = jnp.asarray(hw)
        return chunk, n_valid, w, cj, wj

    return stage


def _staged_chunks(source, weights, dtype, stats: PrefetchStats,
                   stage_dtype=None):
    """Prefetched (host_chunk, n_valid, host_w, dev_chunk, dev_w) stream
    over a (optionally weighted) ChunkSource.  The consumed chunk's
    device buffers retire as the consumer advances (module contract in
    data/prefetch.py).  ``stage_dtype``: see :func:`_stage_to_device`."""
    return Prefetcher(
        _iter_weighted(source, weights, dtype),
        stage=_stage_to_device(dtype, stats, stage_dtype),
        stats=stats,
        retire=True,
    )


# -- multi-host plumbing ----------------------------------------------------
# Each process streams its OWN shard (a per-process ChunkSource); the
# cross-process reductions are host-mediated via process_allgather — the
# DCN analog of the mesh path's ICI psums.  The reduced payloads are tiny
# ((k, d) sums, (d, d) Gram, scalars), so host mediation costs nothing
# next to the per-pass IO, and every process computes bit-identical
# results (deterministic rank-ordered gather + same summation order).


def _world() -> int:
    return jax.process_count()


class _PassGuard:
    """Capture a streaming-source error during a local pass so the next
    cross-process reduction still runs on EVERY rank.

    Without it, a rank whose source raises mid-pass (nondeterministic
    source row-count mismatch, lockstep weight mismatch, IO error) exits
    before its process_allgather while its peers are already blocked
    inside theirs — the world hangs until the distributed timeout.  With
    it, the erroring rank swallows the exception, reaches the reduction,
    and the reduction gathers a 1-byte error flag alongside the data:
    every rank then raises together (the local error is chained on the
    rank that observed it).  Single-process, the original exception is
    re-raised unchanged at the reduction.

    Usage::

        guard = _PassGuard()
        with guard:
            for chunk, n_valid in source: ...accumulate...
        out = _psum_host([...], guard=guard)
    """

    def __init__(self):
        self.err: Exception | None = None

    def __enter__(self) -> "_PassGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and isinstance(exc, Exception):
            self.err = exc
            return True  # swallowed; the next reduction re-raises on ALL ranks
        return False


def _gather_with_guard(arrays, guard: "_PassGuard | None"):
    """Shared core of _psum_host/_allgather_host: the x64-scoped
    process_allgather, with the guard's error flag riding in front of the
    payload so every rank fails together when any rank's pass failed.
    Returns the per-rank stacked arrays (flag already checked+stripped);
    None signals the single-process identity path (guard re-raised)."""
    if _world() == 1:
        if guard is not None and guard.err is not None:
            raise guard.err
        return None
    from jax.experimental import multihost_utils

    from oap_mllib_tpu.utils.timing import x64_scope

    if guard is not None:
        flag = np.asarray([0 if guard.err is None else 1], np.int64)
        arrays = [flag] + arrays
    # the host-mediated reductions are THE collectives of every streamed
    # multi-process pass: a dead peer surfaces exactly here, so the
    # gather is a fault site (collective.dispatch) and runs under the
    # recovery plane's deadline watchdog (utils/recovery) — a rank that
    # never arrives converts this from a hang into a
    # CollectiveTimeoutError on every survivor
    faults.maybe_fault("collective.dispatch")
    if flightrec.enabled():
        # dispatch fingerprint into the event ring BEFORE the
        # cross-check/gather — the seq a divergence diagnosis or a
        # timeout post-mortem points at (telemetry/flightrec.py)
        flightrec.record(
            "collective", "process_allgather",
            "|".join(str(tuple(np.shape(a))) for a in arrays),
        )
    # collective sanitizer seam: the signature (payload shapes + dtypes)
    # is fingerprinted and cross-checked across ranks before the gather —
    # a rank arriving here with a divergent payload raises on every rank
    # instead of wedging process_allgather (utils/sanitizers.py)
    sanitizers.note_collective(
        "process_allgather", "host",
        tuple(tuple(np.shape(a)) for a in arrays),
        ",".join(str(getattr(a, "dtype", "?")) for a in arrays),
    )
    with x64_scope(True):
        gathered = recovery.guarded_dispatch(
            "process_allgather", "host",
            lambda: multihost_utils.process_allgather(arrays),
        )
    if guard is not None:
        if int(np.asarray(gathered[0]).sum()) > 0:
            raise RuntimeError(
                "streamed pass failed on at least one process"
            ) from guard.err
        gathered = gathered[1:]
    return [np.asarray(g) for g in gathered]


def _materialize(arrays, guard: "_PassGuard | None"):
    """Fetch accumulators to host np arrays, under the guard: the
    np.asarray of an async device computation is where a rank-local XLA
    error (e.g. RESOURCE_EXHAUSTED mid-fit on one host) surfaces, and it
    must reach the collective like a source error — not strand peers in
    process_allgather.  On a failed fetch the payload is replaced by
    zeros of the same shapes (rank-consistent gather payloads are a
    collective requirement; the riding error flag aborts the world
    before anyone consumes them)."""
    if guard is not None:
        with guard:
            return [np.asarray(a) for a in arrays]
        return [
            np.zeros(np.shape(a), getattr(a, "dtype", np.float64))
            for a in arrays
        ]
    return [np.asarray(a) for a in arrays]


def _ring_mesh():
    """The device mesh for the streamed ring reduction, or None when the
    psum/host path must run: multi-process world, pure data-parallel
    mesh (a model axis would misalign the one-slot-per-device stacking),
    and Config.ring_reduction armed with >= 2 devices on the data axis
    (kmeans_ops.ring_enabled — the shared fallback contract)."""
    if _world() == 1:
        return None
    from oap_mllib_tpu.config import get_config

    cfg = get_config()
    if cfg.model_parallel != 1:
        return None
    from oap_mllib_tpu.ops.kmeans_ops import ring_enabled
    from oap_mllib_tpu.parallel.mesh import get_mesh

    mesh = get_mesh()
    if not ring_enabled(mesh, cfg.data_axis, cfg):
        return None
    return mesh


def _ring_reduce_f32(arrays, mesh, axis: str):
    """Sum a list of f32 host arrays across processes through ONE packed
    ring reduction (ops/pallas/ring_reduce): the payloads flatten into a
    (D, ceil(total/D)) segment sheet — each ring segment is a real chunk
    of the moments — ride a one-slot-per-device stacked array onto the
    mesh, and come back fully summed on every slot.  This is the
    streamed multi-host half of the ISSUE 9 ring plane: the per-pass
    centroid/Gram moments stop paying a standalone host-mediated
    allgather serialized behind the pass."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oap_mllib_tpu.ops.pallas.ring_reduce import stacked_ring_fn

    d_ax = mesh.shape[axis]
    flat = np.concatenate(
        [np.asarray(a, np.float32).ravel() for a in arrays]
    )
    total = flat.size
    cols = max(1, -(-total // d_ax))
    buf = np.zeros((d_ax, cols), np.float32)
    buf.ravel()[:total] = flat
    if flightrec.enabled():
        flightrec.record(
            "collective", "ring_allreduce", f"{axis}|({d_ax},{cols})"
        )
    sanitizers.note_collective(
        "ring_allreduce", axis, (d_ax, cols), "float32"
    )
    n_slots = d_ax // max(1, jax.process_count())
    local = np.zeros((n_slots, d_ax, cols), np.float32)
    local[0] = buf  # this process's payload in its first device slot
    sharding = NamedSharding(mesh, P(axis, None, None))
    stacked = jax.make_array_from_process_local_data(sharding, local)
    # segmented-start epilogue geometry (ops/pallas/autotune): resolved
    # from config + cache only — a pure function of (config, bucket) on
    # every rank, so the ring program stays rank-uniform (R16; mode "on"
    # under a multi-process world resolves "default-multiproc" for the
    # same reason)
    from oap_mllib_tpu.ops.pallas import autotune

    segments = autotune.resolve(
        "ring", autotune.shape_bucket(d_ax, cols)
    )["segments"]
    out = stacked_ring_fn(mesh, axis, segments=segments)(stacked)
    summed = np.asarray(out.addressable_shards[0].data)[0].ravel()[:total]
    res, off = [], 0
    for a in arrays:
        n = int(np.asarray(a).size)
        res.append(
            summed[off : off + n].reshape(np.shape(a)).astype(a.dtype)
        )
        off += n
    return res


def _psum_host(arrays, guard: "_PassGuard | None" = None):
    """Sum each array across processes; identity single-process.  Returns
    np arrays, identical on every process.  The gather runs under an x64
    scope: process_allgather device_puts its payload, which would
    silently demote f64/i64 (row counts, reservoir state) when the
    session default is x64-off.  ``guard``: see _PassGuard — when given,
    an error flag rides the gather and all ranks fail together.

    With the ring plane armed (:func:`_ring_mesh`), the f32 moment
    payloads reduce through ONE packed device ring instead of the
    host-mediated allgather; the error flag and any non-f32 payloads
    (row counts, reservoir state) keep the host gather, which runs FIRST
    so a failed rank still aborts every peer before the ring launches —
    the route decision is a pure function of dtypes, so every rank
    issues the same collective sequence."""
    arrays = _materialize(arrays, guard)
    if _world() == 1:
        if guard is not None and guard.err is not None:
            raise guard.err
        return arrays
    mesh = _ring_mesh()
    f32_idx = [
        i for i, a in enumerate(arrays)
        if np.asarray(a).dtype == np.float32
    ]
    if mesh is None or not f32_idx:
        gathered = _gather_with_guard(arrays, guard)
        return [g.sum(axis=0) for g in gathered]
    from oap_mllib_tpu.config import get_config

    rest_idx = [i for i in range(len(arrays)) if i not in f32_idx]
    gathered_rest = (
        _gather_with_guard([arrays[i] for i in rest_idx], guard)
        if rest_idx or guard is not None
        else []
    )
    ringed = _ring_reduce_f32(
        [arrays[i] for i in f32_idx], mesh, get_config().data_axis
    )
    out: list = [None] * len(arrays)
    for j, i in enumerate(f32_idx):
        out[i] = ringed[j]
    for j, i in enumerate(rest_idx):
        out[i] = gathered_rest[j].sum(axis=0)
    return out


def _allgather_host(arrays, guard: "_PassGuard | None" = None):
    """Gather each array across processes along a new leading (rank)
    axis; adds the axis single-process too (shape-stable callers).
    x64 scope and ``guard``: see _psum_host."""
    arrays = _materialize(arrays, guard)
    gathered = _gather_with_guard(arrays, guard)
    if gathered is None:
        return [a[None] for a in arrays]
    return gathered


def _fleet_pass(phase: str, stats: PrefetchStats, pass_wall_s: float,
                timings=None) -> None:
    """Fleet rollup seam (telemetry/fleet.py, ISSUE 11): after a pass's
    reduction succeeded on every rank, allgather one FIXED-shape
    per-rank stat frame over the same host-collective plane (so the
    rollup inherits the deadline watchdog and the collective
    sanitizer's fingerprinting) and fold it into the ``oap_fleet_*``
    metrics + the per-fit fleet window.  Disarmed
    (``Config.fleet_stats``) this is one config check; armed, the
    decision is a pure function of (config, world) so every rank
    issues the identical extra collective.

    The straggler controller (parallel/balance.py, ISSUE 15) rides the
    SAME gathered frames — every rank holds identical data, so every
    rank computes the identical re-plan with no additional collective."""
    if not fleet.armed(_world()):
        return
    elapsed = tick()
    frame = fleet.local_frame(stats, pass_wall_s)
    (gathered,) = _allgather_host([frame])
    fleet.fold_pass(phase, gathered)
    from oap_mllib_tpu.parallel import balance

    balance.observe_pass(phase, gathered)
    if timings is not None:
        timings.add("fleet", elapsed())


def capability_sync(frame: np.ndarray) -> np.ndarray:
    """Fit-start capability gather (parallel/balance.py, ISSUE 15): one
    fixed-shape allgather of each rank's ``[capability, origin, hbm,
    host]`` frame over the sanctioned host-collective seam — it
    inherits the deadline watchdog, the collective sanitizer's
    fingerprinting, and the fault site like every other host
    collective.  Called once per (process, world size); balance caches
    the fold.  Returns the gathered ``(world, 4)`` frames, identical on
    every rank."""
    (gathered,) = _allgather_host([np.asarray(frame, np.float64)])
    return gathered


def _checked_entry(validate) -> None:
    """Run entry validation under a guard and sync the outcome across
    ranks (one tiny scalar gather).  Without this, a rank whose
    validation fails (e.g. a malformed per-rank weight shard) raises
    before its first collective while peers with consistent shards
    proceed into the pass and hang in process_allgather.

    Callers skip this entirely for statically-infallible validations
    (sample_weight=None) — the sync only pays for itself when the
    validator can actually raise, and None-ness is assumed consistent
    across ranks (passing a weight source on some ranks only is API
    misuse outside this contract)."""
    guard = _PassGuard()
    with guard:
        validate()
    _psum_host([np.zeros((), np.int64)], guard=guard)


# ---------------------------------------------------------------------------
# K-Means
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("precision", "need_cost", "policy"),
    donate_argnums=(0, 1, 2),
)
def _kmeans_chunk_accum(sums, counts, cost, chunk, w, centers, precision,
                        need_cost, policy="f32"):
    s, c, t = kmeans_ops._accumulate(
        chunk, w, centers, precision, need_cost, policy
    )
    return sums + s, counts + c, cost + t


def _check_weight_source(source: ChunkSource, weights) -> None:
    if weights is None:
        return
    if not isinstance(weights, ChunkSource):
        raise TypeError("sample_weight for a streamed fit must be a ChunkSource")
    if weights.n_features != 1:
        raise ValueError("sample_weight source must have width 1")
    if weights.chunk_rows != source.chunk_rows:
        raise ValueError(
            f"sample_weight chunk_rows {weights.chunk_rows} != data "
            f"chunk_rows {source.chunk_rows}"
        )
    if (
        weights.n_rows is not None
        and source.n_rows is not None
        and weights.n_rows != source.n_rows
    ):
        raise ValueError(
            f"sample_weight rows {weights.n_rows} != data rows {source.n_rows}"
        )


def streamed_accumulate(
    source: ChunkSource, centers, dtype, precision: str, need_cost: bool,
    weights=None, timings=None, phase: str = "lloyd_loop",
    policy: str = "f32",
):
    """One full assignment pass over this process's shard, reduced across
    processes: (sums (k,d), counts (k,), cost) as host arrays (identical
    on every process).  Chunks arrive through the prefetch pipeline —
    chunk N+1 stages/transfers while chunk N's accumulate executes; the
    pass's stage/transfer/compute split lands in ``timings`` under
    ``phase`` when given.  Under the bf16 ``policy`` chunks stage at
    bfloat16 (half the transfer bytes); accumulators stay ``dtype``."""
    k, d = centers.shape
    stage_dtype = psn.staging_dtype(policy, dtype)
    sums = jnp.zeros((k, d), dtype)
    counts = jnp.zeros((k,), dtype)
    cost = jnp.zeros((), dtype)
    stats = PrefetchStats()
    # one key per pass (shapes are static across chunks): the per-chunk
    # program registers with the program-cache registry — record_execute
    # off, the device time is already the prefetch ``compute`` split
    step_key = (
        progcache.backend_fingerprint(),
        (source.chunk_rows, d, k), str(np.dtype(dtype)),
        str(stage_dtype), precision, need_cost, policy,
    )
    elapsed = tick()
    guard = _PassGuard()
    with guard:
        with _staged_chunks(
            source, weights, dtype, stats, stage_dtype
        ) as pf:
            for _, _, _, cj, wj in pf:
                with progcache.launch(
                    "kmeans.stream_accum", step_key, timings, phase,
                    record_execute=False,
                ):
                    sums, counts, cost = _kmeans_chunk_accum(
                        sums, counts, cost, cj, wj, centers, precision,
                        need_cost, policy,
                    )
    pass_wall = elapsed()
    stats.finalize(timings, phase, pass_wall)
    out = _psum_host([sums, counts, cost], guard=guard)
    _fleet_pass(phase, stats, pass_wall, timings)
    return out


@jax.jit
def _center_update(centers, sums, counts):
    safe = counts[:, None] > 0
    new_centers = jnp.where(safe, sums / jnp.maximum(counts[:, None], 1e-30), centers)
    moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
    return new_centers, jnp.max(moved_sq)


def lloyd_run_streamed(
    source: ChunkSource, init_centers: np.ndarray, max_iter: int, tol: float,
    dtype, precision: str = "highest", weights=None, validated: bool = False,
    timings=None, policy: str = "f32", checkpoint=None, resume=None,
):
    """Streamed Lloyd loop; same return contract as kmeans_ops.lloyd_run:
    (centers, n_iter, cost, counts).  Convergence semantics match
    _lloyd_loop (every center's squared move <= tol^2, or max_iter); one
    host sync per iteration (the converged flag) instead of zero — the
    price of host-driven passes.  ``weights`` is an optional width-1
    ChunkSource walked in lockstep (per-row weights); ``validated``
    skips the entry validation + its cross-rank sync when the caller
    (KMeans._fit_source) already ran it — the sync is one collective per
    call and must not triple up inside a single fit.  ``timings``
    accumulates the per-pass stage/transfer/compute split under
    ``lloyd_loop/``.

    ``checkpoint``/``resume`` (utils/checkpoint.py): the elastic-worlds
    channel.  ``resume`` is a restored :class:`RestoreResult` whose
    centroids the CALLER already used as ``init_centers`` (skipping the
    init passes); here it re-enters the loop at the recorded pass.
    ``checkpoint`` writes the post-pass centroids + pass index + the
    converged flag every ``Config.checkpoint_interval`` passes.  The
    pass math is untouched, so continuation is bit-identical in an
    unchanged world; a changed world only reorders the cross-rank
    reduction sums (<= fp tolerance)."""
    if weights is not None and not validated:
        _checked_entry(lambda: _check_weight_source(source, weights))
    from oap_mllib_tpu.utils.resilience import check_finite

    centers = jnp.asarray(np.asarray(init_centers, dtype))
    tol_sq = float(tol) ** 2
    n_iter = 0
    converged = False
    if resume is not None and resume.found:
        n_iter = min(int(resume.step), max_iter)
        converged = bool(resume.extra.get("converged", False))
    while n_iter < max_iter and not converged:
        sums, counts, _ = streamed_accumulate(
            source, centers, dtype, precision, need_cost=False,
            weights=weights, timings=timings, policy=policy,
        )
        centers, max_moved = _center_update(centers, sums, counts)
        n_iter += 1
        # iterate-level guardrail (Config.nonfinite_policy): a NaN/Inf
        # centroid poisons every later pass silently — catch it at the
        # iteration that produced it, while the cause is still nearby
        check_finite(centers, f"K-Means centroids (streamed pass {n_iter})")
        converged = float(max_moved) <= tol_sq
        if checkpoint is not None:
            checkpoint.maybe_write(
                n_iter, {"centers": np.asarray(centers)},
                extra={"converged": converged}, force=converged,
            )
    # final cost/counts pass: full precision INPUTS too (policy="f32" —
    # one extra f32-staged pass).  The cost identity |x|^2 + |c|^2 - 2x.c
    # cancels catastrophically for tight clusters under bf16-rounded
    # inputs (measured ~2x cost inflation where centroids matched to
    # 1e-4): the user-facing objective must not carry the fast policy's
    # rounding — the same contract as the in-memory _lloyd_run_jit,
    # which recomputes against its f32 table
    _, counts, cost = streamed_accumulate(
        source, centers, dtype, "highest", need_cost=True, weights=weights,
        timings=timings, policy="f32",
    )
    return centers, n_iter, cost, counts


# ---------------------------------------------------------------------------
# K-Means init
# ---------------------------------------------------------------------------


def reservoir_sample(
    source: ChunkSource, k: int, seed: int, timings=None,
) -> np.ndarray:
    """Uniform k-row sample in one pass (Algorithm R, vectorized per chunk:
    one rng draw per chunk and a Python loop only over the expected
    O(k log(n/k)) reservoir hits, never over all n rows).  The source is
    prefetched with an identity stage — no device transfer here, but the
    background pull overlaps file IO with the host reservoir updates.

    Multi-process: each process reservoirs its own shard, then the
    per-process reservoirs are merged by weighted sampling without
    replacement (Efraimidis–Spirakis keys; each reservoir row represents
    seen_p / |reservoir_p| rows of the global table).  Deterministic rank
    -ordered gather + a shared seed make every process return the SAME
    sample."""
    rng = np.random.default_rng(seed)
    sample: List[np.ndarray] = []
    seen = 0
    stats = PrefetchStats()
    elapsed = tick()
    guard = _PassGuard()
    with guard, Prefetcher(source, stats=stats) as pf:
        for chunk, n_valid in pf:
            start = 0
            if len(sample) < k:  # head-fill straight into the reservoir
                take = min(k - len(sample), n_valid)
                sample.extend(chunk[i].copy() for i in range(take))
                start = take
            if start < n_valid:
                # row at global index g replaces slot j ~ U[0, g] iff j < k
                highs = np.arange(seen + start + 1, seen + n_valid + 1)
                j = rng.integers(0, highs)  # vectorized per-row draws
                for i in np.nonzero(j < k)[0]:  # sparse hits only
                    sample[j[i]] = chunk[start + i].copy()
            seen += n_valid
    stats.finalize(timings, "init_centers", elapsed())
    if guard.err is not None and _world() == 1:
        raise guard.err
    if _world() > 1:
        d = source.n_features
        local = np.zeros((k, d))
        if sample:
            local[: len(sample)] = np.stack(sample)
        rows_g, nv_g, seen_g = _allgather_host(
            [local, np.asarray([len(sample)]), np.asarray([seen])],
            guard=guard,
        )
        rows = rows_g.reshape(-1, d)  # (nproc*k, d), rank-major
        nv = nv_g.ravel()
        weights = np.zeros(len(rows))
        for p in range(len(nv)):
            if nv[p]:
                weights[p * k : p * k + nv[p]] = seen_g.ravel()[p] / nv[p]
        valid = weights > 0
        if not valid.any():
            raise ValueError("empty source (all processes)")
        # Efraimidis–Spirakis: top-k keys u^(1/w) ~ weighted sample
        # without replacement; same rng stream on every process
        merge_rng = np.random.default_rng(seed + 1000003)
        keys = np.where(
            valid, merge_rng.random(len(rows)) ** (1.0 / np.maximum(weights, 1e-300)), -1.0
        )
        top = np.argsort(-keys, kind="stable")[: min(k, int(valid.sum()))]
        sample = [rows[t] for t in top]
        seen = int(seen_g.sum())
    if not sample:
        raise ValueError("empty source")
    while len(sample) < k:  # fewer rows than clusters: duplicate
        sample.append(sample[len(sample) % max(1, seen)])
    return np.stack(sample)


@functools.partial(jax.jit, static_argnames=("precision",))
def _chunk_min_d2(chunk, dmin, cands, precision="highest"):
    """Fold candidate distances into the chunk's running min."""
    d2 = kmeans_ops.pairwise_sq_dists(chunk, cands, precision)
    return jnp.minimum(dmin, jnp.min(d2, axis=1))


@jax.jit
def _chunk_ownership(chunk, w, cands):
    """(n_cand,) row weight owned by each candidate (segment-sum)."""
    d2 = kmeans_ops.pairwise_sq_dists(chunk, cands)
    owner = jnp.argmin(d2, axis=1)
    return jnp.zeros((cands.shape[0],), w.dtype).at[owner].add(w)


def _pad_cands(cands: np.ndarray, cap: int, d: int) -> np.ndarray:
    """Pad candidate blocks to a static cap with far-away dummies (1e15)
    so per-round shapes stay constant and the fold compiles once."""
    out = np.full((cap, d), 1e15, np.float64)
    if len(cands):
        out[: len(cands)] = cands
    return out


def init_kmeans_parallel_streamed(
    source: ChunkSource, k: int, seed: int, init_steps: int, dtype,
    weights=None, validated: bool = False, timings=None,
    policy: str = "f32",
) -> np.ndarray:
    """Streamed k-means|| (Bahmani), host-orchestrated.

    Differences vs the in-memory device version (kmeans_ops
    .init_kmeans_parallel): the per-row min-distance state lives on host
    (one f32 per row — 400 MB at 100M rows, far under host RAM), and each
    sampling round uses the cost total from the previous pass (one-round
    -stale phi; the l=2k oversampling absorbs the drift — parity tests
    compare converged cost, not centers, survey §7.3).

    Multi-process: each process folds/samples its own shard; phi, the
    per-round picks, and the ownership weights are reduced/gathered across
    processes, so every process ends each round with the SAME candidate
    set (the sampling rng is per-process — distinct shards — while the
    final weighted k-means++ rng is shared).

    ``weights``: optional width-1 ChunkSource of per-row weights, walked
    in lockstep — they scale the sampling cost (phi = sum w*dmin, like
    the in-memory version's weighted _pll_round) and the candidate
    ownership.  ``validated``: see lloyd_run_streamed.  Every pass pulls
    through the prefetch pipeline (chunk staging overlaps the device
    distance fold); per-chunk dmin state stays consumer-side — it is
    final only for chunks the consumer already passed, so the producer
    must not read it ahead."""
    if weights is not None and not validated:
        _checked_entry(lambda: _check_weight_source(source, weights))
    d = source.n_features
    l = 2.0 * k
    cap = 4 * k  # per-round candidate block (2x expected picks)
    # bf16 policy: chunks stage at bfloat16 for the distance folds (the
    # candidate ROWS are picked from the untouched host chunks, so the
    # candidates themselves keep full precision; only the sampling
    # probabilities and ownership weights carry bf16 rounding — Bahmani
    # oversampling is robust to far larger perturbations, and parity
    # compares converged cost, survey §7.3)
    stage_dtype = psn.staging_dtype(policy, dtype)
    # per-process stream for sampling OWN rows; shared stream for the
    # final reduction (must be identical on every process)
    samp_rng = np.random.default_rng(seed + 31 * jax.process_index())
    final_rng = np.random.default_rng(seed + 7777)

    c0 = reservoir_sample(source, 1, seed, timings=timings)
    cands = [c0[0]]
    new_block: np.ndarray = _pad_cands(c0, cap, d)  # picks awaiting dmin fold

    # One pass per round: fold the PREVIOUS round's picks into dmin while
    # sampling this round's with the previous pass's phi (the one-round
    # -stale phi of the docstring).  Round 0 is the distance-init pass —
    # it folds c0 and records phi without sampling.
    dmin_chunks: List[np.ndarray] = []
    phi = 0.0
    for rnd in range(init_steps + 1):
        sampling = rnd > 0
        if sampling and phi <= 0.0:
            break
        cands_dev = (
            jnp.asarray(new_block.astype(dtype)) if len(new_block) else None
        )
        picks: List[np.ndarray] = []
        new_phi = 0.0
        stats = PrefetchStats()
        elapsed = tick()
        guard = _PassGuard()
        with guard, _staged_chunks(
            source, weights, dtype, stats, stage_dtype
        ) as pf:
            for ci, (chunk, n_valid, wv, cj, _) in enumerate(pf):
                if cands_dev is not None:
                    progcache.note(
                        "kmeans.stream_pll_fold",
                        (progcache.backend_fingerprint(),
                         progcache.array_key(cj, cands_dev)),
                    )
                    # the d2 cache is host-resident by design (device
                    # chunks retire); staging the previous round's dmin
                    # up and fetching the fold back are ONE audited
                    # consume step — allow_transfers is the runtime
                    # analog of the lint suppression
                    with sanitizers.allow_transfers():
                        prev = (
                            jnp.asarray(dmin_chunks[ci])
                            if rnd > 0
                            else jnp.full(
                                (source.chunk_rows,), np.inf, dtype)
                        )
                        # oaplint: disable=stream-host-sync -- host d2 cache is the consume step
                        h = np.array(_chunk_min_d2(cj, prev, cands_dev))
                    h[n_valid:] = 0.0  # padded rows carry no cost
                    if rnd > 0:
                        dmin_chunks[ci] = h
                    else:
                        dmin_chunks.append(h)
                else:
                    h = dmin_chunks[ci]
                hw = h * wv  # weighted cost (all-ones when weights is None)
                new_phi += float(hw.sum())
                if sampling:
                    prob = np.minimum(l * hw / max(phi, 1e-300), 1.0)
                    hit = samp_rng.random(source.chunk_rows) < prob
                    hit[n_valid:] = False
                    for i in np.nonzero(hit)[0]:
                        picks.append(chunk[i].copy())
        stats.finalize(timings, "init_centers", elapsed())
        (phi_arr,) = _psum_host([np.asarray([new_phi])], guard=guard)
        phi = float(phi_arr[0])
        if _world() > 1:
            # fixed-shape gather of each process's picks (rank-major, so
            # every process extends cands identically); overflow beyond
            # cap drops, like the in-memory slot buffer
            local = np.zeros((cap, d))
            n_local = min(len(picks), cap)
            if n_local:
                local[:n_local] = np.stack(picks[:n_local])
            rows_g, cnt_g = _allgather_host([local, np.asarray([n_local])])
            picks = [
                rows_g[p, i]
                for p in range(rows_g.shape[0])
                for i in range(int(cnt_g.ravel()[p]))
            ]
        cands.extend(picks)
        new_block = (
            _pad_cands(
                np.stack(picks), cap * ((len(picks) + cap - 1) // cap), d
            )
            if picks
            else np.zeros((0, d))
        )

    cand_arr = np.stack(cands)
    if cand_arr.shape[0] <= k:
        extra = reservoir_sample(
            source, k - cand_arr.shape[0] + 1, seed + 1, timings=timings
        )
        return np.concatenate([cand_arr, extra], axis=0)[:k]

    # ownership pass: weight candidates, then host-side weighted k-means++
    cands_dev = jnp.asarray(cand_arr.astype(dtype))
    own = np.zeros((cand_arr.shape[0],), np.float64)
    stats = PrefetchStats()
    elapsed = tick()
    guard = _PassGuard()
    with guard, _staged_chunks(
        source, weights, dtype, stats, stage_dtype
    ) as pf:
        for _, _, _, cj, wj in pf:
            progcache.note(
                "kmeans.stream_pll_own",
                (progcache.backend_fingerprint(),
                 progcache.array_key(cj, cands_dev)),
            )
            with sanitizers.allow_transfers():  # audited host accumulation
                # oaplint: disable=stream-host-sync -- ownership sums accumulate on host by design
                own += np.asarray(_chunk_ownership(cj, wj, cands_dev))
    stats.finalize(timings, "init_centers", elapsed())
    (own,) = _psum_host([own], guard=guard)
    return kmeans_ops._weighted_kmeans_pp(cand_arr, own, k, final_rng)


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,))
def _colsum_chunk(total, chunk, w):
    return total + jnp.sum(psn.upcast(chunk) * w[:, None], axis=0)


@functools.partial(
    jax.jit, static_argnames=("precision", "policy"), donate_argnums=(0,)
)
def _gram_chunk(gram, chunk, w, mean, precision, policy="f32"):
    xc = (psn.upcast(chunk) - mean[None, :]) * w[:, None]
    return gram + psn.pdot(xc.T, xc, policy, precision)


# Kahan/Neumaier-compensated accumulators for the reduced-precision
# policies: the per-chunk partials carry bf16 input rounding already, so
# the CROSS-PASS f32 accumulation must not add O(n_chunks * eps)
# cancellation on top — the compensation term recovers the bits each
# f32 += loses, keeping the summation error bounded independent of the
# chunk count (the "f32 accumulators with compensated summation across
# passes" half of the policy contract).  Not used by the f32 policy:
# its accumulation order must stay bit-identical to the pre-policy code.


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _colsum_chunk_comp(total, comp, chunk, w):
    s = jnp.sum(psn.upcast(chunk) * w[:, None], axis=0)
    y = s - comp
    t = total + y
    comp = (t - total) - y
    return t, comp


@functools.partial(
    jax.jit, static_argnames=("precision", "policy"), donate_argnums=(0, 1)
)
def _gram_chunk_comp(gram, comp, chunk, w, mean, precision, policy):
    xc = (psn.upcast(chunk) - mean[None, :]) * w[:, None]
    g = psn.pdot(xc.T, xc, policy, precision)
    y = g - comp
    t = gram + y
    comp = (t - gram) - y
    return t, comp


# -- fused-kernel per-chunk accumulators (ops/pallas/pca_kernel) ------------
# Same accumulation structure as the XLA chunk fns above, with the
# center+mask+Gram (and the colsum reduction) fused into one Pallas
# program per chunk — no HBM-materialized centered temp.  Dispatch is
# pca_ops.use_pallas_gram (TPU + single device + f32); the ``interpret``
# static exists so tier-1 can exercise the kernels on CPU.


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_rows", "depth"),
    donate_argnums=(0,),
)
def _colsum_chunk_pallas(total, chunk, w, interpret=False, tile_rows=None,
                         depth=None):
    from oap_mllib_tpu.ops.pallas import pca_kernel as _pk

    _, cs, _ = _pk.moments_traced(
        chunk, w, jnp.zeros((chunk.shape[1],), jnp.float32),
        "highest", interpret, False, tile_rows, depth,
    )
    return total + cs


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_rows", "depth"),
    donate_argnums=(0, 1),
)
def _colsum_chunk_pallas_comp(total, comp, chunk, w, interpret=False,
                              tile_rows=None, depth=None):
    from oap_mllib_tpu.ops.pallas import pca_kernel as _pk

    _, s, _ = _pk.moments_traced(
        chunk, w, jnp.zeros((chunk.shape[1],), jnp.float32),
        "highest", interpret, False, tile_rows, depth,
    )
    y = s - comp
    t = total + y
    comp = (t - total) - y
    return t, comp


@functools.partial(
    jax.jit, static_argnames=("mode", "interpret", "tile_rows", "depth"),
    donate_argnums=(0,),
)
def _gram_chunk_pallas(gram, chunk, w, mean, mode, interpret=False,
                       tile_rows=None, depth=None):
    from oap_mllib_tpu.ops.pallas import pca_kernel as _pk

    g, _, _ = _pk.moments_traced(
        chunk, w, mean, mode, interpret, True, tile_rows, depth
    )
    return gram + g


@functools.partial(
    jax.jit, static_argnames=("mode", "interpret", "tile_rows", "depth"),
    donate_argnums=(0, 1),
)
def _gram_chunk_pallas_comp(gram, comp, chunk, w, mean, mode,
                            interpret=False, tile_rows=None, depth=None):
    from oap_mllib_tpu.ops.pallas import pca_kernel as _pk

    g, _, _ = _pk.moments_traced(
        chunk, w, mean, mode, interpret, True, tile_rows, depth
    )
    y = g - comp
    t = gram + y
    comp = (t - gram) - y
    return t, comp


def covariance_streamed(
    source: ChunkSource, dtype, precision: str = "highest", timings=None,
    policy: str = "f32", checkpoint=None,
):
    """Two-pass streamed covariance: (cov (d,d), mean (d,), n_rows), as
    host arrays identical on every process.

    Pass 1 accumulates column sums (mean), pass 2 the mean-centered Gram —
    identical numerics to ops.pca_ops.covariance, O(chunk) device memory;
    multi-process shards reduce across processes after each pass.  Both
    passes pull through the prefetch pipeline; the split lands in
    ``timings`` under ``covariance_streamed/``.

    ``policy`` (utils/precision.py): bf16 stages chunks at bfloat16
    (half the transfer bytes), runs the per-chunk Gram matmuls on bf16
    operands with f32 accumulation, and compensates the cross-chunk f32
    accumulation (Kahan) so the pass count cannot amplify the rounding;
    f32 keeps the exact pre-policy accumulators.

    ``checkpoint`` (utils/checkpoint.py): PCA's iterate state is its
    pass structure — after the colsum pass the reduced column sums + row
    count checkpoint (the streamed accumulators of the tentpole), so a
    preempted fit resumes straight into the Gram pass.  The reduced
    moments are identical on every rank, so restore is world-size-
    independent by construction.
    """
    d = source.n_features
    stage_dtype = psn.staging_dtype(policy, dtype)
    compensated = policy == "bf16"
    from oap_mllib_tpu.config import get_config
    from oap_mllib_tpu.ops import pca_ops
    from oap_mllib_tpu.utils.resilience import check_finite

    # fused-kernel route (ops/pallas/pca_kernel): same per-chunk
    # accumulation at the kernel tier, one Pallas program per chunk —
    # validated on EVERY streamed fit so a typo'd pca_kernel raises here
    use_pk = pca_ops.use_pallas_gram(
        get_config().pca_kernel, d, precision, dtype
    )
    # tuned kernel geometry, resolved ONCE per pass pair outside the
    # chunk loop (the chunk fns take it as jit statics); default path
    # keeps (None, None) = the hand-picked constants
    pk_rows = pk_depth = None
    if use_pk:
        from oap_mllib_tpu.ops.pallas import autotune

        geo = autotune.resolve("pca", autotune.shape_bucket(d), precision)
        pk_rows, pk_depth = geo["tile_rows"], geo["depth"]

    resume = checkpoint.restore() if checkpoint is not None else None
    base_key = (
        progcache.backend_fingerprint(),
        (source.chunk_rows, d), str(np.dtype(dtype)), str(stage_dtype),
        precision, policy, pk_rows, pk_depth,
    )
    if resume is not None and resume.found and (
            resume.extra.get("stage") == "colsum"):
        total = resume.arrays["colsum"]
        n = int(resume.extra["n_rows"])
    else:
        total = jnp.zeros((d,), dtype)
        comp = jnp.zeros((d,), dtype)
        n = 0
        stats = PrefetchStats()
        elapsed = tick()
        guard = _PassGuard()
        with guard, _staged_chunks(
            source, None, dtype, stats, stage_dtype
        ) as pf:
            for _, n_valid, _, cj, wj in pf:
                with progcache.launch(
                    "pca.stream_colsum", base_key, timings,
                    "covariance_streamed", record_execute=False,
                ):
                    if use_pk and compensated:
                        total, comp = _colsum_chunk_pallas_comp(
                            total, comp, cj, wj,
                            tile_rows=pk_rows, depth=pk_depth,
                        )
                    elif use_pk:
                        total = _colsum_chunk_pallas(
                            total, cj, wj, tile_rows=pk_rows, depth=pk_depth
                        )
                    elif compensated:
                        total, comp = _colsum_chunk_comp(total, comp, cj, wj)
                    else:
                        total = _colsum_chunk(total, cj, wj)
                n += n_valid
        pass_wall = elapsed()
        stats.finalize(timings, "covariance_streamed", pass_wall)
        total, n_arr = _psum_host(
            [total, np.asarray([n], np.int64)], guard=guard
        )
        _fleet_pass("covariance_streamed", stats, pass_wall, timings)
        # per-pass guardrails (Config.nonfinite_policy): an overflowed
        # f32 column sum or Gram silently yields Inf/NaN eigenvectors
        # passes later
        check_finite(total, "PCA column sums (streamed mean pass)")
        n = int(n_arr[0])
        if checkpoint is not None:
            checkpoint.maybe_write(
                1, {"colsum": np.asarray(total)},
                extra={"stage": "colsum", "n_rows": n}, force=True,
            )
    if n < 1:
        raise ValueError("empty source")
    mean = jnp.asarray(total.astype(dtype) / n)
    gram = jnp.zeros((d, d), dtype)
    gcomp = jnp.zeros((d, d), dtype)
    stats = PrefetchStats()
    elapsed = tick()
    guard = _PassGuard()
    with guard, _staged_chunks(source, None, dtype, stats, stage_dtype) as pf:
        for _, _, _, cj, wj in pf:
            with progcache.launch(
                "pca.stream_gram", base_key, timings,
                "covariance_streamed", record_execute=False,
            ):
                if use_pk and compensated:
                    gram, gcomp = _gram_chunk_pallas_comp(
                        gram, gcomp, cj, wj, mean, precision,
                        tile_rows=pk_rows, depth=pk_depth,
                    )
                elif use_pk:
                    gram = _gram_chunk_pallas(
                        gram, cj, wj, mean, precision,
                        tile_rows=pk_rows, depth=pk_depth,
                    )
                elif compensated:
                    gram, gcomp = _gram_chunk_comp(
                        gram, gcomp, cj, wj, mean, precision, policy
                    )
                else:
                    gram = _gram_chunk(gram, cj, wj, mean, precision, policy)
    pass_wall = elapsed()
    stats.finalize(timings, "covariance_streamed", pass_wall)
    (gram,) = _psum_host([gram], guard=guard)
    _fleet_pass("covariance_streamed", stats, pass_wall, timings)
    check_finite(gram, "PCA Gram accumulator (streamed Gram pass)")
    cov = gram.astype(np.float64 if dtype == np.float64 else np.float32)
    cov = cov / max(n - 1.0, 1.0)
    cov = 0.5 * (cov + cov.T)
    return cov, np.asarray(mean), n
