"""Implicit ALS compute kernels: jitted alternating least squares.

Replaces the reference's oneDAL 4-step distributed implicit ALS
(native/ALSDALImpl.cpp): there, each half-iteration runs step1Local
(partial cross-products), gathers serialized partials to the root
(:53-97), the root's step2Master forms the global cross-product (:261-281)
and broadcasts it back, step3Local/step4Local exchange partial models
all-to-all and solve per-block factors (:283-316) — plus a native ratings
shuffle and a transposed item-major CSR copy per rank (ALSShuffle.cpp,
ALSDALImpl.cpp:192-214).

TPU-first redesign — the whole half-iteration is three MXU/VPU passes over
a COO ratings tensor, no transposed copy and no master rank:

1. Gram: ``G = Y^T Y`` — one (r, n)x(n, r) matmul, psum over the mesh.
   (This is steps 1+2: the "cross-product" IS the Gram matrix.)
2. Per-edge contributions: for each rating (u, i, c): gather ``y_i``,
   form ``alpha*c * y_i y_i^T`` (nnz, r, r) and ``(1+alpha*c) y_i``
   (nnz, r), then ``segment_sum`` by user — XLA scatter-adds, the
   all-to-all-free equivalent of steps 3+4's partial-model exchange.
3. Solve: batched (r, r) Cholesky/LU solve over all users at once.

The item update reuses the SAME COO arrays with the index roles swapped —
the reference's per-rank transposed table (ALSDALImpl.cpp:209-213) has no
equivalent here because segment_sum doesn't care about sort order.

Padded COO entries carry ``valid = 0`` so they vanish from both A and b
(survey §2.6 fixed-shape design note).  dtype float32, matching the
reference kernel (ALSDALImpl.cpp:35 ``CpuAlgorithmFPType = float``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache


def _edge_chunks(nnz: int, r: int, budget_elems: int = 1 << 24) -> int:
    """Chunk count for the (chunk, r, r) per-edge outer-product buffer.

    Power-of-two divisors of nnz so the live intermediate stays under
    ``budget_elems`` (peak memory O(chunk * r^2 + n_dst * r^2) instead of
    O(nnz * r^2) — at MovieLens-25M scale the unchunked buffer would blow
    HBM).  Callers pad nnz to a power-of-two-friendly multiple.
    """
    chunks = 1
    while (nnz // chunks) * r * r > budget_elems and nnz % (chunks * 2) == 0:
        chunks *= 2
    return chunks


def normal_eq_partials(
    dst_idx: jax.Array,  # (nnz,) int32 — side being solved (e.g. users)
    src_idx: jax.Array,  # (nnz,) int32 — fixed side (e.g. items)
    conf: jax.Array,  # (nnz,) f32 ratings/confidences
    valid: jax.Array,  # (nnz,) f32 1/0 mask
    src_factors: jax.Array,  # (n_src, r)
    n_dst: int,
    alpha: float,
    implicit: bool,
    policy: str = "f32",
):
    """Per-edge normal-equation partials grouped by dst id — Spark parity.

    Implicit (reference ALS.scala:1781-1795): with c1 = alpha * |r|,
    A += c1 * y y^T for EVERY rating (|r| keeps A PSD for non-positive
    ratings), b += (1 + c1) * y only when r > 0 (preference 0 otherwise),
    and the regularization count n_reg counts only r > 0 ratings.
    Explicit: A += y y^T, b += r * y, n_reg counts all ratings.  The
    returned n_reg feeds both ALS-WR lambda scaling (Spark scales reg by
    the per-row rating count: solve(ne, numExplicits * regParam)) and the
    empty-row factor masking.

    Returns (a_part (n_dst, r, r), b (n_dst, r), n_reg (n_dst,)).  Shared
    by the global-program path (this file) and the block-parallel path
    (als_block.py, which psums these across the mesh) so the two can never
    diverge in the weighting math.  Edge-chunked via lax.scan so the
    (chunk, r, r) outer-product intermediate never scales with nnz.

    ``policy`` (utils/precision.py) governs the per-edge factor outer
    products: bf16 casts the gathered factor rows and accumulates f32
    (b/n segment-sums and the solves stay f32); the f32 default keeps
    the pre-policy HIGHEST einsum bit-for-bit.
    """
    nnz = dst_idx.shape[0]
    r = src_factors.shape[1]
    chunks = _edge_chunks(nnz, r)

    def partial_chunk(dst_c, src_c, conf_c, valid_c):
        ys = src_factors[src_c]  # (cs, r) gather
        if implicit:
            a_w = alpha * jnp.abs(conf_c) * valid_c
            pos = (conf_c > 0).astype(conf_c.dtype) * valid_c
            b_w = (1.0 + alpha * jnp.abs(conf_c)) * pos
            n_w = pos
        else:
            a_w = valid_c
            b_w = conf_c * valid_c
            n_w = valid_c
        outer = psn.peinsum(
            "er,es->ers", ys * a_w[:, None], ys, policy
        )  # (cs, r, r) — f32 accumulation under every policy
        a_c = jax.ops.segment_sum(outer, dst_c, num_segments=n_dst)
        b_c = jax.ops.segment_sum(ys * b_w[:, None], dst_c, num_segments=n_dst)
        n_c = jax.ops.segment_sum(n_w, dst_c, num_segments=n_dst)
        return a_c, b_c, n_c

    if chunks == 1:
        return partial_chunk(dst_idx, src_idx, conf, valid)

    cs = nnz // chunks
    def step(carry, chunk):
        a0, b0, n0 = carry
        a_c, b_c, n_c = partial_chunk(*chunk)
        return (a0 + a_c, b0 + b_c, n0 + n_c), None

    zero = (
        jnp.zeros((n_dst, r, r), src_factors.dtype),
        jnp.zeros((n_dst, r), src_factors.dtype),
        jnp.zeros((n_dst,), src_factors.dtype),
    )
    chunked = tuple(
        a.reshape(chunks, cs) for a in (dst_idx, src_idx, conf, valid)
    )
    (a_part, b, n_reg), _ = lax.scan(step, zero, chunked)
    return a_part, b, n_reg


def _chol_solve_unrolled(a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched SPD solve for TINY static ranks, fully unrolled.

    XLA's general batched ``cholesky`` + ``solve_triangular`` is
    latency-bound at ALS sizes — measured 46.8 ms for a (6040, 10, 10)
    factorization on v5e (round 3), thousands of times the arithmetic
    cost.  With ``r`` small and static, the r elimination steps unroll
    into ~3r fused batch-wide vector ops (each O(B·r) / O(B·r²)):
    column-by-column Cholesky via rank-1 Schur downdates, then unrolled
    forward/back substitution.  Measured 0.9 ms for the same batch —
    ~50x.  Singular/non-SPD inputs produce NaN (sqrt of a negative or
    0-division) exactly like the library path, which the caller's
    nan_to_num + degree mask absorb.
    """
    r = b.shape[-1]
    idx = jnp.arange(r)
    # batch-LAST layout: (r, r, B) puts the big batch dim on the 128-lane
    # axis — batch-first (B, r, r) would pad both r-sized minor dims to
    # the (8, 128) vreg tile, a >10x memory/compute blowup at r=10.
    # NO scatters anywhere (scatter breaks XLA fusion, leaving ~3r
    # sequential kernel launches — that alone measured 22 ms): L lives as
    # a Python list of (r, B) columns, substitution as (B,) rows.
    at = jnp.transpose(a, (1, 2, 0))  # (r, r, B)
    cols = []
    for j in range(r):
        d = jnp.sqrt(at[j, j])  # (B,)
        col = (at[:, j] / d[None, :]) * (idx >= j)[:, None]  # (r, B)
        cols.append(col)
        at = at - col[:, None, :] * col[None, :, :]  # Schur downdate
    rhs = [b.T[j] for j in range(r)]  # (B,) rows
    z = [None] * r
    for j in range(r):  # forward: L z = b
        z[j] = rhs[j] / cols[j][j]
        for i in range(j + 1, r):
            rhs[i] = rhs[i] - cols[j][i] * z[j]
    w = [None] * r
    for j in reversed(range(r)):  # back: L^T w = z; L^T[j, k] = cols[j][k]
        acc = z[j]
        for k in range(j + 1, r):
            acc = acc - cols[j][k] * w[k]
        w[j] = acc / cols[j][j]
    return jnp.stack(w, axis=1)  # (B, r)


def masked_solve(a: jax.Array, b: jax.Array, deg: jax.Array) -> jax.Array:
    """Batched SPD solve via Cholesky; rows with no (reg-counted) ratings
    get zero factors (fallback-path semantics).  Small static ranks (the
    ALS regime — Spark's default is 10) take the unrolled batch-wide
    factorization (:func:`_chol_solve_unrolled`, ~50x the library path's
    latency-bound lowering); larger ranks use the library routines.  A
    singular/non-SPD A (possible at reg=0) yields NaN either way, which
    nan_to_num + the degree mask absorb."""
    if b.shape[-1] <= 32:
        factors = _chol_solve_unrolled(a, b)
    else:
        import jax.scipy.linalg as jsl

        chol = jnp.linalg.cholesky(a)
        z = jsl.solve_triangular(chol, b[:, :, None], lower=True)
        factors = jsl.solve_triangular(
            chol.transpose(0, 2, 1), z, lower=False
        )[:, :, 0]
    return jnp.where(deg[:, None] > 0, jnp.nan_to_num(factors), 0.0)


# ---------------------------------------------------------------------------
# Grouped-edge path: scatter-free normal equations (single-device hot path)
# ---------------------------------------------------------------------------
# The COO path above pays one scatter of (nnz, r, r) outer products per
# half-iteration — measured 83 ms/iter at MovieLens-1M scale on v5e, ~12x
# the cost of streaming the same bytes.  The TPU-first layout instead sorts
# edges by destination ONCE (indices are static across iterations) and pads
# each destination's edge list to a multiple of P, so every P-edge group
# belongs to exactly one destination.  The whole normal-equation build then
# becomes ONE batched MXU matmul per group,
#
#     [Ys | 1]^T @ [a_w*Ys | b_w | n_w]   ->  (r+1, r+2)
#
# whose blocks are A (r x r), b (col r), and the reg count (at [r, r+1]),
# plus a group->destination segment-sum of tiny (r+1, r+2) tiles.  Measured
# 2.7 ms vs the scatter path's 94 ms for the same half-iteration partials
# (BASELINE.md round 3).  This is the reference's blocked-CSR idea
# (ALSDALImpl.scala:184-230 builds per-rank CSR precisely so oneDAL can
# batch row solves) rebuilt for the MXU.


# Guard shared by the single-device and block-parallel dispatchers: the
# grouped layout is taken only while its padded edge total stays within
# this factor of the true edge count (extreme long-tail degree splits fall
# back to the COO programs).  One definition so the two paths cannot
# silently route the same dataset to different kernels.
def unpack_flat_moments(m_flat: jax.Array, r: int):
    """(a, b, n_reg) from a flat (n_dst, (r+1)(r+2)) moment carry — the
    layout every streamed accumulate produces (als_stream,
    als_block_stream)."""
    n_dst = m_flat.shape[0]
    m = m_flat.reshape(n_dst, r + 1, r + 2)
    return m[:, :r, :r], m[:, :r, r], m[:, r, r + 1]


def regularized_solve(a, b, n_reg, reg, eye, gram=None,
                      kernel: str = "xla",
                      geometry=None) -> jax.Array:
    """THE half-update solve every ALS path consumes moments through
    (single-device grouped/COO, streamed, block-parallel, streamed
    block): ALS-WR lambda scaling (reg x per-row rating count — Spark
    parity, reference ALS.scala:1794-1795), optional implicit-feedback
    Gram term, masked Cholesky.  One definition so the paths cannot
    diverge in the regularization convention.

    ``kernel`` selects the consumer: "xla" (default) keeps the
    batch-wide unrolled solve below; "pallas" routes through the fused
    assembly+solve kernel (ops/pallas/als_kernel.solve_traced — same
    elimination sequence, one HBM read of the moments, resolved by
    :func:`resolve_solve_kernel`); "pallas_interpret" is the CPU
    interpret-mode leg tier-1 exercises the full runners through.
    ``geometry``: tuned ``(batch, depth)`` for the pallas consumer
    (ops/pallas/autotune, resolved eagerly by the runner wrappers and
    threaded here as jit statics; None keeps the hand-picked
    constants)."""
    if kernel.startswith("pallas"):
        from oap_mllib_tpu.ops.pallas.als_kernel import solve_traced

        batch, depth = geometry if geometry else (None, None)
        return solve_traced(
            a, b, n_reg, reg, gram, interpret=kernel == "pallas_interpret",
            batch=batch, depth=depth,
        )
    a = a + reg * n_reg[:, None, None] * eye[None]
    if gram is not None:
        a = gram[None] + a
    return masked_solve(a, b, n_reg)


def _factor_gram(factors, kernel: str = "xla", geometry=None):
    """The implicit-feedback Gram ``F^T F`` feeding regularized_solve —
    psn.pdot on the XLA route, the streamed Pallas factor-Gram kernel on
    the pallas routes.  Pinned mode="highest" either way: Grams condition
    the solve and never run reduced (utils/precision.py contract).
    ``geometry``: tuned ``(tile_rows, depth)`` statics, like
    :func:`regularized_solve`."""
    if kernel.startswith("pallas"):
        from oap_mllib_tpu.ops.pallas.als_kernel import factor_gram_traced

        tile_rows, depth = geometry if geometry else (None, None)
        return factor_gram_traced(
            factors, "highest", interpret=kernel == "pallas_interpret",
            tile_rows=tile_rows, depth=depth,
        )
    return psn.pdot(factors.T, factors)


def resolve_solve_kernel(r: int, dtype=None, cfg=None) -> str:
    """Resolve Config.als_solve_kernel to the concrete consumer for this
    fit — the single decision point every ALS runner (single-device,
    block-parallel, streamed) resolves through, so two paths cannot
    route the same fit to different solve kernels.  "auto" takes the
    fused Pallas kernel on TPU with f32 factors in the unrolled-rank
    regime (r <= 32); anything else — CPU tier-1 included — keeps the
    XLA path.  A typo'd value raises on EVERY accelerated fit."""
    import numpy as np

    from oap_mllib_tpu.config import get_config

    cfg = cfg or get_config()
    choice = cfg.als_solve_kernel
    if choice not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"als_solve_kernel must be auto|xla|pallas, got {choice!r}"
        )
    from oap_mllib_tpu.ops.pallas.als_kernel import pallas_solve_preferred

    want = choice == "pallas" or (
        choice == "auto" and pallas_solve_preferred(r)
    )
    if (
        want
        and jax.default_backend() == "tpu"
        and r <= 32
        and (dtype is None or np.dtype(dtype) == np.float32)
    ):
        return "pallas"
    return "xla"


GROUPED_MAX_BLOWUP = 6.0


def grouped_padded_edges(dst, n_dst: int, group_size: int = 0) -> int:
    """Padded edge count the grouped layout WOULD produce for one side —
    the blowup-guard input, from per-destination counts alone (no sort of
    payloads, no (G, P) materialization).  Destinations with zero edges
    pad to zero, so counting only the present ones gives the exact total
    build_grouped_edges would realize.  Prefers the native counting pass
    (O(nnz + n_dst), native/src/grouped_prep.cpp) over np.unique's sort."""
    import numpy as np

    from oap_mllib_tpu.data.io import _force_py

    p = group_size or auto_group_size(len(dst), n_dst)
    if not _force_py():
        from oap_mllib_tpu import native

        total = native.als_grouped_total(np.asarray(dst, np.int64), n_dst, p)
        if total is not None:
            return total
    _, counts = np.unique(np.asarray(dst, np.int64), return_counts=True)
    return int((-(counts // -p) * p).sum())


def auto_group_size(nnz: int, n_dst: int) -> int:
    """Group size adapted to the mean degree so padding stays bounded:
    the next power of two ABOVE the mean degree keeps total padded edges
    <= nnz + n_dst*P < 3*nnz, and larger P is measurably faster — fewer
    groups shrink the (G, r+1, r+2) segment-sum and deepen the per-group
    (P)-contraction on the MXU (ML-1M on v5e: 13.7 ms/iter at P=64 vs
    10.3 at P=256, BASELINE.md ALS table).  Capped at 256: P=512 loses
    the padding it adds (14.1 ms/iter), P=1024 doubles the iteration.
    Long-tail distributions (millions of destinations with ~2 ratings
    each) still get small P; the caller's COO fallback guard handles the
    blowup cases anyway."""
    import numpy as np

    mean_deg = max(1.0, nnz / max(1, n_dst))
    return int(max(8, min(256, 2 ** int(np.ceil(np.log2(mean_deg))))))


def build_grouped_edges(
    dst: "np.ndarray",
    src: "np.ndarray",
    conf: "np.ndarray",
    n_dst: int,
    group_size: int = 0,
):
    """Host-side one-time prep: sort edges by ``dst`` and pad each dst's
    edge list to a multiple of ``group_size`` (0 = auto-size from the
    mean degree, see :func:`auto_group_size`).

    Returns (src_g (G, P) int32, conf_g (G, P) f32, valid_g (G, P) f32,
    group_dst (G,) int32).  Padding entries carry src=0, valid=0 so they
    vanish from every weighted sum.  ~1.2x edge blowup at P=64 on
    MovieLens-like degree distributions.

    Prefers the native stable counting sort (O(nnz + n_dst),
    native/src/grouped_prep.cpp — the reference's host-side CSR prep
    analog, ALSDALImpl.cpp:184-230) over the NumPy argsort path.
    """
    import numpy as np

    from oap_mllib_tpu.data.io import _force_py

    P = group_size or auto_group_size(len(dst), n_dst)
    if not _force_py():
        from oap_mllib_tpu import native

        built = native.als_group_edges(dst, src, conf, n_dst, P)
        if built is not None:
            return built
    dst = np.asarray(dst, np.int64)
    order = np.argsort(dst, kind="stable")
    d = dst[order]
    counts = np.bincount(d, minlength=n_dst)
    padded = ((counts + P - 1) // P) * P
    starts = np.concatenate([[0], np.cumsum(padded)])[:-1]
    first = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = starts[d] + (np.arange(len(d)) - first[d])
    total = int(padded.sum())
    src_g = np.zeros(total, np.int32)
    conf_g = np.zeros(total, np.float32)
    valid_g = np.zeros(total, np.float32)
    src_g[slot] = np.asarray(src, np.int32)[order]
    conf_g[slot] = np.asarray(conf, np.float32)[order]
    valid_g[slot] = 1.0
    group_dst = np.repeat(np.arange(n_dst, dtype=np.int32), padded // P)
    G = total // P
    return (
        src_g.reshape(G, P),
        conf_g.reshape(G, P),
        valid_g.reshape(G, P),
        group_dst,
    )


# live-element budget for one grouped-partials block: the (r+..., Gc, P)
# intermediates of a block stay near 256 MB f32 so ML-25M-scale sides
# (40M+ padded edges) fit one chip — unchunked, XLA materialized a
# (padded_nnz, r) gather fusion whose (8,128) lane padding alone was
# 21 GB (measured OOM at 25M nnz, round 3)
_GROUPED_BUDGET_ELEMS = 1 << 26


def _grouped_block_count(G: int, P: int, r: int) -> int:
    """Smallest power-of-two block count keeping a block under budget.

    The per-block cost model charges XLA's (8, 128) lane padding — a
    (…, Gb, P) buffer with P < 128 still occupies 128 lanes — and the ~3
    concurrently-live (r+2)-deep intermediates (ys / lhs / rhs), so the
    bound holds for small-P long-tail sides too, not just the aligned
    P=128/256 layouts it was measured on.  Stops subdividing at one
    group per block (a budget below a single padded row cannot hang)."""
    lanes = max(P, 128)
    n = 1
    while n < G and (-(-G // n)) * lanes * (r + 2) * 3 > _GROUPED_BUDGET_ELEMS:
        n *= 2
    return n


def grouped_block_moments(
    src_b: jax.Array,  # (Gb, P) int32
    conf_b: jax.Array,
    valid_b: jax.Array,
    src_factors: jax.Array,  # (n_src, r)
    alpha,
    implicit: bool,
    policy: str = "f32",
) -> jax.Array:
    """(Gb, r+1, r+2) normal-equation moment matrices for one group
    block — the MXU inner kernel shared by the in-memory grouped partials
    (:func:`normal_eq_partials_grouped`) and the host-chunked streamed
    accumulate (ops/als_stream.py), so the two paths cannot diverge in
    the weighting math.  Layout note: the transposed gather keeps the big
    static group width P on the 128-lane axis (see the grouped-path
    module notes)."""
    ys = src_factors.T[:, src_b]  # (r, Gb, P) transposed gather
    if implicit:
        a_w = alpha * jnp.abs(conf_b) * valid_b
        pos = (conf_b > 0).astype(conf_b.dtype) * valid_b
        b_w = (1.0 + alpha * jnp.abs(conf_b)) * pos
        n_w = pos
    else:
        a_w = valid_b
        b_w = conf_b * valid_b
        n_w = valid_b
    lhs = jnp.concatenate(
        [ys, jnp.ones_like(conf_b)[None]], axis=0
    )  # (r+1, Gb, P)
    rhs = jnp.concatenate(
        [ys * a_w[None], b_w[None], n_w[None]], axis=0
    )  # (r+2, Gb, P)
    return psn.peinsum(
        "agp,bgp->gab", lhs, rhs, policy
    )  # (Gb, r+1, r+2)  <- batched MXU, P-lane contraction; bf16 policy
    # casts the factor-carrying lhs/rhs tiles and accumulates f32 — the
    # per-destination moment tiles (and the solves they feed) stay f32


def normal_eq_partials_grouped(
    src_g: jax.Array,  # (G, P) int32
    conf_g: jax.Array,  # (G, P) f32
    valid_g: jax.Array,  # (G, P) f32
    group_dst: jax.Array,  # (G,) int32, sorted
    src_factors: jax.Array,  # (n_src, r)
    n_dst: int,
    alpha: float,
    implicit: bool,
    policy: str = "f32",
):
    """Scatter-free normal-equation partials: same math and Spark-parity
    weighting as :func:`normal_eq_partials`, grouped-edge layout.

    Layout note: every (…, G, P) intermediate keeps the big static group
    width P on the minor (128-lane) axis — gathering ``(G, P, r)`` with
    the rank (~10) minor pads each buffer ~12.8x to the vreg tile and
    measured 11x slower on v5e (30.9 vs 2.8 ms for the ML-1M user-side
    partials, round 3).  Hence the gather runs against the TRANSPOSED
    factor table and the batched matmul contracts the lane axis.

    Sides whose (r, G, P) intermediates exceed ``_GROUPED_BUDGET_ELEMS``
    are processed as a ``lax.scan`` over group blocks, accumulating the
    per-destination moments in a flat (n_dst, (r+1)*(r+2)) carry (flat so
    the carry pads to lane tiles once, not per (r+1, r+2) matrix).

    Returns (a_part (n_dst, r, r), b (n_dst, r), n_reg (n_dst,)).
    """
    r = src_factors.shape[1]
    G, P = src_g.shape

    def block_moments(src_b, conf_b, valid_b):
        return grouped_block_moments(
            src_b, conf_b, valid_b, src_factors, alpha, implicit, policy
        )

    blocks = _grouped_block_count(G, P, r)
    if blocks == 1:
        M = jax.ops.segment_sum(
            block_moments(src_g, conf_g, valid_g),
            group_dst, num_segments=n_dst, indices_are_sorted=True,
        )
        return M[:, :r, :r], M[:, :r, r], M[:, r, r + 1]

    gb = -(-G // blocks)
    pad = blocks * gb - G
    # dummy groups: valid=0 rows contribute exact zeros to dst n_dst-1
    src_p = jnp.pad(src_g, ((0, pad), (0, 0)))
    conf_p = jnp.pad(conf_g, ((0, pad), (0, 0)))
    valid_p = jnp.pad(valid_g, ((0, pad), (0, 0)))
    gd_p = jnp.pad(group_dst, (0, pad), constant_values=n_dst - 1)
    width = (r + 1) * (r + 2)

    def step(M_flat, blk):
        src_b, conf_b, valid_b, gd_b = blk
        m = block_moments(src_b, conf_b, valid_b).reshape(gb, width)
        return (
            M_flat
            + jax.ops.segment_sum(
                m, gd_b, num_segments=n_dst, indices_are_sorted=True
            ),
            None,
        )

    M_flat, _ = lax.scan(
        step,
        jnp.zeros((n_dst, width), src_factors.dtype),
        (
            src_p.reshape(blocks, gb, P),
            conf_p.reshape(blocks, gb, P),
            valid_p.reshape(blocks, gb, P),
            gd_p.reshape(blocks, gb),
        ),
    )
    M = M_flat.reshape(n_dst, r + 1, r + 2)
    return M[:, :r, :r], M[:, :r, r], M[:, r, r + 1]


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_users", "n_items", "max_iter", "implicit", "policy",
        "solve_kernel", "solve_geo", "gram_geo",
    ),
)
def _als_run_grouped_jit(
    u_src_g, u_conf_g, u_valid_g, u_group_dst,  # item ids grouped by user
    i_src_g, i_conf_g, i_valid_g, i_group_dst,  # user ids grouped by item
    x0: jax.Array,
    y0: jax.Array,
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    alpha: float,
    implicit: bool,
    policy: str = "f32",
    solve_kernel: str = "xla",
    solve_geo=None,
    gram_geo=None,
) -> Tuple[jax.Array, jax.Array]:
    r = x0.shape[1]
    eye = jnp.eye(r, dtype=x0.dtype)

    def half(src_g, conf_g, valid_g, group_dst, factors, n_dst):
        a, b, n_reg = normal_eq_partials_grouped(
            src_g, conf_g, valid_g, group_dst, factors, n_dst, alpha,
            implicit, policy,
        )
        gram = (
            _factor_gram(factors, solve_kernel, gram_geo)
            if implicit else None
        )
        return regularized_solve(
            a, b, n_reg, reg, eye, gram, solve_kernel, solve_geo
        ).astype(factors.dtype)

    def body(carry, _):
        x, y = carry
        x = half(u_src_g, u_conf_g, u_valid_g, u_group_dst, y, n_users)
        y = half(i_src_g, i_conf_g, i_valid_g, i_group_dst, x, n_items)
        return (x, y), None

    (x, y), _ = lax.scan(body, (x0, y0), None, length=max_iter)
    return x, y


def als_run_grouped(
    u_src_g, u_conf_g, u_valid_g, u_group_dst,
    i_src_g, i_conf_g, i_valid_g, i_group_dst,
    x0: jax.Array,
    y0: jax.Array,
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    alpha: float,
    implicit: bool,
    timings=None,
    phase: str = "als_iterations",
    policy: str = "f32",
    solve_kernel: str = "",
) -> Tuple[jax.Array, jax.Array]:
    """Full ALS loop on the grouped-edge layout (both feedback modes).

    ~15x the COO path at MovieLens-1M scale on v5e: scatter-free partials
    + Cholesky solves (BASELINE.md round 3).  The launch registers with
    the program-cache registry (utils/progcache); ``timings`` receives
    the ``<phase>/compile`` / ``<phase>/execute`` wall split.  ``policy``
    is the compute-precision policy (utils/precision.py) for the moment
    matmuls — the Gram and every solve stay f32 under all policies.
    ``solve_kernel``: "" resolves Config.als_solve_kernel
    (:func:`resolve_solve_kernel`); explicit values are the test seam."""
    solve_kernel = solve_kernel or resolve_solve_kernel(
        x0.shape[1], x0.dtype
    )
    solve_geo, gram_geo = _tuned_geometry(
        x0.shape[1], solve_kernel, implicit
    )
    # reg/alpha are traced scalars, not statics — they do not key a new
    # program and so stay out of the cache key
    key = (
        progcache.backend_fingerprint(),
        progcache.array_key(u_src_g, i_src_g, x0, y0),
        n_users, n_items, max_iter, implicit, policy, solve_kernel,
        solve_geo, gram_geo,
    )
    with progcache.launch("als.run_grouped", key, timings, phase):
        return _als_run_grouped_jit(
            u_src_g, u_conf_g, u_valid_g, u_group_dst,
            i_src_g, i_conf_g, i_valid_g, i_group_dst,
            x0, y0, n_users, n_items, max_iter, reg, alpha, implicit,
            policy, solve_kernel, solve_geo, gram_geo,
        )


def _tuned_geometry(r: int, solve_kernel: str, implicit: bool):
    """Tuned ALS kernel geometry for the pallas consumers (ops/pallas/
    autotune): ``(solve_geo, gram_geo)`` as hashable static tuples —
    ``(batch, depth)`` and ``(tile_rows, depth)`` — or ``(None, None)``
    on the XLA route.  Resolved EAGERLY by the runner wrappers (never
    inside a traced body) so the cache/sweep machinery runs exactly once
    per program build."""
    if not solve_kernel.startswith("pallas"):
        return None, None
    from oap_mllib_tpu.ops.pallas import autotune

    g = autotune.resolve("als_solve", autotune.shape_bucket(r))
    solve_geo = (g["batch"], g["depth"])
    gram_geo = None
    if implicit:
        gg = autotune.resolve("als_gram", autotune.shape_bucket(r))
        gram_geo = (gg["tile_rows"], gg["depth"])
    return solve_geo, gram_geo


def _half_update(
    dst_idx: jax.Array,
    src_idx: jax.Array,
    conf: jax.Array,
    valid: jax.Array,
    src_factors: jax.Array,
    n_dst: int,
    reg: float,
    alpha: float,
    policy: str = "f32",
    solve_kernel: str = "xla",
    solve_geo=None,
    gram_geo=None,
) -> jax.Array:
    """Solve one side's factors given the other side's. Returns (n_dst, r)."""
    r = src_factors.shape[1]
    # (r, r) <- MXU, psum over mesh — stays full f32 under every policy
    # (the Gram conditions the solve; its cost is O(n*r^2), not the hot path)
    gram = _factor_gram(src_factors, solve_kernel, gram_geo)
    a_part, b, n_reg = normal_eq_partials(
        dst_idx, src_idx, conf, valid, src_factors, n_dst, alpha, True,
        policy,
    )
    eye = jnp.eye(r, dtype=src_factors.dtype)
    return regularized_solve(
        a_part, b, n_reg, reg, eye, gram, solve_kernel, solve_geo
    ).astype(src_factors.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_users", "n_items", "max_iter", "policy", "solve_kernel",
        "solve_geo", "gram_geo",
    ),
)
def _als_implicit_run_jit(
    u_idx: jax.Array,
    i_idx: jax.Array,
    conf: jax.Array,
    valid: jax.Array,
    x0: jax.Array,  # (n_users, r)
    y0: jax.Array,  # (n_items, r)
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    alpha: float,
    policy: str = "f32",
    solve_kernel: str = "xla",
    solve_geo=None,
    gram_geo=None,
) -> Tuple[jax.Array, jax.Array]:

    def body(carry, _):
        x, y = carry
        x = _half_update(
            u_idx, i_idx, conf, valid, y, n_users, reg, alpha, policy,
            solve_kernel, solve_geo, gram_geo,
        )
        y = _half_update(
            i_idx, u_idx, conf, valid, x, n_items, reg, alpha, policy,
            solve_kernel, solve_geo, gram_geo,
        )
        return (x, y), None

    (x, y), _ = lax.scan(body, (x0, y0), None, length=max_iter)
    return x, y


def als_implicit_run(
    u_idx, i_idx, conf, valid, x0, y0,
    n_users: int, n_items: int, max_iter: int, reg: float, alpha: float,
    timings=None, phase: str = "als_iterations", policy: str = "f32",
    solve_kernel: str = "",
) -> Tuple[jax.Array, jax.Array]:
    """Full training loop: alternating user/item updates under lax.scan
    (the reference's trainModel loop, ALSDALImpl.cpp:318-438).
    Registry-tracked (utils/progcache), like :func:`als_run_grouped`."""
    solve_kernel = solve_kernel or resolve_solve_kernel(
        x0.shape[1], x0.dtype
    )
    solve_geo, gram_geo = _tuned_geometry(x0.shape[1], solve_kernel, True)
    key = (
        progcache.backend_fingerprint(),
        progcache.array_key(u_idx, x0, y0),
        n_users, n_items, max_iter, policy, solve_kernel, solve_geo,
        gram_geo,
    )
    with progcache.launch("als.implicit_coo", key, timings, phase):
        return _als_implicit_run_jit(
            u_idx, i_idx, conf, valid, x0, y0,
            n_users, n_items, max_iter, reg, alpha, policy, solve_kernel,
            solve_geo, gram_geo,
        )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_users", "n_items", "max_iter", "policy", "solve_kernel",
        "solve_geo",
    ),
)
def _als_explicit_run_jit(
    u_idx: jax.Array,
    i_idx: jax.Array,
    rating: jax.Array,
    valid: jax.Array,
    x0: jax.Array,
    y0: jax.Array,
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    policy: str = "f32",
    solve_kernel: str = "xla",
    solve_geo=None,
) -> Tuple[jax.Array, jax.Array]:

    def half(dst_idx, src_idx, src_factors, n_dst):
        r = src_factors.shape[1]
        a_part, b, n_reg = normal_eq_partials(
            dst_idx, src_idx, rating, valid, src_factors, n_dst, 0.0,
            False, policy,
        )
        eye = jnp.eye(r, dtype=src_factors.dtype)
        return regularized_solve(
            a_part, b, n_reg, reg, eye, None, solve_kernel, solve_geo
        ).astype(src_factors.dtype)

    def body(carry, _):
        x, y = carry
        x = half(u_idx, i_idx, y, n_users)
        y = half(i_idx, u_idx, x, n_items)
        return (x, y), None

    (x, y), _ = lax.scan(body, (x0, y0), None, length=max_iter)
    return x, y


def als_explicit_run(
    u_idx, i_idx, rating, valid, x0, y0,
    n_users: int, n_items: int, max_iter: int, reg: float,
    timings=None, phase: str = "als_iterations", policy: str = "f32",
    solve_kernel: str = "",
) -> Tuple[jax.Array, jax.Array]:
    """Explicit-feedback ALS (beyond the reference's accelerated surface —
    it falls back to Spark for explicit; we accelerate both).
    Registry-tracked (utils/progcache), like :func:`als_run_grouped`."""
    solve_kernel = solve_kernel or resolve_solve_kernel(
        x0.shape[1], x0.dtype
    )
    solve_geo, _ = _tuned_geometry(x0.shape[1], solve_kernel, False)
    key = (
        progcache.backend_fingerprint(),
        progcache.array_key(u_idx, x0, y0),
        n_users, n_items, max_iter, policy, solve_kernel, solve_geo,
    )
    with progcache.launch("als.explicit_coo", key, timings, phase):
        return _als_explicit_run_jit(
            u_idx, i_idx, rating, valid, x0, y0,
            n_users, n_items, max_iter, reg, policy, solve_kernel,
            solve_geo,
        )


@jax.jit
def predict_pairs(x: jax.Array, y: jax.Array, users: jax.Array, items: jax.Array) -> jax.Array:
    return jnp.sum(x[users] * y[items], axis=1)
