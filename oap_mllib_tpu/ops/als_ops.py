"""Implicit ALS compute kernels: jitted alternating least squares.

Replaces the reference's oneDAL 4-step distributed implicit ALS
(native/ALSDALImpl.cpp): there, each half-iteration runs step1Local
(partial cross-products), gathers serialized partials to the root
(:53-97), the root's step2Master forms the global cross-product (:261-281)
and broadcasts it back, step3Local/step4Local exchange partial models
all-to-all and solve per-block factors (:283-316) — plus a native ratings
shuffle and a transposed item-major CSR copy per rank (ALSShuffle.cpp,
ALSDALImpl.cpp:192-214).

TPU-first redesign — the whole half-iteration is three MXU/VPU passes over
a COO ratings tensor, no transposed copy and no master rank:

1. Gram: ``G = Y^T Y`` — one (r, n)x(n, r) matmul, psum over the mesh.
   (This is steps 1+2: the "cross-product" IS the Gram matrix.)
2. Per-edge contributions: for each rating (u, i, c): gather ``y_i``,
   form ``alpha*c * y_i y_i^T`` (nnz, r, r) and ``(1+alpha*c) y_i``
   (nnz, r), then ``segment_sum`` by user — XLA scatter-adds, the
   all-to-all-free equivalent of steps 3+4's partial-model exchange.
3. Solve: batched (r, r) Cholesky/LU solve over all users at once.

The item update reuses the SAME COO arrays with the index roles swapped —
the reference's per-rank transposed table (ALSDALImpl.cpp:209-213) has no
equivalent here because segment_sum doesn't care about sort order.

Padded COO entries carry ``valid = 0`` so they vanish from both A and b
(survey §2.6 fixed-shape design note).  dtype float32, matching the
reference kernel (ALSDALImpl.cpp:35 ``CpuAlgorithmFPType = float``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _edge_chunks(nnz: int, r: int, budget_elems: int = 1 << 24) -> int:
    """Chunk count for the (chunk, r, r) per-edge outer-product buffer.

    Power-of-two divisors of nnz so the live intermediate stays under
    ``budget_elems`` (peak memory O(chunk * r^2 + n_dst * r^2) instead of
    O(nnz * r^2) — at MovieLens-25M scale the unchunked buffer would blow
    HBM).  Callers pad nnz to a power-of-two-friendly multiple.
    """
    chunks = 1
    while (nnz // chunks) * r * r > budget_elems and nnz % (chunks * 2) == 0:
        chunks *= 2
    return chunks


def normal_eq_partials(
    dst_idx: jax.Array,  # (nnz,) int32 — side being solved (e.g. users)
    src_idx: jax.Array,  # (nnz,) int32 — fixed side (e.g. items)
    conf: jax.Array,  # (nnz,) f32 ratings/confidences
    valid: jax.Array,  # (nnz,) f32 1/0 mask
    src_factors: jax.Array,  # (n_src, r)
    n_dst: int,
    alpha: float,
    implicit: bool,
):
    """Per-edge normal-equation partials grouped by dst id — Spark parity.

    Implicit (reference ALS.scala:1781-1795): with c1 = alpha * |r|,
    A += c1 * y y^T for EVERY rating (|r| keeps A PSD for non-positive
    ratings), b += (1 + c1) * y only when r > 0 (preference 0 otherwise),
    and the regularization count n_reg counts only r > 0 ratings.
    Explicit: A += y y^T, b += r * y, n_reg counts all ratings.  The
    returned n_reg feeds both ALS-WR lambda scaling (Spark scales reg by
    the per-row rating count: solve(ne, numExplicits * regParam)) and the
    empty-row factor masking.

    Returns (a_part (n_dst, r, r), b (n_dst, r), n_reg (n_dst,)).  Shared
    by the global-program path (this file) and the block-parallel path
    (als_block.py, which psums these across the mesh) so the two can never
    diverge in the weighting math.  Edge-chunked via lax.scan so the
    (chunk, r, r) outer-product intermediate never scales with nnz.
    """
    nnz = dst_idx.shape[0]
    r = src_factors.shape[1]
    chunks = _edge_chunks(nnz, r)

    def partial_chunk(dst_c, src_c, conf_c, valid_c):
        ys = src_factors[src_c]  # (cs, r) gather
        if implicit:
            a_w = alpha * jnp.abs(conf_c) * valid_c
            pos = (conf_c > 0).astype(conf_c.dtype) * valid_c
            b_w = (1.0 + alpha * jnp.abs(conf_c)) * pos
            n_w = pos
        else:
            a_w = valid_c
            b_w = conf_c * valid_c
            n_w = valid_c
        outer = jnp.einsum("er,es->ers", ys * a_w[:, None], ys,
                           precision=lax.Precision.HIGHEST)  # (cs, r, r)
        a_c = jax.ops.segment_sum(outer, dst_c, num_segments=n_dst)
        b_c = jax.ops.segment_sum(ys * b_w[:, None], dst_c, num_segments=n_dst)
        n_c = jax.ops.segment_sum(n_w, dst_c, num_segments=n_dst)
        return a_c, b_c, n_c

    if chunks == 1:
        return partial_chunk(dst_idx, src_idx, conf, valid)

    cs = nnz // chunks
    def step(carry, chunk):
        a0, b0, n0 = carry
        a_c, b_c, n_c = partial_chunk(*chunk)
        return (a0 + a_c, b0 + b_c, n0 + n_c), None

    zero = (
        jnp.zeros((n_dst, r, r), src_factors.dtype),
        jnp.zeros((n_dst, r), src_factors.dtype),
        jnp.zeros((n_dst,), src_factors.dtype),
    )
    chunked = tuple(
        a.reshape(chunks, cs) for a in (dst_idx, src_idx, conf, valid)
    )
    (a_part, b, n_reg), _ = lax.scan(step, zero, chunked)
    return a_part, b, n_reg


def masked_solve(a: jax.Array, b: jax.Array, deg: jax.Array) -> jax.Array:
    """Batched SPD solve; rows with no (reg-counted) ratings get zero
    factors (fallback-path semantics) — also shields against NaN from a
    singular A when reg == 0."""
    factors = jnp.linalg.solve(a, b[:, :, None])[:, :, 0]
    return jnp.where(deg[:, None] > 0, jnp.nan_to_num(factors), 0.0)


def _half_update(
    dst_idx: jax.Array,
    src_idx: jax.Array,
    conf: jax.Array,
    valid: jax.Array,
    src_factors: jax.Array,
    n_dst: int,
    reg: float,
    alpha: float,
) -> jax.Array:
    """Solve one side's factors given the other side's. Returns (n_dst, r)."""
    r = src_factors.shape[1]
    # (r, r) <- MXU, psum over mesh
    gram = jnp.matmul(src_factors.T, src_factors, precision=lax.Precision.HIGHEST)
    a_part, b, n_reg = normal_eq_partials(
        dst_idx, src_idx, conf, valid, src_factors, n_dst, alpha, True
    )
    eye = jnp.eye(r, dtype=src_factors.dtype)
    # ALS-WR: lambda scaled by the per-row rating count (Spark parity)
    a = gram[None, :, :] + a_part + reg * n_reg[:, None, None] * eye[None, :, :]
    return masked_solve(a, b, n_reg).astype(src_factors.dtype)


@functools.partial(
    jax.jit, static_argnames=("n_users", "n_items", "max_iter")
)
def als_implicit_run(
    u_idx: jax.Array,
    i_idx: jax.Array,
    conf: jax.Array,
    valid: jax.Array,
    x0: jax.Array,  # (n_users, r)
    y0: jax.Array,  # (n_items, r)
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    alpha: float,
) -> Tuple[jax.Array, jax.Array]:
    """Full training loop: alternating user/item updates under lax.scan
    (the reference's trainModel loop, ALSDALImpl.cpp:318-438)."""

    def body(carry, _):
        x, y = carry
        x = _half_update(u_idx, i_idx, conf, valid, y, n_users, reg, alpha)
        y = _half_update(i_idx, u_idx, conf, valid, x, n_items, reg, alpha)
        return (x, y), None

    (x, y), _ = lax.scan(body, (x0, y0), None, length=max_iter)
    return x, y


@functools.partial(
    jax.jit, static_argnames=("n_users", "n_items", "max_iter")
)
def als_explicit_run(
    u_idx: jax.Array,
    i_idx: jax.Array,
    rating: jax.Array,
    valid: jax.Array,
    x0: jax.Array,
    y0: jax.Array,
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
) -> Tuple[jax.Array, jax.Array]:
    """Explicit-feedback ALS (beyond the reference's accelerated surface —
    it falls back to Spark for explicit; we accelerate both)."""

    def half(dst_idx, src_idx, src_factors, n_dst):
        r = src_factors.shape[1]
        a_part, b, n_reg = normal_eq_partials(
            dst_idx, src_idx, rating, valid, src_factors, n_dst, 0.0, False
        )
        eye = jnp.eye(r, dtype=src_factors.dtype)
        # ALS-WR lambda scaling (Spark parity)
        a = a_part + reg * n_reg[:, None, None] * eye[None, :, :]
        return masked_solve(a, b, n_reg).astype(src_factors.dtype)

    def body(carry, _):
        x, y = carry
        x = half(u_idx, i_idx, y, n_users)
        y = half(i_idx, u_idx, x, n_items)
        return (x, y), None

    (x, y), _ = lax.scan(body, (x0, y0), None, length=max_iter)
    return x, y


@jax.jit
def predict_pairs(x: jax.Array, y: jax.Array, users: jax.Array, items: jax.Array) -> jax.Array:
    return jnp.sum(x[users] * y[items], axis=1)
