"""Compute kernels: jitted, mesh-sharded JAX/XLA programs.

TPU-native replacement for the reference's L0 native kernels
(mllib-dal/src/main/native/{KMeans,PCA,ALS}DALImpl.cpp, which call oneDAL's
distributed step1Local/step2Master algorithms and stitch them together with
oneCCL collectives).  Here each algorithm is a single compiled program over
the sharded table: local math and cross-device reductions are expressed
globally and XLA lowers the reductions to ICI collectives — there is no
separate "master step" rank; reductions materialize replicated results
everywhere (survey §2.6 TPU-equivalent row).
"""
