"""Block-parallel implicit ALS: the distributed 2-D layout under shard_map.

This is the scalable counterpart of ops/als_ops.py (which jits one global
program and lets GSPMD place the segment-sums).  Here the distribution is
explicit, mirroring — and simplifying — the reference's 4-step oneDAL
scheme (native/ALSDALImpl.cpp):

- Edges (ratings) are sharded by USER BLOCK over the ``data`` mesh axis —
  the layout produced by the ratings shuffle (parallel/shuffle.py, the
  cShuffleData analog).  User ids are LOCAL to the block; item ids global.
- User factors X are sharded by the same blocks: the user update is fully
  local — each rank solves only its users (reference step3/step4Local,
  ALSDALImpl.cpp:283-316), zero communication.
- Item factors Y are replicated.  The item update computes per-rank
  partial normal equations (A_i, b_i) for ALL items from local edges,
  then one ``psum`` over the mesh — collapsing the reference's
  gather -> step2Master -> broadcast -> all2all chain
  (ALSDALImpl.cpp:336-431, 4 collective rounds per half-iteration) into a
  single ICI allreduce.
- The Gram matrix Y^T Y is computed redundantly per rank (r x r, trivial);
  X^T X needs one psum because X is sharded.

Cost model per iteration: psum traffic = n_items * r * (r + 1) floats
(the reference moves the same magnitude through gather+bcast+all2all,
serialized through a root rank; here it rides ICI as one fused collective).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oap_mllib_tpu.config import get_config
# shared normal-equation math — the block path only inserts psums between
# partials and solve, so the two paths cannot diverge in the weighting
from oap_mllib_tpu.ops.als_ops import masked_solve, normal_eq_partials


def als_block_run(
    u_local: jax.Array,  # (world * epr,) int32, LOCAL user ids, block-sharded
    i_global: jax.Array,  # (world * epr,) int32 global item ids
    conf: jax.Array,
    valid: jax.Array,
    x0: jax.Array,  # (world * upb, r) user factors, block-sharded rows
    y0: jax.Array,  # (n_items, r) item factors, replicated
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Run block-parallel ALS (implicit or explicit) over the mesh.

    Returns (X, Y).  Shapes: every rank holds ``epr`` edges and ``upb``
    user rows (padded — the shuffle guarantees equal shapes; invalid edges
    carry valid=0).  The explicit mode drops the Gram term and uses rating
    b-weights; both modes apply ALS-WR lambda scaling (Spark parity,
    reference ALS.scala:1794-1795) via the shared normal_eq_partials.
    """
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    upb = x0.shape[0] // world  # users per block (padded)
    n_items, r = y0.shape
    eye = jnp.eye(r, dtype=y0.dtype)

    def rank_program(u_loc, i_glob, cf, vl, x_blk, y):
        # x_blk: (upb, r) this rank's users; y: (n_items, r) replicated
        def body(carry, _):
            x_blk, y = carry
            # ---- user update: fully local (reference step3/4Local) ----
            a_u, b_u, n_u = normal_eq_partials(
                u_loc, i_glob, cf, vl, y, upb, alpha, implicit
            )
            a_u = a_u + reg * n_u[:, None, None] * eye[None]
            if implicit:
                gram_y = jnp.matmul(y.T, y, precision=lax.Precision.HIGHEST)
                a_u = gram_y[None] + a_u
            x_blk = masked_solve(a_u, b_u, n_u).astype(y.dtype)
            # ---- item update: partials + ONE psum (replaces the
            #      gather/step2Master/bcast/all2all chain) ----
            a_i, b_i, n_i = normal_eq_partials(
                i_glob, u_loc, cf, vl, x_blk, n_items, alpha, implicit
            )
            a_i = lax.psum(a_i, axis)
            b_i = lax.psum(b_i, axis)
            n_i = lax.psum(n_i, axis)
            a_i = a_i + reg * n_i[:, None, None] * eye[None]
            if implicit:
                gram_x = lax.psum(
                    jnp.matmul(x_blk.T, x_blk, precision=lax.Precision.HIGHEST),
                    axis,
                )
                a_i = gram_x[None] + a_i
            y = masked_solve(a_i, b_i, n_i).astype(y.dtype)
            return (x_blk, y), None

        (x_blk, y), _ = lax.scan(body, (x_blk, y), None, length=max_iter)
        return x_blk, y

    shard = P(axis)
    rep = P()
    fn = jax.jit(
        jax.shard_map(
            rank_program,
            mesh=mesh,
            in_specs=(shard, shard, shard, shard, P(axis, None), rep),
            out_specs=(P(axis, None), rep),
            check_vma=False,
        )
    )
    return fn(u_local, i_global, conf, valid, x0, y0)


def prepare_block_inputs(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
):
    """Shuffle ratings into the block layout and build device inputs.

    Returns (u_local, i_global, conf, valid, offsets, upb) where the edge
    arrays are block-sharded over the mesh and user ids are local to each
    rank's block (padded user rows run to ``upb`` per rank).
    """
    from oap_mllib_tpu.parallel.shuffle import exchange_ratings

    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    u, i, r, valid, offsets = exchange_ratings(users, items, ratings, mesh, n_users)
    upb = int(np.max(np.diff(offsets))) if world > 1 else n_users
    upb = max(upb, 1)
    # rebase global user ids to block-local ids on device: id - offset[rank]
    per_rank = u.shape[0] // world
    rank_of_row = jnp.repeat(jnp.arange(world, dtype=jnp.int32), per_rank)
    off = jnp.asarray(offsets[:-1], jnp.int32)[rank_of_row]
    u_local = jnp.where(valid > 0, u - off, upb - 1).astype(jnp.int32)
    # clamp invalid edges to a real row; valid=0 zeroes their contribution
    u_local = jnp.clip(u_local, 0, upb - 1)
    return u_local, i, r, valid, offsets, upb
