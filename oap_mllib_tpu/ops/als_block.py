"""Block-parallel implicit ALS: the distributed 2-D layout under shard_map.

This is the scalable counterpart of ops/als_ops.py (which jits one global
program and lets GSPMD place the segment-sums).  Here the distribution is
explicit, mirroring — and simplifying — the reference's 4-step oneDAL
scheme (native/ALSDALImpl.cpp):

- Edges (ratings) are sharded by USER BLOCK over the ``data`` mesh axis —
  the layout produced by the ratings shuffle (parallel/shuffle.py, the
  cShuffleData analog).  User ids are LOCAL to the block; item ids global.
- User factors X are sharded by the same blocks: the user update is fully
  local — each rank solves only its users (reference step3/step4Local,
  ALSDALImpl.cpp:283-316), zero communication.
Two item-factor layouts (config ``als_item_layout``):

- **replicated** (small n_items): Y lives on every device.  The item
  update computes per-rank partial normal equations (A_i, b_i) for ALL
  items from local edges, then one ``psum`` over the mesh — collapsing
  the reference's gather -> step2Master -> broadcast -> all2all chain
  (ALSDALImpl.cpp:336-431, 4 collective rounds per half-iteration) into a
  single ICI allreduce.  Cost per iteration: psum traffic
  ~2 * n_items * r * (r + 1) floats (allreduce = reduce-scatter +
  all-gather), transient per-device partials O(n_items * r^2).
- **sharded** (the full 2-D user x item grid, the reference's per-rank
  transposed item blocks — ALSDALImpl.cpp:192-214 builds an item-major
  CSR per rank, computeStep4Local:301-316 solves only that rank's item
  partition): edges are shuffled a SECOND time by item block, Y is
  block-sharded like X, and each half-iteration all_gathers the other
  side's factors instead of psumming full item partials.  Cost per
  iteration: all_gather traffic ~(n_users + n_items) * r floats —
  ~(r + 1)x less than replicated — and both the per-rank item partials
  and resident Y shrink world-fold.  Prep pays a second shuffle +
  grouped build.

- The Gram matrices (r x r) cost one psum each in the sharded layout
  (both sides block-sharded); replicated needs it only for X^T X.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import get_config
# shared normal-equation math — the block path only inserts psums between
# partials and solve, so the two paths cannot diverge in the weighting
from oap_mllib_tpu.ops.als_ops import (
    GROUPED_MAX_BLOWUP,
    _factor_gram,
    normal_eq_partials,
    normal_eq_partials_grouped,
    regularized_solve,
    resolve_solve_kernel,
)
from oap_mllib_tpu.parallel import collective
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.jax_compat import shard_map


# Auto-crossover for als_item_layout="auto": the replicated layout
# allreduces ~2 * n_items * r * (r+1) * 4 bytes per iteration AND holds a
# transient (n_items, r, r) partial per device; the sharded layout
# replaces both with two factor all_gathers at the price of a second
# shuffle + grouped build at fit time.  Shard once the per-iteration
# replicated psum payload (n_items * r * (r+1) * 4 bytes) crosses this
# bound — below it the psum is cheap and the replicated path's simpler
# prep wins (ML-25M at r=10 is ~26 MB/iter: replicated).
ITEM_SHARD_AUTO_BYTES = 1 << 27  # 128 MB


def als_item_layout_cfg() -> str:
    """Validated Config.als_item_layout.  Called on EVERY accelerated
    dispatch — single-device included, where the knob has no layout
    effect — so a typo raises everywhere, matching the als_kernel
    contract (it must not surface only once deployed to a mesh)."""
    layout = get_config().als_item_layout
    if layout not in ("auto", "replicated", "sharded"):
        raise ValueError(
            f"als_item_layout must be auto|replicated|sharded, got {layout!r}"
        )
    return layout


def item_layout_sharded(
    n_items: int, r: int, world: int, n_users: int = 0
) -> bool:
    """Resolve config.als_item_layout to a concrete layout decision.

    "auto" shards when BOTH hold: the replicated psum payload
    (n_items·r·(r+1)·4 bytes/iter) crosses ITEM_SHARD_AUTO_BYTES, AND
    the sharded layout's traffic is actually lower — its per-iteration
    all_gathers move ~(n_users+n_items)·r vs the psum's
    ~2·n_items·r·(r+1), so a USER-dominated workload
    (n_users > n_items·(2r+1)) would trade a big psum for a bigger X
    all_gather and stays replicated."""
    layout = als_item_layout_cfg()
    if layout != "auto":
        return layout == "sharded"
    return (
        world > 1
        and n_items * r * (r + 1) * 4 > ITEM_SHARD_AUTO_BYTES
        and n_users <= n_items * (2 * r + 1)
    )


def _block_body(user_partials, item_partials, reg, implicit, axis, eye,
                solve_kernel="xla"):
    """One alternating iteration of the block layout, shared by the COO and
    grouped-edge programs: user update fully local, item update partials +
    ONE psum (replacing the reference's gather/step2Master/bcast/all2all
    chain, ALSDALImpl.cpp:336-431).  ``user_partials(y)`` /
    ``item_partials(x_blk)`` return (A, b, n_reg) from whichever edge
    layout the caller closed over.  ``solve_kernel`` picks the
    regularized_solve consumer (als_ops.resolve_solve_kernel)."""

    def body(carry, _):
        x_blk, y = carry
        a_u, b_u, n_u = user_partials(y)
        gram_y = (
            _factor_gram(y, solve_kernel)
            if implicit else None
        )
        x_blk = regularized_solve(
            a_u, b_u, n_u, reg, eye, gram_y, solve_kernel
        ).astype(y.dtype)
        a_i, b_i, n_i = item_partials(x_blk)
        a_i = collective.psum(a_i, axis)
        b_i = collective.psum(b_i, axis)
        n_i = collective.psum(n_i, axis)
        gram_x = (
            collective.psum(
                _factor_gram(x_blk, solve_kernel),
                axis,
            )
            if implicit else None
        )
        y = regularized_solve(
            a_i, b_i, n_i, reg, eye, gram_x, solve_kernel
        ).astype(x_blk.dtype)
        return (x_blk, y), None

    return body


def _block_body_2d(user_partials, item_partials, reg, implicit, axis, eye,
                   solve_kernel="xla"):
    """One alternating iteration of the fully-sharded 2-D layout: BOTH
    factor matrices block-sharded.  Each half-iteration all_gathers the
    other side's factors (tiled, so the gathered array IS the padded
    global layout — see the prepare_* identity-mapping note), builds
    partials only for this rank's destinations, and solves locally — the
    reference's computeStep4Local (ALSDALImpl.cpp:301-316) with the
    4-collective exchange chain replaced by one all_gather.  The implicit
    Gram needs a psum on both sides now (each side holds only its block;
    padded rows are zero so the psum of block Grams is the exact Gram).

    ``user_partials(y_full)`` -> (A, b, n) for this rank's upb users;
    ``item_partials(x_full)`` -> (A, b, n) for this rank's ipb items."""

    def body(carry, _):
        x_blk, y_blk = carry
        y_full = collective.all_gather(y_blk, axis, tiled=True)
        a_u, b_u, n_u = user_partials(y_full)
        gram_y = (
            collective.psum(
                _factor_gram(y_blk, solve_kernel),
                axis,
            )
            if implicit else None
        )
        x_blk = regularized_solve(
            a_u, b_u, n_u, reg, eye, gram_y, solve_kernel
        ).astype(y_blk.dtype)
        x_full = collective.all_gather(x_blk, axis, tiled=True)
        a_i, b_i, n_i = item_partials(x_full)
        gram_x = (
            collective.psum(
                _factor_gram(x_blk, solve_kernel),
                axis,
            )
            if implicit else None
        )
        y_blk = regularized_solve(
            a_i, b_i, n_i, reg, eye, gram_x, solve_kernel
        ).astype(y_blk.dtype)
        return (x_blk, y_blk), None

    return body


def als_block_run(
    u_local: jax.Array,  # (world * epr,) int32, LOCAL user ids, block-sharded
    i_global: jax.Array,  # (world * epr,) int32 global item ids
    conf: jax.Array,
    valid: jax.Array,
    x0: jax.Array,  # (world * upb, r) user factors, block-sharded rows
    y0: jax.Array,  # (n_items, r) item factors, replicated
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Run block-parallel ALS (implicit or explicit) over the mesh.

    Returns (X, Y).  Shapes: every rank holds ``epr`` edges and ``upb``
    user rows (padded — the shuffle guarantees equal shapes; invalid edges
    carry valid=0).  The explicit mode drops the Gram term and uses rating
    b-weights; both modes apply ALS-WR lambda scaling (Spark parity,
    reference ALS.scala:1794-1795) via the shared normal_eq_partials.
    ``policy`` is the compute-precision policy (utils/precision.py) for
    the per-edge factor matmuls; Grams and solves stay f32.
    """
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    upb = x0.shape[0] // world  # users per block (padded)
    n_items, r = y0.shape
    solve_kernel = resolve_solve_kernel(r, y0.dtype, cfg)

    # the jitted shard_map program is registry-cached (utils/progcache):
    # rebuilding the closure per fit — the pattern every runner in this
    # module had — re-jitted and recompiled on each call even for
    # identical layouts.  reg/alpha ARE key components here (unlike the
    # single-device entries' traced scalars): they bake into the traced
    # program as closure constants.
    def build():
        eye = jnp.eye(r, dtype=y0.dtype)

        def rank_program(u_loc, i_glob, cf, vl, x_blk, y):
            # x_blk: (upb, r) this rank's users; y: (n_items, r) replicated
            body = _block_body(
                lambda y_: normal_eq_partials(
                    u_loc, i_glob, cf, vl, y_, upb, alpha, implicit,
                    policy,
                ),
                lambda x_: normal_eq_partials(
                    i_glob, u_loc, cf, vl, x_, n_items, alpha, implicit,
                    policy,
                ),
                reg, implicit, axis, eye, solve_kernel,
            )
            (x_blk, y), _ = lax.scan(body, (x_blk, y), None, length=max_iter)
            return x_blk, y

        shard = P(axis)
        rep = P()
        return jax.jit(
            shard_map(
                rank_program,
                mesh=mesh,
                in_specs=(shard, shard, shard, shard, P(axis, None), rep),
                out_specs=(P(axis, None), rep),
                check_vma=False,
            )
        )

    key = (
        progcache.mesh_fingerprint(mesh), axis, upb, n_items, r,
        max_iter, reg, alpha, implicit, str(y0.dtype), policy,
        solve_kernel,
    )
    fn = progcache.get_or_build("als_block.coo", key, build)
    launch_key = key + (progcache.array_key(u_local, x0),)
    with progcache.launch("als_block.coo.run", launch_key):
        return fn(u_local, i_global, conf, valid, x0, y0)


# ---------------------------------------------------------------------------
# Grouped-edge block path: the scatter-free layout (als_ops grouped-path
# notes) applied per rank.  Each rank's local edges are sorted/padded by
# destination ONCE on the host — by local user for the user update, by
# global item for the item update (the reference's per-rank CSR + transposed
# CSR pair, ALSDALImpl.cpp:192-214, as two grouped layouts) — then every
# iteration's normal-equation build is batched MXU matmuls with zero
# scatters.  Ranks pad their group counts to the global maxima so the
# shard_map program keeps equal shapes.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GroupedBlocks:
    """Device-resident grouped-edge layouts, block-sharded over the mesh."""

    u_src: jax.Array  # (world * Gu, Pu) item ids grouped by local user
    u_conf: jax.Array
    u_valid: jax.Array
    u_dst: jax.Array  # (world * Gu,) local user id per group (sorted/rank)
    i_src: jax.Array  # (world * Hi, Pi) user ids grouped by global item
    i_conf: jax.Array
    i_valid: jax.Array
    i_dst: jax.Array  # (world * Hi,) global item id per group (sorted/rank)


def _global_sum(arr) -> np.ndarray:
    """Elementwise int64 sum of a host array across processes (identity in
    single-process worlds) — the one definition every cross-process
    reduction in this module goes through."""
    arr = np.asarray(arr, np.int64)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        g = np.asarray(multihost_utils.process_allgather(arr))
        return g.reshape((-1,) + arr.shape).sum(axis=0)
    return arr


def _global_max(arr) -> np.ndarray:
    """Elementwise int64 max across processes (identity single-process)."""
    arr = np.asarray(arr, np.int64)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        g = np.asarray(multihost_utils.process_allgather(arr))
        return g.reshape((-1,) + arr.shape).max(axis=0)
    return arr


def _group_sizes(nnz_global: int, world: int, users_per_block: int,
                 n_items: int):
    """(p_u, p_i) — ONE derivation shared by the pre-shuffle guard and the
    layout build, so they can never size different layouts."""
    from oap_mllib_tpu.ops.als_ops import auto_group_size

    p_u = auto_group_size(max(1, nnz_global), world * users_per_block)
    p_i = auto_group_size(max(1, nnz_global // world), n_items)
    return p_u, p_i


def block_grouped_guard(
    users: np.ndarray,
    items: np.ndarray,
    n_users: int,
    n_items: int,
    world: int,
    max_blowup: float = GROUPED_MAX_BLOWUP,
):
    """Grouped-vs-COO decision for the block path, BEFORE the shuffle and
    from host degree counts alone — a COO decision must pay neither the
    grouped build nor the device->host pull of the shuffled blocks.

    Returns ``(use_grouped, (p_u, p_i, nnz_global))``; the sizes tuple is
    threaded into :func:`prepare_grouped_inputs` so the build uses exactly
    the layout the guard priced.

    Accounting matches what the build REALIZES: every rank is padded to
    the global max group counts, so the estimate is ``world * (max_b
    padded_u_b + max_b padded_i_b)`` over per-block padded totals — a
    sum over blocks would undercount skewed splits by up to ``world``x.
    The per-block totals are computable pre-shuffle because the shuffle
    routes every edge to block ``min(u // kpb, world - 1)``.
    Multi-process worlds sum per-block totals across processes (degrees
    split across processes pad per process — an overestimate, so
    borderline datasets conservatively take COO).
    """
    nnz_global = int(_global_sum([len(users)])[0])
    kpb = max(1, -(-n_users // world))
    p_u, p_i = _group_sizes(nnz_global, world, kpb, n_items)
    u = np.asarray(users, np.int64)
    it = np.asarray(items, np.int64)
    # user side: a user's edges land in ONE block — shared ceil-padding
    # accounting with the 2-D guard (one formula, both guards)
    pu_b = _side_padded_per_block(u, kpb, world, p_u)
    # item side (replicated layout): each item's edges SPLIT across user
    # blocks, so the per-(block, item) pair counts pad independently
    pi_b = np.zeros((world,), np.int64)
    block = np.minimum(u // kpb, world - 1)
    ki, ci = np.unique(block * n_items + it, return_counts=True)
    np.add.at(pi_b, ki // n_items, (-(ci // -p_i)) * p_i)
    pu_b = _global_sum(pu_b)
    pi_b = _global_sum(pi_b)
    total = world * (int(pu_b.max()) + int(pi_b.max()))
    return total <= max_blowup * max(nnz_global, 1), (p_u, p_i, nnz_global)


def _host_blocks(arr: jax.Array, world: int) -> dict:
    """Per-rank host views of a block-sharded device array ({rank: rows}).
    Multi-process worlds see only their addressable blocks."""
    per = arr.shape[0] // world
    if arr.is_fully_addressable:
        h = np.asarray(arr)
        return {b: h[b * per : (b + 1) * per] for b in range(world)}
    out = {}
    for sh in arr.addressable_shards:
        start = sh.index[0].start or 0
        out[start // per] = np.asarray(sh.data)  # model-axis dupes collapse
    return out


def _pad_groups(grouped, g_max: int, n_dst: int):
    """Pad a rank's grouped arrays to ``g_max`` groups.  Padding groups
    carry valid=0 and dst = n_dst - 1 (keeps group_dst sorted, so the
    segment-sum's indices_are_sorted contract holds)."""
    src_g, conf_g, valid_g, gdst = grouped
    pad = g_max - src_g.shape[0]
    if pad > 0:
        p = src_g.shape[1]
        src_g = np.concatenate([src_g, np.zeros((pad, p), np.int32)])
        conf_g = np.concatenate([conf_g, np.zeros((pad, p), np.float32)])
        valid_g = np.concatenate([valid_g, np.zeros((pad, p), np.float32)])
        gdst = np.concatenate(
            [gdst, np.full((pad,), n_dst - 1, np.int32)]
        )
    return src_g, conf_g, valid_g, gdst


def _build_grouped_side(dst_b, src_b, conf_b, valid_b, n_dst: int, p: int):
    """Per-rank grouped layouts for ONE side: {block: grouped tuple}.
    Shared by the 1-D and 2-D preps so the build semantics cannot
    diverge between the replicated and sharded item layouts."""
    from oap_mllib_tpu.ops.als_ops import build_grouped_edges

    out = {}
    for b in dst_b:
        sel = valid_b[b] > 0
        out[b] = build_grouped_edges(
            dst_b[b][sel].astype(np.int64),
            src_b[b][sel].astype(np.int64),
            conf_b[b][sel].astype(np.float32),
            n_dst, p,
        )
    return out


def _pad_stack_place(by_user, by_item, u_ndst: int, i_ndst: int, mesh: Mesh):
    """Shared tail of both grouped preps: pad every rank to the GLOBAL
    max group counts (one allgather covers both sides), stack rank-major,
    and place block-sharded on the mesh."""
    cfg = get_config()
    axis = cfg.data_axis
    gu_local = max(g[0].shape[0] for g in by_user.values())
    hi_local = max(g[0].shape[0] for g in by_item.values())
    gu, hi = (int(v) for v in _global_max([gu_local, hi_local]))

    blocks = sorted(by_user)
    u_pad = {b: _pad_groups(by_user[b], gu, u_ndst) for b in blocks}
    i_pad = {b: _pad_groups(by_item[b], hi, i_ndst) for b in blocks}
    u_stack = [
        np.concatenate([u_pad[b][j] for b in blocks]) for j in range(4)
    ]
    i_stack = [
        np.concatenate([i_pad[b][j] for b in blocks]) for j in range(4)
    ]

    def place(local):
        sharding = NamedSharding(mesh, P(axis, *([None] * (local.ndim - 1))))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, local)
        return jax.device_put(local, sharding)

    u_dev = [place(m) for m in u_stack]
    i_dev = [place(m) for m in i_stack]
    return GroupedBlocks(
        u_src=u_dev[0], u_conf=u_dev[1], u_valid=u_dev[2], u_dst=u_dev[3],
        i_src=i_dev[0], i_conf=i_dev[1], i_valid=i_dev[2], i_dst=i_dev[3],
    )


def prepare_grouped_inputs(
    u_local: jax.Array,
    i_global: jax.Array,
    conf: jax.Array,
    valid: jax.Array,
    mesh: Mesh,
    upb: int,
    n_items: int,
    *,
    sizes=None,
):
    """Build per-rank grouped-edge layouts from the shuffled block arrays.

    Returns a :class:`GroupedBlocks`.  The grouped-vs-COO decision is NOT
    made here — :func:`block_grouped_guard` is the single decision point
    (it runs pre-shuffle so a COO decision pays nothing); ``sizes`` is its
    ``(p_u, p_i, nnz_global)`` tuple, threaded through so the build uses
    exactly the layout the guard priced (and skips a redundant allgather
    round).  Host cost is one sort of each rank's local edges — indices
    are static across iterations, so this runs once per fit (same
    contract as the single-device grouped prep).
    """
    cfg = get_config()
    world = mesh.shape[cfg.data_axis]
    ub = _host_blocks(u_local, world)
    ib = _host_blocks(i_global, world)
    cb = _host_blocks(conf, world)
    vb = _host_blocks(valid, world)

    if sizes is not None:
        p_u, p_i, _ = sizes
    else:
        nnz_local = sum(int((vb[b] > 0).sum()) for b in vb)
        nnz_global = int(_global_sum([nnz_local])[0])
        # group sizes from GLOBAL stats so every process compiles
        # identical static shapes
        p_u, p_i = _group_sizes(nnz_global, world, upb, n_items)

    by_user = _build_grouped_side(ub, ib, cb, vb, upb, p_u)
    by_item = _build_grouped_side(ib, ub, cb, vb, n_items, p_i)
    return _pad_stack_place(by_user, by_item, upb, n_items, mesh)


def als_block_run_grouped(
    gb: GroupedBlocks,
    x0: jax.Array,  # (world * upb, r) block-sharded user factors
    y0: jax.Array,  # (n_items, r) replicated item factors
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Block-parallel ALS on the grouped-edge layout (both feedback modes).

    Identical math and collective structure to :func:`als_block_run` (one
    psum per item update) with the scatter-free partials — the multi-device
    form of the 12x single-device win (BASELINE.md round 3)."""
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    upb = x0.shape[0] // world
    n_items, r = y0.shape
    solve_kernel = resolve_solve_kernel(r, y0.dtype, cfg)

    def build():
        eye = jnp.eye(r, dtype=y0.dtype)

        def rank_program(su, cu, vu, gu, si, ci, vi, gi, x_blk, y):
            body = _block_body(
                lambda y_: normal_eq_partials_grouped(
                    su, cu, vu, gu, y_, upb, alpha, implicit, policy
                ),
                lambda x_: normal_eq_partials_grouped(
                    si, ci, vi, gi, x_, n_items, alpha, implicit, policy
                ),
                reg, implicit, axis, eye, solve_kernel,
            )
            (x_blk, y), _ = lax.scan(body, (x_blk, y), None, length=max_iter)
            return x_blk, y

        sh2 = P(axis, None)
        sh1 = P(axis)
        rep = P()
        return jax.jit(
            shard_map(
                rank_program,
                mesh=mesh,
                in_specs=(sh2, sh2, sh2, sh1, sh2, sh2, sh2, sh1, sh2, rep),
                out_specs=(sh2, rep),
                check_vma=False,
            )
        )

    key = (
        progcache.mesh_fingerprint(mesh), axis, upb, n_items, r,
        max_iter, reg, alpha, implicit, str(y0.dtype), policy,
        solve_kernel,
    )
    fn = progcache.get_or_build("als_block.grouped", key, build)
    launch_key = key + (progcache.array_key(gb.u_src, gb.i_src, x0),)
    with progcache.launch("als_block.grouped.run", launch_key):
        return fn(
            gb.u_src, gb.u_conf, gb.u_valid, gb.u_dst,
            gb.i_src, gb.i_conf, gb.i_valid, gb.i_dst,
            x0, y0,
        )


def als_block_run_2d(
    u_local: jax.Array,  # user-sharded copy: (world * epr,) LOCAL user ids
    i_row: jax.Array,  # global item ids == padded-Y rows (identity mapping)
    conf_u: jax.Array,
    valid_u: jax.Array,
    i_local: jax.Array,  # item-sharded copy: (world * epr2,) LOCAL item ids
    u_row: jax.Array,  # global user ids == padded-X rows
    conf_i: jax.Array,
    valid_i: jax.Array,
    x0: jax.Array,  # (world * upb, r) block-sharded user factors
    y0: jax.Array,  # (world * ipb, r) block-sharded item factors
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """COO 2-D ALS: both factor sides block-sharded (see _block_body_2d).

    Takes TWO shuffled edge copies — by user block (u_local local,
    i_row global) and by item block (i_local local, u_row global); the
    global ids index the all_gathered padded factor layouts directly
    (prepare_block_inputs identity-mapping note)."""
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    upb = x0.shape[0] // world
    ipb = y0.shape[0] // world
    r = y0.shape[1]
    solve_kernel = resolve_solve_kernel(r, y0.dtype, cfg)

    def build():
        eye = jnp.eye(r, dtype=y0.dtype)

        def rank_program(ul, ir, cu, vu, il, ur, ci, vi, x_blk, y_blk):
            body = _block_body_2d(
                lambda y_full: normal_eq_partials(
                    ul, ir, cu, vu, y_full, upb, alpha, implicit, policy
                ),
                lambda x_full: normal_eq_partials(
                    il, ur, ci, vi, x_full, ipb, alpha, implicit, policy
                ),
                reg, implicit, axis, eye, solve_kernel,
            )
            (x_blk, y_blk), _ = lax.scan(
                body, (x_blk, y_blk), None, length=max_iter
            )
            return x_blk, y_blk

        sh1 = P(axis)
        sh2 = P(axis, None)
        return jax.jit(
            shard_map(
                rank_program,
                mesh=mesh,
                in_specs=(sh1,) * 8 + (sh2, sh2),
                out_specs=(sh2, sh2),
                check_vma=False,
            )
        )

    key = (
        progcache.mesh_fingerprint(mesh), axis, upb, ipb, r,
        max_iter, reg, alpha, implicit, str(y0.dtype), policy,
        solve_kernel,
    )
    fn = progcache.get_or_build("als_block.coo_2d", key, build)
    launch_key = key + (progcache.array_key(u_local, i_local, x0),)
    with progcache.launch("als_block.coo_2d.run", launch_key):
        return fn(
            u_local, i_row, conf_u, valid_u, i_local, u_row, conf_i,
            valid_i, x0, y0,
        )


def als_block_run_grouped_2d(
    gb: GroupedBlocks,
    x0: jax.Array,  # (world * upb, r) block-sharded user factors
    y0: jax.Array,  # (world * ipb, r) block-sharded item factors
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Grouped-edge 2-D ALS: scatter-free partials on both block-sharded
    sides.  ``gb`` comes from :func:`prepare_grouped_inputs_2d` — its
    u_* arrays group the user-sharded edge copy by LOCAL user (src =
    padded-Y rows) and its i_* arrays group the item-sharded copy by
    LOCAL item (src = padded-X rows)."""
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    upb = x0.shape[0] // world
    ipb = y0.shape[0] // world
    r = y0.shape[1]
    solve_kernel = resolve_solve_kernel(r, y0.dtype, cfg)

    def build():
        eye = jnp.eye(r, dtype=y0.dtype)

        def rank_program(su, cu, vu, gu, si, ci, vi, gi, x_blk, y_blk):
            body = _block_body_2d(
                lambda y_full: normal_eq_partials_grouped(
                    su, cu, vu, gu, y_full, upb, alpha, implicit, policy
                ),
                lambda x_full: normal_eq_partials_grouped(
                    si, ci, vi, gi, x_full, ipb, alpha, implicit, policy
                ),
                reg, implicit, axis, eye, solve_kernel,
            )
            (x_blk, y_blk), _ = lax.scan(
                body, (x_blk, y_blk), None, length=max_iter
            )
            return x_blk, y_blk

        sh2 = P(axis, None)
        sh1 = P(axis)
        return jax.jit(
            shard_map(
                rank_program,
                mesh=mesh,
                in_specs=(sh2, sh2, sh2, sh1, sh2, sh2, sh2, sh1, sh2, sh2),
                out_specs=(sh2, sh2),
                check_vma=False,
            )
        )

    key = (
        progcache.mesh_fingerprint(mesh), axis, upb, ipb, r,
        max_iter, reg, alpha, implicit, str(y0.dtype), policy,
        solve_kernel,
    )
    fn = progcache.get_or_build("als_block.grouped_2d", key, build)
    launch_key = key + (progcache.array_key(gb.u_src, gb.i_src, x0),)
    with progcache.launch("als_block.grouped_2d.run", launch_key):
        return fn(
            gb.u_src, gb.u_conf, gb.u_valid, gb.u_dst,
            gb.i_src, gb.i_conf, gb.i_valid, gb.i_dst,
            x0, y0,
        )


def _side_padded_per_block(ids: np.ndarray, kpb: int, world: int, p: int):
    """(world,) padded edge totals one grouped side would realize, from
    host degree counts alone — every id's edges land in ONE block (ids
    are partitioned contiguously by ``kpb``), so the block's total is the
    sum of per-id ceil-paddings."""
    k, c = np.unique(np.asarray(ids, np.int64), return_counts=True)
    out = np.zeros((world,), np.int64)
    np.add.at(out, np.minimum(k // kpb, world - 1), (-(c // -p)) * p)
    return out


def block_grouped_guard_2d(
    users: np.ndarray,
    items: np.ndarray,
    n_users: int,
    n_items: int,
    world: int,
    max_blowup: float = GROUPED_MAX_BLOWUP,
):
    """Grouped-vs-COO decision for the 2-D sharded-item path.

    Symmetric pricing: both sides are block-partitioned by id, so each
    side's realized total is ``world * max_b (per-block padded sum)``
    (rank group counts pad to the global max, exactly like the user side
    of :func:`block_grouped_guard`).  Returns
    ``(use_grouped, (p_u, p_i, nnz_global))`` for
    :func:`prepare_grouped_inputs_2d`."""
    nnz_global = int(_global_sum([len(users)])[0])
    kpb_u = max(1, -(-n_users // world))
    kpb_i = max(1, -(-n_items // world))
    p_u, p_i = _group_sizes_2d(nnz_global, world, kpb_u, kpb_i)
    pu_b = _global_sum(_side_padded_per_block(users, kpb_u, world, p_u))
    pi_b = _global_sum(_side_padded_per_block(items, kpb_i, world, p_i))
    total = world * (int(pu_b.max()) + int(pi_b.max()))
    return total <= max_blowup * max(nnz_global, 1), (p_u, p_i, nnz_global)


def _group_sizes_2d(nnz_global: int, world: int, upb: int, ipb: int):
    """Group sizes for the 2-D layout.  Unlike the replicated layout
    (whose item side spreads each item's edges over all ranks), both
    sides here keep every destination's edges on one rank, so both size
    from the GLOBAL mean degree."""
    from oap_mllib_tpu.ops.als_ops import auto_group_size

    p_u = auto_group_size(max(1, nnz_global), world * upb)
    p_i = auto_group_size(max(1, nnz_global), world * ipb)
    return p_u, p_i


def prepare_grouped_inputs_2d(
    u_local: jax.Array,
    i_row: jax.Array,
    conf_u: jax.Array,
    valid_u: jax.Array,
    i_local: jax.Array,
    u_row: jax.Array,
    conf_i: jax.Array,
    valid_i: jax.Array,
    mesh: Mesh,
    upb: int,
    ipb: int,
    *,
    sizes=None,
):
    """Grouped-edge layouts for the 2-D path, one per shuffled copy:
    by-LOCAL-user from the user-sharded copy (src = padded-Y rows) and
    by-LOCAL-item from the item-sharded copy (src = padded-X rows) — the
    reference's per-rank CSR + transposed-CSR pair (ALSDALImpl.cpp
    :192-214) where, unlike :func:`prepare_grouped_inputs`, the item side
    also covers only this rank's item partition.  Returns a
    :class:`GroupedBlocks` for :func:`als_block_run_grouped_2d`."""
    cfg = get_config()
    world = mesh.shape[cfg.data_axis]
    ub = _host_blocks(u_local, world)
    irb = _host_blocks(i_row, world)
    cub = _host_blocks(conf_u, world)
    vub = _host_blocks(valid_u, world)
    ib = _host_blocks(i_local, world)
    urb = _host_blocks(u_row, world)
    cib = _host_blocks(conf_i, world)
    vib = _host_blocks(valid_i, world)

    if sizes is not None:
        p_u, p_i, _ = sizes
    else:
        nnz_local = sum(int((vub[b] > 0).sum()) for b in vub)
        nnz_global = int(_global_sum([nnz_local])[0])
        p_u, p_i = _group_sizes_2d(nnz_global, world, upb, ipb)

    by_user = _build_grouped_side(ub, irb, cub, vub, upb, p_u)
    by_item = _build_grouped_side(ib, urb, cib, vib, ipb, p_i)
    return _pad_stack_place(by_user, by_item, upb, ipb, mesh)


def prepare_block_inputs(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    mesh: Mesh,
    n_users: int,
    offsets: "np.ndarray | None" = None,
):
    """Shuffle ratings into the block layout and build device inputs.

    Returns (u_local, i_global, conf, valid, offsets, upb) where the edge
    arrays are block-sharded over the mesh and user ids are local to each
    rank's block (padded user rows run to ``upb`` per rank).

    Identity-mapping note (load-bearing for the 2-D layout): with the
    default uniform layout, blocks are contiguous id ranges of width
    kpb = ceil(n/world) and ``upb == kpb`` whenever world > 1, so a
    GLOBAL id g living in block b sits at padded row
    ``b * upb + (g - b * kpb) == g`` of the block-stacked factor array.
    The 2-D runners exploit this: the OTHER side's global ids in each
    edge copy index the all_gathered padded factors directly, no remap
    tensor needed.  ``offsets`` (the capability-weighted uneven layout,
    parallel/balance.plan_block_offsets) BREAKS that identity, so the
    caller must only pass it on the replicated-item layout — the
    models/als dispatch enforces this; the rebasing and every consumer
    of (offsets, upb) here is boundary-generic.
    """
    from oap_mllib_tpu.parallel.shuffle import exchange_ratings

    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    u, i, r, valid, offsets = exchange_ratings(
        users, items, ratings, mesh, n_users, offsets=offsets
    )
    upb = int(np.max(np.diff(offsets))) if world > 1 else n_users
    upb = max(upb, 1)
    # rebase global user ids to block-local ids on device: id - offset[rank]
    per_rank = u.shape[0] // world
    rank_of_row = jnp.repeat(jnp.arange(world, dtype=jnp.int32), per_rank)
    off = jnp.asarray(offsets[:-1], jnp.int32)[rank_of_row]
    u_local = jnp.where(valid > 0, u - off, upb - 1).astype(jnp.int32)
    # clamp invalid edges to a real row; valid=0 zeroes their contribution
    u_local = jnp.clip(u_local, 0, upb - 1)
    return u_local, i, r, valid, offsets, upb
