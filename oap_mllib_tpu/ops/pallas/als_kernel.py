"""Batched ALS normal-equation kernels: per-user Gram assembly + rank-r
Cholesky solve, and the streamed factor Gram.

The XLA half-update (ops/als_ops.regularized_solve) assembles the batched
(n_dst, r, r) systems — ALS-WR regularization + the implicit-feedback
Gram term — as separate HBM-materialized intermediates before the
unrolled batch-wide solve (``_chol_solve_unrolled``), paying ~3 extra
reads/writes of the (n_dst, r, r) tensor.  ``solve_normal_eq_pallas``
fuses the whole consumer: each grid step loads one batch tile of flat
moments into VMEM, assembles A = moments + reg*n_reg*I (+ Gram) in
registers, runs the unrolled rank-r Cholesky + both substitutions, masks
empty rows, and writes only the (r, batch) factor tile back — one HBM
read of the moments, one write of the factors.

Layout: batch on the 128-LANE axis throughout (the grouped-path lesson,
als_ops module notes: a (B, r, r) layout pads every r-minor buffer ~13x
to the vreg tile at r=10).  Inputs arrive as one flat (r*r + r + 1, B)
moment sheet — A row-major, then b, then n_reg — so every unrolled
Cholesky step is a (1, B) lane-wide VPU op.

Numerics: the solve is pinned f32 at EVERY tier, matching the package
contract that Grams and solves never run reduced (utils/precision.py —
the solve's conditioning is what the policy protects); ``mode`` is
validated through the shared tier vocabulary so policy aliases pass
through uniformly, and governs only :func:`factor_gram_pallas` (the
(r, r) Gram streamed over the factor table with the hand-rolled hi/lo
split tiers).  The elimination sequence replicates
``_chol_solve_unrolled`` operation-for-operation (lower triangle only —
the reference's masked upper-triangle work feeds only zeroed columns),
so results are bit-identical to the XLA path on the same backend.

Caller contract: rank r <= 32 (the unrolled-solve bound shared with
masked_solve), batch pads to the 256-column tile with n_reg = 0 rows
(masked to zero factors, sliced off by the wrapper).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas import _dbuf
from oap_mllib_tpu.ops.pallas._tiers import (
    LANE,
    check_mode,
    kernel_launch,
    note_emitted,
    pad_to,
    tiered_dot,
)
from oap_mllib_tpu.utils import progcache

_BATCH = 256  # solve batch tile (lane axis)
_GRAM_BLOCK_ROWS = 512
MAX_RANK = 32  # the unrolled-solve bound (als_ops.masked_solve contract)
# double-buffered solve keeps the whole (r, n) factor sheet VMEM-resident;
# past this element budget the walk falls back to the grid pipeline
_DBUF_SOLVE_BUDGET = 1 << 22


def _solve_tile(m, gram, reg, r: int, use_gram: bool):
    """One batch tile's assemble + unrolled Cholesky + substitutions on
    a resident (r*r + r + 1, B) moment sheet.  Shared by the grid
    kernel, the double-buffered walk, and the schedule-identical XLA
    fallback.  Returns the r masked (1, B) factor rows."""
    w_a = r * r  # flat-sheet row offsets: A row-major, then b, then n_reg
    nr = m[w_a + r : w_a + r + 1, :]  # n_reg (1, B)

    # assemble the lower triangle of A: moments + ALS-WR reg
    # (reg * n_reg on the diagonal) + the implicit Gram term, in the
    # exact addition order of als_ops.regularized_solve
    # (a + reg*n*I first, gram added second) so bits match
    at = {}
    for i in range(r):
        for j in range(i + 1):
            a_ij = m[i * r + j : i * r + j + 1, :]
            if i == j:
                a_ij = a_ij + reg * nr
            if use_gram:
                a_ij = gram[i, j] + a_ij
            at[(i, j)] = a_ij

    # unrolled batch-wide Cholesky via rank-1 Schur downdates —
    # operation-for-operation the sequence of
    # als_ops._chol_solve_unrolled, lower triangle only (the
    # reference's masked upper-triangle entries feed only zeroed
    # columns and never change a result bit)
    cols = {}
    for j in range(r):
        d = jnp.sqrt(at[(j, j)])
        for i in range(j, r):
            cols[(i, j)] = at[(i, j)] / d
        for i1 in range(j + 1, r):
            for i2 in range(j + 1, i1 + 1):
                at[(i1, i2)] = at[(i1, i2)] - cols[(i1, j)] * cols[(i2, j)]

    rhs = [m[w_a + j : w_a + j + 1, :] for j in range(r)]
    z = [None] * r
    for j in range(r):  # forward: L z = b
        z[j] = rhs[j] / cols[(j, j)]
        for i in range(j + 1, r):
            rhs[i] = rhs[i] - cols[(i, j)] * z[j]
    w = [None] * r
    for j in reversed(range(r)):  # back: L^T w = z
        acc = z[j]
        for k in range(j + 1, r):
            acc = acc - cols[(k, j)] * w[k]
        w[j] = acc / cols[(j, j)]

    # empty rows (n_reg == 0) get zero factors
    return [jnp.where(nr > 0, jnp.nan_to_num(w[j]), 0.0) for j in range(r)]


def _make_solve_kernel(r: int, use_gram: bool):
    def _kernel(m_ref, gram_ref, reg_ref, out_ref):
        rows = _solve_tile(
            m_ref[:], gram_ref[:], reg_ref[0, 0], r, use_gram
        )
        for j in range(r):
            out_ref[j : j + 1, :] = rows[j]

    return _kernel


def _pallas_solve(m_t, gram, reg, r, use_gram, interpret, batch=_BATCH):
    """Raw pallas_call on the pre-packed (W, B) moment sheet (traced
    inside the jitted wrappers — no jit of its own)."""
    w_rows, n = m_t.shape
    grid = (n // batch,)
    out = pl.pallas_call(
        _make_solve_kernel(r, use_gram),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (w_rows, batch), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((r, r), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (r, batch), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.float32),
        interpret=interpret,
    )(m_t, gram, reg)
    return out


# -- double-buffered solve walk (explicit DMA overlap; ROADMAP item 4) -------


def _make_dbuf_solve_kernel(r, use_gram, batch, depth, num_tiles):
    def _kernel(m_hbm, gram_ref, reg_ref, out_ref, mbuf, msem):
        """Column walk over the HBM moment sheet: the next batch tile
        streams into the rotation buffer while the current tile's
        assemble + Cholesky runs; factor rows write straight into the
        VMEM-resident (r, n) output."""
        reg = reg_ref[0, 0]
        gram = gram_ref[:]

        def body(t, views):
            (m,) = views  # (w_rows, batch)
            rows = _solve_tile(m, gram, reg, r, use_gram)
            for j in range(r):
                out_ref[j : j + 1, pl.ds(t * batch, batch)] = rows[j]

        _dbuf.tile_walk(
            [m_hbm], [mbuf], [msem], batch, num_tiles, depth, body,
            axes=(1,),
        )

    return _kernel


def _pallas_solve_dbuf(m_t, gram, reg, r, use_gram, interpret, batch,
                       depth):
    w_rows, n = m_t.shape
    num_tiles = n // batch
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            has_side_effects=True
        )
    return pl.pallas_call(
        _make_dbuf_solve_kernel(r, use_gram, batch, depth, num_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.float32),
        scratch_shapes=_dbuf.rotation_scratch(depth, [(w_rows, batch)]),
        interpret=interpret,
        **kwargs,
    )(m_t, gram, reg)


def _xla_solve_walk(m_t, gram, reg, r, use_gram, batch):
    """Schedule-identical XLA fallback: scan the same batch tiles through
    the same ``_solve_tile`` (tiles are independent, so order is for
    structure, not numerics)."""
    w_rows, n = m_t.shape
    num_tiles = n // batch
    tiles = jnp.moveaxis(m_t.reshape(w_rows, num_tiles, batch), 1, 0)

    def step(_, m):
        rows = _solve_tile(m, gram, reg, r, use_gram)
        return 0, jnp.concatenate(rows, axis=0)  # (r, batch)

    _, out = jax.lax.scan(step, 0, tiles)  # (num_tiles, r, batch)
    return jnp.moveaxis(out, 0, 1).reshape(r, n)


def _solve_any(m_t, gram, reg, r, use_gram, interpret, batch, depth):
    """Kernel-variant dispatch on the packed sheet: grid pipeline at
    depth < 2 (or when the walk's VMEM-resident (r, n) output exceeds
    its budget), double-buffered walk otherwise."""
    if depth >= 2 and m_t.shape[1] * r <= _DBUF_SOLVE_BUDGET:
        if interpret or jax.default_backend() == "tpu":
            return _pallas_solve_dbuf(
                m_t, gram, reg, r, use_gram, interpret, batch, depth
            )
        return _xla_solve_walk(m_t, gram, reg[0, 0], r, use_gram, batch)
    return _pallas_solve(m_t, gram, reg, r, use_gram, interpret, batch)


def solve_traced(a, b, n_reg, reg, gram=None, interpret=False, batch=None,
                 depth=None):
    """Traced pack + kernel + slice (no jit of its own) — the seam the
    ALS runners' jitted bodies call through (als_ops.regularized_solve
    with kernel="pallas").  Returns (n_dst, r) factors, f32.
    ``batch``/``depth`` carry tuned geometry (depth >= 2 = the
    double-buffered column walk)."""
    note_emitted("als.solve")
    batch = _BATCH if batch is None else int(batch)
    depth = 0 if depth is None else int(depth)
    if depth >= 2:
        _dbuf.check_depth(depth)
    n, r = b.shape
    if r > MAX_RANK:
        raise ValueError(
            f"pallas ALS solve supports rank <= {MAX_RANK}, got {r} "
            "(the unrolled-solve bound; larger ranks use the XLA path)"
        )
    n_pad = pad_to(max(n, batch), batch)
    # flat moment sheet: A row-major | b | n_reg, batch on lanes —
    # padding columns carry n_reg 0 so they solve to masked zeros
    m = jnp.concatenate(
        [
            a.astype(jnp.float32).reshape(n, r * r),
            b.astype(jnp.float32),
            n_reg.astype(jnp.float32)[:, None],
        ],
        axis=1,
    )
    m_t = jnp.zeros((r * r + r + 1, n_pad), jnp.float32).at[:, :n].set(m.T)
    use_gram = gram is not None
    g = (
        gram.astype(jnp.float32)
        if use_gram
        else jnp.zeros((r, r), jnp.float32)
    )
    reg_arr = jnp.full((1, 1), reg, jnp.float32)
    out = _solve_any(m_t, g, reg_arr, r, use_gram, interpret, batch, depth)
    return out[:, :n].T


@functools.partial(
    jax.jit, static_argnames=("use_gram", "interpret", "batch", "depth")
)
def _solve_jit(a, b, n_reg, reg, gram, use_gram, interpret, batch=None,
               depth=None):
    return solve_traced(
        a, b, n_reg, reg, gram if use_gram else None, interpret, batch,
        depth,
    )


def solve_normal_eq_pallas(
    a: jax.Array,
    b: jax.Array,
    n_reg: jax.Array,
    reg,
    gram: jax.Array = None,
    mode: str = "highest",
    interpret: bool = False,
    batch: int = None,
    depth: int = None,
) -> jax.Array:
    """Standalone entry over :func:`solve_traced`: one registry-tracked
    jitted program (pack + kernel + slice).  ``mode`` is validated for
    API uniformity with the other kernels but the solve always runs f32
    (module docstring: the package pins solves full-precision under
    every policy)."""
    check_mode(mode)
    use_gram = gram is not None
    progcache.note(
        "als.pallas_solve",
        (progcache.backend_fingerprint(),
         progcache.array_key(a, b), use_gram, interpret, batch, depth),
    )
    with kernel_launch("als.solve"):
        return _solve_jit(
            a, b, n_reg, jnp.asarray(reg, jnp.float32),
            gram if use_gram else jnp.zeros((b.shape[1],) * 2, jnp.float32),
            use_gram, interpret, batch, depth,
        )


# -- streamed factor Gram ----------------------------------------------------


def _make_gram_kernel(mode):
    def _kernel(f_ref, gram_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            gram_ref[:] = jnp.zeros_like(gram_ref)

        f = f_ref[:]  # (bn, r_pad)
        gram_ref[:] += tiered_dot(f, f, (((0,), (0,)), ((), ())), mode)

    return _kernel


def _pallas_factor_gram(f_p, mode, interpret, block_rows=_GRAM_BLOCK_ROWS):
    n, r_pad = f_p.shape
    grid = (n // block_rows,)
    return pl.pallas_call(
        _make_gram_kernel(mode),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (block_rows, r_pad), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (r_pad, r_pad), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((r_pad, r_pad), jnp.float32),
        interpret=interpret,
    )(f_p)


def _make_dbuf_gram_kernel(mode, tile_rows, depth, num_tiles):
    def _kernel(f_hbm, gram_ref, fbuf, fsem):
        gram_ref[:] = jnp.zeros_like(gram_ref)

        def body(t, views):
            (f,) = views
            gram_ref[:] += tiered_dot(f, f, (((0,), (0,)), ((), ())), mode)

        _dbuf.tile_walk(
            [f_hbm], [fbuf], [fsem], tile_rows, num_tiles, depth, body
        )

    return _kernel


def _pallas_factor_gram_dbuf(f_p, mode, interpret, tile_rows, depth):
    n, r_pad = f_p.shape
    num_tiles = n // tile_rows
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            has_side_effects=True
        )
    return pl.pallas_call(
        _make_dbuf_gram_kernel(mode, tile_rows, depth, num_tiles),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_pad, r_pad), jnp.float32),
        scratch_shapes=_dbuf.rotation_scratch(depth, [(tile_rows, r_pad)]),
        interpret=interpret,
        **kwargs,
    )(f_p)


def _xla_gram_walk(f_p, mode, tile_rows):
    """Schedule-identical XLA fallback for the Gram walk."""
    n, r_pad = f_p.shape
    num_tiles = n // tile_rows
    tiles = f_p.reshape(num_tiles, tile_rows, r_pad)

    def step(gram, f):
        return gram + tiered_dot(f, f, (((0,), (0,)), ((), ())), mode), None

    gram, _ = jax.lax.scan(
        step, jnp.zeros((r_pad, r_pad), jnp.float32), tiles
    )
    return gram


def factor_gram_traced(factors, mode="highest", interpret=False,
                       tile_rows=None, depth=None):
    """Traced pad + kernel + slice: the (r, r) factor Gram ``F^T F``
    streamed over the factor table in row tiles — the implicit-feedback
    Gram term of the ALS half-update, with the shared hi/lo split tiers.
    Production call sites pin mode="highest" (solves and the Grams that
    condition them never run reduced — utils/precision.py contract); the
    split tiers exist for parity tests and shapes where a caller
    explicitly prices them.  ``tile_rows``/``depth`` carry tuned
    geometry (depth >= 2 = the double-buffered walk)."""
    note_emitted("als.factor_gram")
    tile_rows = _GRAM_BLOCK_ROWS if tile_rows is None else int(tile_rows)
    depth = 0 if depth is None else int(depth)
    if depth >= 2:
        _dbuf.check_depth(depth)
    n, r = factors.shape
    n_pad = pad_to(max(n, tile_rows), tile_rows)
    r_pad = pad_to(r, LANE)
    f_p = jnp.zeros((n_pad, r_pad), jnp.float32).at[:n, :r].set(
        factors.astype(jnp.float32)
    )
    if depth >= 2:
        if interpret or jax.default_backend() == "tpu":
            gram = _pallas_factor_gram_dbuf(
                f_p, mode, interpret, tile_rows, depth
            )
        else:
            gram = _xla_gram_walk(f_p, mode, tile_rows)
    else:
        gram = _pallas_factor_gram(f_p, mode, interpret, tile_rows)
    return gram[:r, :r]


@functools.partial(
    jax.jit, static_argnames=("mode", "interpret", "tile_rows", "depth")
)
def _factor_gram_jit(factors, mode, interpret, tile_rows=None, depth=None):
    return factor_gram_traced(factors, mode, interpret, tile_rows, depth)


def factor_gram_pallas(
    factors: jax.Array, mode: str = "highest", interpret: bool = False,
    tile_rows: int = None, depth: int = None,
) -> jax.Array:
    """Standalone registry-tracked entry over :func:`factor_gram_traced`."""
    mode = check_mode(mode)
    progcache.note(
        "als.pallas_factor_gram",
        (progcache.backend_fingerprint(),
         progcache.array_key(factors), mode, interpret, tile_rows, depth),
    )
    with kernel_launch("als.factor_gram"):
        return _factor_gram_jit(factors, mode, interpret, tile_rows, depth)


def pallas_solve_preferred(r: int) -> bool:
    """Shape rule for als_solve_kernel="auto": the fused assembly+solve
    covers the unrolled-rank regime (r <= 32, Spark's default is 10);
    larger ranks keep the library Cholesky path."""
    return r <= MAX_RANK
