"""Shared plumbing for the Pallas kernel plane: precision tiers, padding
arithmetic, and per-kernel telemetry booking.

Every kernel in ``ops/pallas/`` exposes the same three precision tiers —
Mosaic only lowers ``Precision.HIGHEST``/``DEFAULT`` on the MXU, so the
intermediate "high" tier is hand-rolled from bf16 hi/lo splits (the
``kmeans_kernel`` pattern, generalized here so the PCA Gram and ALS
normal-equation kernels cannot drift from it):

- ``highest``: full-f32 ``Precision.HIGHEST`` dots — the parity tier.
- ``high``: bf16_3x-equivalent — operands split into bf16 hi+lo pairs
  and recombined from the three significant cross passes (hi*hi, hi*lo,
  lo*hi; lo*lo is below f32 resolution), ~1e-5 of full f32 at 3/6 the
  MXU passes.
- ``default``: single-pass all-bf16 with f32 accumulation — the XLA
  default tier's ~1e-3 envelope at its speed.

The compute-precision policy names (utils/precision.py) alias onto the
tiers — ``f32``→highest, ``tf32``→high, ``bf16``→default — so a resolved
policy can be passed straight through (:func:`check_mode`), which is what
lets ``precision.kernel_tier`` price the bf16 policy ON Pallas.

Telemetry: :func:`kernel_launch` books every kernel-wrapper dispatch into
the process metrics registry (``oap_kernel_launches_total{kernel=}`` +
``oap_kernel_dispatch_seconds``) and notes it on the active span, so fits
report which Pallas kernels ran next to their phase walls.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

MODES = ("highest", "high", "default")
# compute-precision policy names (utils/precision.py) accepted as mode
# aliases: the kernels' tiers already ARE the policy's hand-rolled bf16
# splits, so callers resolving a policy can pass its name straight through
MODE_ALIASES = {"f32": "highest", "tf32": "high", "bf16": "default"}

LANE = 128  # TPU minor-axis tile (f32 lane multiple)


def check_mode(mode: str) -> str:
    """Canonicalize a tier: legacy names pass through, policy names map
    via :data:`MODE_ALIASES`, anything else raises (a typo must not
    silently run a different tier)."""
    mode = MODE_ALIASES.get(mode, mode)
    if mode not in MODES:
        raise ValueError(
            f"mode must be one of {MODES} (or a policy alias "
            f"{tuple(MODE_ALIASES)}), got {mode!r}"
        )
    return mode


def pad_to(x: int, m: int) -> int:
    """Round ``x`` up to a multiple of ``m``."""
    return ((x + m - 1) // m) * m


def split_bf16(a):
    """f32 -> (hi, lo) bf16 pair with a ~= hi + lo."""
    hi = a.astype(jnp.bfloat16)
    lo = (a - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def dot_f32(a, b, dn):
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def dot_bf16(a, b, dn):
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn, preferred_element_type=jnp.float32
    )


def tiered_dot(a, b, dn, mode: str):
    """``dot_general(a, b)`` at a kernel tier, f32 accumulation always.

    ``high`` is the hand-rolled bf16_3x: both operands hi/lo-split, the
    lo*lo pass dropped (it is below f32 resolution for operands whose
    magnitudes the hi parts carry).  Operand order inside the sum runs
    hi*hi + hi*lo + lo*hi so every kernel using this helper shares one
    summation order.
    """
    if mode == "highest":
        return dot_f32(a, b, dn)
    if mode == "default":
        return dot_bf16(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16), dn)
    a_hi, a_lo = split_bf16(a)
    b_hi, b_lo = split_bf16(b)
    return (
        dot_bf16(a_hi, b_hi, dn)
        + dot_bf16(a_hi, b_lo, dn)
        + dot_bf16(a_lo, b_hi, dn)
    )


def note_emitted(kernel: str) -> None:
    """Trace-time census of Pallas kernels emitted INTO compiled programs
    (the collective facade's ``oap_collective_emitted_total`` pattern):
    kernels traced inside an outer jit/scan body cannot book per-dispatch
    telemetry, so they count once per program build instead."""
    from oap_mllib_tpu.telemetry import metrics as _tm

    _tm.counter(
        "oap_kernel_emitted_total", {"kernel": kernel},
        help="Pallas kernels emitted into compiled programs "
             "(trace-time census, not a dispatch count)",
    ).inc()


@contextlib.contextmanager
def kernel_launch(kernel: str):
    """Book one Pallas-kernel wrapper dispatch: invocation count + wall
    into the metrics registry, plus a note on the active span (the same
    pattern as the collective facade's ``_instrumented``).  The wall is
    dispatch time — trace + compile on a first shape, async dispatch
    after — not device occupancy (the profiler trace layer owns that)."""
    from oap_mllib_tpu.telemetry import metrics as _tm
    from oap_mllib_tpu.telemetry.spans import current_span
    from oap_mllib_tpu.utils.timing import tick

    elapsed = tick()
    try:
        yield
    finally:
        dt = elapsed()
        lab = {"kernel": kernel}
        _tm.counter(
            "oap_kernel_launches_total", lab,
            help="Pallas kernel wrapper dispatches by kernel",
        ).inc()
        _tm.histogram(
            "oap_kernel_dispatch_seconds", lab,
            help="Per-dispatch wall of Pallas kernel wrappers "
                 "(compile included on first shape)",
        ).observe(dt)
        sp = current_span()
        if sp is not None:
            sp.attrs.setdefault("kernels", {})
            sp.attrs["kernels"][kernel] = (
                sp.attrs["kernels"].get(kernel, 0) + 1
            )
