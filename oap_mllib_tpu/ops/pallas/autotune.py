"""Persistent per-(backend, shape-bucket) kernel-geometry autotuner.

The double-buffered tile walks (ops/pallas/_dbuf) and the segmented ring
epilogue (ops/pallas/ring_reduce) expose geometry knobs — tile rows,
VMEM rotation depth, solve batch, ring segment count — whose best values
depend on the backend and the problem's shape regime, not on the exact
operand sizes.  This module owns the resolution of those knobs
(ROADMAP item 4; the communication-avoiding formulation of
arXiv:2601.17136 leaves exactly these free parameters):

- :func:`shape_bucket` quantizes kernel-relevant dims to the next power
  of two, so one tuned entry covers a whole shape regime and a SECOND
  fit anywhere on the same backend/bucket launches pre-tuned with zero
  sweeps — the row count ``n`` deliberately never enters a bucket.
- :func:`resolve` maps ``(kernel, bucket, tier)`` to a geometry dict,
  consulting (in order) the ``Config.tuning`` mode, the in-process
  cache, and the persistent JSON cache under ``Config.tuning_cache_dir``
  (entries named by ``progcache.key_digest`` over the full key, which
  includes ``progcache.backend_fingerprint()`` — a cache directory
  shared across heterogeneous backends never cross-pollinates).
- A cache miss in mode ``"on"`` runs :func:`_sweep`: a deterministic
  measured best-of-N over the per-kernel candidate grid, on operands
  from a fixed-seed generator.  Wall-clock noise cannot corrupt shared
  state across processes because the sweep's winner is what's
  persisted and every LATER process resolves from the cache — the
  determinism contract is cache-mediated, not timing-mediated.
- Multi-process worlds must resolve rank-uniformly (a rank-local sweep
  choosing different geometry per rank would diverge collective
  programs — the R16 hazard).  Plain :func:`resolve` therefore refuses
  to sweep when ``jax.process_count() > 1`` (decision
  ``"default-multiproc"``); :func:`resolve_world` is the multi-process
  entry: rank 0 resolves (sweeping if so configured) and the winning
  geometry rides the sanctioned host-collective seam
  (ops/stream_ops._allgather_host) to every rank.

Every resolution is recorded: ``oap_tuning_{hits,misses,sweeps}_total``
counters, a ``tuning`` node on the active span (sweep wall), and the
:func:`mark`/:func:`delta` window that models attach to fit summaries
as ``summary["tuning"]``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from oap_mllib_tpu.utils import locktrace, progcache

log = logging.getLogger("oap_mllib_tpu")

MODES = ("auto", "on", "off")  # plus "pin:<json>"

# knob vocabulary per kernel — pins outside this raise, like any typo
KNOBS = {
    "kmeans": ("tile_rows", "depth"),
    "pca": ("tile_rows", "depth"),
    "als_solve": ("batch", "depth"),
    "als_gram": ("tile_rows", "depth"),
    "ring": ("segments",),
}

# the hand-picked constants every kernel shipped with — mode "off", and
# the no-cache fallback of mode "auto"
DEFAULTS = {
    "kmeans": {"tile_rows": 512, "depth": 2},
    "pca": {"tile_rows": 512, "depth": 2},
    "als_solve": {"batch": 256, "depth": 2},
    "als_gram": {"tile_rows": 512, "depth": 2},
    "ring": {"segments": 1},
}

# sweep grids: small on purpose — geometry response surfaces are flat
# away from the VMEM/occupancy cliffs, so a coarse grid finds the
# plateau and the bucket quantization amortizes the sweep forever
CANDIDATES = {
    "kmeans": [
        {"tile_rows": t, "depth": dp}
        for t in (256, 512, 1024) for dp in (2, 3)
    ],
    "pca": [
        {"tile_rows": t, "depth": dp}
        for t in (256, 512, 1024) for dp in (2, 3)
    ],
    "als_solve": [
        {"batch": b, "depth": dp} for b in (128, 256, 512) for dp in (2, 3)
    ],
    "als_gram": [
        {"tile_rows": t, "depth": dp}
        for t in (256, 512, 1024) for dp in (2, 3)
    ],
    "ring": [{"segments": s} for s in (1, 2, 4)],
}

_BEST_OF = 3  # min-of-N per candidate (min rejects scheduler noise)

_LOCK = locktrace.TrackedLock("autotune.cache")
_MEM: Dict[tuple, Dict[str, int]] = {}
_DECISIONS: List[Dict[str, Any]] = []  # append-only; mark()/delta() window


# -- mode / pins -------------------------------------------------------------


def parse_mode(spec: str) -> Tuple[str, Optional[Dict[str, Dict[str, int]]]]:
    """Validate ``Config.tuning`` into ``(mode, pins)``.

    ``pins`` is the per-kernel geometry dict of ``pin:<json>`` (None for
    the plain modes).  Unknown modes, malformed JSON, unknown kernels or
    knob names, and non-integer values all raise ValueError — a typo
    silently tuning nothing is the failure mode this guards."""
    spec = str(spec)
    if spec in MODES:
        return spec, None
    if spec.startswith("pin:"):
        try:
            pins = json.loads(spec[4:])
        except json.JSONDecodeError as e:
            raise ValueError(f"Config.tuning pin payload is not JSON: {e}")
        if not isinstance(pins, dict):
            raise ValueError(
                "Config.tuning pin payload must be a JSON object of "
                f"{{kernel: {{knob: int}}}}, got {type(pins).__name__}"
            )
        for kern, geo in pins.items():
            if kern not in KNOBS:
                raise ValueError(
                    f"Config.tuning pins unknown kernel {kern!r} "
                    f"(known: {sorted(KNOBS)})"
                )
            if not isinstance(geo, dict):
                raise ValueError(
                    f"Config.tuning pin for {kern!r} must be an object, "
                    f"got {type(geo).__name__}"
                )
            for knob, val in geo.items():
                if knob not in KNOBS[kern]:
                    raise ValueError(
                        f"Config.tuning pins unknown knob {knob!r} for "
                        f"kernel {kern!r} (known: {KNOBS[kern]})"
                    )
                if not isinstance(val, int) or isinstance(val, bool):
                    raise ValueError(
                        f"Config.tuning pin {kern}.{knob} must be an "
                        f"integer, got {val!r}"
                    )
        return "pin", pins
    raise ValueError(
        f"Config.tuning must be one of {MODES} or 'pin:<json>', "
        f"got {spec!r}"
    )


def _mode() -> Tuple[str, Optional[Dict[str, Dict[str, int]]]]:
    from oap_mllib_tpu.config import get_config

    return parse_mode(get_config().tuning)


# -- shape buckets -----------------------------------------------------------


def _pow2(v: int) -> int:
    v = max(1, int(v))
    return 1 << (v - 1).bit_length()


def shape_bucket(*dims: int) -> Tuple[int, ...]:
    """Quantize kernel-relevant dims (k, d, r, world, cols — NEVER n) to
    the next power of two: the bucket identity under which tuned
    geometry is cached and reused."""
    return tuple(_pow2(d) for d in dims)


def cache_key(kernel: str, bucket: Tuple[int, ...], tier: str) -> tuple:
    return (
        progcache.backend_fingerprint(), kernel, tuple(int(b) for b in bucket),
        str(tier),
    )


# -- persistent cache --------------------------------------------------------


def _disk_path(cache_dir: str, key: tuple) -> str:
    return os.path.join(cache_dir, f"tune-{progcache.key_digest(key)}.json")


def _valid_geometry(kernel: str, geo: Any) -> bool:
    return (
        isinstance(geo, dict)
        and set(geo) == set(KNOBS[kernel])
        and all(
            isinstance(v, int) and not isinstance(v, bool)
            for v in geo.values()
        )
    )


def _disk_load(cache_dir: str, kernel: str, key: tuple):
    """Load one persisted entry; a corrupt or mismatched file logs a
    warning and reads as a miss (fresh sweep in mode "on") — the cache
    must never be able to crash a fit."""
    path = _disk_path(cache_dir, key)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            entry = json.load(f)
        geo = entry["geometry"]
        if entry.get("key") != repr(key) or not _valid_geometry(kernel, geo):
            raise ValueError("stale or malformed entry")
        return {k: int(v) for k, v in geo.items()}
    except Exception as e:  # corrupt file, bad JSON, wrong schema, IO
        log.warning(
            "tuning cache entry %s unreadable (%s); ignoring it and "
            "re-resolving fresh", path, e,
        )
        return None


def _disk_store(cache_dir: str, kernel: str, key: tuple,
                geometry: Dict[str, int]) -> None:
    """Best-effort atomic persist (tmp + rename); an unwritable cache
    dir degrades to in-process memory with a warning."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = _disk_path(cache_dir, key)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(
                {"key": repr(key), "kernel": kernel, "geometry": geometry},
                f, indent=1, sort_keys=True,
            )
        os.replace(tmp, path)
    except OSError as e:
        log.warning("tuning cache dir %s unwritable (%s); tuned geometry "
                    "kept in-process only", cache_dir, e)


def clear() -> None:
    """Drop the in-process tuning cache and decision log (tests)."""
    with _LOCK:
        _MEM.clear()
        del _DECISIONS[:]


# -- telemetry ---------------------------------------------------------------


def _count(event: str, kernel: str) -> None:
    from oap_mllib_tpu.telemetry import metrics as _tm

    helps = {
        "hits": "tuning-cache geometry hits (memory or disk) by kernel",
        "misses": "tuning-cache misses by kernel (resolved to default, "
                  "pin, or a fresh sweep per Config.tuning)",
        "sweeps": "autotune candidate sweeps executed by kernel",
    }
    _tm.counter(
        f"oap_tuning_{event}_total", {"kernel": kernel}, help=helps[event]
    ).inc()


def _record(kernel: str, bucket, tier: str, decision: str,
            geometry: Dict[str, int]) -> Dict[str, int]:
    with _LOCK:
        _DECISIONS.append({
            "kernel": kernel,
            "bucket": list(bucket),
            "tier": tier,
            "decision": decision,
            "geometry": dict(geometry),
        })
    if decision in ("hit",):
        _count("hits", kernel)
    elif decision in ("default", "default-multiproc", "sweep"):
        _count("misses", kernel)
    sp = _span()
    if sp is not None:
        node = sp.node("tuning")
        node.attrs.setdefault("decisions", []).append(
            f"{kernel}:{decision}"
        )
    return geometry


def _span():
    from oap_mllib_tpu.telemetry.spans import current_span

    return current_span()


def mark() -> int:
    """Snapshot the decision log at fit entry (pairs with :func:`delta`,
    the ``progcache.stats()``/``delta`` pattern)."""
    with _LOCK:
        return len(_DECISIONS)


def delta(since: int) -> Dict[str, Any]:
    """Per-fit tuning activity since :func:`mark`: the decision list
    plus rollup counts — what models attach as ``summary["tuning"]``."""
    from oap_mllib_tpu.config import get_config

    with _LOCK:
        window = [dict(d) for d in _DECISIONS[since:]]
    return {
        "mode": get_config().tuning,
        "decisions": window,
        "sweeps": sum(1 for d in window if d["decision"] == "sweep"),
        "hits": sum(1 for d in window if d["decision"] == "hit"),
        "misses": sum(
            1 for d in window
            if d["decision"] in ("default", "default-multiproc", "sweep")
        ),
    }


# -- resolution --------------------------------------------------------------


def resolve(kernel: str, bucket, tier: str = "f32",
            interpret: bool = False) -> Dict[str, int]:
    """Resolve tuned geometry for one kernel launch site.

    Decision ladder (each recorded in the fit summary / metrics):
    ``off`` → hand-picked defaults, cache ignored; ``pin`` → defaults
    overlaid with the pinned knobs, verbatim; cache ``hit`` (memory,
    then ``Config.tuning_cache_dir``) → the tuned winner, zero sweeps;
    miss in ``auto`` → ``default`` (never sweeps — zero overhead);
    miss in ``on`` → ``sweep`` once, persist, then it's a hit
    everywhere; miss in ``on`` under a multi-process world →
    ``default-multiproc`` (rank-local sweeps are refused — see
    :func:`resolve_world`)."""
    if kernel not in KNOBS:
        raise ValueError(f"unknown tunable kernel {kernel!r}")
    bucket = tuple(int(b) for b in bucket)
    mode, pins = _mode()
    if mode == "off":
        return _record(kernel, bucket, tier, "off", dict(DEFAULTS[kernel]))
    if mode == "pin" and kernel in (pins or {}):
        geo = dict(DEFAULTS[kernel])
        geo.update(pins[kernel])
        return _record(kernel, bucket, tier, "pin", geo)

    key = cache_key(kernel, bucket, tier)
    with _LOCK:
        cached = _MEM.get(key)
    if cached is not None:
        return _record(kernel, bucket, tier, "hit", dict(cached))

    from oap_mllib_tpu.config import get_config

    cache_dir = get_config().tuning_cache_dir
    if cache_dir:
        loaded = _disk_load(cache_dir, kernel, key)
        if loaded is not None:
            with _LOCK:
                _MEM[key] = dict(loaded)
            return _record(kernel, bucket, tier, "hit", loaded)

    if mode != "on":
        return _record(
            kernel, bucket, tier, "default", dict(DEFAULTS[kernel])
        )
    import jax

    if jax.process_count() > 1:
        # rank-local sweeps could pick per-rank geometry and diverge
        # collective programs (R16); resolve_world is the sanctioned way
        return _record(
            kernel, bucket, tier, "default-multiproc",
            dict(DEFAULTS[kernel]),
        )
    geometry = _sweep(kernel, bucket, tier, interpret)
    with _LOCK:
        _MEM[key] = dict(geometry)
    if cache_dir:
        _disk_store(cache_dir, kernel, key, geometry)
    return _record(kernel, bucket, tier, "sweep", geometry)


def resolve_world(kernel: str, bucket, tier: str = "f32",
                  interpret: bool = False) -> Dict[str, int]:
    """Rank-uniform resolution for multi-process worlds: rank 0 resolves
    (sweeping on a miss if ``tuning="on"``) and broadcasts the winning
    geometry over the sanctioned host-collective seam, so every rank
    traces the identical program geometry (R16).  Single-process this is
    exactly :func:`resolve`."""
    import jax

    if jax.process_count() < 2:
        return resolve(kernel, bucket, tier, interpret)
    knobs = KNOBS[kernel]
    if jax.process_index() == 0:
        mode, pins = _mode()
        if mode == "on":
            # rank 0 may sweep: temporarily lift the multi-process
            # refusal by resolving through the single-process ladder
            geo = _resolve_rank0(kernel, bucket, tier, interpret)
        else:
            geo = resolve(kernel, bucket, tier, interpret)
        frame = np.asarray([float(geo[k]) for k in knobs], np.float32)
    else:
        frame = np.zeros((len(knobs),), np.float32)
    from oap_mllib_tpu.ops import stream_ops

    (gathered,) = stream_ops._allgather_host([frame])
    geo = {k: int(gathered[0, i]) for i, k in enumerate(knobs)}
    if jax.process_index() != 0:
        _record(kernel, tuple(int(b) for b in bucket), tier, "hit", geo)
    return geo


def _resolve_rank0(kernel, bucket, tier, interpret) -> Dict[str, int]:
    """Rank 0's leg of resolve_world in mode "on": same ladder as
    :func:`resolve` but sweeping despite the multi-process world — the
    result is broadcast, so uniformity is preserved by construction."""
    bucket = tuple(int(b) for b in bucket)
    key = cache_key(kernel, bucket, tier)
    with _LOCK:
        cached = _MEM.get(key)
    if cached is not None:
        return _record(kernel, bucket, tier, "hit", dict(cached))
    from oap_mllib_tpu.config import get_config

    cache_dir = get_config().tuning_cache_dir
    if cache_dir:
        loaded = _disk_load(cache_dir, kernel, key)
        if loaded is not None:
            with _LOCK:
                _MEM[key] = dict(loaded)
            return _record(kernel, bucket, tier, "hit", loaded)
    geometry = _sweep(kernel, bucket, tier, interpret)
    with _LOCK:
        _MEM[key] = dict(geometry)
    if cache_dir:
        _disk_store(cache_dir, kernel, key, geometry)
    return _record(kernel, bucket, tier, "sweep", geometry)


# -- the sweep ---------------------------------------------------------------


def _bench_operands(kernel: str, bucket, rng) -> tuple:
    """Fixed-seed operands sized for the bucket, capped so a sweep stays
    cheap (rows 2048, dims 256 — beyond the caps the geometry response
    is governed by the same tile arithmetic)."""
    if kernel == "kmeans":
        k, d = (min(int(bucket[0]), 256), min(int(bucket[1]), 256))
        x = rng.standard_normal((2048, d)).astype(np.float32)
        w = np.ones((2048,), np.float32)
        c = rng.standard_normal((max(k, 2), d)).astype(np.float32)
        return (x, w, c)
    if kernel == "pca":
        d = min(int(bucket[0]), 256)
        x = rng.standard_normal((2048, d)).astype(np.float32)
        mask = np.ones((2048,), np.float32)
        return (x, mask)
    if kernel == "als_solve":
        r = min(int(bucket[0]), 32)
        n = 1024
        a = rng.standard_normal((n, r, r)).astype(np.float32)
        a = a @ a.transpose(0, 2, 1) + 4.0 * np.eye(r, dtype=np.float32)
        b = rng.standard_normal((n, r)).astype(np.float32)
        n_reg = np.full((n,), 3.0, np.float32)
        return (a, b, n_reg)
    if kernel == "als_gram":
        r = min(int(bucket[0]), 32)
        return (rng.standard_normal((2048, r)).astype(np.float32),)
    raise ValueError(f"no sweep bench for kernel {kernel!r}")


def _measure(kernel: str, operands, geometry: Dict[str, int], tier: str,
             interpret: bool) -> float:
    """One candidate's cost: min wall of ``_BEST_OF`` timed launches
    after a warm-up call that absorbs trace + compile."""
    import jax

    from oap_mllib_tpu.utils.timing import tick

    def launch():
        if kernel == "kmeans":
            from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
                lloyd_accumulate_walk,
            )

            x, w, c = operands
            return lloyd_accumulate_walk(
                x, w, c, mode=tier, interpret=interpret,
                tile_rows=geometry["tile_rows"], depth=geometry["depth"],
            )
        if kernel == "pca":
            from oap_mllib_tpu.ops.pallas.pca_kernel import (
                pca_moments_pallas,
            )

            x, mask = operands
            return pca_moments_pallas(
                x, mask, mode=tier, interpret=interpret,
                tile_rows=geometry["tile_rows"], depth=geometry["depth"],
            )
        if kernel == "als_solve":
            from oap_mllib_tpu.ops.pallas.als_kernel import (
                solve_normal_eq_pallas,
            )

            a, b, n_reg = operands
            return solve_normal_eq_pallas(
                a, b, n_reg, 0.1, interpret=interpret,
                batch=geometry["batch"], depth=geometry["depth"],
            )
        if kernel == "als_gram":
            from oap_mllib_tpu.ops.pallas.als_kernel import (
                factor_gram_pallas,
            )

            (factors,) = operands
            return factor_gram_pallas(
                factors, mode=tier, interpret=interpret,
                tile_rows=geometry["tile_rows"], depth=geometry["depth"],
            )
        raise ValueError(kernel)

    jax.block_until_ready(launch())  # warm-up: trace + compile
    best = float("inf")
    for _ in range(_BEST_OF):
        elapsed = tick()
        jax.block_until_ready(launch())
        best = min(best, elapsed())
    return best


def _sweep(kernel: str, bucket, tier: str,
           interpret: bool) -> Dict[str, int]:
    """Measured best-of-N over the candidate grid.  Deterministic
    operands (fixed seed per (kernel, bucket)); ties break toward the
    earlier candidate, so the grid order is part of the contract.  The
    whole sweep's wall books under the active span's ``tuning`` node.

    ``ring`` has no single-device bench (its cost is the inter-device
    schedule, which a local loopback cannot rank honestly) — it resolves
    to its default geometry here, counted as a sweep so the caching
    contract stays uniform."""
    from oap_mllib_tpu.utils.timing import tick

    _count("sweeps", kernel)
    elapsed = tick()
    if kernel == "ring":
        best = dict(DEFAULTS["ring"])
        results = []
    else:
        # process-stable seed (builtin hash is salted per interpreter)
        seed = int(
            progcache.key_digest((kernel,) + tuple(bucket))[:8], 16
        )
        rng = np.random.default_rng(seed)
        operands = _bench_operands(kernel, bucket, rng)
        best, best_t, results = None, float("inf"), []
        for cand in CANDIDATES[kernel]:
            t = _measure(kernel, operands, cand, tier, interpret)
            results.append((cand, t))
            if t < best_t:
                best, best_t = dict(cand), t
    wall = elapsed()
    sp = _span()
    if sp is not None:
        node = sp.node("tuning")
        node.record(wall)
        node.attrs.setdefault("sweeps", []).append({
            "kernel": kernel,
            "bucket": list(bucket),
            "candidates": len(results),
            "winner": dict(best),
        })
    log.info(
        "autotune sweep %s bucket=%s tier=%s -> %s (%d candidates, %.3fs)",
        kernel, list(bucket), tier, best, len(results), wall,
    )
    return best
