"""Fused Lloyd accumulate: distance + argmin + cluster sums in one kernel.

The XLA path (ops/kmeans_ops._accumulate) materializes the (n, k) distance
matrix and an (n, k) one-hot in HBM each iteration — 2*n*k*4 bytes of
traffic on top of reading X.  This kernel streams X once per iteration:
for each row block, it computes the (bn, k) distances in VMEM, reduces
min/argmin on the VPU, forms the block one-hot in VMEM, and accumulates
``one_hot.T @ x`` into the (k, d) sums output, exploiting the TPU grid's
sequential execution for safe read-modify-write accumulation (the pallas
accumulate pattern).  HBM traffic per iteration drops from
O(n*d + 2*n*k) to O(n*d + k*d).

Precision tiers (``mode``) — shared vocabulary in ops/pallas/_tiers.py
(Mosaic only lowers Precision.HIGHEST/DEFAULT, so split tiers are
implemented by hand with bf16 hi/lo splits):

- ``highest``: both matmuls f32 Precision.HIGHEST.  Parity default.
- ``high``: distance cross-term single-pass bf16 (the tier contract —
  kmeans_ops._assign_prec — runs the assignment matmul at bf16: argmin is
  decision-only); cluster sums via an *exact-split* trick: the unweighted
  one-hot is 0/1 — exactly representable in bf16 — so ``one_hot.T @
  (w*x)`` with (w*x) split into bf16 hi+lo needs only TWO bf16 passes
  and is accurate to ~f32, meeting the XLA "high" tier's error envelope.
- ``default``: bf16 assignment + SINGLE-pass bf16 sums — the XLA default
  tier's ~1e-3 error envelope at its speed.

Caller contract (see ``lloyd_accumulate_pallas``): rows padded to the block
size with weight 0; k and d padded to lane multiples (128) by the wrapper —
dummy centers get +inf-like coordinates so no row ever selects them.  The
single-shot path pads INSIDE one jitted program (pad + kernel + slice),
so progcache sees one program per input signature instead of a spray of
eager padding dispatches per call (ISSUE 9 satellite).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas._tiers import (
    LANE,
    check_mode,
    dot_bf16,
    dot_f32,
    kernel_launch,
    pad_to,
    split_bf16,
)
from oap_mllib_tpu.utils import progcache

_BLOCK_ROWS = 512


def _cross_term(x, c, mode):
    """x @ c.T (bn, k) at the requested precision tier.

    "high" and "default" share the single-pass bf16 path: the tier
    definition (kmeans_ops._assign_prec) runs the ASSIGNMENT matmul at
    bf16 for both — argmin is a discrete decision, and the tiers differ
    only in the cluster-sums accuracy (which this kernel's exact-split
    sums exceed in both modes)."""
    dn = (((1,), (1,)), ((), ()))
    if mode == "highest":
        return dot_f32(x, c, dn)
    # high/default: single-pass bf16 — argmin only flips on near-ties
    return dot_bf16(x.astype(jnp.bfloat16), c.astype(jnp.bfloat16), dn)


def _cluster_sums(one_hot01, wx, mode):
    """one_hot.T @ (w*x) (k, d).  one_hot is exactly 0/1 in bf16, so the
    split tiers lose nothing on it; "high" hi/lo-splits wx for ~f32
    accuracy (2 bf16 passes); "default" is single-pass all-bf16 — the
    same error envelope as the XLA default tier (~1e-3)."""
    dn = (((0,), (0,)), ((), ()))
    if mode == "highest":
        return dot_f32(one_hot01, wx, dn)
    oh = one_hot01.astype(jnp.bfloat16)  # exact
    if mode == "default":
        return dot_bf16(oh, wx.astype(jnp.bfloat16), dn)
    wx_hi, wx_lo = split_bf16(wx)
    return dot_bf16(oh, wx_hi, dn) + dot_bf16(oh, wx_lo, dn)


def _make_kernel(mode, need_cost=True):
    def _kernel(x_ref, w_ref, c_ref, sums_ref, counts_ref, cost_ref):
        """One grid step: process a (bn, d) row block against all k centers."""
        # zero accumulators on the first block (sequential TPU grid)
        @pl.when(pl.program_id(0) == 0)
        def _init():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)
            cost_ref[0, 0] = jnp.float32(0.0)

        x = x_ref[:]  # (bn, d)
        w = w_ref[:]  # (bn, 1)
        c = c_ref[:]  # (k, d)
        k = c.shape[0]

        c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, k)
        cross = _cross_term(x, c, mode)  # (bn, k)  <- MXU

        if need_cost:
            # squared distances via the matmul identity (MXU)
            x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # (bn, 1)
            d2 = jnp.maximum(x_sq + c_sq - 2.0 * cross, 0.0)
            assign = jnp.argmin(d2, axis=1)  # (bn,)
            min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (bn, 1)
        else:
            # loop mode: argmin is invariant to the per-row |x|^2 term, so
            # rank on the half-score x.c - c_sq/2 (argMAX) — no d2 assembly,
            # no maximum, no min pass (cost is dead inside the Lloyd loop:
            # the caller recomputes it at "highest" after convergence).
            # NB keep the (bn, k) term on the LEFT of the subtract: with the
            # broadcast (1, k) operand first, Mosaic's lowering allocates a
            # ~32 MB scoped-vmem temp and fails to compile (argmax of
            # cross - c_sq/2 selects the same center, same first-index
            # tie-break as argmin of the negation)
            assign = jnp.argmax(cross - 0.5 * c_sq, axis=1)  # (bn,)

        # unweighted 0/1 one-hot (VPU compare against 2-D iota); weights fold
        # into w*x so the one-hot stays exactly representable in bf16
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1)
        one_hot = jnp.where(col_ids == assign[:, None], 1.0, 0.0)  # (bn, k)

        sums_ref[:] += _cluster_sums(one_hot, w * x, mode)
        if mode == "highest":
            # strict-parity tier: exact f32 VPU reduction
            counts_ref[:] += jnp.sum(one_hot * w, axis=0, keepdims=True)
        else:
            # fast tiers: counts as (1, bn) @ (bn, k) bf16 matmuls with
            # f32 accumulation — the one-hot is exact 0/1 and w rides a
            # hi/lo split, so counts stay ~f32-exact for ANY weights
            # while the two VPU passes over (bn, k) disappear (measured
            # -1.1 ms/iter at 1M x 256 k=1000).  NB bf16 single-pass at
            # this shape compiles where the f32-HIGHEST variant blew
            # Mosaic's scoped vmem (see the assignment note above).
            oh = one_hot.astype(jnp.bfloat16)
            w_hi, w_lo = split_bf16(w)
            dn = (((1,), (0,)), ((), ()))
            counts_ref[:] += dot_bf16(w_hi.T, oh, dn) + dot_bf16(w_lo.T, oh, dn)
        if need_cost:
            cost_ref[0, 0] += jnp.sum(min_d2 * w)

    return _kernel


def _pallas_accumulate(x, w, centers, mode="highest", interpret=False,
                       need_cost=True):
    """Raw pallas_call on pre-padded operands (traced inside the jitted
    wrappers below — no jit of its own)."""
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // _BLOCK_ROWS,)
    sums, counts, cost = pl.pallas_call(
        _make_kernel(mode, need_cost),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, centers)
    return sums, counts, cost


@functools.partial(jax.jit, static_argnames=("mode", "interpret", "need_cost"))
def _call(x, w, centers, mode="highest", interpret=False, need_cost=True):
    return _pallas_accumulate(x, w, centers, mode, interpret, need_cost)


def _pad_operands_traced(x, weights, centers):
    """Padding math shared by the jitted wrappers (traced, never eager):
    rows to the 512-row block, k and d to lane multiples.  Dummy centers
    sit at 1e15 so no real row selects them; dummy feature columns of
    real centers are 0 (matching padded x columns)."""
    n, d = x.shape
    k = centers.shape[0]
    n_pad = pad_to(max(n, _BLOCK_ROWS), _BLOCK_ROWS)
    d_pad = pad_to(d, LANE)
    k_pad = pad_to(k, LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(x.astype(jnp.float32))
    w_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights.astype(jnp.float32))
    c_p = jnp.full((k_pad, d_pad), 1e15, jnp.float32).at[:k, :d].set(
        centers.astype(jnp.float32)
    )
    c_p = c_p.at[:k, d:].set(0.0)
    return x_p, w_p, c_p


def _pad_operands(x, weights, centers):
    """One compiled program per shape signature for the loop entry's pad
    step — previously ~6 eager dispatches per call.  Built through the
    program-cache registry (R1: jit lives in a get_or_build builder)."""
    fn = progcache.get_or_build(
        "kmeans.pallas_pad", (),
        lambda: jax.jit(_pad_operands_traced),
    )
    return fn(x, weights, centers)


@functools.partial(jax.jit, static_argnames=("mode", "interpret", "need_cost"))
def _accumulate_jit(x, weights, centers, mode, interpret, need_cost):
    """Single-shot fused accumulate: pad + kernel + slice in ONE jitted
    program.  The old path ran ``_pad_operands`` eagerly before a jitted
    kernel call — roughly six XLA dispatches of padding scatter/concat per
    invocation that the program cache could not see (``lloyd_run_pallas``
    pads once outside its loop and never had the problem)."""
    k, d = centers.shape[0], x.shape[1]
    x_p, w_p, c_p = _pad_operands_traced(x, weights, centers)
    sums, counts, cost = _pallas_accumulate(
        x_p, w_p, c_p, mode, interpret, need_cost
    )
    return sums[:k, :d], counts[0, :k], cost[0, 0]


def lloyd_accumulate_pallas(
    x: jax.Array,
    weights: jax.Array,
    centers: jax.Array,
    mode: str = "highest",
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Drop-in replacement for ops.kmeans_ops._accumulate (f32 only).

    One registry-tracked jitted program per input signature (padding
    included — see ``_accumulate_jit``).
    """
    mode = check_mode(mode)
    progcache.note(
        "kmeans.pallas_accumulate",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, weights, centers), mode, interpret),
    )
    with kernel_launch("kmeans.accumulate"):
        return _accumulate_jit(x, weights, centers, mode, interpret, True)


@functools.partial(jax.jit, static_argnames=("max_iter", "mode", "interpret"))
def _lloyd_loop_padded(x_p, w_p, c_p, max_iter, tol, mode="highest", interpret=False):
    """while_loop over the fused kernel on pre-padded operands."""
    tol_sq = tol * tol

    def cond(state):
        _, it, converged = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _ = state
        sums, counts, _ = _pallas_accumulate(
            x_p, w_p, centers, mode, interpret, need_cost=False
        )
        counts_col = counts[0][:, None]  # (k_pad, 1)
        new_centers = jnp.where(
            counts_col > 0, sums / jnp.maximum(counts_col, 1e-30), centers
        )
        moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged

    state = (c_p, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    centers, n_iter, _ = jax.lax.while_loop(cond, body, state)
    # final cost + counts w.r.t. the returned centers, always at full
    # precision — the user-facing objective should not carry the fast
    # tiers' distance error
    _, counts, cost = _pallas_accumulate(
        x_p, w_p, centers, "highest", interpret, need_cost=True
    )
    return centers, n_iter, cost[0, 0], counts[0]


def lloyd_run_pallas(x, weights, init_centers, max_iter, tol,
                     mode: str = "highest", interpret: bool = False):
    """Fused-kernel Lloyd loop; same contract as ops.kmeans_ops.lloyd_run
    (f32, adds per-cluster counts). Pads once outside the loop (one
    compiled pad program), slices the result back."""
    mode = check_mode(mode)
    d = x.shape[1]
    k = init_centers.shape[0]
    with kernel_launch("kmeans.lloyd_loop"):
        x_p, w_p, c_p = _pad_operands(x, weights, init_centers)
        centers, n_iter, cost, counts = _lloyd_loop_padded(
            x_p, w_p, c_p, max_iter, jnp.asarray(tol, jnp.float32), mode,
            interpret,
        )
    return centers[:k, :d], n_iter, cost, counts[:k]
