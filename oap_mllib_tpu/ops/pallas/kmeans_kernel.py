"""Fused Lloyd accumulate: distance + argmin + cluster sums in one kernel.

The XLA path (ops/kmeans_ops._accumulate) materializes the (n, k) distance
matrix and an (n, k) one-hot in HBM each iteration — 2*n*k*4 bytes of
traffic on top of reading X.  This kernel streams X once per iteration:
for each row block, it computes the (bn, k) distances in VMEM, reduces
min/argmin on the VPU, forms the block one-hot in VMEM, and accumulates
``one_hot.T @ x`` into the (k, d) sums output, exploiting the TPU grid's
sequential execution for safe read-modify-write accumulation (the pallas
accumulate pattern).  HBM traffic per iteration drops from
O(n*d + 2*n*k) to O(n*d + k*d).

Precision tiers (``mode``) — Mosaic only lowers Precision.HIGHEST/DEFAULT,
so split tiers are implemented by hand with bf16 hi/lo splits:

- ``highest``: both matmuls f32 Precision.HIGHEST.  Parity default.
- ``high``: distance cross-term single-pass bf16 (the tier contract —
  kmeans_ops._assign_prec — runs the assignment matmul at bf16: argmin is
  decision-only); cluster sums via an *exact-split* trick: the unweighted
  one-hot is 0/1 — exactly representable in bf16 — so ``one_hot.T @
  (w*x)`` with (w*x) split into bf16 hi+lo needs only TWO bf16 passes
  and is accurate to ~f32, meeting the XLA "high" tier's error envelope.
- ``default``: bf16 assignment + SINGLE-pass bf16 sums — the XLA default
  tier's ~1e-3 error envelope at its speed.

Caller contract (see ``lloyd_accumulate_pallas``): rows padded to the block
size with weight 0; k and d padded to lane multiples (128) by the wrapper —
dummy centers get +inf-like coordinates so no row ever selects them.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK_ROWS = 512
_LANE = 128
_MODES = ("highest", "high", "default")
# compute-precision policy names (utils/precision.py) accepted as mode
# aliases: the kernel's tiers already ARE the policy's hand-rolled bf16
# splits — "tf32" is the bf16_3x "high" tier, "bf16" the single-pass
# bf16 "default" tier, "f32" the full-f32 "highest" tier — so callers
# resolving a policy can pass its name straight through.
_MODE_ALIASES = {"f32": "highest", "tf32": "high", "bf16": "default"}


def _split_bf16(a):
    """f32 -> (hi, lo) bf16 pair with a ~= hi + lo."""
    hi = a.astype(jnp.bfloat16)
    lo = (a - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _dot_f32(a, b, dn):
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _dot_bf16(a, b, dn):
    return jax.lax.dot_general(
        a, b, dimension_numbers=dn, preferred_element_type=jnp.float32
    )


def _cross_term(x, c, mode):
    """x @ c.T (bn, k) at the requested precision tier.

    "high" and "default" share the single-pass bf16 path: the tier
    definition (kmeans_ops._assign_prec) runs the ASSIGNMENT matmul at
    bf16 for both — argmin is a discrete decision, and the tiers differ
    only in the cluster-sums accuracy (which this kernel's exact-split
    sums exceed in both modes)."""
    dn = (((1,), (1,)), ((), ()))
    if mode == "highest":
        return _dot_f32(x, c, dn)
    # high/default: single-pass bf16 — argmin only flips on near-ties
    return _dot_bf16(x.astype(jnp.bfloat16), c.astype(jnp.bfloat16), dn)


def _cluster_sums(one_hot01, wx, mode):
    """one_hot.T @ (w*x) (k, d).  one_hot is exactly 0/1 in bf16, so the
    split tiers lose nothing on it; "high" hi/lo-splits wx for ~f32
    accuracy (2 bf16 passes); "default" is single-pass all-bf16 — the
    same error envelope as the XLA default tier (~1e-3)."""
    dn = (((0,), (0,)), ((), ()))
    if mode == "highest":
        return _dot_f32(one_hot01, wx, dn)
    oh = one_hot01.astype(jnp.bfloat16)  # exact
    if mode == "default":
        return _dot_bf16(oh, wx.astype(jnp.bfloat16), dn)
    wx_hi, wx_lo = _split_bf16(wx)
    return _dot_bf16(oh, wx_hi, dn) + _dot_bf16(oh, wx_lo, dn)


def _make_kernel(mode, need_cost=True):
    def _kernel(x_ref, w_ref, c_ref, sums_ref, counts_ref, cost_ref):
        """One grid step: process a (bn, d) row block against all k centers."""
        # zero accumulators on the first block (sequential TPU grid)
        @pl.when(pl.program_id(0) == 0)
        def _init():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)
            cost_ref[0, 0] = jnp.float32(0.0)

        x = x_ref[:]  # (bn, d)
        w = w_ref[:]  # (bn, 1)
        c = c_ref[:]  # (k, d)
        k = c.shape[0]

        c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, k)
        cross = _cross_term(x, c, mode)  # (bn, k)  <- MXU

        if need_cost:
            # squared distances via the matmul identity (MXU)
            x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # (bn, 1)
            d2 = jnp.maximum(x_sq + c_sq - 2.0 * cross, 0.0)
            assign = jnp.argmin(d2, axis=1)  # (bn,)
            min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (bn, 1)
        else:
            # loop mode: argmin is invariant to the per-row |x|^2 term, so
            # rank on the half-score x.c - c_sq/2 (argMAX) — no d2 assembly,
            # no maximum, no min pass (cost is dead inside the Lloyd loop:
            # the caller recomputes it at "highest" after convergence).
            # NB keep the (bn, k) term on the LEFT of the subtract: with the
            # broadcast (1, k) operand first, Mosaic's lowering allocates a
            # ~32 MB scoped-vmem temp and fails to compile (argmax of
            # cross - c_sq/2 selects the same center, same first-index
            # tie-break as argmin of the negation)
            assign = jnp.argmax(cross - 0.5 * c_sq, axis=1)  # (bn,)

        # unweighted 0/1 one-hot (VPU compare against 2-D iota); weights fold
        # into w*x so the one-hot stays exactly representable in bf16
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1)
        one_hot = jnp.where(col_ids == assign[:, None], 1.0, 0.0)  # (bn, k)

        sums_ref[:] += _cluster_sums(one_hot, w * x, mode)
        if mode == "highest":
            # strict-parity tier: exact f32 VPU reduction
            counts_ref[:] += jnp.sum(one_hot * w, axis=0, keepdims=True)
        else:
            # fast tiers: counts as (1, bn) @ (bn, k) bf16 matmuls with
            # f32 accumulation — the one-hot is exact 0/1 and w rides a
            # hi/lo split, so counts stay ~f32-exact for ANY weights
            # while the two VPU passes over (bn, k) disappear (measured
            # -1.1 ms/iter at 1M x 256 k=1000).  NB bf16 single-pass at
            # this shape compiles where the f32-HIGHEST variant blew
            # Mosaic's scoped vmem (see the assignment note above).
            oh = one_hot.astype(jnp.bfloat16)
            w_hi, w_lo = _split_bf16(w)
            dn = (((1,), (0,)), ((), ()))
            counts_ref[:] += _dot_bf16(w_hi.T, oh, dn) + _dot_bf16(w_lo.T, oh, dn)
        if need_cost:
            cost_ref[0, 0] += jnp.sum(min_d2 * w)

    return _kernel


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("mode", "interpret", "need_cost"))
def _call(x, w, centers, mode="highest", interpret=False, need_cost=True):
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // _BLOCK_ROWS,)
    sums, counts, cost = pl.pallas_call(
        _make_kernel(mode, need_cost),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, centers)
    return sums, counts, cost


def _check_mode(mode: str) -> str:
    """Canonicalize a mode: legacy tier names pass through, policy names
    map via _MODE_ALIASES, anything else raises (typos must not silently
    run a different tier)."""
    mode = _MODE_ALIASES.get(mode, mode)
    if mode not in _MODES:
        raise ValueError(
            f"mode must be one of {_MODES} (or a policy alias "
            f"{tuple(_MODE_ALIASES)}), got {mode!r}"
        )
    return mode


def lloyd_accumulate_pallas(
    x: jax.Array,
    weights: jax.Array,
    centers: jax.Array,
    mode: str = "highest",
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Drop-in replacement for ops.kmeans_ops._accumulate (f32 only).

    Pads rows to the 512-row block, k and d to 128-lane multiples.  Dummy
    centers are placed at 1e15 so no real row selects them; their
    counts/sums come back zero and are sliced off.
    """
    mode = _check_mode(mode)
    n, d = x.shape
    k = centers.shape[0]
    x_p, w_p, c_p = _pad_operands(x, weights, centers)
    sums, counts, cost = _call(x_p, w_p, c_p, mode=mode, interpret=interpret)
    return sums[:k, :d], counts[0, :k], cost[0, 0]


def _pad_operands(x, weights, centers):
    n, d = x.shape
    k = centers.shape[0]
    n_pad = _pad_to(max(n, _BLOCK_ROWS), _BLOCK_ROWS)
    d_pad = _pad_to(d, _LANE)
    k_pad = _pad_to(k, _LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(x.astype(jnp.float32))
    w_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights.astype(jnp.float32))
    c_p = jnp.full((k_pad, d_pad), 1e15, jnp.float32).at[:k, :d].set(
        centers.astype(jnp.float32)
    )
    # dummy feature columns of real centers must be 0 (match padded x cols)
    c_p = c_p.at[:k, d:].set(0.0)
    return x_p, w_p, c_p


@functools.partial(jax.jit, static_argnames=("max_iter", "mode", "interpret"))
def _lloyd_loop_padded(x_p, w_p, c_p, max_iter, tol, mode="highest", interpret=False):
    """while_loop over the fused kernel on pre-padded operands."""
    tol_sq = tol * tol

    def cond(state):
        _, it, converged = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _ = state
        sums, counts, _ = _call(
            x_p, w_p, centers, mode=mode, interpret=interpret, need_cost=False
        )
        counts_col = counts[0][:, None]  # (k_pad, 1)
        new_centers = jnp.where(
            counts_col > 0, sums / jnp.maximum(counts_col, 1e-30), centers
        )
        moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged

    state = (c_p, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    centers, n_iter, _ = jax.lax.while_loop(cond, body, state)
    # final cost + counts w.r.t. the returned centers, always at full
    # precision — the user-facing objective should not carry the fast
    # tiers' distance error
    _, counts, cost = _call(x_p, w_p, centers, mode="highest", interpret=interpret)
    return centers, n_iter, cost[0, 0], counts[0]


def lloyd_run_pallas(x, weights, init_centers, max_iter, tol,
                     mode: str = "highest", interpret: bool = False):
    """Fused-kernel Lloyd loop; same contract as ops.kmeans_ops.lloyd_run
    (f32, adds per-cluster counts). Pads once outside the loop, slices the
    result back."""
    mode = _check_mode(mode)
    d = x.shape[1]
    k = init_centers.shape[0]
    x_p, w_p, c_p = _pad_operands(x, weights, init_centers)
    centers, n_iter, cost, counts = _lloyd_loop_padded(
        x_p, w_p, c_p, max_iter, jnp.asarray(tol, jnp.float32), mode, interpret
    )
    return centers[:k, :d], n_iter, cost, counts[:k]
