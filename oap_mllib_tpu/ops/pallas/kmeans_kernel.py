"""Fused Lloyd accumulate: distance + argmin + cluster sums in one kernel.

The XLA path (ops/kmeans_ops._accumulate) materializes the (n, k) distance
matrix and an (n, k) one-hot in HBM each iteration — 2*n*k*4 bytes of
traffic on top of reading X.  This kernel streams X once per iteration:
for each row block, it computes the (bn, k) distances in VMEM, reduces
min/argmin on the VPU, forms the block one-hot in VMEM, and accumulates
``one_hot.T @ x`` into the (k, d) sums output, exploiting the TPU grid's
sequential execution for safe read-modify-write accumulation (the pallas
accumulate pattern).  HBM traffic per iteration drops from
O(n*d + 2*n*k) to O(n*d + k*d).

Caller contract (see ``lloyd_accumulate_pallas``): rows padded to the block
size with weight 0; k and d padded to lane multiples (128) by the wrapper —
dummy centers get +inf-like coordinates so no row ever selects them.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import numpy as np

_BLOCK_ROWS = 512
_LANE = 128


def _kernel(x_ref, w_ref, c_ref, sums_ref, counts_ref, cost_ref):
    """One grid step: process a (bn, d) row block against all k centers."""
    # zero accumulators on the first block (sequential TPU grid)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[0, 0] = jnp.float32(0.0)

    x = x_ref[:]  # (bn, d)
    w = w_ref[:]  # (bn, 1)
    c = c_ref[:]  # (k, d)

    # squared distances via the matmul identity (MXU)
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # (bn, 1)
    c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, k)
    cross = jax.lax.dot_general(
        x, c,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # (bn, k)
    d2 = jnp.maximum(x_sq + c_sq - 2.0 * cross, 0.0)

    assign = jnp.argmin(d2, axis=1)  # (bn,)
    min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (bn, 1)

    # block one-hot weighted by row weights (VPU compare against 2-D iota)
    k = c.shape[0]
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1)
    one_hot = jnp.where(col_ids == assign[:, None], w, 0.0)  # (bn, k)

    # accumulate cluster sums on the MXU: (k, bn) @ (bn, d)
    sums_ref[:] += jax.lax.dot_general(
        one_hot, x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    counts_ref[:] += jnp.sum(one_hot, axis=0, keepdims=True)  # (1, k)
    cost_ref[0, 0] += jnp.sum(min_d2 * w)


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("interpret",))
def _call(x, w, centers, interpret=False):
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // _BLOCK_ROWS,)
    sums, counts, cost = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, centers)
    return sums, counts, cost


def lloyd_accumulate_pallas(
    x: jax.Array,
    weights: jax.Array,
    centers: jax.Array,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Drop-in replacement for ops.kmeans_ops._accumulate (f32 only).

    Pads rows to the 512-row block, k and d to 128-lane multiples.  Dummy
    centers are placed at 1e15 so no real row selects them; their
    counts/sums come back zero and are sliced off.
    """
    n, d = x.shape
    k = centers.shape[0]
    n_pad = _pad_to(max(n, _BLOCK_ROWS), _BLOCK_ROWS)
    d_pad = _pad_to(d, _LANE)
    k_pad = _pad_to(k, _LANE)

    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(x.astype(jnp.float32))
    w_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights.astype(jnp.float32))
    c_p = jnp.full((k_pad, d_pad), 1e15, jnp.float32).at[:k, :d].set(
        centers.astype(jnp.float32)
    )
    # dummy feature columns of real centers must be 0 (match padded x cols)
    c_p = c_p.at[:k, d:].set(0.0)

    sums, counts, cost = _call(x_p, w_p, c_p, interpret=interpret)
    return sums[:k, :d], counts[0, :k], cost[0, 0]


@functools.partial(jax.jit, static_argnames=("max_iter", "interpret"))
def _lloyd_loop_padded(x_p, w_p, c_p, max_iter, tol, interpret=False):
    """while_loop over the fused kernel on pre-padded operands."""
    tol_sq = tol * tol

    def cond(state):
        _, it, converged, _ = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _, _ = state
        sums, counts, cost = _call(x_p, w_p, centers, interpret=interpret)
        counts_col = counts[0][:, None]  # (k_pad, 1)
        new_centers = jnp.where(
            counts_col > 0, sums / jnp.maximum(counts_col, 1e-30), centers
        )
        moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged, cost[0, 0]

    state = (c_p, jnp.asarray(0, jnp.int32), jnp.asarray(False), jnp.float32(0))
    centers, n_iter, _, _ = jax.lax.while_loop(cond, body, state)
    _, _, cost = _call(x_p, w_p, centers, interpret=interpret)
    return centers, n_iter, cost[0, 0]


def lloyd_run_pallas(x, weights, init_centers, max_iter, tol, interpret=False):
    """Fused-kernel Lloyd loop; same contract as ops.kmeans_ops.lloyd_run
    (f32). Pads once outside the loop, slices the result back."""
    n, d = x.shape
    k = init_centers.shape[0]
    n_pad = _pad_to(max(n, _BLOCK_ROWS), _BLOCK_ROWS)
    d_pad = _pad_to(d, _LANE)
    k_pad = _pad_to(k, _LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(x.astype(jnp.float32))
    w_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights.astype(jnp.float32))
    c_p = jnp.full((k_pad, d_pad), 1e15, jnp.float32).at[:k, :d].set(
        init_centers.astype(jnp.float32)
    )
    c_p = c_p.at[:k, d:].set(0.0)
    centers, n_iter, cost = _lloyd_loop_padded(
        x_p, w_p, c_p, max_iter, jnp.asarray(tol, jnp.float32), interpret
    )
    return centers[:k, :d], n_iter, cost
